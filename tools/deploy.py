"""Bring a local control plane up / down.

Reference parity: py/deploy.py — cluster setup/teardown around the test
runner (GKE + ksonnet there; a supervised operator daemon here — the
"cluster" on a TPU host is the operator process itself). State lives in a
deploy dir: the daemon pid, its log, and the API URL the other tools read.

Usage:
    python -m tools.deploy up   [--port 8080] [--deploy-dir /tmp/tpujob-deploy]
    python -m tools.deploy status
    python -m tools.deploy down
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

DEFAULT_DIR = "/tmp/tpujob-deploy"


def _paths(d: str) -> dict:
    return {
        "pid": os.path.join(d, "operator.pid"),
        "url": os.path.join(d, "server.url"),
        "log": os.path.join(d, "operator.log"),
        "proc_logs": os.path.join(d, "process-logs"),
    }


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def up(args) -> int:
    paths = _paths(args.deploy_dir)
    os.makedirs(args.deploy_dir, exist_ok=True)
    if os.path.exists(paths["pid"]):
        pid = int(open(paths["pid"]).read())
        if _alive(pid):
            print(f"operator already running (pid {pid})")
            return 0
        os.unlink(paths["pid"])
    log = open(paths["log"], "ab")
    cmd = [
        sys.executable, "-m", "tf_operator_tpu.cli.operator",
        "--port", str(args.port), "--host", args.host,
        "--log-dir", paths["proc_logs"],
        "--backend", args.backend,
    ]
    if args.chaos_level:
        cmd += ["--chaos-level", str(args.chaos_level)]
    if args.local_agents:
        cmd += [
            "--local-agents", str(args.local_agents),
            "--agent-chips", str(args.agent_chips),
        ]
    child = subprocess.Popen(
        cmd, stdout=log, stderr=subprocess.STDOUT, start_new_session=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    log.close()
    url = f"http://{args.host}:{args.port}"
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url + "/healthz", timeout=2):
                break
        except OSError:
            if child.poll() is not None:
                print(f"operator exited {child.returncode}; see {paths['log']}")
                return 1
            time.sleep(0.3)
    else:
        child.terminate()
        print("operator never became healthy")
        return 1
    with open(paths["pid"], "w") as f:
        f.write(str(child.pid))
    with open(paths["url"], "w") as f:
        f.write(url)
    print(f"operator up: pid {child.pid}, api {url}, ui {url}/ui")
    return 0


def status(args) -> int:
    paths = _paths(args.deploy_dir)
    if not os.path.exists(paths["pid"]):
        print("not deployed")
        return 1
    pid = int(open(paths["pid"]).read())
    url = open(paths["url"]).read() if os.path.exists(paths["url"]) else "?"
    if not _alive(pid):
        print(f"stale deploy (pid {pid} dead)")
        return 1
    try:
        with urllib.request.urlopen(url + "/api/tpujob", timeout=3) as resp:
            n = len(json.load(resp)["items"])
    except OSError:
        print(f"operator pid {pid} alive but API unreachable at {url}")
        return 1
    print(f"operator pid {pid}, api {url}, {n} jobs")
    return 0


def down(args) -> int:
    paths = _paths(args.deploy_dir)
    if not os.path.exists(paths["pid"]):
        print("not deployed")
        return 0
    pid = int(open(paths["pid"]).read())
    if _alive(pid):
        os.kill(pid, signal.SIGTERM)
        deadline = time.time() + 15
        while time.time() < deadline and _alive(pid):
            time.sleep(0.2)
        if _alive(pid):
            os.kill(pid, signal.SIGKILL)
    for key in ("pid", "url"):
        try:
            os.unlink(paths[key])
        except OSError:
            pass
    print(f"operator pid {pid} stopped")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpujob-deploy")
    p.add_argument("command", choices=("up", "status", "down"))
    p.add_argument("--deploy-dir", default=DEFAULT_DIR)
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--backend", choices=("native", "local"), default="native")
    p.add_argument("--chaos-level", type=int, default=0)
    p.add_argument("--local-agents", type=int, default=0,
                   help="run N in-process host agents (multi-host mode: gang "
                        "scheduler + per-host launch on one machine)")
    p.add_argument("--agent-chips", type=int, default=8,
                   help="chip capacity each local agent advertises")
    args = p.parse_args(argv)
    return {"up": up, "status": status, "down": down}[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
