"""Junit XML emission (reference: py/test_util.py:1-191 — TestCase records
with failure messages serialized for the CI artifact store)."""

from __future__ import annotations

import time
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class TestCase:
    name: str
    class_name: str = "tpujob"
    time_s: float = 0.0
    failure_message: Optional[str] = None

    @property
    def failed(self) -> bool:
        return self.failure_message is not None


@dataclass
class TestSuite:
    name: str
    cases: List[TestCase] = field(default_factory=list)

    def timed_case(self, name: str):
        """Context manager: times the block; an exception marks the case
        failed (and is re-raised unless it's an AssertionError, which is
        recorded and swallowed so later cases still run)."""
        suite = self

        class _Ctx:
            def __enter__(self):
                self.case = TestCase(name=name)
                self.t0 = time.perf_counter()
                return self.case

            def __exit__(self, exc_type, exc, tb):
                self.case.time_s = time.perf_counter() - self.t0
                if exc is not None:
                    self.case.failure_message = f"{exc_type.__name__}: {exc}"
                suite.cases.append(self.case)
                return exc_type is not None and issubclass(exc_type, AssertionError)

        return _Ctx()

    @property
    def failures(self) -> int:
        return sum(1 for c in self.cases if c.failed)

    def to_xml(self) -> str:
        root = ET.Element(
            "testsuite",
            name=self.name,
            tests=str(len(self.cases)),
            failures=str(self.failures),
            time=f"{sum(c.time_s for c in self.cases):.3f}",
        )
        for c in self.cases:
            el = ET.SubElement(
                root, "testcase", classname=c.class_name, name=c.name,
                time=f"{c.time_s:.3f}",
            )
            if c.failed:
                f = ET.SubElement(el, "failure", message=c.failure_message or "")
                f.text = c.failure_message
        return ET.tostring(root, encoding="unicode")

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            f.write('<?xml version="1.0" encoding="UTF-8"?>\n')
            f.write(self.to_xml())
