"""Measure the attached chip's *practical* matmul ceiling.

MFU is conventionally quoted against the datasheet peak, but the
achievable ceiling for real layer shapes is lower (layout, tiling, and
scheduling overheads inside XLA). This probe times chained bf16 matmuls
at configurable shapes entirely on-device (a `fori_loop` inside one jit —
per-dispatch tunnel overhead would otherwise dominate: a single dispatch
costs ~10 ms through the remote-TPU tunnel, swamping a ~1.5 ms op) and
prints the effective TFLOP/s, i.e. the number a model at those shapes
should be compared against instead of the datasheet.

Usage:  python -m tools.roofline [--m 16384] [--k 768] [--n 3072] [--iters 100]

v5e (TPU v5 lite) measurements for the record: [16384,768]x[768,3072]
pairs sustain ~103 TFLOP/s (52% of the 197 nominal bf16 peak);
[16384,4096]x[4096,4096] ~118 TFLOP/s (60%). A model step at 6ND-MFU 37%
on d=768 shapes is therefore at ~94% of what the chip actually gives
dense matmuls at that size once full-remat's recompute (+~33% FLOPs) is
accounted for.
"""

from __future__ import annotations

import argparse
import sys
import time


def measure(m: int, k: int, n: int, iters: int) -> float:
    """Return effective TFLOP/s for a chained [m,k]x[k,n] -> [m,n]x[n,k] pair."""
    import jax
    import jax.numpy as jnp

    a = jax.random.normal(jax.random.PRNGKey(0), (m, k)).astype(jnp.bfloat16)
    w1 = jax.random.normal(jax.random.PRNGKey(1), (k, n)).astype(jnp.bfloat16) * 0.01
    w2 = jax.random.normal(jax.random.PRNGKey(2), (n, k)).astype(jnp.bfloat16) * 0.01

    @jax.jit
    def chain(a):
        # the w2 hop keeps shapes closed under iteration so the loop stays
        # on-device; *0.01 weights keep values finite across iters
        return jax.lax.fori_loop(0, iters, lambda i, a: (a @ w1) @ w2, a)

    jax.block_until_ready(chain(a))  # compile
    t0 = time.perf_counter()
    jax.block_until_ready(chain(a))
    dt = time.perf_counter() - t0
    flops = 2 * m * k * n * 2 * iters
    return flops / dt / 1e12


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--m", type=int, default=16384)
    p.add_argument("--k", type=int, default=768)
    p.add_argument("--n", type=int, default=3072)
    p.add_argument("--iters", type=int, default=100)
    args = p.parse_args(argv)

    import jax

    dev = jax.devices()[0]
    tflops = measure(args.m, args.k, args.n, args.iters)
    print(
        f"[{args.m},{args.k}]x[{args.k},{args.n}] chained bf16 matmul on "
        f"{getattr(dev, 'device_kind', dev.platform)}: {tflops:.1f} TFLOP/s"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
