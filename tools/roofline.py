"""Measure the attached chip's *practical* matmul and convolution ceilings.

MFU is conventionally quoted against the datasheet peak, but the
achievable ceiling for real layer shapes is lower (layout, tiling, and
scheduling overheads inside XLA). This probe times chained bf16 ops
at configurable shapes entirely on-device (a `fori_loop` inside one jit —
per-dispatch tunnel overhead would otherwise dominate: a single dispatch
costs ~10 ms through the remote-TPU tunnel, swamping a ~1.5 ms op) and
prints the effective TFLOP/s, i.e. the number a model at those shapes
should be compared against instead of the datasheet.

Usage:
  python -m tools.roofline [--m 16384] [--k 768] [--n 3072] [--iters 100]
  python -m tools.roofline --mode conv [--batch 128] [--image 224] [--fwd-only]

``--mode conv`` enumerates every convolution in the bench ResNet-50
(s2d stem, b=128, 224²) and measures each unique shape's sustained
TFLOP/s — forward alone and forward+backward (dgrad+wgrad via autodiff,
dy produced by a sum-of-squares head so the cotangent is a real tensor,
as in training). The FLOP-weighted aggregate over the layer inventory is
the *measured conv ceiling*: the MFU a ResNet-50 train step could reach
if convolutions were the only cost. BENCH_r02 reports achieved MFU
against both the 0.50 north star and this ceiling.

v5e (TPU v5 lite) matmul measurements for the record: [16384,768]x[768,3072]
pairs sustain ~103 TFLOP/s (52% of the 197 nominal bf16 peak);
[16384,4096]x[4096,4096] ~118 TFLOP/s (60%). A model step at 6ND-MFU 37%
on d=768 shapes is therefore at ~94% of what the chip actually gives
dense matmuls at that size once full-remat's recompute (+~33% FLOPs) is
accounted for.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List, NamedTuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def slope_per_iter(time_once, iters: int, retries: int = 2) -> float:
    """Seconds per iteration as the SLOPE between an ``iters``- and a
    5x-``iters``-sized run (r4 protocol, shared by every probe in this
    file): ``time_once(n)`` must build/warm an n-iteration loop and
    return the wall seconds of ONE synced execution. A single timed run
    divided by n carries the tunnel's fixed ~70-100 ms sync term — at
    iters=100 on a sub-ms body that fixed term UNDER-reported the chip
    by ~2x (see BASELINE.md "CORRECTED r4" row); the slope cancels every
    fixed cost. Tunnel jitter can make an unlucky pair non-positive —
    retried, then raised, never silently reported as throughput."""
    for _ in range(retries + 1):
        lo, hi = time_once(iters), time_once(5 * iters)
        if hi > lo:
            return (hi - lo) / (4 * iters)
    raise RuntimeError(
        "non-positive timing slope: tunnel jitter exceeded the signal; "
        "re-run with a larger --iters"
    )


def measure(m: int, k: int, n: int, iters: int) -> float:
    """Return effective TFLOP/s for a chained [m,k]x[k,n] -> [m,n]x[n,k] pair.

    r4 PROTOCOL FIX: the per-iteration time is the SLOPE between an
    ``iters``-iteration loop and a 5x one, both synced by a scalar fetch.
    The previous single-run protocol divided one wall time by iters, and
    through the remote-TPU tunnel that wall time carries a fixed
    ~70-100 ms sync/RTT term — at iters=100 on a sub-ms body the fixed
    term dominated and UNDER-reported the chip by ~2x (the archived r2
    "104 TF/s / 52% practical ceiling" row at [16k,768]x[768,3072]
    re-measures at ~190 TF/s under this protocol; every shape tried —
    d=768 through d=8192 — lands at 180-193 TF/s = 91-97% of nominal
    with VMEM-resident weights, so the old "ceiling rises with d" story
    was mostly the artifact shrinking as runs got longer)."""
    import jax
    import jax.numpy as jnp

    a = jax.random.normal(jax.random.PRNGKey(0), (m, k)).astype(jnp.bfloat16)
    w1 = jax.random.normal(jax.random.PRNGKey(1), (k, n)).astype(jnp.bfloat16) * 0.01
    w2 = jax.random.normal(jax.random.PRNGKey(2), (n, k)).astype(jnp.bfloat16) * 0.01

    def time_once(steps):
        @jax.jit
        def chain(a):
            # the w2 hop keeps shapes closed under iteration so the loop
            # stays on-device; *0.01 weights keep values finite
            a = jax.lax.fori_loop(0, steps, lambda i, a: (a @ w1) @ w2, a)
            return jnp.sum(a.astype(jnp.float32) ** 2)
        _ = float(chain(a))  # compile + warm; float() is the tunnel sync
        t0 = time.perf_counter()
        _ = float(chain(a))
        return time.perf_counter() - t0

    dt = slope_per_iter(time_once, iters)
    return 2 * m * k * n * 2 / dt / 1e12


class ConvShape(NamedTuple):
    """One convolution site in the network (count = occurrences)."""

    label: str
    h: int
    w: int
    cin: int
    cout: int
    kh: int
    kw: int
    stride: int
    count: int

    def out_hw(self):
        # SAME padding: ceil(h / stride)
        return (-(-self.h // self.stride), -(-self.w // self.stride))

    def fwd_flops(self, batch: int) -> float:
        oh, ow = self.out_hw()
        return 2.0 * batch * oh * ow * self.kh * self.kw * self.cin * self.cout


def resnet50_conv_inventory(image: int = 224) -> List[ConvShape]:
    """Every conv in the bench ResNet-50 (models/resnet.py, s2d stem),
    deduped with counts — derived from the SAME ResNetConfig the bench
    runs, so a config change (widths, depths) cannot leave this inventory
    silently stale against the published ceiling."""
    sys.path.insert(0, _REPO_ROOT)
    from tf_operator_tpu.models.resnet import ResNetConfig

    cfg = ResNetConfig.resnet50()
    shapes: List[ConvShape] = []
    h = image // 2  # after space-to-depth
    # s2d stem: 4x4/s1 conv on [h/2, w/2, 12] -> 64 channels
    shapes.append(ConvShape("stem-s2d", h, h, 12, 64, 4, 4, 1, 1))
    h //= 2  # maxpool /2 -> 56
    cin = 64
    for si, (n_blocks, width) in enumerate(
        zip(cfg.stage_sizes, cfg.widths)
    ):
        cout = width * 4
        for bi in range(n_blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            oh = h // stride
            shapes.append(ConvShape(f"s{si}b{bi}-1x1a", h, h, cin, width, 1, 1, 1, 1))
            shapes.append(ConvShape(f"s{si}b{bi}-3x3", h, h, width, width, 3, 3, stride, 1))
            shapes.append(ConvShape(f"s{si}b{bi}-1x1b", oh, oh, width, cout, 1, 1, 1, 1))
            if stride != 1 or cin != cout:
                shapes.append(ConvShape(f"s{si}b{bi}-proj", h, h, cin, cout, 1, 1, stride, 1))
            cin = cout
            h = oh
    # merge identical (h,w,cin,cout,k,stride) rows into counts
    merged = {}
    for s in shapes:
        key = (s.h, s.w, s.cin, s.cout, s.kh, s.kw, s.stride)
        if key in merged:
            m = merged[key]
            merged[key] = m._replace(count=m.count + 1)
        else:
            merged[key] = s
    return list(merged.values())


def measure_conv(
    batch: int, s: ConvShape, bwd: bool, target_flops: float = 2e12
) -> float:
    """Sustained TFLOP/s for one conv shape, scan-chained on-device.

    Methodology (matters a lot — naive probes read 3-5x low): the chain is
    a ``lax.scan`` over K DISTINCT stacked weights with the output feeding
    the next input — exactly how the model itself executes convs (stacked
    layer params under scan), so XLA schedules weight DMA/compute overlap
    the same way. A fori_loop re-invoking ONE conv on a loop-carried
    scalar measured 16 TFLOP/s where this chain measures 44+ on the same
    shape — that serialization artifact, not the hardware, was the old
    number. Shapes that don't close (cin != cout, stride > 1) are closed
    with a real 1x1 conv back to cin (mirroring the bottleneck's own
    1x1 pattern) plus a cheap spatial repeat for strides; the closer's
    FLOPs are counted in the denominator, so the row is the efficiency of
    the (conv + closer) unit — labeled ``+1x1`` in the table.

    ``bwd`` differentiates the WHOLE chain (0.5*sum(y²) head, so dy is a
    real tensor): per-layer dgrad+wgrad through scan, 3x fwd FLOPs — the
    training-step execution shape.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    fan_in = s.kh * s.kw * s.cin
    oh, ow = s.out_hw()
    needs_closer = (s.cin != s.cout) or (s.stride != 1)
    flops_iter = s.fwd_flops(batch)
    if needs_closer:
        flops_iter += 2.0 * batch * oh * ow * s.cout * s.cin  # 1x1 closer
    total_mult = 3.0 if bwd else 1.0
    iters = max(4, min(64, int(target_flops / (flops_iter * total_mult))))

    x0 = (
        jax.random.normal(jax.random.PRNGKey(0), (batch, s.h, s.w, s.cin))
        .astype(jnp.bfloat16)
    )
    ks = (
        jax.random.normal(
            jax.random.PRNGKey(1), (iters, s.kh, s.kw, s.cin, s.cout)
        )
        * (2.0 / fan_in) ** 0.5
    ).astype(jnp.bfloat16)
    kc = (
        jax.random.normal(jax.random.PRNGKey(2), (iters, 1, 1, s.cout, s.cin))
        * (2.0 / s.cout) ** 0.5
    ).astype(jnp.bfloat16)

    def conv(x_, k_, stride=1):
        return lax.conv_general_dilated(
            x_,
            k_,
            window_strides=(stride, stride),
            padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    def chain(x, stacked):
        def body(x, kpair):
            k, k_close = kpair
            y = conv(x, k, s.stride)
            if needs_closer:
                y = conv(y, k_close)
                if s.stride != 1:
                    y = jnp.repeat(jnp.repeat(y, s.stride, axis=1), s.stride, axis=2)
                    y = y[:, : s.h, : s.w]
            # keep magnitudes bounded across the chain
            return (y * jnp.bfloat16(0.5)).astype(jnp.bfloat16), None

        out, _ = lax.scan(body, x, stacked)
        return out

    if bwd:
        def head(x, stacked):
            return 0.5 * jnp.sum(jnp.square(chain(x, stacked).astype(jnp.float32)))

        run = jax.jit(jax.grad(head, argnums=(0, 1)))

        def fetch(r):
            return float(r[0][0, 0, 0, 0])
    else:
        run = jax.jit(chain)

        def fetch(r):
            return float(r[0, 0, 0, 0])

    stacked = (ks, kc)
    fetch(run(x0, stacked))  # compile + sync (host fetch: tunnel-safe)

    # slope between 2 and 10 back-to-back dispatch bursts — the old
    # single-burst timing carried the tunnel's fixed ~70-100 ms sync
    # term, which at the ~25-75 ms bursts these shapes produce read the
    # per-layer chains ~2x low (see slope_per_iter).
    def time_once(reps):
        t0 = time.perf_counter()
        r = None
        for _ in range(reps):  # back-to-back dispatch, one final fetch
            r = run(x0, stacked)
        fetch(r)
        return time.perf_counter() - t0

    dt = slope_per_iter(time_once, 2)
    return flops_iter * total_mult * iters / dt / 1e12


def _measure_with_retry(batch, s, bwd, attempts: int = 3) -> float:
    """The tunneled TPU's remote_compile sporadically drops the connection
    mid-run; a transient transport error must not kill a 30-minute sweep."""
    for i in range(attempts):
        try:
            return measure_conv(batch, s, bwd=bwd)
        except Exception as exc:  # jax.errors.JaxRuntimeError et al.
            if i == attempts - 1:
                raise
            print(f"  (retry {s.label} {'bwd' if bwd else 'fwd'}: {exc})", flush=True)
            time.sleep(5.0)


def convnet_ceiling(batch: int, image: int, bwd: bool, reps: int = 4) -> float:
    """THE conv ceiling: the bench ResNet-50 with batch-norm deleted —
    exact conv/relu/residual/pool/head graph at exact shapes, so XLA
    schedules cross-op overlap exactly as in the real model. Per-layer
    chains (the table above this in the output) systematically undershoot
    — an isolated conv chain denies XLA the inter-op pipelining the full
    network enjoys — so the achievable-MFU comparison uses THIS number:
    train MFU / convnet_ceiling(bwd) = fraction of the conv-stack's
    achievable rate the full step (BN + loss + optimizer on top) reaches.
    Returns TFLOP/s using the SAME flops_per_image accounting bench.py
    uses, so the ratio to bench MFU is apples-to-apples."""
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, _REPO_ROOT)
    from tf_operator_tpu.models.resnet import (
        ResNetConfig,
        _conv,
        _stem_s2d,
        init_resnet,
    )

    cfg = ResNetConfig.resnet50()
    params, _ = init_resnet(jax.random.PRNGKey(0), cfg)

    def fwd(params, x):
        x = x.astype(jnp.bfloat16)
        x = _stem_s2d(x, params["stem"]["conv"])
        x = jax.nn.relu(x)
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
        )
        for si, n_blocks in enumerate(cfg.stage_sizes):
            for bi in range(n_blocks):
                bp = params[f"stage{si}"][bi]
                stride = 2 if (si > 0 and bi == 0) else 1
                y = jax.nn.relu(_conv(x, bp["conv1"]))
                y = jax.nn.relu(_conv(y, bp["conv2"], stride))
                y = _conv(y, bp["conv3"])
                shortcut = _conv(x, bp["proj"], stride) if "proj" in bp else x
                x = jax.nn.relu(y + shortcut)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        return x @ params["head"]["w"] + params["head"]["b"]

    x0 = jax.random.normal(jax.random.PRNGKey(1), (batch, image, image, 3))
    # Device loop (K iterations inside ONE program, chained by a tiny
    # input perturbation from the previous output): per-dispatch tunnel
    # jitter makes single-program timings swing ±20%, exactly as bench.py's
    # device loop found for the train step; the loop amortizes it away.
    K = 8

    def keepalive(tree):
        # Reduce EVERY leaf into the carry: a carry touching only one
        # element lets XLA dead-code-eliminate the rest of the computation
        # (measured: a head-bias-only carry "ran" the backward at 130% of
        # peak — i.e. mostly deleted). Means are cheap vs the convs.
        return sum(
            jnp.mean(leaf.astype(jnp.float32))
            for leaf in jax.tree_util.tree_leaves(tree)
        ) * 1e-30

    if bwd:
        g = jax.grad(lambda p, x: 0.5 * jnp.sum(jnp.square(fwd(p, x))))

        def body(i, carry):
            s, x = carry
            return (keepalive(g(params, x + s)), x)
    else:
        def body(i, carry):
            s, x = carry
            return (keepalive(fwd(params, x + s)), x)

    run = jax.jit(
        lambda x: jax.lax.fori_loop(0, K, body, (jnp.float32(0.0), x))[0]
    )
    float(run(x0))  # compile + sync
    t0 = time.perf_counter()
    r = None
    for _ in range(reps):
        r = run(x0)
    float(r)
    dt = (time.perf_counter() - t0) / (reps * K)
    flops = cfg.flops_per_image(image) * batch * (3.0 if bwd else 1.0)
    return flops / dt / 1e12


def conv_roofline(batch: int, image: int, fwd_only: bool = False) -> int:
    """Measure every ResNet-50 conv shape; print per-layer rows and the
    FLOP-weighted ceiling (the MFU a train step could reach if convs were
    the only cost)."""
    sys.path.insert(0, _REPO_ROOT)
    from tf_operator_tpu.train.compile_cache import enable as enable_compile_cache

    enable_compile_cache()  # ~36 probe kernels; persist compiles across runs

    import jax

    from tf_operator_tpu.train.metrics import peak_flops_per_chip

    dev = jax.devices()[0]
    peak = peak_flops_per_chip(dev) / 1e12
    inv = resnet50_conv_inventory(image)
    modes = ("fwd",) if fwd_only else ("fwd", "fwd+bwd")
    print(
        f"# conv roofline: ResNet-50 b={batch} {image}² bf16 NHWC on "
        f"{getattr(dev, 'device_kind', dev.platform)} (peak {peak:.0f} TFLOP/s)",
        flush=True,
    )
    print(f"# {'layer':<12} {'shape':<30} {'count':>5} " + " ".join(f"{m:>9}" for m in modes))
    totals = {m: [0.0, 0.0] for m in modes}  # [weighted flops, weighted time]
    for s in inv:
        row = []
        for m in modes:
            tf = _measure_with_retry(batch, s, bwd=(m == "fwd+bwd"))
            row.append(tf)
            wf = s.fwd_flops(batch) * s.count * (3.0 if m == "fwd+bwd" else 1.0)
            totals[m][0] += wf
            totals[m][1] += wf / (tf * 1e12)
        closer = "+1x1" if (s.cin != s.cout or s.stride != 1) else ""
        desc = f"{s.h}x{s.w}x{s.cin}->{s.cout} k{s.kh} s{s.stride}{closer}"
        print(
            f"  {s.label:<12} {desc:<30} {s.count:>5} "
            + " ".join(f"{tf:>5.1f}T/{tf / peak:>4.0%}" for tf in row),
            flush=True,
        )
    for m in modes:
        wf, wt = totals[m]
        ceiling = wf / wt / 1e12
        print(
            f"# weighted per-layer {m}: {ceiling:.1f} TFLOP/s = "
            f"{ceiling / peak:.1%} of peak (diagnostic — isolated chains "
            "undershoot, see convnet ceiling below)",
            flush=True,
        )
    # The honest ceiling: the full conv-only network (exact graph, XLA's
    # real cross-op scheduling). Train MFU should be judged against the
    # fwd+bwd number.
    cf = convnet_ceiling(batch, image, bwd=False)
    print(
        f"# convnet (BN-free ResNet-50) fwd ceiling: {cf:.1f} TFLOP/s = "
        f"{cf / peak:.1%} of peak",
        flush=True,
    )
    if not fwd_only:
        cb = convnet_ceiling(batch, image, bwd=True)
        print(
            f"# convnet (BN-free ResNet-50) fwd+bwd ceiling: {cb:.1f} TFLOP/s "
            f"= {cb / peak:.1%} of peak -> max train MFU if convs were the "
            f"whole step: {cb / peak:.1%}",
            flush=True,
        )
    return 0


def measure_attn(b, t, h, d, causal, impl, iters=20, h_kv=None,
                 repeat_from=None):
    """Sustained ms/step for one attention config, fwd+bwd (training path),
    chained on-device like the other probes (tiny data-dependent weight
    perturbation defeats loop hoisting). ``h_kv`` < h measures the
    GQA-native path (k/v carry h_kv heads end to end); ``repeat_from``
    instead measures the pre-r3 layout — k/v allocated at repeat_from
    heads and jnp.repeat-expanded to h INSIDE the differentiated function,
    so the broadcast copy and its backward group-sum are part of the
    measurement."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    sys.path.insert(0, _REPO_ROOT)
    from tf_operator_tpu.ops.flash_attention import (
        flash_attention,
        reference_attention,
    )

    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    kv_heads = repeat_from or h_kv or h
    q = jax.random.normal(keys[0], (b, t, h, d)).astype(jnp.bfloat16)
    k = jax.random.normal(keys[1], (b, t, kv_heads, d)).astype(jnp.bfloat16)
    v = jax.random.normal(keys[2], (b, t, kv_heads, d)).astype(jnp.bfloat16)

    if impl == "flash":
        def base_attn(q_, k_, v_):
            return flash_attention(q_, k_, v_, causal=causal, force_kernel=True)
    else:
        def base_attn(q_, k_, v_):
            return reference_attention(q_, k_, v_, causal=causal)
    if repeat_from:
        g_rep = h // repeat_from

        def attn(q_, k_, v_):
            return base_attn(
                q_, jnp.repeat(k_, g_rep, axis=2), jnp.repeat(v_, g_rep, axis=2)
            )
    else:
        attn = base_attn

    def head(q_, k_, v_):
        return 0.5 * jnp.sum(jnp.square(attn(q_, k_, v_).astype(jnp.float32)))

    g = jax.grad(head, argnums=(0, 1, 2))

    def body(i, carry):
        gq, gk, gv = g(q + carry.astype(jnp.bfloat16), k, v)
        return (gq[0, 0, 0, 0] + gk[0, 0, 0, 0] + gv[0, 0, 0, 0]).astype(
            jnp.float32
        ) * 1e-30

    # slope protocol (see slope_per_iter) — the old single-call timing
    # overstated ms-scale bodies 2-4x and COMPRESSED A/B ratios toward 1
    # (the r2 flash-vs-dense table understates the kernel's true
    # advantage; its gate decisions were conservative, not wrong).
    def time_once(n):
        run = jax.jit(lambda c: lax.fori_loop(0, n, body, c))
        float(run(jnp.float32(0.0)))  # compile + sync
        t0 = time.perf_counter()
        float(run(jnp.float32(0.0)))
        return time.perf_counter() - t0

    return slope_per_iter(time_once, iters) * 1e3  # ms per fwd+bwd


def gqa_roofline(d: int = 128) -> int:
    """GQA A/B (r3, VERDICT #2 done-bar): flash fwd+bwd at a GQA shape —
    native h_kv-head K/V vs the pre-r3 materialized repeat (k/v expanded
    to h heads before the kernel). Reports the time ratio and the K/V
    activation bytes each layout keeps resident per layer."""
    sys.path.insert(0, _REPO_ROOT)
    from tf_operator_tpu.train.compile_cache import enable as enable_compile_cache

    enable_compile_cache()
    import jax

    dev = jax.devices()[0]
    h, h_kv = 16, 2
    print(f"# GQA flash fwd+bwd, causal, bf16, hd={d}, {h}q/{h_kv}kv heads on "
          f"{getattr(dev, 'device_kind', dev.platform)}")
    print(f"# {'b':>3} {'t':>6}  {'repeat ms':>10} {'native ms':>10} "
          f"{'speedup':>8} {'kv MiB rep':>10} {'kv MiB nat':>10}")
    for b, t in ((4, 2048), (2, 4096), (1, 8192)):
        # pre-r3 layout: h_kv-head K/V repeat-expanded INSIDE the step
        rep = measure_attn(b, t, h, d, True, "flash", repeat_from=h_kv)
        nat = measure_attn(b, t, h, d, True, "flash", h_kv=h_kv)
        mib = lambda heads: 2 * b * t * heads * d * 2 / 2**20
        print(f"  {b:>3} {t:>6}  {rep:>10.2f} {nat:>10.2f} "
              f"{rep / nat:>7.2f}x {mib(h):>10.1f} {mib(h_kv):>10.1f}")
    return 0


def attn_roofline(d: int = 64) -> int:
    """flash-vs-dense crossover table at head_dim ``d`` (fwd+bwd, causal),
    the measurement behind flash_attention's dispatch gate."""
    sys.path.insert(0, _REPO_ROOT)
    from tf_operator_tpu.train.compile_cache import enable as enable_compile_cache

    enable_compile_cache()
    import jax

    dev = jax.devices()[0]
    print(f"# attention fwd+bwd, causal, bf16, hd={d} on "
          f"{getattr(dev, 'device_kind', dev.platform)} (b x t x h chosen ~const tokens)")
    print(f"# {'b':>3} {'t':>6} {'h':>3}  {'dense ms':>9} {'flash ms':>9} {'flash/dense':>11}")
    for b, t, h in ((8, 512, 12), (4, 1024, 12), (2, 2048, 12), (1, 4096, 12), (1, 8192, 12)):
        dense = measure_attn(b, t, h, d, True, "dense")
        flash = measure_attn(b, t, h, d, True, "flash")
        print(f"  {b:>3} {t:>6} {h:>3}  {dense:>9.2f} {flash:>9.2f} {dense / flash:>10.2f}x")
    return 0


def moe_roofline(tokens: int = 32768, d: int = 768, f: int = 3072,
                 n_experts: int = 8, k_top: int = 1,
                 capacity_factor: float = 2.0, iters: int = 40) -> int:
    """Decompose the single-chip MoE step cost at bench shapes (r4,
    VERDICT item 2: where do the other 82% of active-MFU go?).

    Times fwd+bwd of five bodies over the same [T, d] activations:
      dense        one SwiGLU over all T tokens at [T,d]x[d,f] — the
                   "active FLOPs at ideal shape" reference
      experts-loop the expert compute exactly as _moe_single runs it
                   (fori_loop over E, [C,d]x[d,f] each) on a fixed inbox
      experts-vmap the same compute as ONE batched [E,C,d]x[E,d,f]
                   einsum chain (what removing the loop buys)
      routing      moe_apply with an identity expert_fn — router + sort/
                   scatter/gather + combine, zero expert FLOPs
      full         the real moe layer (router + dispatch + experts +
                   combine)
    and prints a table: ms, implied active-MFU (6·T_active·params_mlp /
    time), and the share of `full`. Padding waste is structural:
    capacity rows C·E = cf·k·T, so the expert stage runs cf·k× the
    active FLOPs — measured directly by the experts rows.
    """
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.parallel.moe import moe_apply

    from tf_operator_tpu.train.metrics import peak_flops_per_chip

    dev = jax.devices()[0]
    peak = peak_flops_per_chip(dev)
    cap = max(1, int(capacity_factor * k_top * tokens / n_experts))
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 6)
    x = (jax.random.normal(ks[0], (tokens, d)) * 0.02).astype(jnp.bfloat16)
    router = (jax.random.normal(ks[1], (d, n_experts)) * 0.02).astype(jnp.bfloat16)
    wp = {
        "w_gate": (jax.random.normal(ks[2], (n_experts, d, f)) * 0.02).astype(jnp.bfloat16),
        "w_up": (jax.random.normal(ks[3], (n_experts, d, f)) * 0.02).astype(jnp.bfloat16),
        "w_down": (jax.random.normal(ks[4], (n_experts, f, d)) * 0.02).astype(jnp.bfloat16),
    }
    dense_w = {k_: v[0] for k_, v in wp.items()}
    inbox = (jax.random.normal(ks[5], (n_experts, cap, d)) * 0.02).astype(jnp.bfloat16)

    def swiglu(w, t):
        return (jax.nn.silu(t @ w["w_gate"]) * (t @ w["w_up"])) @ w["w_down"]

    def expert_fn(w, t):
        return swiglu(w, t)

    # Every body differentiates wrt activations AND weights — the
    # training cost shape (fwd 2 + bwd 4 FLOPs per param-token); an
    # input-only grad would skip the dW matmuls and over-report MFU 1.5x.
    def body_dense(args):
        return jnp.sum(swiglu(args["w"], args["x"]).astype(jnp.float32) ** 2)

    def body_experts_loop(args):
        inbox, w = args["x"], args["w"]

        def run(e, acc):
            w_e = jax.tree_util.tree_map(lambda a: a[e], w)
            return acc + jnp.sum(swiglu(w_e, inbox[e]).astype(jnp.float32) ** 2)
        return jax.lax.fori_loop(0, n_experts, run, jnp.float32(0.0))

    def body_experts_vmap(args):
        out = jax.vmap(swiglu, in_axes=(0, 0))(args["w"], args["x"])
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def body_routing(args):
        gl = args["x"] @ args["w"]
        out = moe_apply(args["x"], gl, {"w": jnp.zeros((n_experts, 1))},
                        lambda w, t: t, None, capacity_factor=capacity_factor,
                        k_top=k_top, dropped="zero")
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def body_full(args):
        gl = args["x"] @ args["wr"]
        out = moe_apply(args["x"], gl, args["w"], expert_fn, None,
                        capacity_factor=capacity_factor, k_top=k_top,
                        dropped="zero")
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def body_full_gmm(args):
        # r5: the padding-free grouped-matmul layer (ops/grouped_matmul)
        gl = args["x"] @ args["wr"]
        out = moe_apply(args["x"], gl, args["w"], expert_fn, None,
                        k_top=k_top, dispatch_impl="gmm")
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def body_experts_gmm(args):
        # the grouped matmul alone on ACTIVE rows (uniform groups): the
        # "experts-vmap at cf" rows vs this one isolates the padding
        # term. r6: the down projection runs with the fused combine
        # epilogue (row_scale) exactly as the shipped layer does, and
        # the dw walk behind this row is the regridded
        # (expert, col-tile, block-walk) kernel — this row is where its
        # retired per-step accumulator round trip shows up.
        from tf_operator_tpu.ops.grouped_matmul import gmm as gmm_op

        xs, w = args["x"], args["w"]
        nb_blocks = xs.shape[0] // 256  # the kernel's shipping block size
        # nondecreasing block→expert map covering every block even when
        # nb_blocks % n_experts != 0 (a repeat() of nb//E entries would
        # leave the tail blocks reading out of the prefetch buffer)
        be = (
            jnp.arange(nb_blocks, dtype=jnp.int32) * n_experts // nb_blocks
        ).astype(jnp.int32)
        zg = gmm_op(xs, w["w_gate"], be)
        zu = gmm_op(xs, w["w_up"], be)
        out = gmm_op(jax.nn.silu(zg) * zu, w["w_down"], be,
                     row_scale=args["rs"])
        return jnp.sum(out.astype(jnp.float32) ** 2)

    # Active-FLOP reference: 6·(3·d·f)·T_active fwd+bwd matmul FLOPs
    # (2 fwd + 4 bwd per param-token).
    active_flops = 6 * (3 * d * f) * tokens * k_top

    def timeit(fn, arg):
        # fori_loop INSIDE one jit (the file-header protocol): host-side
        # iteration pays ~10 ms of tunnel dispatch per call, which at
        # these ~10 ms bodies measured 2-10x the true cost. Feeding each
        # iteration's grad back into its input keeps the body
        # loop-varying so XLA cannot hoist it. The sync fetch must be a
        # SCALAR (np.asarray on the full carry moves tens of MB through
        # the ~17 MB/s tunnel), and even the scalar fetch pays ~70-100 ms
        # RTT — so the per-iteration time is taken as the SLOPE between
        # a short and a long loop, cancelling every fixed cost.
        g = jax.grad(fn)

        def time_once(n):
            @jax.jit
            def loop(args):
                def body(i, args):
                    ga = g(args)
                    return jax.tree_util.tree_map(
                        lambda a, da: (a + 1e-6 * da).astype(a.dtype),
                        args, ga)
                args = jax.lax.fori_loop(0, n, body, args)
                return jnp.sum(
                    jax.tree_util.tree_leaves(args)[0].astype(jnp.float32) ** 2
                )
            _ = float(loop(arg))  # compile + warm
            t0 = time.perf_counter()
            _ = float(loop(arg))
            return time.perf_counter() - t0

        return slope_per_iter(time_once, iters)

    x_active = (jax.random.normal(ks[5], (tokens * k_top, d)) * 0.02).astype(
        jnp.bfloat16
    )
    rows = [
        ("dense", body_dense, {"x": x, "w": dense_w}),
        ("experts-loop", body_experts_loop, {"x": inbox, "w": wp}),
        ("experts-vmap", body_experts_vmap, {"x": inbox, "w": wp}),
        ("experts-gmm", body_experts_gmm,
         {"x": x_active, "w": wp,
          "rs": jnp.ones((tokens * k_top,), jnp.float32)}),
        ("routing", body_routing, {"x": x, "w": router}),
        ("full", body_full, {"x": x, "wr": router, "w": wp}),
        ("full-gmm", body_full_gmm, {"x": x, "wr": router, "w": wp}),
    ]
    results = {}
    for name, fn, arg in rows:
        results[name] = timeit(fn, arg)
    full_ms = results["full"] * 1e3
    print(f"MoE roofline on {getattr(dev, 'device_kind', dev.platform)}: "
          f"T={tokens} d={d} f={f} E={n_experts} top-{k_top} cf={capacity_factor} "
          f"C={cap} (expert rows = {capacity_factor * k_top:.2f}x active)")
    print(f"  {'stage':<14} {'ms':>8}  {'active-MFU':>10}  {'% of full':>9}")
    for name, _, _ in rows:
        dt = results[name]
        amfu = active_flops / dt / peak
        print(f"  {name:<14} {dt * 1e3:>8.2f}  {amfu:>10.1%}  "
              f"{dt * 1e3 / full_ms:>9.1%}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--mode", choices=("matmul", "conv", "attn", "gqa", "moe"),
                   default="matmul")
    p.add_argument("--m", type=int, default=16384)
    p.add_argument("--k", type=int, default=768)
    p.add_argument("--n", type=int, default=3072)
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--image", type=int, default=224)
    p.add_argument("--fwd-only", action="store_true")
    p.add_argument("--d", type=int, default=None,
                   help="head_dim (default: 64 for --mode attn, 128 for gqa)")
    p.add_argument("--k-top", type=int, default=1, help="--mode moe: top-k")
    p.add_argument("--cf", type=float, default=2.0,
                   help="--mode moe: capacity factor")
    args = p.parse_args(argv)

    import jax

    if args.mode == "conv":
        return conv_roofline(args.batch, args.image, args.fwd_only)
    if args.mode == "attn":
        return attn_roofline(args.d or 64)
    if args.mode == "gqa":
        return gqa_roofline(args.d or 128)
    if args.mode == "moe":
        return moe_roofline(tokens=args.m, k_top=args.k_top,
                            capacity_factor=args.cf)

    dev = jax.devices()[0]
    tflops = measure(args.m, args.k, args.n, args.iters)
    print(
        f"[{args.m},{args.k}]x[{args.k},{args.n}] chained bf16 matmul on "
        f"{getattr(dev, 'device_kind', dev.platform)}: {tflops:.1f} TFLOP/s"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
