"""CI/test/release tooling (reference: the ``py/`` tree — test runner,
deploy, release, prow glue — and ``test/e2e`` — the TAP smoke driver).
Run modules from the repo root: ``python -m tools.test_runner …``."""
