"""CI pipeline runner.

Reference parity: the Prow→Argo orchestration (prow_config.yaml +
test/workflows/components/workflows.libsonnet) collapsed into a local stage
runner: sequential stages, fail-fast except ``always`` stages (the
teardown-cluster semantics), artifacts dir for junit XML (the
copy-artifacts/GCS step).

Usage:
    python -m tools.ci [--pipeline ci/pipeline.yaml] [--artifacts /tmp/ci-out]
"""

from __future__ import annotations

import argparse
import os
import shlex
import socket
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_stage(stage: dict, subs: dict) -> int:
    cmd = stage["run"].format(**subs)
    print(f"\n=== stage {stage['name']}: {cmd}", flush=True)
    t0 = time.perf_counter()
    r = subprocess.run(shlex.split(cmd), cwd=REPO_ROOT)
    print(f"=== stage {stage['name']}: exit {r.returncode} "
          f"({time.perf_counter() - t0:.1f}s)", flush=True)
    return r.returncode


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpujob-ci")
    p.add_argument("--pipeline", default=os.path.join(REPO_ROOT, "ci", "pipeline.yaml"))
    p.add_argument("--artifacts", default="/tmp/tpujob-ci-artifacts")
    args = p.parse_args(argv)

    import yaml

    with open(args.pipeline) as f:
        pipeline = yaml.safe_load(f)
    os.makedirs(args.artifacts, exist_ok=True)
    subs = {"port": free_port(), "port2": free_port(), "artifacts": args.artifacts}

    failed = None
    results = []
    for stage in pipeline["stages"]:
        if failed is not None and not stage.get("always"):
            results.append((stage["name"], "skipped"))
            continue
        rc = run_stage(stage, subs)
        results.append((stage["name"], "ok" if rc == 0 else f"FAIL({rc})"))
        if rc != 0 and failed is None:
            failed = stage["name"]

    print(f"\n{pipeline.get('name', 'pipeline')} summary:")
    for name, outcome in results:
        print(f"  {outcome:10} {name}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
