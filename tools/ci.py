"""CI pipeline runner.

Reference parity: the Prow→Argo orchestration (prow_config.yaml +
test/workflows/components/workflows.libsonnet) collapsed into a local stage
runner: sequential stages, fail-fast except ``always`` stages (the
teardown-cluster semantics), artifacts dir for junit XML (the
copy-artifacts/GCS step).

Usage:
    python -m tools.ci [--pipeline ci/pipeline.yaml] [--artifacts /tmp/ci-out]
"""

from __future__ import annotations

import argparse
import os
import shlex
import socket
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_stage(stage: dict, subs: dict, sink=None) -> int:
    cmd = stage["run"].format(**subs)
    print(f"\n=== stage {stage['name']}: {cmd}", flush=True)
    t0 = time.perf_counter()
    if sink is not None:
        # tee: terminal keeps streaming, the sink archives the build log
        # (the per-stage build-log.txt of the Gubernator layout)
        with sink.open_log(f"build-log-{stage['name']}.txt") as logf:
            proc = subprocess.Popen(
                shlex.split(cmd), cwd=REPO_ROOT,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
            for line in proc.stdout:
                sys.stdout.write(line)
                logf.write(line)
            proc.wait()
            rc = proc.returncode
    else:
        rc = subprocess.run(shlex.split(cmd), cwd=REPO_ROOT).returncode
    print(f"=== stage {stage['name']}: exit {rc} "
          f"({time.perf_counter() - t0:.1f}s)", flush=True)
    return rc


def shard_pytest(argv) -> int:
    """Run the unit tiers in parallel pytest shards (r6: the suite grew
    past 500 tests / ~36 min serial; the e2e/chaos tiers are marked and
    staged separately, and everything that spawns an operator binds
    ephemeral ports, so file-level parallelism is safe).

    With pytest-xdist installed this simply execs ``pytest -n N``; the
    CI container has no xdist, so the fallback partitions test FILES
    over N concurrent pytest subprocesses (greedy by file size — a crude
    but monotone duration proxy), each with its own junit artifact. Exit
    is nonzero if any shard fails; "no tests collected" (pytest exit 5 —
    a shard whose files were entirely deselected by -m) counts as pass.
    The pass count is the sum over shards — identical to the serial run
    by construction (same selection expression, disjoint file sets).

    Usage: python -m tools.ci shard-pytest [--shards N]
               [--junit-prefix P] -- <pytest args...>
    """
    p = argparse.ArgumentParser(prog="tpujob-ci shard-pytest")
    p.add_argument("--shards", type=int, default=0,
                   help="0 = auto (cpu_count//4, clamped to [2, 6])")
    p.add_argument("--junit-prefix", default=None,
                   help="write <prefix>-shard<i>.xml per shard")
    p.add_argument("rest", nargs=argparse.REMAINDER,
                   help="pytest args after --")
    args = p.parse_args(argv)
    rest = [a for a in args.rest if a != "--"]
    n = args.shards or max(2, min(6, (os.cpu_count() or 4) // 4))

    try:
        import xdist  # noqa: F401

        cmd = [sys.executable, "-m", "pytest", "-n", str(n), *rest]
        if args.junit_prefix:
            cmd.append(f"--junitxml={args.junit_prefix}-xdist.xml")
        print(f"shard-pytest: xdist available, exec {' '.join(cmd)}",
              flush=True)
        return subprocess.run(cmd, cwd=REPO_ROOT).returncode
    except ImportError:
        pass

    import glob
    import re as _re
    import threading

    files = sorted(glob.glob(os.path.join(REPO_ROOT, "tests", "test_*.py")))
    if not files:
        print("shard-pytest: no test files found", file=sys.stderr)
        return 2
    # greedy longest-processing-time partition on file size
    buckets = [[] for _ in range(n)]
    sizes = [0] * n
    for f in sorted(files, key=lambda f: -os.path.getsize(f)):
        i = sizes.index(min(sizes))
        buckets[i].append(os.path.relpath(f, REPO_ROOT))
        sizes[i] += os.path.getsize(f)
    buckets = [b for b in buckets if b]

    results = [None] * len(buckets)

    def run_shard(i):
        cmd = [sys.executable, "-m", "pytest", *buckets[i], *rest]
        if args.junit_prefix:
            cmd.append(f"--junitxml={args.junit_prefix}-shard{i}.xml")
        proc = subprocess.run(
            cmd, cwd=REPO_ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        results[i] = (proc.returncode, proc.stdout)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=run_shard, args=(i,))
               for i in range(len(buckets))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    passed = failed = 0
    bad = False
    for i, (rc, out) in enumerate(results):
        tail = out.strip().splitlines()[-1] if out.strip() else ""
        print(f"--- shard {i} ({len(buckets[i])} files): exit {rc}: {tail}",
              flush=True)
        if rc not in (0, 5):
            bad = True
            # full log only for failing shards — the passing ones would
            # bury the failure under thousands of dots
            print(out, flush=True)
        for key, pat in (("passed", r"(\d+) passed"),
                         ("failed", r"(\d+) failed")):
            m = _re.search(pat, out)
            if m:
                if key == "passed":
                    passed += int(m.group(1))
                else:
                    failed += int(m.group(1))
    print(f"shard-pytest: {len(buckets)} shards, {passed} passed, "
          f"{failed} failed in {time.perf_counter() - t0:.1f}s", flush=True)
    return 1 if bad else 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "shard-pytest":
        return shard_pytest(argv[1:])
    p = argparse.ArgumentParser(prog="tpujob-ci")
    p.add_argument("--pipeline", default=os.path.join(REPO_ROOT, "ci", "pipeline.yaml"))
    p.add_argument("--artifacts", default="/tmp/tpujob-ci-artifacts")
    p.add_argument("--output-base", default=os.environ.get("CI_OUTPUT_BASE"),
                   help="artifact sink base (dir or gs://bucket/prefix): "
                        "archives a versioned started.json/finished.json/"
                        "artifacts tree per the Prow/Gubernator layout "
                        "(reference py/prow.py:36-60); JOB_NAME/BUILD_NUMBER/"
                        "PULL_NUMBER env select the path")
    args = p.parse_args(argv)

    import yaml

    with open(args.pipeline) as f:
        pipeline = yaml.safe_load(f)
    os.makedirs(args.artifacts, exist_ok=True)
    subs = {"port": free_port(), "port2": free_port(), "artifacts": args.artifacts}

    sink = None
    if args.output_base:
        from tools.artifacts import make_sink

        sink = make_sink(args.output_base)
        sink.started()
        print(f"artifact sink: {sink.root}")

    failed = None
    results = []
    try:
        for stage in pipeline["stages"]:
            if failed is not None and not stage.get("always"):
                results.append((stage["name"], "skipped"))
                continue
            rc = run_stage(stage, subs, sink=sink)
            results.append((stage["name"], "ok" if rc == 0 else f"FAIL({rc})"))
            if rc != 0 and failed is None:
                failed = stage["name"]
    except BaseException:
        failed = failed or "runner-crash"
        raise
    finally:
        # finished.json must exist for FAILED runs too (a crashed stage
        # command / bad substitution would otherwise leave the tree
        # permanently "running" — exactly the runs the contract records).
        if sink is not None:
            sink.add_tree(args.artifacts)  # junit + logs from the working dir
            sink.finished(passed=failed is None,
                          metadata={"stages": dict(results)})
            if hasattr(sink, "upload"):
                sink.upload()

    print(f"\n{pipeline.get('name', 'pipeline')} summary:")
    for name, outcome in results:
        print(f"  {outcome:10} {name}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
