"""CI pipeline runner.

Reference parity: the Prow→Argo orchestration (prow_config.yaml +
test/workflows/components/workflows.libsonnet) collapsed into a local stage
runner: sequential stages, fail-fast except ``always`` stages (the
teardown-cluster semantics), artifacts dir for junit XML (the
copy-artifacts/GCS step).

Usage:
    python -m tools.ci [--pipeline ci/pipeline.yaml] [--artifacts /tmp/ci-out]
"""

from __future__ import annotations

import argparse
import os
import shlex
import socket
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_stage(stage: dict, subs: dict, sink=None) -> int:
    cmd = stage["run"].format(**subs)
    print(f"\n=== stage {stage['name']}: {cmd}", flush=True)
    t0 = time.perf_counter()
    if sink is not None:
        # tee: terminal keeps streaming, the sink archives the build log
        # (the per-stage build-log.txt of the Gubernator layout)
        with sink.open_log(f"build-log-{stage['name']}.txt") as logf:
            proc = subprocess.Popen(
                shlex.split(cmd), cwd=REPO_ROOT,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
            for line in proc.stdout:
                sys.stdout.write(line)
                logf.write(line)
            proc.wait()
            rc = proc.returncode
    else:
        rc = subprocess.run(shlex.split(cmd), cwd=REPO_ROOT).returncode
    print(f"=== stage {stage['name']}: exit {rc} "
          f"({time.perf_counter() - t0:.1f}s)", flush=True)
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpujob-ci")
    p.add_argument("--pipeline", default=os.path.join(REPO_ROOT, "ci", "pipeline.yaml"))
    p.add_argument("--artifacts", default="/tmp/tpujob-ci-artifacts")
    p.add_argument("--output-base", default=os.environ.get("CI_OUTPUT_BASE"),
                   help="artifact sink base (dir or gs://bucket/prefix): "
                        "archives a versioned started.json/finished.json/"
                        "artifacts tree per the Prow/Gubernator layout "
                        "(reference py/prow.py:36-60); JOB_NAME/BUILD_NUMBER/"
                        "PULL_NUMBER env select the path")
    args = p.parse_args(argv)

    import yaml

    with open(args.pipeline) as f:
        pipeline = yaml.safe_load(f)
    os.makedirs(args.artifacts, exist_ok=True)
    subs = {"port": free_port(), "port2": free_port(), "artifacts": args.artifacts}

    sink = None
    if args.output_base:
        from tools.artifacts import make_sink

        sink = make_sink(args.output_base)
        sink.started()
        print(f"artifact sink: {sink.root}")

    failed = None
    results = []
    try:
        for stage in pipeline["stages"]:
            if failed is not None and not stage.get("always"):
                results.append((stage["name"], "skipped"))
                continue
            rc = run_stage(stage, subs, sink=sink)
            results.append((stage["name"], "ok" if rc == 0 else f"FAIL({rc})"))
            if rc != 0 and failed is None:
                failed = stage["name"]
    except BaseException:
        failed = failed or "runner-crash"
        raise
    finally:
        # finished.json must exist for FAILED runs too (a crashed stage
        # command / bad substitution would otherwise leave the tree
        # permanently "running" — exactly the runs the contract records).
        if sink is not None:
            sink.add_tree(args.artifacts)  # junit + logs from the working dir
            sink.finished(passed=failed is None,
                          metadata={"stages": dict(results)})
            if hasattr(sink, "upload"):
                sink.upload()

    print(f"\n{pipeline.get('name', 'pipeline')} summary:")
    for name, outcome in results:
        print(f"  {outcome:10} {name}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
