"""E2E smoke driver with TAP output.

Reference parity: test/e2e/main.go — builds the canonical small job
programmatically (1 coordinator + workers, main.go:83-97), polls it to
Succeeded (:106-129), asserts per-replica resources exist (:135-148),
deletes and asserts GC (:150-191), TAP output (:244-252), and ``--num-jobs``
parallel submissions (:208-238). The TF_CONFIG-era MASTER/PS/WORKER
topology collapses to Coordinator/Worker on a TPU slice.

Usage:
    python -m tools.e2e --server http://127.0.0.1:8080 [--num-jobs 2]
"""

from __future__ import annotations

import argparse
import sys
import threading

from tf_operator_tpu.api.types import (
    ObjectMeta,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    TPUJob,
    TPUJobSpec,
)
from tf_operator_tpu.dashboard.client import TPUJobApiError, TPUJobClient

# CPU-safe env for the smoke gang (the e2e driver must run anywhere,
# including hosts whose ambient env pins the TPU plugin).
_CPU_ENV = {
    "JAX_PLATFORMS": "cpu",
    "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
    "PALLAS_AXON_POOL_IPS": "",
    "XLA_FLAGS": "",
}


def build_smoke_job(name: str, workers: int) -> TPUJob:
    """The tf_smoke analogue: every process joins the gang and the mesh-wide
    matmul checks every device (examples/tf_sample/tf_sample/tf_smoke.py)."""
    template = ProcessTemplate(
        entrypoint="tf_operator_tpu.workloads.smoke:main", env=dict(_CPU_ENV)
    )
    return TPUJob(
        metadata=ObjectMeta(name=name),
        spec=TPUJobSpec(
            replica_specs={
                ReplicaType.COORDINATOR: ReplicaSpec(replicas=1, template=template),
                ReplicaType.WORKER: ReplicaSpec(replicas=workers, template=template),
            },
            workload={"dim": 32},
        ),
    )


def run_one(client: TPUJobClient, name: str, workers: int, timeout: float) -> str:
    """Run the full lifecycle for one job; returns '' or a failure message."""
    ns = "default"
    try:
        job = build_smoke_job(name, workers)
        client.create(job)
        # per-replica resources exist while running (main.go:135-148)
        detail = None
        import time

        deadline = time.time() + timeout
        want = 1 + workers
        while time.time() < deadline:
            detail = client.get(ns, name)
            if len(detail.get("processes", [])) >= want:
                break
            if detail["job"].get("phase") in ("Failed", "Done"):
                break
            time.sleep(0.5)
        n_procs = len((detail or {}).get("processes", []))
        if n_procs != want:
            return f"expected {want} processes, saw {n_procs}"
        done = client.wait_for_job(ns, name, timeout=timeout)
        phase = done.status.phase().value
        if phase != "Done":
            return f"terminal phase {phase}: {done.status.message}"
        client.delete(ns, name)
        client.wait_for_delete(ns, name, timeout=60)
        return ""
    except (TPUJobApiError, TimeoutError, OSError) as exc:
        try:  # best-effort cleanup so reruns aren't poisoned
            client.delete(ns, name)
        except Exception:
            pass
        return str(exc)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpujob-e2e")
    p.add_argument("--server", default="http://127.0.0.1:8080")
    p.add_argument("--num-jobs", type=int, default=1,
                   help="parallel submissions (main.go:208-238)")
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--timeout", type=float, default=300.0)
    args = p.parse_args(argv)

    client = TPUJobClient(args.server)
    results: dict = {}

    def worker(i: int) -> None:
        results[i] = run_one(client, f"e2e-smoke-{i}", args.workers, args.timeout)

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(args.num_jobs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # TAP (main.go:244-252)
    print(f"1..{args.num_jobs}")
    failures = 0
    for i in range(args.num_jobs):
        msg = results.get(i, "no result")
        if msg:
            failures += 1
            print(f"not ok {i + 1} - e2e-smoke-{i}: {msg}")
        else:
            print(f"ok {i + 1} - e2e-smoke-{i}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
