"""Orchestration-level test runner against a live operator.

Reference parity: py/test_runner.py — submit the job, wait for the terminal
state, assert the EVENTS ORACLE (number of process-create events equals the
sum of replica counts, test_runner.py:311-338), delete, assert GC, and run
two trials under the same name to prove delete→recreate works
(test_runner.py:276-280). Junit XML output for the CI artifact store.

Usage:
    python -m tools.test_runner --server http://127.0.0.1:8080 \
        --spec examples/smoke_local_cpu.json [--junit-path out.xml]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from tf_operator_tpu.api.types import TPUJob
from tf_operator_tpu.controller.events import REASON_SUCCESSFUL_CREATE
from tf_operator_tpu.dashboard.client import TPUJobApiError, TPUJobClient
from tools.junit import TestSuite


def _create_event_count(client: TPUJobClient, namespace: str, job_name: str) -> int:
    """Total aggregated SuccessfulCreateProcess count for the job."""
    total = 0
    for ev in client.events(namespace):
        if (
            ev.get("reason") == REASON_SUCCESSFUL_CREATE
            and ev.get("involved_name") == job_name
        ):
            total += int(ev.get("count", 1))
    return total


def expected_replicas(job: TPUJob) -> int:
    return sum(spec.replicas or 1 for spec in job.spec.replica_specs.values())


def run_trial(
    client: TPUJobClient,
    job: TPUJob,
    timeout: float,
    trial: int,
    suite: TestSuite,
) -> None:
    ns = job.metadata.namespace or "default"
    name = job.metadata.name
    base_events = _create_event_count(client, ns, name)  # trials share the name

    with suite.timed_case(f"trial{trial}-submit-and-complete"):
        client.create(job)
        done = client.wait_for_job(ns, name, timeout=timeout)
        phase = done.status.phase().value
        conds = "; ".join(
            f"{c.type.value}({c.reason}): {c.message}" for c in done.status.conditions
        )
        assert phase == "Done", f"job finished {phase} [{conds}]"

    with suite.timed_case(f"trial{trial}-events-oracle"):
        want = expected_replicas(job)
        got = _create_event_count(client, ns, name) - base_events
        assert got == want, (
            f"process-create events {got} != sum of replicas {want} "
            "(reference oracle: test_runner.py:311-338)"
        )

    with suite.timed_case(f"trial{trial}-delete-and-gc"):
        client.delete(ns, name)
        client.wait_for_delete(ns, name, timeout=60)
        # Children are GC'd with the job: the detail endpoint 404s and no
        # process of this job remains (wait_for_pods_to_be_deleted analogue).
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                client.get(ns, name)
            except TPUJobApiError as exc:
                if exc.code == 404:
                    return
            time.sleep(0.5)
        raise AssertionError("job detail still served after delete")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpujob-test-runner")
    p.add_argument("--server", default="http://127.0.0.1:8080")
    p.add_argument("--spec", required=True, help="TPUJob JSON spec file")
    p.add_argument("--trials", type=int, default=2,
                   help="submissions under the same name (reference runs 2 "
                        "to verify delete->recreate, test_runner.py:276-280)")
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--junit-path", default=None)
    args = p.parse_args(argv)

    with open(args.spec) as f:
        spec = json.load(f)

    client = TPUJobClient(args.server)
    suite = TestSuite(name=f"test_runner:{args.spec}")
    for trial in range(1, args.trials + 1):
        job = TPUJob.from_dict(json.loads(json.dumps(spec)))
        run_trial(client, job, args.timeout, trial, suite)

    if args.junit_path:
        suite.write(args.junit_path)
    for case in suite.cases:
        status = "FAIL" if case.failed else "ok"
        print(f"{status:4} {case.name} ({case.time_s:.1f}s)"
              + (f" — {case.failure_message}" if case.failed else ""))
    print(f"{len(suite.cases)} cases, {suite.failures} failures")
    return 1 if suite.failures else 0


if __name__ == "__main__":
    sys.exit(main())
