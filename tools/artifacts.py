"""CI artifact sink: the Prow/Gubernator job-artifact contract, locally.

Reference parity: ``/root/reference/py/prow.py:36-60`` computes a GCS
output directory from JOB_NAME / BUILD_NUMBER / PULL_NUMBER per the
kubernetes test-infra artifact layout, then copies junit + logs there and
writes started.json / finished.json so the results UI (Gubernator) can
render runs. This module reproduces that contract with a pluggable sink:

- layout:  ``{base}/logs/{job}/{build}/``  (postsubmit)  or
           ``{base}/pr-logs/pull/{repo}/{pull}/{job}/{build}/``  (presubmit)
- content: ``started.json`` (timestamp, repo sha), per-stage build logs +
           junit under ``artifacts/``, ``finished.json`` (result, passed)

``LocalSink`` writes the tree to a directory; a ``gs://`` base selects
``GcsSink``, which stages locally and uploads with gsutil when present
(this environment has no egress, so the upload step degrades to a loud
log line — the LAYOUT is what the contract specifies, and it is what the
tests pin).
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import time
from typing import Optional


def output_path(base: str, job: str, build: str,
                pull_number: Optional[str] = None,
                repo: str = "tf-operator-tpu") -> str:
    """The Gubernator layout rule (prow.py get_gcs_output)."""
    if pull_number:
        return f"{base.rstrip('/')}/pr-logs/pull/{repo}/{pull_number}/{job}/{build}"
    return f"{base.rstrip('/')}/logs/{job}/{build}"


def _git_sha() -> str:
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    from tf_operator_tpu.utils.version import git_sha

    return git_sha()


class LocalSink:
    """Artifact tree on local disk (the substrate CI runs on here)."""

    def __init__(self, base: str, job: Optional[str] = None,
                 build: Optional[str] = None,
                 pull_number: Optional[str] = None) -> None:
        self.job = job or os.environ.get("JOB_NAME", "tpujob-ci")
        self.build = (build or os.environ.get("BUILD_NUMBER")
                      or time.strftime("%Y%m%d-%H%M%S"))
        self.pull_number = pull_number or os.environ.get("PULL_NUMBER")
        self.root = output_path(base, self.job, self.build, self.pull_number)
        self.artifacts_dir = os.path.join(self.root, "artifacts")
        os.makedirs(self.artifacts_dir, exist_ok=True)

    # -- lifecycle (started/finished: the Gubernator metadata contract) --

    def started(self) -> None:
        self._write_json("started.json", {
            "timestamp": int(time.time()),
            "repos": {"tf-operator-tpu": _git_sha() or "unknown"},
        })

    def finished(self, passed: bool, metadata: Optional[dict] = None) -> None:
        self._write_json("finished.json", {
            "timestamp": int(time.time()),
            "result": "SUCCESS" if passed else "FAILURE",
            "passed": passed,
            "metadata": metadata or {},
        })

    # -- content ----------------------------------------------------------

    def open_log(self, name: str):
        """Writable text stream under artifacts/ (per-stage build logs)."""
        return open(os.path.join(self.artifacts_dir, name), "w")

    def add_file(self, path: str, name: Optional[str] = None) -> None:
        if os.path.isfile(path):
            shutil.copy2(path, os.path.join(self.artifacts_dir,
                                            name or os.path.basename(path)))

    def add_tree(self, directory: str) -> None:
        """Copy every junit/log/json file from a working dir into the tree
        (the copy-artifacts step)."""
        if not os.path.isdir(directory):
            return
        for dirpath, _, files in os.walk(directory):
            for f in files:
                if f.endswith((".xml", ".log", ".txt", ".json")):
                    rel = os.path.relpath(os.path.join(dirpath, f), directory)
                    dst = os.path.join(self.artifacts_dir, rel)
                    os.makedirs(os.path.dirname(dst), exist_ok=True)
                    shutil.copy2(os.path.join(dirpath, f), dst)

    def _write_json(self, name: str, payload: dict) -> None:
        with open(os.path.join(self.root, name), "w") as f:
            json.dump(payload, f, indent=2)


class GcsSink(LocalSink):
    """GCS-shaped sink: stages the identical tree locally, then uploads
    with gsutil if available. With no egress (this environment), the
    upload is skipped LOUDLY — the versioned tree still exists locally
    for inspection, which is the part CI consumes here."""

    def __init__(self, gs_base: str, staging_root: str = "/tmp/tpujob-gcs-staging",
                 **kw) -> None:
        assert gs_base.startswith("gs://")
        self.gs_base = gs_base
        super().__init__(os.path.join(staging_root, gs_base[len("gs://"):]), **kw)

    def upload(self) -> bool:
        # Destination carries the FULL layout path (logs/{job}/{build} or
        # pr-logs/...): a bare `cp -r <root> gs://base` would nest only the
        # build-number basename, landing runs outside the layout and
        # colliding same-numbered builds across jobs.
        dest = output_path(self.gs_base, self.job, self.build, self.pull_number)
        gsutil = shutil.which("gsutil")
        if not gsutil:
            print(f"[artifacts] gsutil unavailable; tree staged at {self.root} "
                  f"(would rsync to {dest})")
            return False
        r = subprocess.run([gsutil, "-m", "rsync", "-r", self.root, dest])
        return r.returncode == 0


def make_sink(base: str, **kw):
    if base.startswith("gs://"):
        return GcsSink(base, **kw)
    return LocalSink(base, **kw)
