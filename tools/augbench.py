"""Host augmentation throughput: numpy loop vs native dataops (r3).

The input pipeline's augmentation runs on the host inside the
DeviceLoader's prefetch thread; its throughput bounds how large a batch
the loader can hide behind a step. Same RNG draws feed both paths
(outputs are bit-identical — pinned in tests/test_data.py), so this is a
pure gather-speed A/B of train/data.augment_images' two backends.

    python -m tools.augbench [--batch 256] [--size 224] [--iters 30]

Prints one JSON line per path plus the speedup.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)


def run(native: bool, images: np.ndarray, iters: int) -> float:
    from tf_operator_tpu.train.data import augment_images

    rng = np.random.default_rng(0)
    augment_images(images, rng, native=native)  # warm (build/load the lib)
    t0 = time.perf_counter()
    for _ in range(iters):
        augment_images(images, rng, native=native)
    dt = time.perf_counter() - t0
    return images.shape[0] * iters / dt


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--size", type=int, default=224)
    p.add_argument("--iters", type=int, default=30)
    args = p.parse_args(argv)
    images = (
        np.random.default_rng(1)
        .random((args.batch, args.size, args.size, 3)) * 255
    ).astype(np.uint8)
    rates = {}
    for name, native in (("numpy", False), ("native", True)):
        try:
            rates[name] = run(native, images, args.iters)
        except RuntimeError as exc:
            print(json.dumps({"metric": f"aug_{name}", "error": str(exc)}))
            continue
        print(json.dumps({
            "metric": f"aug_{name}_images_per_s", "value": round(rates[name], 1),
            "batch": args.batch, "size": args.size,
        }), flush=True)
    if len(rates) == 2:
        print(json.dumps({
            "metric": "aug_native_speedup",
            "value": round(rates["native"] / rates["numpy"], 2),
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
