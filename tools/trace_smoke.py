"""Trace smoke test: the CI gate for end-to-end lifecycle tracing.

Submits a small batch of no-op jobs against a RUNNING operator (the
deployed cluster the e2e stage already stood up), waits for them to
finish, fetches one job's trace through the dashboard's trace endpoint
(the same document ``tpujob trace`` prints), and asserts the contract
the observability subsystem exists to keep:

- the document is valid Chrome trace-event JSON (``traceEvents`` of
  M/X/i events with pid/tid/ts);
- the timeline contains the ``scheduled`` and ``first-step`` spans
  (so submit→scheduled and TTFS are derivable);
- spans from >= 3 distinct components are present (controller +
  agent/backend + trainer at minimum — the cross-component stitching
  is the whole point);
- a smoke serve job's trace carries the per-request span schema
  (``request-admitted`` → ``first-token`` → ``finished``, one finished
  span per request, each with a ``tokens`` attr — the rows the
  reconciler folds into ``tpujob_request_ttft_seconds`` /
  ``tpujob_request_tokens_total`` at terminal);
- the job's ``/telemetry`` payload carries >= 1 ring batch with
  per-rank monotonic step ranges and finite MFU (the r13 telemetry
  plane works end to end even for a no-op payload).

Usage:
    python -m tools.trace_smoke --server http://127.0.0.1:8080
"""

from __future__ import annotations

import argparse
import sys
import time

from tf_operator_tpu.dashboard.client import TPUJobApiError, TPUJobClient
from tf_operator_tpu.serve.spec import build_serve_job
from tools.genjob import build_job

REQUIRED_EVENT_KEYS = ("name", "ph", "pid", "tid")


def validate_chrome_trace(doc: dict) -> list:
    """Schema violations in a Chrome trace-event document; [] = valid."""
    errs = []
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        return [f"traceEvents missing/empty: {type(events).__name__}"]
    for i, ev in enumerate(events):
        for k in REQUIRED_EVENT_KEYS:
            if k not in ev:
                errs.append(f"event {i} missing {k!r}: {ev}")
        ph = ev.get("ph")
        if ph not in ("M", "X", "i", "B", "E"):
            errs.append(f"event {i} has unknown phase {ph!r}")
        if ph in ("X", "i") and "ts" not in ev:
            errs.append(f"event {i} ({ph}) missing ts")
        if ph == "X" and "dur" not in ev:
            errs.append(f"event {i} (X) missing dur")
    return errs


def telemetry_errors(payload: dict) -> list:
    """Schema violations in a /telemetry payload; [] = valid. The golden
    contract: >= 1 batch, per-rank monotonic step ranges, finite MFU."""
    import math

    errs = []
    batches = payload.get("batches")
    if not isinstance(batches, list) or not batches:
        return [f"telemetry batches missing/empty: {batches!r}"]
    by_rank: dict = {}
    for i, b in enumerate(batches):
        for k in ("rank", "seq", "start_step", "end_step", "step_time_s", "mfu"):
            if k not in b:
                errs.append(f"batch {i} missing {k!r}: {sorted(b)}")
        if not math.isfinite(float(b.get("mfu", 0.0))):
            errs.append(f"batch {i} has non-finite mfu: {b.get('mfu')!r}")
        if int(b.get("end_step", 0)) < int(b.get("start_step", 0)):
            errs.append(f"batch {i} step range inverted: {b}")
        by_rank.setdefault(int(b.get("rank", -1)), []).append(b)
    for rank, bs in by_rank.items():
        bs.sort(key=lambda b: int(b.get("seq", 0)))
        for prev, cur in zip(bs, bs[1:]):
            if int(cur["end_step"]) <= int(prev["end_step"]):
                errs.append(
                    f"rank {rank} steps not monotonic across seqs: "
                    f"{prev['end_step']} -> {cur['end_step']}"
                )
    summary = payload.get("summary") or {}
    if not summary.get("ranks"):
        errs.append(f"summary missing/empty: {summary!r}")
    return errs


SERVE_SMOKE_REQUESTS = 4


def serve_trace_errors(doc: dict, requests: int) -> list:
    """Request-span schema violations in a serve job's trace; [] = valid."""
    errs = validate_chrome_trace(doc)
    slices = [ev for ev in doc.get("traceEvents", ()) if ev.get("ph") == "X"]
    by_op: dict = {}
    for ev in slices:
        by_op.setdefault(ev.get("name"), []).append(ev)
    for op in ("request-admitted", "first-token", "finished"):
        if op not in by_op:
            errs.append(
                f"serve trace missing {op!r} spans (ops: {sorted(by_op)})"
            )
    finished = by_op.get("finished", [])
    if len(finished) != requests:
        errs.append(
            f"expected {requests} 'finished' spans (one per request), "
            f"got {len(finished)}"
        )
    for ev in finished:
        args = ev.get("args", {})
        if "request" not in args:
            errs.append(f"finished span missing 'request' attr: {args}")
        tokens = args.get("tokens")
        if not (isinstance(tokens, str) and tokens.isdigit() and int(tokens) > 0):
            errs.append(f"finished span 'tokens' attr not a count: {tokens!r}")
    return errs


def run_serve_smoke(client: TPUJobClient, timeout: float) -> list:
    """Submit one smoke serve job, return request-span schema errors."""
    name = f"tracesmoke-serve-{int(time.time()) % 100000}"
    job = build_serve_job(name, workload={
        "requests": SERVE_SMOKE_REQUESTS, "prompt_len": 6,
        "max_new_tokens": 6, "arrival_rate": 0.0,
    })
    client.create(job)
    try:
        done = client.wait_for_job("default", name, timeout=timeout)
        phase = done.status.phase().value
        if phase != "Done":
            return [f"serve smoke job finished {phase}"]
        doc = client.trace("default", name)
        errs = serve_trace_errors(doc, SERVE_SMOKE_REQUESTS)
        if not errs:
            print(
                f"serve trace ok: {name} events={len(doc['traceEvents'])} "
                f"requests={SERVE_SMOKE_REQUESTS}"
            )
        return errs
    finally:
        try:
            client.delete("default", name)
        except TPUJobApiError:
            pass


def run(server: str, jobs: int, workers: int, timeout: float) -> int:
    client = TPUJobClient(server)
    names = []
    for i in range(jobs):
        job = build_job(
            f"tracesmoke-{int(time.time()) % 100000}-{i}", workers, 1,
            "tf_operator_tpu.workloads.noop:main", "", True,
        )
        client.create(job)
        names.append(job.metadata.name)
    print(f"submitted {jobs} no-op jobs")
    for name in names:
        done = client.wait_for_job("default", name, timeout=timeout)
        phase = done.status.phase().value
        if phase != "Done":
            print(f"FAIL: {name} finished {phase}", file=sys.stderr)
            return 1

    # One job's trace is the assertion target; the rest exercised volume.
    target = names[0]
    doc = client.trace("default", target)
    errs = validate_chrome_trace(doc)

    ops = {
        ev.get("name")
        for ev in doc.get("traceEvents", ())
        if ev.get("ph") in ("X", "i")
    }
    for required in ("scheduled", "first-step"):
        if required not in ops:
            errs.append(f"trace missing required span {required!r} (ops: {sorted(ops)})")
    components = doc.get("otherData", {}).get("components", [])
    if len(components) < 3:
        errs.append(f"expected spans from >= 3 components, got {components}")
    timings = doc.get("otherData", {})
    if timings.get("time_to_first_step_s") is None:
        errs.append("otherData.time_to_first_step_s not derived")

    # The telemetry plane rides the same smoke job: even a no-op payload
    # must land >= 1 ring batch with a sane schema (r13).
    telemetry = client.telemetry("default", target)
    terrs = telemetry_errors(telemetry)
    if not terrs:
        print(
            f"telemetry ok: {target} batches={len(telemetry['batches'])} "
            f"ranks={telemetry['summary']['ranks']}"
        )
    errs.extend(terrs)

    errs.extend(run_serve_smoke(client, timeout))

    # best-effort cleanup so reruns aren't poisoned
    for name in names:
        try:
            client.delete("default", name)
        except TPUJobApiError:
            pass

    if errs:
        for e in errs:
            print(f"FAIL: {e}", file=sys.stderr)
        return 1
    print(
        f"trace ok: {target} events={len(doc['traceEvents'])} "
        f"components={components} "
        f"ttfs={timings.get('time_to_first_step_s'):.3f}s "
        f"scheduled={timings.get('time_to_scheduled_s'):.3f}s"
    )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpujob-trace-smoke")
    p.add_argument("--server", default="http://127.0.0.1:8080")
    p.add_argument("--jobs", type=int, default=3)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--timeout", type=float, default=300.0)
    args = p.parse_args(argv)
    try:
        return run(args.server, args.jobs, args.workers, args.timeout)
    except (TPUJobApiError, TimeoutError, OSError) as exc:
        print(f"FAIL: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
