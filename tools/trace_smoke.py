"""Trace smoke test: the CI gate for end-to-end lifecycle tracing.

Submits a small batch of no-op jobs against a RUNNING operator (the
deployed cluster the e2e stage already stood up), waits for them to
finish, fetches one job's trace through the dashboard's trace endpoint
(the same document ``tpujob trace`` prints), and asserts the contract
the observability subsystem exists to keep:

- the document is valid Chrome trace-event JSON (``traceEvents`` of
  M/X/i events with pid/tid/ts);
- the timeline contains the ``scheduled`` and ``first-step`` spans
  (so submit→scheduled and TTFS are derivable);
- spans from >= 3 distinct components are present (controller +
  agent/backend + trainer at minimum — the cross-component stitching
  is the whole point).

Usage:
    python -m tools.trace_smoke --server http://127.0.0.1:8080
"""

from __future__ import annotations

import argparse
import sys
import time

from tf_operator_tpu.dashboard.client import TPUJobApiError, TPUJobClient
from tools.genjob import build_job

REQUIRED_EVENT_KEYS = ("name", "ph", "pid", "tid")


def validate_chrome_trace(doc: dict) -> list:
    """Schema violations in a Chrome trace-event document; [] = valid."""
    errs = []
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        return [f"traceEvents missing/empty: {type(events).__name__}"]
    for i, ev in enumerate(events):
        for k in REQUIRED_EVENT_KEYS:
            if k not in ev:
                errs.append(f"event {i} missing {k!r}: {ev}")
        ph = ev.get("ph")
        if ph not in ("M", "X", "i", "B", "E"):
            errs.append(f"event {i} has unknown phase {ph!r}")
        if ph in ("X", "i") and "ts" not in ev:
            errs.append(f"event {i} ({ph}) missing ts")
        if ph == "X" and "dur" not in ev:
            errs.append(f"event {i} (X) missing dur")
    return errs


def run(server: str, jobs: int, workers: int, timeout: float) -> int:
    client = TPUJobClient(server)
    names = []
    for i in range(jobs):
        job = build_job(
            f"tracesmoke-{int(time.time()) % 100000}-{i}", workers, 1,
            "tf_operator_tpu.workloads.noop:main", "", True,
        )
        client.create(job)
        names.append(job.metadata.name)
    print(f"submitted {jobs} no-op jobs")
    for name in names:
        done = client.wait_for_job("default", name, timeout=timeout)
        phase = done.status.phase().value
        if phase != "Done":
            print(f"FAIL: {name} finished {phase}", file=sys.stderr)
            return 1

    # One job's trace is the assertion target; the rest exercised volume.
    target = names[0]
    doc = client.trace("default", target)
    errs = validate_chrome_trace(doc)

    ops = {
        ev.get("name")
        for ev in doc.get("traceEvents", ())
        if ev.get("ph") in ("X", "i")
    }
    for required in ("scheduled", "first-step"):
        if required not in ops:
            errs.append(f"trace missing required span {required!r} (ops: {sorted(ops)})")
    components = doc.get("otherData", {}).get("components", [])
    if len(components) < 3:
        errs.append(f"expected spans from >= 3 components, got {components}")
    timings = doc.get("otherData", {})
    if timings.get("time_to_first_step_s") is None:
        errs.append("otherData.time_to_first_step_s not derived")

    # best-effort cleanup so reruns aren't poisoned
    for name in names:
        try:
            client.delete("default", name)
        except TPUJobApiError:
            pass

    if errs:
        for e in errs:
            print(f"FAIL: {e}", file=sys.stderr)
        return 1
    print(
        f"trace ok: {target} events={len(doc['traceEvents'])} "
        f"components={components} "
        f"ttfs={timings.get('time_to_first_step_s'):.3f}s "
        f"scheduled={timings.get('time_to_scheduled_s'):.3f}s"
    )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpujob-trace-smoke")
    p.add_argument("--server", default="http://127.0.0.1:8080")
    p.add_argument("--jobs", type=int, default=3)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--timeout", type=float, default=300.0)
    args = p.parse_args(argv)
    try:
        return run(args.server, args.jobs, args.workers, args.timeout)
    except (TPUJobApiError, TimeoutError, OSError) as exc:
        print(f"FAIL: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
