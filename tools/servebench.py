"""Serving bench: continuous-batching A/B + the operator preemption probe.

The r10 acceptance oracle, in two halves:

- **A/B bench** (default): one in-process ServeEngine serves the SAME
  seeded request trace twice — ``mode="continuous"`` (iteration-level
  admission, immediate eviction) vs ``mode="static"`` (admit only into an
  empty batch, hold every slot until the whole batch drains: the
  classic request-level batcher). Same params, same compiled step
  functions (a warmup run pays the jit once, outside both timed runs),
  same arrival schedule — the only variable is the batching policy.
  Emits a one-line JSON artifact (tokens/s both modes, ratio, p50/p99
  TTFT, per-token latency) and gates: every request completed in both
  modes, zero KV page leaks, continuous >= --min-ratio x static
  tokens/s at equal-or-better p99 TTFT.

- **--probe**: deploys a FRESH operator daemon and replays the
  mixed-priority story end to end: a training job (lm, checkpointing)
  holds a one-job-quota Queue; a serve job submitted with
  job_class="serving" (fleet base priority 100 vs training's 0) must
  preempt it; the victim must drain and warm-resume (preemption_count
  1, restart_count 0, cause "preemption") and still finish, while every
  serve request completes (eval_metrics receipt) and the reconciler
  folds the request spans into tpujob_request_ttft_seconds /
  tpujob_request_tokens_total at terminal.

Usage:
    python -m tools.servebench --seed 7 --out artifacts/servebench.json
    python -m tools.servebench --seed 7 --probe --out ...   # + operator run
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _quantile(xs, q):
    if not xs:
        return 0.0
    ys = sorted(xs)
    idx = min(len(ys) - 1, int(round(q * (len(ys) - 1))))
    return ys[idx]


# ---- in-process continuous-vs-static A/B --------------------------------


def _mode_row(res, n_requests: int) -> dict:
    ttfts = res.ttfts()
    lats = res.token_latencies()
    return {
        "completed": res.completed,
        "requests": n_requests,
        "generated_tokens": res.generated_tokens,
        "steps": res.steps,
        "wall_s": round(res.wall_s, 3),
        "tokens_per_s": round(res.tokens_per_s, 1),
        "ttft_p50_ms": round(_quantile(ttfts, 0.50) * 1e3, 1),
        "ttft_p99_ms": round(_quantile(ttfts, 0.99) * 1e3, 1),
        "token_latency_p50_ms": round(_quantile(lats, 0.50) * 1e3, 2),
        "token_latency_p99_ms": round(_quantile(lats, 0.99) * 1e3, 2),
        "kv_page_leaks": res.free_pages_start - res.free_pages_end,
    }


def run_ab(args) -> dict:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, REPO_ROOT)
    import jax

    from tf_operator_tpu.models.transformer import init_transformer, preset
    from tf_operator_tpu.serve.engine import ServeConfig, ServeEngine
    from tf_operator_tpu.workloads.serve import synthesize_requests

    cfg = preset(args.preset)
    scfg = ServeConfig(
        page_size=args.kv_page_size,
        pool_pages=args.kv_pool_pages,
        max_slots=args.max_slots,
        prefill_chunk=args.prefill_chunk,
    )
    params = init_transformer(jax.random.PRNGKey(args.seed), cfg)
    engine = ServeEngine(cfg, params, scfg)
    wl = {
        "seed": args.seed,
        "requests": args.requests,
        "prompt_len": args.prompt_len,
        "max_new_tokens": args.max_new_tokens,
        "arrival_rate": args.arrival_rate,
    }
    # warmup: pay prefill+decode jit outside both timed runs so the A/B
    # compares policies, not compile order
    engine.run(synthesize_requests({**wl, "requests": 2}, cfg.vocab))

    rows = {}
    for mode in ("continuous", "static"):
        reqs = synthesize_requests(wl, cfg.vocab)
        res = engine.run(reqs, mode=mode)
        rows[mode] = _mode_row(res, len(reqs))
        print(f"{mode}: {json.dumps(rows[mode])}", flush=True)
    cont, stat = rows["continuous"], rows["static"]
    ratio = (
        cont["tokens_per_s"] / stat["tokens_per_s"]
        if stat["tokens_per_s"] else 0.0
    )
    return {
        "metric": "serve_bench",
        "unit": "tokens/s",
        "preset": args.preset,
        "seed": args.seed,
        "requests": args.requests,
        "max_slots": args.max_slots,
        "kv_page_size": args.kv_page_size,
        "kv_pool_pages": args.kv_pool_pages,
        "arrival_rate": args.arrival_rate,
        "continuous": cont,
        "static": stat,
        "continuous_vs_static": round(ratio, 2),
    }


def gate_ab(artifact: dict, min_ratio: float) -> list:
    """The CI contract as a list of human-readable failures (empty = pass)."""
    bad = []
    for mode in ("continuous", "static"):
        row = artifact[mode]
        if row["completed"] != row["requests"]:
            bad.append(
                f"{mode}: only {row['completed']}/{row['requests']} "
                f"requests completed"
            )
        if row["kv_page_leaks"]:
            bad.append(f"{mode}: {row['kv_page_leaks']} KV pages leaked")
    ratio = artifact["continuous_vs_static"]
    if ratio < min_ratio:
        bad.append(
            f"continuous/static tokens/s ratio {ratio} under the "
            f"{min_ratio}x floor"
        )
    if artifact["continuous"]["ttft_p99_ms"] > artifact["static"]["ttft_p99_ms"]:
        bad.append(
            f"continuous p99 TTFT {artifact['continuous']['ttft_p99_ms']}ms "
            f"worse than static {artifact['static']['ttft_p99_ms']}ms"
        )
    return bad


# ---- --probe: serve-preempts-training on a live operator ----------------


def _cpu_env() -> dict:
    return {
        "JAX_PLATFORMS": "cpu",
        "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "",
        # native tracebacks in the kept process logs when a probe child
        # dies on a signal — costs nothing, saves a bisect.
        "PYTHONFAULTHANDLER": "1",
    }


def _victim_job(checkpoint_dir: str, chips: int):
    """Low-priority (job_class defaults to training → fleet base 0) lm
    trainer with periodic checkpoints, long enough to still be running
    when the serve job lands, short enough to finish after warm-resume."""
    from tf_operator_tpu.api.types import (
        ObjectMeta,
        ProcessTemplate,
        ReplicaSpec,
        ReplicaType,
        SchedulingSpec,
        TPUJob,
        TPUJobSpec,
    )

    return TPUJob(
        metadata=ObjectMeta(name="victim", namespace="probe"),
        spec=TPUJobSpec(
            replica_specs={
                ReplicaType.WORKER: ReplicaSpec(
                    replicas=1,
                    template=ProcessTemplate(
                        entrypoint="tf_operator_tpu.workloads.lm:main",
                        env=_cpu_env(),
                        chips_per_process=chips,
                    ),
                )
            },
            workload={
                "preset": "tiny", "steps": 3000, "batch_size": 2,
                "seq_len": 16, "checkpoint_dir": checkpoint_dir,
                "checkpoint_every": 50, "data": "fixed",
            },
            scheduling=SchedulingSpec(queue="main"),
        ),
    )


def run_probe(args) -> dict:
    sys.path.insert(0, REPO_ROOT)
    import urllib.request

    from tf_operator_tpu.api.types import ObjectMeta
    from tf_operator_tpu.dashboard.client import TPUJobClient
    from tf_operator_tpu.sched.objects import Queue, QueueSpec
    from tf_operator_tpu.serve.spec import build_serve_job
    from tools.genjob import (
        _parse_histogram,
        _scrape_counter,
        _start_operator,
        _stop_operator,
    )

    chips = 4
    out = {"ok": False, "error": ""}
    op_args = argparse.Namespace(bench_backend=args.backend)
    operator, server, workdir, log_path = _start_operator(op_args, "serve")
    try:
        client = TPUJobClient(server)
        # exactly one job's chips fit: the serve job can only run by
        # preempting the training victim
        client.create_object(Queue(
            metadata=ObjectMeta(name="main", namespace="probe"),
            spec=QueueSpec(quota_chips=chips),
        ))
        ckpt_dir = os.path.join(workdir, "victim-ckpt")
        client.create(_victim_job(ckpt_dir, chips))
        deadline = time.time() + 60
        while time.time() < deadline:
            if client.get_job("probe", "victim").status.phase().value == "Running":
                break
            time.sleep(0.25)
        else:
            out["error"] = "victim never started running"
            return out
        # wait for one committed checkpoint so the resume is warm, not a
        # from-scratch rerun (bounded: preemption is correct either way)
        deadline = time.time() + 45
        while time.time() < deadline:
            if os.path.isdir(ckpt_dir) and any(os.scandir(ckpt_dir)):
                break
            time.sleep(0.5)

        serve = build_serve_job(
            "server", namespace="probe", queue="main", chips=chips,
            workload={
                "requests": 6, "prompt_len": 8, "max_new_tokens": 8,
                "arrival_rate": 0.0, "seed": args.seed, "report_every": 1,
            },
        )
        t0 = time.time()
        client.create(serve)
        sjob = client.wait_for_job("probe", "server", timeout=180)
        out["serve_wait_s"] = round(time.time() - t0, 2)
        out["serve_phase"] = sjob.status.phase().value
        # eval_metrics round-trips through the REST store as a plain dict
        # ({"step":..., "metrics": {...}}) on client-fetched jobs.
        em = sjob.status.eval_metrics
        if isinstance(em, dict):
            metrics = em.get("metrics") or {}
        else:
            metrics = getattr(em, "metrics", {}) or {}
        out["requests_total"] = int(metrics.get("requests_total", 0))
        out["requests_completed"] = int(metrics.get("requests_completed", 0))

        victim = client.wait_for_job("probe", "victim", timeout=300)
        out.update(
            victim_phase=victim.status.phase().value,
            preemption_count=victim.status.preemption_count,
            restart_count=victim.status.restart_count,
            last_restart_cause=victim.status.last_restart_cause,
        )

        # terminal-fold receipt: the reconciler turned the serve job's
        # request spans into fleet metrics
        with urllib.request.urlopen(server + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
        _, ttft_n = _parse_histogram(text, "tpujob_request_ttft_seconds")
        out["ttft_samples"] = ttft_n
        out["tokens_total_metric"] = _scrape_counter(
            text, "tpujob_request_tokens_total"
        )

        if sjob.status.phase().value != "Done":
            out["error"] = f"serve job finished {sjob.status.phase().value}"
        elif out["requests_completed"] != out["requests_total"] or not out["requests_total"]:
            out["error"] = (
                f"serve completed {out['requests_completed']}/"
                f"{out['requests_total']} requests"
            )
        elif victim.status.phase().value != "Done":
            out["error"] = "victim did not finish after preemption"
        elif victim.status.preemption_count != 1:
            out["error"] = (
                f"victim preemption_count {victim.status.preemption_count}, "
                "expected exactly 1"
            )
        elif victim.status.restart_count != 0:
            out["error"] = "preemption was charged to restart_count/backoff"
        elif victim.status.last_restart_cause != "preemption":
            out["error"] = (
                f"restart cause {victim.status.last_restart_cause!r}, "
                "expected 'preemption'"
            )
        elif not ttft_n:
            out["error"] = "no tpujob_request_ttft_seconds samples at terminal"
        else:
            out["ok"] = True
    except Exception as exc:  # probe failures fail the bench, not crash it
        out["error"] = f"{type(exc).__name__}: {exc}"
        out["log"] = log_path
    finally:
        _stop_operator(operator, workdir, keep=not out["ok"])
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--preset", default="tiny")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--prompt-len", type=int, default=8)
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--arrival-rate", type=float, default=0.0,
                   help="Poisson req/s; 0 = all at t=0 (pure policy A/B)")
    p.add_argument("--max-slots", type=int, default=6)
    p.add_argument("--kv-page-size", type=int, default=8)
    p.add_argument("--kv-pool-pages", type=int, default=96)
    p.add_argument("--prefill-chunk", type=int, default=16)
    p.add_argument("--min-ratio", type=float, default=1.5,
                   help="continuous must beat static tokens/s by this factor")
    p.add_argument("--probe", action="store_true",
                   help="also run the serve-preempts-training operator probe")
    p.add_argument("--backend", choices=("native", "local"), default="native",
                   help="process backend for the probe's operator")
    p.add_argument("--out", default=None,
                   help="write the one-line JSON artifact here")
    args = p.parse_args(argv)

    artifact = run_ab(args)
    bad = gate_ab(artifact, args.min_ratio)

    if args.probe:
        probe = run_probe(args)
        artifact["probe"] = probe
        if not probe.get("ok"):
            bad.append(f"probe: {probe.get('error')}")

    line = json.dumps(artifact)
    print(line)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(line + "\n")
    for msg in bad:
        print(f"FAIL: {msg}", file=sys.stderr)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
