"""Selective-remat sweep at north-star shapes (r5, VERDICT r4 #1).

Full remat replays qkv+attn+wo+gate+up in the backward (+23 of the 31
per-layer fwd matmul units at gqa-2048 shapes); "dots" saves every matmul
output and OOMs at every batch that fits full remat. This tool measures
the ladder BETWEEN them (transformer._REMAT_SAVE_SETS — named-activation
policies over the flash residuals, the post-attention residual stream,
and the MLP pre-activations) on the real chip, batch by batch.

Each (policy, batch) cell runs ``bench.py`` in a SUBPROCESS
(BENCH_MODEL=gqa-2048) so every measurement starts from an empty chip —
a fragmented heap would otherwise fake OOMs for the larger policies. OOM
is detected from RESOURCE_EXHAUSTED in the child's stderr and reported
as a row, not an error: "this policy does not fit at this batch" is the
receipt the sweep exists to produce.

``--flops`` instead compiles the train step under each policy (no
execution — works on CPU too) and prints the compiled-executable FLOP
counts: the driver-verifiable receipt that each tier actually retires
recompute rather than renaming it.

Usage:
    python -m tools.rematsweep [--policies full,save_qkv_mid,...] \
        [--batches 6,4,2,1] [--steps 20] [--out REMAT_SWEEP.json]
    python -m tools.rematsweep --flops [--batch 1]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

DEFAULT_POLICIES = (
    "full",
    "save_mid",
    "save_qkv",
    "save_qkv_mid",
    "save_qkv_mid_up",
    "save_qkv_mid_mlp",
    "save_mlp_mid",
)


def _memplan_gb(policy: str, batch: int, seq: int) -> float:
    from tools.memplan import plan

    remat = True if policy == "full" else policy
    out = plan("gqa-2048", {"dp": 1}, batch, seq, remat=remat)
    return out["total_gb"]


def run_cell(policy: str, batch: int, seq: int, steps: int, timeout: int):
    env = dict(
        os.environ,
        BENCH_MODEL="gqa-2048",
        BENCH_BATCH=str(batch),
        BENCH_SEQ=str(seq),
        BENCH_STEPS=str(steps),
        BENCH_NORTHSTAR="0",
        BENCH_ATTN="flash",
        BENCH_REMAT="1" if policy == "full" else policy,
        BENCH_DATA="fixed",
        BENCH_ACCUM="1",
    )
    env.pop("BENCH_PROFILE", None)
    env.pop("BENCH_DEVICE_LOOP", None)
    row = {"policy": policy, "batch": batch, "seq": seq}
    try:
        row["memplan_gb"] = round(_memplan_gb(policy, batch, seq), 2)
    except Exception as exc:  # noqa: BLE001 — the plan is advisory
        row["memplan_gb"] = f"error: {exc}"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO_ROOT, "bench.py")],
        env=env, capture_output=True, text=True, timeout=timeout,
    )
    if proc.returncode != 0:
        err = proc.stderr[-2000:]
        if "RESOURCE_EXHAUSTED" in err or "Out of memory" in err:
            row["status"] = "OOM"
            for line in reversed(proc.stderr.splitlines()):
                if "RESOURCE_EXHAUSTED" in line:
                    row["oom_detail"] = line.strip()[:200]
                    break
        else:
            row["status"] = f"error rc={proc.returncode}"
            row["stderr_tail"] = err[-400:]
        return row
    bench = json.loads(proc.stdout.strip().splitlines()[-1])
    row.update(
        status="ok",
        mfu=bench["mfu"],
        mfu_6nd=bench["mfu_6nd"],
        tokens_per_sec_per_chip=bench["value"],
        step_time_s=bench["step_time_s"],
        loss=bench["loss"],
    )
    return row


def flops_receipt(batch: int, seq: int, policies) -> list:
    """Compiled-executable FLOPs per policy (no execution). The recompute
    each tier retires must show up HERE, in XLA's own cost model."""
    import jax

    from tf_operator_tpu.models.transformer import (
        init_transformer,
        lm_loss,
        preset,
    )

    rows = []
    for policy in policies:
        remat = True if policy == "full" else policy
        cfg = preset("gqa-2048", max_seq=seq, attn_impl="flash", remat=remat)
        params = jax.eval_shape(
            lambda k: init_transformer(k, cfg), jax.random.PRNGKey(0)
        )
        tok = jax.ShapeDtypeStruct((batch, seq), "int32")

        def step(p, t, _cfg=cfg):
            return jax.grad(lambda q: lm_loss(q, t, _cfg))(p)

        compiled = jax.jit(step).lower(params, tok).compile()
        cost = compiled.cost_analysis()
        cost = cost[0] if isinstance(cost, list) else cost
        rows.append(
            {
                "policy": policy,
                "batch": batch,
                "seq": seq,
                "compiled_gflops": round(float(cost.get("flops", 0.0)) / 1e9, 1),
                "bytes_accessed_gb": round(
                    float(cost.get("bytes accessed", 0.0)) / 2**30, 2
                ),
            }
        )
        print(json.dumps(rows[-1]), flush=True)
    return rows


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--policies", default=",".join(DEFAULT_POLICIES))
    p.add_argument("--batches", default="6,4,2,1")
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--timeout", type=int, default=600)
    p.add_argument("--out", default=None, help="write rows as JSON to this path")
    p.add_argument("--flops", action="store_true",
                   help="compiled-FLOPs receipt instead of timed runs")
    p.add_argument("--batch", type=int, default=1, help="--flops batch size")
    args = p.parse_args(argv)
    policies = [s.strip() for s in args.policies.split(",") if s.strip()]

    if args.flops:
        rows = flops_receipt(args.batch, args.seq, policies)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(rows, f, indent=1)
        return 0

    rows = []
    for policy in policies:
        for batch in (int(b) for b in args.batches.split(",")):
            row = run_cell(policy, batch, args.seq, args.steps, args.timeout)
            rows.append(row)
            print(json.dumps(row), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rows, f, indent=1)
    best = max(
        (r for r in rows if r.get("status") == "ok"),
        key=lambda r: r["mfu"],
        default=None,
    )
    if best:
        print("# best:", json.dumps(best))
    return 0


if __name__ == "__main__":
    sys.exit(main())
