"""Per-chip HBM plan for a transformer job BEFORE it is submitted.

VERDICT r1 (weak #6): the llama2-7b presets existed but nothing validated
that a given config's sharding + remat + batch actually FIT a chip. This
tool computes the plan from the REAL machinery, not a formula sheet:

- params + optimizer: built from ``Trainer.state_template()`` under the
  job's actual mesh and logical-axis rules, so every leaf's per-chip bytes
  come from ``NamedSharding.shard_shape`` — tp/fsdp/pp/ep sharding is
  accounted exactly as GSPMD will lay it out.
- activations: an estimate (documented formula, not a trace): with full
  remat the live set is the per-layer residual stream saved at each of
  L layers plus one layer's working set plus the loss head; the fused
  cross-entropy head avoids the [b*t, vocab] logits array.

Usage:
    python -m tools.memplan --preset llama2-7b --mesh dp=4,fsdp=8,tp=4 \
        --batch 32 --seq 4096 [--remat full] [--optimizer adamw] [--hbm-gb 95]
    python -m tools.memplan --job examples/llama2_7b_v5p128.json [--hbm-gb 95]

Exit code 1 when the plan exceeds the HBM budget — usable as an admission
check. Runs on the CPU backend with a virtual device mesh (no TPU
needed): shard SHAPES don't care what the devices are.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Per-chip HBM by generation (GiB, usable ballpark).
HBM_GB = {"v4": 32, "v5e": 16, "v5 lite": 16, "v5p": 95, "v6e": 32}


def _parse_mesh(s: str) -> dict:
    out = {}
    for part in s.split(","):
        if not part:
            continue
        k, _, v = part.partition("=")
        out[k.strip()] = int(v)
    return out


def plan(preset_name: str, mesh_axes: dict, batch: int, seq: int,
         remat="full", optimizer: str = "adamw", dtype_bytes: int = 2,
         grad_accum: int = 1, pp_microbatches: int = 0,
         workload: dict | None = None):
    """Returns a dict of per-chip byte totals for one train step.

    ``grad_accum`` > 1 (TrainerConfig.grad_accum) scales the activation
    term by 1/accum — only one microbatch's activations are live at a
    time inside the accumulation scan — but ADDS a params-sized f32
    transient: the scan's grad carry and the current microbatch's grads
    coexist at the accumulate (r4, measured: the L=14 gqa-2048 plan said
    14.9 GB and the chip requested 19.9). A second transient applies
    regardless of accum: the bf16 compute cast of the f32 master params
    (~params/2). Both are in ``transient_gb``. XLA workspace/fragmentation
    is NOT modeled — treat a margin under ~2% of budget as "does not
    fit" (the gqa-2048 b=8 plan margin was 0.04 GB and the chip OOM'd
    by 22 MB)."""
    import math

    n_chips = math.prod(mesh_axes.values()) or 1
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_chips}"
        ).strip()
    sys.path.insert(0, _REPO_ROOT)
    import jax

    if jax.config.jax_platforms != "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        init_transformer,
        lm_loss,
        preset,
        preset_from_workload,
        transformer_logical_axes,
    )
    from tf_operator_tpu.parallel import build_mesh
    from tf_operator_tpu.train.trainer import Trainer, TrainerConfig

    if jax.device_count() < n_chips:
        raise SystemExit(
            f"need {n_chips} virtual devices, have {jax.device_count()} — "
            "run in a fresh process (XLA_FLAGS is read at backend init)"
        )
    if workload is not None:
        # --job mode: build the config exactly as every RUNNING role does
        # (preset_from_workload honors all CONFIG_OVERRIDE_FIELDS) — a
        # hand-threaded subset here would let the memory plan size a
        # different model than the one the job launches.
        wl = dict(workload)
        wl.setdefault("preset", preset_name)
        wl["max_seq"] = seq
        wl.setdefault("remat", remat)
        if pp_microbatches:
            wl.setdefault("pp_microbatches", pp_microbatches)
        cfg = preset_from_workload(wl)
    else:
        overrides = (
            {"pp_microbatches": pp_microbatches} if pp_microbatches else {}
        )
        cfg = preset(preset_name, max_seq=seq, remat=remat, **overrides)
    mesh = build_mesh(mesh_axes, devices=jax.devices()[:n_chips])
    trainer = Trainer(
        mesh,
        loss_fn=lambda p, b, e: lm_loss(p, b, cfg, mesh=mesh),
        init_fn=lambda k: init_transformer(k, cfg),
        logical_axes=transformer_logical_axes(cfg),
        config=TrainerConfig(optimizer=optimizer),
    )
    tmpl = trainer.state_template()

    def shard_bytes(tree):
        total = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            shape = leaf.sharding.shard_shape(leaf.shape)
            total += math.prod(shape) * leaf.dtype.itemsize
        return total

    params_b = shard_bytes(tmpl.params)
    opt_b = shard_bytes(tmpl.opt_state)
    # gradients materialize alongside params during the update
    grads_b = params_b
    # step-transients (r4): the bf16 compute cast of the f32 master
    # params is live through fwd+bwd; with accumulation the scan's f32
    # grad carry and the microbatch grads coexist at the accumulate
    transient_b = params_b * dtype_bytes // 4
    if grad_accum > 1:
        transient_b += params_b

    # Activation estimate. Batch shards over (dp, fsdp); seq over cp;
    # within a shard, full remat keeps L residual-stream saves [b,t,d]
    # plus ~1 layer's working set (qkv + attn + mlp intermediates ≈
    # 2*(4d + 2*d_ff) values per token) plus the head.
    data_shards = 1
    for ax in ("dp", "fsdp"):
        data_shards *= mesh_axes.get(ax, 1)
    pp = mesh_axes.get("pp", 1)
    pp_micro = int(getattr(cfg, "pp_microbatches", 0) or 0)
    pipelined = pp > 1 and pp_micro > 0
    if cfg.n_experts and pipelined and mesh_axes.get("ep", 1) > 1:
        # ep-inside-pipeline (r4): ep is an additional TOKEN axis there
        # (only when the pipeline actually runs — non-pipelined MoE
        # shards tokens over dp/fsdp and routes over ep internally)
        data_shards *= mesh_axes["ep"]
    seq_shards = mesh_axes.get("cp", 1)
    tp = mesh_axes.get("tp", 1)
    local_tokens = (batch // max(1, data_shards)) * (seq // max(1, seq_shards))
    if grad_accum > 1:
        if batch % grad_accum:
            raise SystemExit(
                f"batch {batch} not divisible by grad_accum {grad_accum}"
            )
        local_tokens = max(1, local_tokens // grad_accum)
    d, f, L, v = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.vocab
    # K/V projection width: n_kv_heads * head_dim — for GQA (llama2-70b:
    # 8 kv vs 64 q heads) the k/v activations are kv/d = 1/8 the width of
    # q, and r3's repeat-free attention keeps them that size end to end.
    kv = cfg.n_kv_heads * cfg.head_dim
    # Selective-remat name policies (r5): saved width per token per layer
    # on TOP of the full-remat layer-input save, in dtype units. All of
    # these activations shard over tp — q/k/v over heads, gate/up over
    # d_ff — so every width divides by tp (r6: flash widths previously
    # didn't, over-counting flash-saving plans at tp>1). The flash
    # custom-vjp's own (o, lse) residuals are rebuilt in the backward
    # regardless of the save set (FLASH_SAVE_NAMES note) and are NOT part
    # of a name policy's saved bytes.
    from tf_operator_tpu.models.transformer import remat_save_names

    _name_width = {
        "flash_q": d // tp, "flash_k": kv // tp, "flash_v": kv // tp,
        "resid_mid": d, "mlp_gate": f // tp, "mlp_up": f // tp,
    }
    save_names = remat_save_names(cfg.remat)
    policy_width = (
        sum(_name_width.get(n, 0) for n in save_names) if save_names else 0
    )
    if pipelined:
        # Pipeline: the working set below shrinks to one microbatch.
        # 1f1b holds M microbatch-INPUT saves per stage plus ONE
        # microbatch's transient backward saves for the stage's L/pp
        # layers; gpipe's autodiff instead saves per-TICK residuals for
        # all M+S-1 ticks (fill/drain included). Per-layer save width
        # follows remat: d bytes/token with full remat (+ the policy's
        # named saves), the wide intermediates without.
        local_tokens = max(1, local_tokens // pp_micro)
        per_layer = (
            d + policy_width
            if cfg.remat in (True, "full") or save_names is not None
            else (3 * d + kv + 2 * f // tp)
        )
        l_stage = L // pp
        if getattr(cfg, "pp_schedule", "1f1b") == "gpipe":
            ticks = pp_micro + pp - 1
            saved = ticks * local_tokens * (d + l_stage * per_layer) * dtype_bytes
        else:
            saved = (
                (pp_micro * d + l_stage * per_layer)
                * local_tokens * dtype_bytes
            )
    elif cfg.remat in (True, "full") or save_names is not None:
        saved = L * local_tokens * (d + policy_width) * dtype_bytes
    else:  # no remat: every layer's intermediates persist to the backward
        saved = L * local_tokens * (3 * d + kv + 2 * f // tp) * dtype_bytes
    # working set: q + attn-out + 2 residual-stream temporaries (d each),
    # k + v (kv each), gate/up/act/down intermediates (4f/tp)
    working = local_tokens * (6 * d + 2 * kv + 4 * f // tp) * dtype_bytes
    if (cfg.n_experts and mesh_axes.get("ep", 1) > 1
            and getattr(cfg, "moe_dispatch", "sort") == "gmm" and not pipelined):
        # ep-gmm dispatch (r6): the padding-free exchange trades capacity
        # queues for statically-sized BLOCK-QUANTUM all_to_all buffers —
        # one segment per (source, dest) pair of seg_rows =
        # ceil(T_moe·k/B)·B + (E/ep)·B rows (lossless bound: any source
        # may route everything to one destination, plus worst-case
        # per-expert round-up to the kernel's B-row block). Live set per
        # MoE layer: the [ep·seg_rows, d] payload on each side of BOTH
        # exchanges (x_send/x_rcv, h/h_ret) and the two [ep·seg_rows, f]
        # SwiGLU intermediates between the grouped matmuls. The f32 gate
        # sidecars are noise. T_moe = this chip's tokens / ep (tokens
        # shard over (data axes × ep) inside moe_apply).
        ep = mesh_axes["ep"]
        bq = int(os.environ.get("TPUJOB_GMM_BLOCK_ROWS", "256"))
        t_moe = max(1, local_tokens // ep)
        k_top = int(getattr(cfg, "moe_top_k", 1))
        e_local = max(1, cfg.n_experts // ep)
        seg_rows = -(-t_moe * k_top // bq) * bq + e_local * bq
        buf_rows = ep * seg_rows
        working += buf_rows * (4 * d + 2 * f) * dtype_bytes
    if cfg.fused_xent:
        head = local_tokens * d * dtype_bytes * 2  # hidden + recompute block
    else:
        head = local_tokens * (v // tp) * 4  # f32 logits
    acts_b = saved + working + head

    total = params_b + opt_b + grads_b + transient_b + acts_b
    return {
        "preset": preset_name,
        "mesh": mesh_axes,
        "n_chips": n_chips,
        "batch": batch,
        "seq": seq,
        "grad_accum": grad_accum,
        "remat": str(cfg.remat),
        "params_gb": params_b / 2**30,
        "optimizer_gb": opt_b / 2**30,
        "grads_gb": grads_b / 2**30,
        "transient_gb": transient_b / 2**30,
        "activations_gb": acts_b / 2**30,
        "total_gb": total / 2**30,
    }


def serve_plan(preset_name: str, workload: dict | None = None,
               kv_page_size: int = 16, kv_pool_pages: int = 64,
               max_slots: int = 4, prefill_chunk: int = 16):
    """Per-chip HBM plan for a SERVE job (r10): f32 params + the paged KV
    pool + the decode-step working set. No optimizer, no gradients, no
    remat saves — inference holds none of the training state. The pool is
    the dominant steady-state term and is preallocated up front by
    serve/engine.py, so an overflow here is an overflow at step 0, not a
    load-dependent surprise."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, _REPO_ROOT)
    import math

    import jax

    from tf_operator_tpu.models.transformer import (
        init_transformer,
        preset_from_workload,
    )
    from tf_operator_tpu.serve.kvcache import pages_needed, pool_bytes

    wl = dict(workload or {})
    wl.setdefault("preset", preset_name)
    kv_page_size = int(wl.get("kv_page_size", kv_page_size))
    kv_pool_pages = int(wl.get("kv_pool_pages", kv_pool_pages))
    max_slots = int(wl.get("max_slots", max_slots))
    prefill_chunk = int(wl.get("prefill_chunk", prefill_chunk))
    cfg = preset_from_workload(wl)

    # the engine casts params to f32 for deterministic greedy decode
    shapes = jax.eval_shape(
        lambda k: init_transformer(k, cfg), jax.random.PRNGKey(0)
    )
    params_b = sum(
        math.prod(leaf.shape) * 4 for leaf in jax.tree_util.tree_leaves(shapes)
    )
    kv_b = pool_bytes(
        cfg.n_layers, kv_pool_pages, kv_page_size,
        cfg.n_kv_heads, cfg.head_dim, dtype_bytes=4,
    )
    # working set per step: the wider of a decode batch (max_slots rows)
    # and a prefill chunk, through one layer's intermediates plus the
    # f32 logits row for sampling
    rows = max(max_slots, prefill_chunk)
    d, f = cfg.d_model, cfg.d_ff
    kv_width = cfg.n_kv_heads * cfg.head_dim
    working_b = rows * (6 * d + 2 * kv_width + 4 * f) * 4
    working_b += max_slots * cfg.vocab * 4

    total = params_b + kv_b + working_b
    out = {
        "preset": wl.get("preset", preset_name),
        "mode": "serve",
        "kv_page_size": kv_page_size,
        "kv_pool_pages": kv_pool_pages,
        "max_slots": max_slots,
        "max_pages_per_seq": pages_needed(cfg.max_seq, kv_page_size),
        "params_gb": params_b / 2**30,
        "kv_pool_gb": kv_b / 2**30,
        "working_gb": working_b / 2**30,
        "total_gb": total / 2**30,
    }
    # A single max-length sequence that cannot fit the pool can never be
    # admitted — that is a config error, not a capacity question.
    if out["max_pages_per_seq"] > kv_pool_pages:
        out["warning"] = (
            f"a max_seq={cfg.max_seq} sequence needs "
            f"{out['max_pages_per_seq']} pages but the pool has only "
            f"{kv_pool_pages} — such a request can NEVER be admitted"
        )
    return out


def _is_serve_workload(doc: dict) -> bool:
    spec = doc.get("spec", {})
    wl = spec.get("workload", {})
    if "kv_pool_pages" in wl or "kv_page_size" in wl:
        return True
    if spec.get("scheduling", {}).get("job_class") == "serving":
        return True
    for rs in spec.get("replica_specs", {}).values():
        entry = rs.get("template", {}).get("entrypoint", "")
        if entry.startswith("tf_operator_tpu.workloads.serve"):
            return True
    return False


def _finish_serve(out: dict, args) -> int:
    """Print a serve plan; REFUSE loudly when it exceeds the HBM budget
    or when the pool cannot hold even one max-length sequence — the
    engine would preallocate-and-OOM (or never admit) at step 0, so a
    quiet exit code is not enough."""
    for k, val in out.items():
        print(f"  {k:<16} {val if not isinstance(val, float) else f'{val:.2f}'}")
    if "warning" in out:
        print(f"REFUSED: {out['warning']}", file=sys.stderr)
        return 1
    if args.hbm_gb is not None:
        fits = out["total_gb"] <= args.hbm_gb
        print(f"  {'fits':<16} {fits} (budget {args.hbm_gb} GiB/chip)")
        if not fits:
            print(
                f"REFUSED: serve plan needs {out['total_gb']:.2f} GiB/chip "
                f"(kv pool alone is {out['kv_pool_gb']:.2f} GiB) but the "
                f"budget is {args.hbm_gb} GiB — shrink kv_pool_pages/"
                f"kv_page_size or pick a smaller preset; the engine "
                f"preallocates the whole pool at startup, so this WILL "
                f"OOM at step 0, not under load",
                file=sys.stderr,
            )
            return 1
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--preset", default=None)
    p.add_argument("--mesh", default="dp=1", help="e.g. dp=4,fsdp=8,tp=4")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--remat", default="full")
    p.add_argument("--optimizer", default="adamw")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="TrainerConfig.grad_accum microbatching (activations "
                        "scale ~1/accum at the same global batch)")
    p.add_argument("--pp-microbatches", type=int, default=0,
                   help="1f1b microbatches (activations scale ~1/M per "
                        "stage; read from the job spec in --job mode)")
    p.add_argument("--job", default=None,
                   help="read preset/mesh/batch/seq from a TPUJob JSON spec")
    p.add_argument("--serve", action="store_true",
                   help="plan a SERVE job (f32 params + paged KV pool, no "
                        "optimizer/grads); auto-detected in --job mode")
    p.add_argument("--kv-page-size", type=int, default=16)
    p.add_argument("--kv-pool-pages", type=int, default=64)
    p.add_argument("--max-slots", type=int, default=4)
    p.add_argument("--hbm-gb", type=float, default=None,
                   help="per-chip HBM budget; exit 1 if the plan exceeds it")
    args = p.parse_args(argv)

    if args.job:
        with open(args.job) as f:
            doc = json.load(f)
        wl = doc["spec"].get("workload", {})
        if args.serve or _is_serve_workload(doc):
            return _finish_serve(
                serve_plan(wl.get("preset", "tiny"), wl), args
            )
        mesh_axes = doc["spec"].get("topology", {}).get("mesh_axes", {}) or {"dp": 1}
        preset_name = wl.get("preset", "tiny")
        batch = int(wl.get("batch_size", args.batch))
        seq = int(wl.get("seq_len", args.seq))
        remat = wl.get("remat", args.remat)
        args.grad_accum = int(wl.get("grad_accum", args.grad_accum))
        args.pp_microbatches = int(
            wl.get("pp_microbatches", args.pp_microbatches)
        )
    else:
        if not args.preset:
            p.error("--preset or --job required")
        if args.serve:
            return _finish_serve(
                serve_plan(
                    args.preset,
                    kv_page_size=args.kv_page_size,
                    kv_pool_pages=args.kv_pool_pages,
                    max_slots=args.max_slots,
                ),
                args,
            )
        wl = None
        preset_name, mesh_axes = args.preset, _parse_mesh(args.mesh)
        batch, seq, remat = args.batch, args.seq, args.remat

    out = plan(preset_name, mesh_axes, batch, seq, remat, args.optimizer,
               grad_accum=args.grad_accum,
               pp_microbatches=args.pp_microbatches,
               workload=wl if args.job else None)
    for k, val in out.items():
        print(f"  {k:<16} {val if not isinstance(val, float) else f'{val:.2f}'}")
    if args.hbm_gb is not None:
        fits = out["total_gb"] <= args.hbm_gb
        print(f"  {'fits':<16} {fits} (budget {args.hbm_gb} GiB/chip)")
        return 0 if fits else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
