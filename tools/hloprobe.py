"""Compile-level overlap receipts: AOT-compile real train steps for a TPU
topology and assert async collectives are scheduled to HIDE behind
compute (r6, ISSUE 5).

The loss-is-finite dryrun proves sharded steps are CORRECT; it says
nothing about whether the zero-3 all-gathers, tp psums, or ep
all-to-alls actually overlap compute — the entire premise of those
layouts' throughput. The receipt lives in the compiler's SCHEDULED HLO:
XLA:TPU splits a hidden collective into an async pair
(``all-gather-start`` … ``all-gather-done``) and the latency-hiding
scheduler moves compute between the two. A collective that canNOT hide
schedules its ``-done`` immediately after its ``-start``.

This tool cross-compiles the fsdp / tp / flagship-MoE step on a virtual
TPU topology (``jax.experimental.topologies`` — no TPU chips needed,
only the compiler; libtpu ships in the image) through the REAL Trainer
(`state_template()` is ShapeDtypeStructs + shardings, so nothing is
materialized), then parses the scheduled module.

OVERLAP CRITERION (the one the CI stage enforces, documented here and in
docs/design.md): for every probed config,
  1. the scheduled module contains at least one async collective pair —
     a config whose collectives all compiled away would prove nothing;
  2. at least one pair of each PRESENT kind (all-gather, all-reduce,
     collective-permute, all-to-all) has >= 1 compute op (fusion / dot /
     convolution / while / custom-call) scheduled strictly between start
     and done — i.e. the scheduler found something to hide it behind;
  3. the fraction of overlapped pairs is reported per kind (the receipt
     artifact), but only total starvation (a kind where ZERO pairs
     overlap) fails the stage: small tails (e.g. the last all-gather of
     a layer stack with nothing left to overlap) are expected and
     visible in the artifact rather than gamed into the pass bar.

Usage:
    python -m tools.hloprobe [--probe fsdp,tp,flagship]
        [--topology v5e:2x4] [--json artifacts/hloprobe.json]

Exit 1 when any probed config violates the criterion. If the TPU
compiler/topology cannot initialize at all (no libtpu in the
environment), prints SKIP and exits 0 — the receipt is only meaningful
where the real compiler runs; CI containers have it.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# AOT uses the CPU client as the host platform; libtpu is loaded only as
# a compiler. The metadata probes would otherwise stall ~60 s each
# looking for a GCE TPU VM that doesn't exist.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-8")
os.environ.setdefault("TPU_WORKER_ID", "0")
os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")

COMPUTE_RE = re.compile(
    r"%[\w.-]+ = \S+ (fusion|dot|convolution|while|custom-call)\("
)
COLLECTIVE_KINDS = ("all-gather", "all-reduce", "collective-permute",
                    "all-to-all", "reduce-scatter")
# plain async form: %all-gather-start.2 = ... all-gather-start(...)
# (the result type may be a TUPLE with spaces — match lazily to the op)
PLAIN_START_RE = re.compile(
    r"%(?P<name>[\w.-]+) = .*? (?P<kind>" +
    "|".join(COLLECTIVE_KINDS) + r")-start\("
)
PLAIN_DONE_RE = re.compile(
    r"(?:" + "|".join(COLLECTIVE_KINDS) +
    r")-done\([^%]*%(?P<start>[\w.-]+)"
)
# TPU async-collective-fusion form: the backend wraps the collective in a
#   %async-collective-start[.N] = (...) fusion(...), calls=%async_collective_fusion.M
#   %get-tuple-element.K = ... get-tuple-element((...) %async-collective-start[.N]), index=...
#   %async-collective-done[.N'] = ... fusion(... %get-tuple-element.K ...)
# pair; the collective's kind lives in the called fusion computation.
ACF_START_RE = re.compile(
    r"%(?P<name>[\w.-]+) = .*? fusion\(.*calls=%(?P<called>[\w.-]+)"
)
ACF_DONE_RE = re.compile(r"%(?P<name>async-collective-done[\w.-]*) = ")
GTE_RE = re.compile(
    r"%(?P<name>get-tuple-element[\w.-]*) = .*get-tuple-element\("
    r"[^%]*%(?P<producer>[\w.-]+)\)"
)
COMP_DEF_RE = re.compile(r"^%(?P<name>[\w.-]+) \(")


def _called_fusion_kinds(hlo_text: str) -> dict:
    """Map computation name -> collective kind for every called
    computation whose body holds a collective op (the TPU backend's
    async-collective-start wrappers call such computations — sometimes
    named async_collective_fusion.*, sometimes plain fused_computation.*
    with the collective inside)."""
    kinds = {}
    for block in hlo_text.split("\n\n"):
        header = block.lstrip().splitlines()[0] if block.strip() else ""
        m = COMP_DEF_RE.match(header)
        if not m:
            continue
        for kind in COLLECTIVE_KINDS:
            if re.search(rf"= \S+ {kind}[.(]", block):
                kinds[m.group("name")] = kind
                break
    return kinds


def analyze_schedule(hlo_text: str) -> dict:
    """Per async-pair overlap census over a scheduled HLO module.

    Scheduled modules list instructions in execution order within each
    computation, so "compute between start and done" is literally the
    compute lines between them (same computation body). Handles both
    async spellings: plain ``<kind>-start``/``-done`` ops and the TPU
    backend's ``async-collective-start``/``-done`` fusion wrappers
    (kind resolved through the called computation; pairing resolved
    through the done's get-tuple-element operands)."""
    called_kinds = _called_fusion_kinds(hlo_text)
    pairs = []  # (kind, n_compute_between)
    for body in hlo_text.split("\n\n"):
        lines = body.splitlines()
        open_starts = {}  # name -> (kind, compute_count_at_start)
        gte_producer = {}
        compute_seen = 0
        for ln in lines:
            m = GTE_RE.search(ln)
            if m:
                gte_producer[m.group("name")] = m.group("producer")
            m = PLAIN_DONE_RE.search(ln)
            if m and m.group("start") in open_starts:
                kind, at_start = open_starts.pop(m.group("start"))
                pairs.append((kind, compute_seen - at_start))
                continue
            m = ACF_DONE_RE.search(ln)
            if m:
                # the done wrapper is ALSO a fusion with calls= — match
                # it before the start patterns or it would be swallowed
                # as a new start
                for op in re.findall(r"%(get-tuple-element[\w.-]*)", ln):
                    start = gte_producer.get(op)
                    if start in open_starts:
                        kind, at_start = open_starts.pop(start)
                        pairs.append((kind, compute_seen - at_start))
                        break
                continue
            m = PLAIN_START_RE.search(ln)
            if m:
                open_starts[m.group("name")] = (m.group("kind"), compute_seen)
                continue
            m = ACF_START_RE.search(ln)
            if m and m.group("called") in called_kinds:
                # a fusion wrapping a collective: the async-start form
                # (named %async-collective-start.N at top level, plain
                # %fusion.N inside while bodies — the matching done
                # resolves it through its get-tuple-element operands)
                open_starts[m.group("name")] = (
                    called_kinds[m.group("called")], compute_seen)
                continue
            if COMPUTE_RE.search(ln) and "async-collective-" not in ln:
                compute_seen += 1
    kinds: dict = {}
    for kind, n in pairs:
        k = kinds.setdefault(kind, {"pairs": 0, "overlapped": 0})
        k["pairs"] += 1
        k["overlapped"] += 1 if n >= 1 else 0
    return {"kinds": kinds, "total_pairs": len(pairs)}


def _probe_configs():
    import jax.numpy as jnp

    # (name, preset kwargs, mesh axes, global batch, seq). Shapes are
    # the smallest where XLA's cost model bothers to ASYNCIFY: at toy
    # dims (d=64) the compiler leaves collectives synchronous — the
    # probe would report "nothing to check" rather than overlap.
    dense = dict(
        name="llama2-7b", d_model=512, n_layers=4, n_heads=8, n_kv_heads=8,
        d_ff=1408, vocab=8192, max_seq=512, dtype=jnp.bfloat16, remat=True,
    )
    return {
        # zero-3: params shard over fsdp, all-gathered per layer — the
        # all-gathers must hide behind the layer matmuls
        "fsdp": (dict(dense), {"fsdp": 8}, 16, 512),
        # megatron tp: row-parallel psums must hide behind the partial
        # matmuls; dp grads all-reduce behind the optimizer
        "tp": (dict(dense), {"dp": 4, "tp": 2}, 16, 512),
        # the flagship-MoE layout (mixtral ep x fsdp x dp, gmm dispatch):
        # ep all-to-alls + zero-3 all-gathers in one step
        "flagship": (dict(name="tiny-moe", d_model=256, n_heads=4,
                          n_kv_heads=4, d_ff=512, vocab=4096, max_seq=256,
                          dtype=jnp.bfloat16, remat=False, moe_top_k=2,
                          moe_dispatch="gmm"),
                     {"dp": 2, "fsdp": 2, "ep": 2}, 16, 256),
    }


def compile_step(topo_name: str, preset_kwargs: dict, mesh_axes: dict,
                 batch: int, seq: int) -> str:
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import Mesh

    from tf_operator_tpu.models.transformer import (
        init_transformer,
        lm_loss,
        preset,
        transformer_logical_axes,
    )
    from tf_operator_tpu.train.trainer import Trainer, TrainerConfig

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=topo_name)
    devs = np.array(topo.devices).reshape(
        tuple(mesh_axes.values())
    )
    mesh = Mesh(devs, tuple(mesh_axes))
    kwargs = dict(preset_kwargs)
    cfg = preset(kwargs.pop("name"), **kwargs)
    trainer = Trainer(
        mesh,
        loss_fn=lambda p, b, e: lm_loss(p, b, cfg, mesh=mesh),
        init_fn=lambda k: init_transformer(k, cfg),
        logical_axes=transformer_logical_axes(cfg),
        config=TrainerConfig(optimizer="adamw", learning_rate=1e-3),
    )
    tmpl = trainer.state_template()
    batch_spec = jax.ShapeDtypeStruct((batch, seq), jnp.int32,
                                      sharding=trainer.batch_sharding)
    fn = trainer._build_step()
    compiled = fn.lower(
        tmpl.params, tmpl.opt_state, tmpl.step, tmpl.extra, batch_spec
    ).compile()
    return compiled.as_text()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--probe", default="fsdp,tp,flagship")
    p.add_argument("--topology", default="v5e:2x4",
                   help="virtual TPU topology (8 devices)")
    p.add_argument("--json", default=None, help="write the receipt artifact")
    p.add_argument("--dump-hlo-dir", default=None,
                   help="also save each config's scheduled HLO text")
    args = p.parse_args(argv)
    sys.path.insert(0, _REPO_ROOT)

    try:
        from jax.experimental import topologies

        topologies.get_topology_desc(platform="tpu",
                                     topology_name=args.topology)
    except Exception as exc:  # noqa: BLE001
        print(f"hloprobe SKIP: TPU compiler topology unavailable "
              f"({type(exc).__name__}: {exc}) — the receipt needs libtpu; "
              "CI images ship it", file=sys.stderr)
        return 0

    configs = _probe_configs()
    results, failed = {}, []
    for name in args.probe.split(","):
        name = name.strip()
        if name not in configs:
            print(f"unknown probe config {name!r}; have {sorted(configs)}",
                  file=sys.stderr)
            return 2
        preset_kwargs, mesh_axes, batch, seq = configs[name]
        print(f"[{name}] AOT-compiling for {args.topology} "
              f"mesh={mesh_axes} ...", flush=True)
        txt = compile_step(args.topology, preset_kwargs, mesh_axes, batch,
                           seq)
        if args.dump_hlo_dir:
            os.makedirs(args.dump_hlo_dir, exist_ok=True)
            with open(os.path.join(args.dump_hlo_dir, f"{name}.hlo.txt"),
                      "w") as f:
                f.write(txt)
        res = analyze_schedule(txt)
        results[name] = res
        ok = res["total_pairs"] >= 1 and all(
            k["overlapped"] >= 1 for k in res["kinds"].values()
        )
        if not ok:
            failed.append(name)
        print(f"[{name}] {'PASS' if ok else 'FAIL'}: "
              f"{res['total_pairs']} async pairs; " + "; ".join(
                  f"{kind}: {v['overlapped']}/{v['pairs']} overlapped"
                  for kind, v in sorted(res["kinds"].items())
              ), flush=True)

    if args.json:
        with open(args.json, "w") as f:
            json.dump({"topology": args.topology, "results": results,
                       "failed": failed}, f, indent=2)
    if failed:
        print(f"hloprobe: overlap criterion FAILED for {failed}",
              file=sys.stderr)
        return 1
    print("hloprobe: overlap criterion met for all probed configs")
    return 0


if __name__ == "__main__":
    sys.exit(main())
