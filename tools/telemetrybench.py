"""Telemetry-plane acceptance bench: the r13 CI receipt.

In-process rig (Store + controller + dashboard API + 3 one-chip host
agents — the elastic-soak topology) that runs a soak job whose rank 1 is
deliberately slowed (``slow_ranks``/``slow_extra_s``) and every rank pays
a known per-step input stall (``data_wait_s``), then gates the four
things the telemetry plane promises:

1. **Straggler flagged fast** — the reconciler's cross-rank median-ratio
   detector raises the ``SlowHost`` event within <= 3 complete telemetry
   windows of the slow rank's first report.
2. **Placement avoids the flagged host** — a second gang submitted after
   the flag lands only on unflagged hosts (``place_gang``
   deprioritization).
3. **On-demand profiling round-trips** — a ``/profile`` directive
   published mid-run produces a ``profile-capture`` span whose ``xplane``
   attribute points at an artifact directory that exists and is
   non-empty.
4. **Goodput attribution is arithmetic, not vibes** — the reported
   ``tpujob_goodput_ratio`` matches the hand-computed lost time
   (trace-derived compile/init + the injected data-wait schedule) within
   5% of wall.

Writes the one-line JSON receipt CI checks in
``artifacts/telemetrybench_r13.json``.

Usage:
    python -m tools.telemetrybench --out artifacts/telemetrybench_r13.json
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

from tf_operator_tpu.api.types import (
    LABEL_JOB_NAME,
    ConditionType,
    ObjectMeta,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    TopologySpec,
    TPUJob,
    TPUJobSpec,
)
from tf_operator_tpu.chaos.soak import DATAPLANE_ENV, _ROOT
from tf_operator_tpu.controller import TPUJobController
from tf_operator_tpu.controller.status import has_condition, is_finished
from tf_operator_tpu.dashboard import DashboardServer
from tf_operator_tpu.dashboard.client import TPUJobClient
from tf_operator_tpu.obs.spans import job_trace
from tf_operator_tpu.runtime import (
    FakeProcessControl,
    HostAgent,
    LocalProcessControl,
    Store,
)

# The injected schedule: every rank pays DATA_WAIT_S of input stall per
# step; rank 1 additionally sleeps SLOW_EXTRA_S (the modeled slow host).
# STEPS leaves the chief enough runway to absorb the profiler's first-use
# initialization stall (~3s for jax.profiler.start_trace on CPU) after
# the straggler flag lands and still capture PROFILE_STEPS steps.
STEPS = 36
STEP_SLEEP_S = 0.05
DATA_WAIT_S = 0.15
SLOW_EXTRA_S = 0.35
TELEMETRY_EVERY = 2
PROFILE_STEPS = 3
FLAG_WINDOW_BOUND = 3


def _bench_job(name: str, workers: int, workload: Dict[str, Any]) -> TPUJob:
    env = dict(DATAPLANE_ENV)
    env["PYTHONPATH"] = _ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")
    entry = workload.pop("__entrypoint__", "tf_operator_tpu.workloads.soak:main")
    job = TPUJob(
        metadata=ObjectMeta(name=name),
        spec=TPUJobSpec(
            replica_specs={
                ReplicaType.WORKER: ReplicaSpec(
                    replicas=workers,
                    template=ProcessTemplate(
                        entrypoint=entry, env=env, chips_per_process=1,
                    ),
                )
            },
            topology=TopologySpec(num_hosts=workers, chips_per_host=1),
        ),
    )
    job.spec.workload = workload
    return job


def _wait(store: Store, name: str, timeout: float) -> Any:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = store.get("TPUJob", "default", name).status
        if is_finished(st):
            return st
        time.sleep(0.25)
    raise TimeoutError(f"job {name} not finished after {timeout}s")


def _slow_host_event(store: Store, job_name: str) -> Optional[Any]:
    for e in store.list("Event", namespace="default"):
        if e.reason == "SlowHost" and e.involved_name == job_name:
            return e
    return None


def run(seed: int, timeout: float) -> Dict[str, Any]:
    tmp = tempfile.mkdtemp(prefix="tpujob-telembench-")
    ckpt_dir = os.path.join(tmp, "ckpt")
    job1, job2 = "telem-soak", "telem-follow"
    errs: List[str] = []

    store = Store()
    fake = FakeProcessControl()
    ctl = TPUJobController(store, fake, resync_period=0.3)
    dashboard = DashboardServer(store, host="127.0.0.1", port=0)
    dashboard.start()
    ctl.api_url = dashboard.url
    agents = [
        HostAgent(
            store, f"telem-h{i}", total_chips=1, heartbeat_interval=0.25,
            backend=LocalProcessControl(
                store, log_dir=os.path.join(tmp, "logs")
            ),
        )
        for i in range(3)
    ]
    client = TPUJobClient(dashboard.url)

    flag_windows = None
    flagged_host = ""
    profile: Dict[str, Any] = {}
    goodput: Dict[str, Any] = {}
    job2_hosts: List[str] = []
    try:
        for a in agents:
            a.start()
        ctl.run(workers=2)
        store.create(_bench_job(job1, 3, {
            "steps": STEPS,
            "step_sleep_s": STEP_SLEEP_S,
            "data_wait_s": DATA_WAIT_S,
            "slow_ranks": [1],
            "slow_extra_s": SLOW_EXTRA_S,
            "telemetry_every": TELEMETRY_EVERY,
            "checkpoint_dir": ckpt_dir,
            "checkpoint_every": 8,
            "checkpoint_backend": "npy",
        }))
        submit_t = time.time()

        # Gate 1: the SlowHost event must land while the gang runs, and
        # its window count (parsed from the event message the operator
        # shows humans) must be within the bound. This gate runs FIRST:
        # profiling stalls the chief ~3s (first-use jax profiler init),
        # which would hold every window incomplete until the flag
        # deadline passed.
        ev = None
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and ev is None:
            ev = _slow_host_event(store, job1)
            if ev is None:
                time.sleep(0.2)

        # Gate 3 setup: publish the profile directive mid-run — the
        # chief still has >10 steps of budget when the flag lands.
        directive = None
        while time.monotonic() < deadline and directive is None:
            try:
                directive = client.profile(
                    "default", job1, PROFILE_STEPS,
                )["profile_directive"]
            except Exception:
                time.sleep(0.25)  # job not admitted yet
        if directive is None:
            errs.append("profile directive could not be published")
        if ev is None:
            errs.append("no SlowHost event before timeout")
        else:
            m = re.search(r"on host (\S+) .*after (\d+) windows", ev.message)
            if m:
                flagged_host = m.group(1)
                flag_windows = int(m.group(2))
                if flag_windows > FLAG_WINDOW_BOUND:
                    errs.append(
                        f"straggler flagged after {flag_windows} windows "
                        f"(bound {FLAG_WINDOW_BOUND}): {ev.message}"
                    )
            else:
                errs.append(f"unparseable SlowHost message: {ev.message!r}")

        st1 = _wait(store, job1, timeout)
        if not has_condition(st1, ConditionType.SUCCEEDED):
            errs.append(f"job 1 did not succeed: {st1.conditions}")

        # Gate 3: the capture span + on-disk artifact.
        trace = job_trace(store, "default", job1)
        cap = next((s for s in trace if s.op == "profile-capture"), None)
        if cap is None:
            errs.append(
                "no profile-capture span in trace "
                f"(ops: {sorted({s.op for s in trace})})"
            )
        else:
            xplane = cap.attrs.get("xplane", "")
            profile = {
                "xplane": xplane,
                "epoch": cap.attrs.get("epoch"),
                "steps": cap.attrs.get("steps"),
            }
            if not xplane or not os.path.exists(xplane):
                errs.append(f"profile capture path missing: {xplane!r}")
            else:
                found = [
                    os.path.join(r, f)
                    for r, _, fs in os.walk(xplane) for f in fs
                ]
                profile["artifacts"] = len(found)
                if not found:
                    errs.append(f"profile capture dir empty: {xplane}")
        ack = (st1.profile_directive or {}).get("completed_epoch")
        profile["completed_epoch"] = ack
        if directive is not None and ack != directive.get("epoch"):
            errs.append(
                f"profile ack epoch {ack} != directive "
                f"{directive.get('epoch')}"
            )

        # Gate 4: reported goodput vs the hand-computed injected lost
        # time. Lost = compile/init (trace: submit -> first-step start)
        # + the injected data-wait schedule. Every step pays DATA_WAIT_S,
        # but the job completes on chief success — the slow rank is
        # reaped mid-schedule, so hand-compute from each rank's actual
        # completed-step count (max end_step it reported) rather than
        # assuming all ranks ran the full budget.
        job_obj = store.get("TPUJob", "default", job1)
        wall = (st1.completion_time or time.time()) - (
            job_obj.metadata.creation_timestamp or submit_t
        )
        first_step = min(
            (s.start_time for s in trace if s.op == "first-step"),
            default=None,
        )
        ttfs = (
            max(0.0, first_step - job_obj.metadata.creation_timestamp)
            if first_step else 0.0
        )
        steps_by_rank: Dict[int, int] = {}
        for b in client.telemetry("default", job1).get("batches", []):
            r = int(b.get("rank", -1))
            steps_by_rank[r] = max(steps_by_rank.get(r, 0), int(b.get("end_step", 0)))
        mean_steps = (
            sum(steps_by_rank.values()) / len(steps_by_rank)
            if steps_by_rank else STEPS
        )
        expected_lost = ttfs + mean_steps * DATA_WAIT_S
        gauge = re.search(
            r'tpujob_goodput_ratio\{[^}]*job="%s"[^}]*\} (\S+)' % job1,
            ctl.metrics.render(),
        )
        if gauge is None:
            errs.append("tpujob_goodput_ratio gauge not exported for job 1")
        else:
            ratio = float(gauge.group(1))
            reported_lost = (1.0 - ratio) * wall
            tolerance = max(0.5, 0.05 * wall)
            goodput = {
                "ratio": round(ratio, 4),
                "wall_s": round(wall, 3),
                "reported_lost_s": round(reported_lost, 3),
                "expected_lost_s": round(expected_lost, 3),
                "tolerance_s": round(tolerance, 3),
            }
            if abs(reported_lost - expected_lost) > tolerance:
                errs.append(
                    f"goodput mismatch: reported lost {reported_lost:.2f}s "
                    f"vs hand-computed {expected_lost:.2f}s "
                    f"(tolerance {tolerance:.2f}s, ratio {ratio:.3f})"
                )

        # Gate 2: a gang submitted AFTER the flag avoids the slow host.
        store.create(_bench_job(job2, 2, {
            "__entrypoint__": "tf_operator_tpu.workloads.noop:main",
            "sleep_s": 0.3,
        }))
        _wait(store, job2, timeout)
        job2_hosts = sorted({
            p.spec.node_name
            for p in store.list(
                "Process", namespace="default",
                label_selector={LABEL_JOB_NAME: job2},
            )
            if p.spec.node_name
        })
        if not job2_hosts:
            errs.append("job 2 left no placed processes to inspect")
        elif flagged_host and flagged_host in job2_hosts:
            errs.append(
                f"gang placed on flagged host {flagged_host}: {job2_hosts}"
            )
    finally:
        ctl.stop()
        for a in agents:
            a.stop()
        dashboard.stop()
        fake.clear()

    return {
        "bench": "telemetry",
        "seed": seed,
        "flag_windows": flag_windows,
        "flag_window_bound": FLAG_WINDOW_BOUND,
        "flagged_host": flagged_host,
        "job2_hosts": job2_hosts,
        "avoided_flagged_host": bool(
            flagged_host and job2_hosts and flagged_host not in job2_hosts
        ),
        "profile": profile,
        "goodput": goodput,
        "injected": {
            "steps": STEPS,
            "data_wait_s": DATA_WAIT_S,
            "slow_ranks": [1],
            "slow_extra_s": SLOW_EXTRA_S,
        },
        "errors": errs,
        "pass": not errs,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpujob-telemetry-bench")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--timeout", type=float, default=120.0)
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)
    artifact = run(args.seed, args.timeout)
    line = json.dumps(artifact)
    print(line)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(line + "\n")
    if not artifact["pass"]:
        for e in artifact["errors"]:
            print(f"FAIL: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
