"""TPUJob load generator + control-plane bench.

Reference parity: hack/genjob/genjob.go — templated job generation for
controller load/gang-scheduling experiments (``--nr-tfjobs``,
``--scheduler-name``); here ``--nr-jobs`` with optional direct submission
so one command can put O(100) concurrent jobs on the operator (the
reference's design scale target, tf_job_design_doc.md:24-26).

``--bench`` (r6) is the control-plane scale oracle: for each level in
``--bench-levels`` it deploys a FRESH operator daemon, submits that many
concurrent no-op jobs over HTTP, waits for every job to reach a terminal
state, scrapes /metrics for the reconcile-latency histogram, and emits a
one-line JSON artifact (jobs/min + p50/p99 sync latency per level) —
the checked-in ``artifacts/controlplane_r*.json`` format. Exit is
nonzero if ANY job at ANY level fails or never finishes, which is what
lets CI run a small level as a correctness gate.

Usage:
    python -m tools.genjob --nr-jobs 20 --out-dir /tmp/jobs        # write specs
    python -m tools.genjob --nr-jobs 20 --submit --server http://… # submit
    python -m tools.genjob --bench --bench-levels 50,200,500 \
        --bench-out artifacts/controlplane_r6.json                 # bench
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from tf_operator_tpu.api.types import (
    ObjectMeta,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    TPUJob,
    TPUJobSpec,
    TopologySpec,
)
from tf_operator_tpu.api.types import _to_jsonable

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The r5 baseline this round's tentpole is measured against
# (BASELINE.md "500 concurrent" row): 189.4 jobs/min, submit 60.8 s.
R5_BASELINE_500 = 189.4


def build_job(
    name: str,
    workers: int,
    steps: int,
    entrypoint: str,
    topology: str,
    cpu_env: bool,
) -> TPUJob:
    env = {}
    if cpu_env:
        env = {
            "JAX_PLATFORMS": "cpu",
            "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "",
        }
    template = ProcessTemplate(entrypoint=entrypoint, env=env)
    spec = TPUJobSpec(
        replica_specs={ReplicaType.WORKER: ReplicaSpec(replicas=workers, template=template)},
        workload={"dim": 16, "steps": steps},
    )
    if topology:
        spec.topology = TopologySpec(slice_type=topology)
    return TPUJob(metadata=ObjectMeta(name=name), spec=spec)


def wait_for_terminal(client, jobs, timeout: float, t0: float) -> dict:
    """Poll the job list until every submitted job is terminal (or the
    deadline passes); returns the load report the --wait path prints.
    One LIST per round (not a GET per job): polling must not load the
    very server whose throughput is being measured, and one transient
    HTTP error must not abort the test."""
    terminal = {"Done", "Failed"}
    pending = {j.metadata.name for j in jobs}
    done: dict = {}
    deadline = time.time() + timeout
    while pending and time.time() < deadline:
        try:
            listed = client.list("default")
        except Exception:
            time.sleep(0.5)
            continue
        for j in listed:
            name = j.metadata.name
            if name in pending:
                phase = j.status.phase().value
                if phase in terminal:
                    done[name] = phase
                    pending.discard(name)
        if pending:
            time.sleep(0.5)
    wall_s = time.perf_counter() - t0
    succeeded = sum(1 for v in done.values() if v == "Done")
    return {
        "metric": "controller_jobs_per_min",
        "value": round(len(done) / wall_s * 60.0, 1) if wall_s else 0.0,
        "unit": "jobs/min",
        "jobs": len(jobs),
        "succeeded": succeeded,
        "failed": len(done) - succeeded,
        "unfinished": len(pending),
        "wall_s": round(wall_s, 2),
    }


# ---- --bench: the control-plane scale oracle ----------------------------


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _histogram_quantile(buckets, total: int, q: float) -> float:
    """Estimate a quantile (seconds) from cumulative Prometheus buckets
    [(le_seconds, cumulative_count)] by linear interpolation within the
    containing bucket — the standard histogram_quantile() estimate."""
    if total <= 0:
        return 0.0
    rank = q * total
    prev_le, prev_cum = 0.0, 0
    for le, cum in buckets:
        if cum >= rank:
            span = cum - prev_cum
            frac = (rank - prev_cum) / span if span else 1.0
            return prev_le + (le - prev_le) * frac
        prev_le, prev_cum = le, cum
    return prev_le  # rank beyond the last finite bucket: clamp


def _parse_histogram(text: str, family: str) -> tuple:
    """Extract ([(le_seconds, cumulative)], count) for one unlabeled
    Prometheus histogram family from exposition text."""
    import re

    buckets = []
    total = 0
    for line in text.splitlines():
        m = re.match(rf'{family}_bucket\{{le="([^"]+)"\}} (\d+)', line)
        if m:
            le = m.group(1)
            if le != "+Inf":
                buckets.append((float(le), int(m.group(2))))
            continue
        m = re.match(rf"{family}_count (\d+)", line)
        if m:
            total = int(m.group(1))
    return buckets, total


def _scrape_sync_latency(server: str) -> dict:
    """Read the reconcile-latency + TTFS histograms from /metrics →
    p50/p99 ms. TTFS (submit→first-step, trace-span-derived) is the
    cross-component number the whole framework is graded on."""
    import urllib.request

    with urllib.request.urlopen(server + "/metrics", timeout=10) as resp:
        text = resp.read().decode()
    buckets, total = _parse_histogram(text, "tpujob_sync_duration_seconds")
    out = {
        "syncs": total,
        "sync_p50_ms": round(_histogram_quantile(buckets, total, 0.5) * 1e3, 2),
        "sync_p99_ms": round(_histogram_quantile(buckets, total, 0.99) * 1e3, 2),
    }
    tb, tn = _parse_histogram(text, "tpujob_time_to_first_step_seconds")
    out["ttfs_jobs"] = tn
    out["ttfs_p50_ms"] = round(_histogram_quantile(tb, tn, 0.5) * 1e3, 1)
    out["ttfs_p99_ms"] = round(_histogram_quantile(tb, tn, 0.99) * 1e3, 1)
    return out


def _bench_level(n_jobs: int, args) -> dict:
    """One bench level: fresh operator daemon → submit n_jobs no-op jobs
    → wait terminal → scrape latency → tear down."""
    import shutil
    import signal
    import subprocess
    import tempfile
    import urllib.request

    from tf_operator_tpu.dashboard.client import TPUJobClient

    port = _free_port()
    server = f"http://127.0.0.1:{port}"
    workdir = tempfile.mkdtemp(prefix=f"tpujob-bench-{n_jobs}-")
    log_path = os.path.join(workdir, "operator.log")
    cmd = [
        sys.executable, "-m", "tf_operator_tpu.cli.operator",
        "--port", str(port),
        "--log-dir", os.path.join(workdir, "process-logs"),
        "--backend", args.bench_backend,
    ]
    with open(log_path, "ab") as log:
        operator = subprocess.Popen(
            cmd, stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True, cwd=REPO_ROOT,
        )
    try:
        deadline = time.time() + 30
        while True:
            try:
                with urllib.request.urlopen(server + "/healthz", timeout=2):
                    break
            except OSError:
                if operator.poll() is not None or time.time() > deadline:
                    raise RuntimeError(
                        f"operator never became healthy; see {log_path}"
                    )
                time.sleep(0.2)

        jobs = [
            build_job(
                f"bench{n_jobs}-{i}", args.workers, args.steps,
                "tf_operator_tpu.workloads.noop:main", args.topology, True,
            )
            for i in range(n_jobs)
        ]
        client = TPUJobClient(server)
        t0 = time.perf_counter()
        for job in jobs:
            client.create(job)
        submit_s = time.perf_counter() - t0
        report = wait_for_terminal(client, jobs, args.timeout, t0)
        latency = _scrape_sync_latency(server)
        row = {
            "jobs": n_jobs,
            "jobs_per_min": report["value"],
            "succeeded": report["succeeded"],
            "failed": report["failed"],
            "unfinished": report["unfinished"],
            "submit_s": round(submit_s, 2),
            "wall_s": report["wall_s"],
            **latency,
        }
        print(json.dumps(row), flush=True)
        return row
    finally:
        if operator.poll() is None:
            operator.send_signal(signal.SIGTERM)
            try:
                operator.wait(timeout=15)
            except subprocess.TimeoutExpired:
                operator.kill()
                operator.wait()
        shutil.rmtree(workdir, ignore_errors=True)


def run_bench(args) -> int:
    levels = [int(s) for s in str(args.bench_levels).split(",") if s.strip()]
    rows = [_bench_level(n, args) for n in levels]
    artifact = {
        "metric": "controlplane_bench",
        "unit": "jobs/min",
        "backend": args.bench_backend,
        "workers_per_job": args.workers,
        "payload": "tf_operator_tpu.workloads.noop:main",
        "levels": rows,
        "baseline_r5_jobs_per_min_500": R5_BASELINE_500,
    }
    line = json.dumps(artifact)
    print(line)
    if args.bench_out:
        os.makedirs(os.path.dirname(args.bench_out) or ".", exist_ok=True)
        with open(args.bench_out, "w") as f:
            f.write(line + "\n")
    # Correctness gate (the CI stage's contract): every job at every
    # level must have Succeeded.
    bad = [
        r for r in rows
        if r["failed"] or r["unfinished"] or r["succeeded"] != r["jobs"]
    ]
    return 1 if bad else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpujob-genjob")
    p.add_argument("--nr-jobs", type=int, default=1)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--steps", type=int, default=2)
    p.add_argument("--prefix", default="genjob")
    p.add_argument("--entrypoint", default="tf_operator_tpu.workloads.smoke:main")
    p.add_argument("--topology", default="", help="slice type, e.g. v5p-32")
    p.add_argument("--no-cpu-env", action="store_true",
                   help="don't inject the CPU-platform env (run on real TPU)")
    p.add_argument("--out-dir", default=None, help="write one JSON spec per job")
    p.add_argument("--submit", action="store_true", help="submit to the operator")
    p.add_argument("--server", default="http://127.0.0.1:8080")
    p.add_argument("--wait", action="store_true",
                   help="after --submit, wait for every job to reach a "
                        "terminal state and print a JSON load report "
                        "(jobs/min, success count) — the controller-scale "
                        "oracle for the reference's O(100)-job design target")
    p.add_argument("--timeout", type=float, default=900.0)
    p.add_argument("--cleanup", action="store_true",
                   help="delete the generated jobs after the report")
    p.add_argument("--bench", action="store_true",
                   help="self-contained control-plane bench: per level in "
                        "--bench-levels, deploy a fresh operator, submit "
                        "that many concurrent no-op jobs, report jobs/min "
                        "+ p50/p99 sync latency as one JSON line; exit "
                        "nonzero unless every job Succeeded")
    p.add_argument("--bench-levels", default="50,200,500",
                   help="comma-separated concurrent-job counts")
    p.add_argument("--bench-out", default=None,
                   help="also write the bench JSON line to this path "
                        "(the artifacts/controlplane_r*.json format)")
    p.add_argument("--bench-backend", choices=("native", "local"),
                   default="native",
                   help="process backend for the benched operator "
                        "(native = C++ supervisor, the deploy default)")
    args = p.parse_args(argv)

    if args.bench:
        return run_bench(args)

    jobs = [
        build_job(
            f"{args.prefix}-{i}", args.workers, args.steps, args.entrypoint,
            args.topology, not args.no_cpu_env,
        )
        for i in range(args.nr_jobs)
    ]

    if args.out_dir:
        os.makedirs(args.out_dir, exist_ok=True)
        for job in jobs:
            path = os.path.join(args.out_dir, f"{job.metadata.name}.json")
            with open(path, "w") as f:
                json.dump(_to_jsonable(job.to_dict()), f, indent=2)
        print(f"wrote {len(jobs)} specs to {args.out_dir}")

    if args.submit:
        from tf_operator_tpu.dashboard.client import TPUJobClient

        client = TPUJobClient(args.server)
        t0 = time.perf_counter()
        for job in jobs:
            client.create(job)
        submit_s = time.perf_counter() - t0
        print(f"submitted {len(jobs)} jobs to {args.server} in {submit_s:.2f}s")

        if args.wait:
            report = wait_for_terminal(client, jobs, args.timeout, t0)
            report["submit_s"] = round(submit_s, 2)
            print(json.dumps(report))
            if args.cleanup:
                for job in jobs:
                    try:
                        client.delete("default", job.metadata.name)
                    except Exception:
                        pass
            if report["unfinished"] or report["succeeded"] != len(jobs):
                return 1
    elif not args.out_dir:
        for job in jobs:
            print(json.dumps(_to_jsonable(job.to_dict())))
    return 0


if __name__ == "__main__":
    sys.exit(main())
