"""TPUJob load generator + control-plane bench.

Reference parity: hack/genjob/genjob.go — templated job generation for
controller load/gang-scheduling experiments (``--nr-tfjobs``,
``--scheduler-name``); here ``--nr-jobs`` with optional direct submission
so one command can put O(100) concurrent jobs on the operator (the
reference's design scale target, tf_job_design_doc.md:24-26).

``--bench`` (r6) is the control-plane scale oracle: for each level in
``--bench-levels`` it deploys a FRESH operator daemon, submits that many
concurrent no-op jobs over HTTP, waits for every job to reach a terminal
state, scrapes /metrics for the reconcile-latency histogram, and emits a
one-line JSON artifact (jobs/min + p50/p99 sync latency per level) —
the checked-in ``artifacts/controlplane_r*.json`` format. Exit is
nonzero if ANY job at ANY level fails or never finishes, which is what
lets CI run a small level as a correctness gate.

Usage:
    python -m tools.genjob --nr-jobs 20 --out-dir /tmp/jobs        # write specs
    python -m tools.genjob --nr-jobs 20 --submit --server http://… # submit
    python -m tools.genjob --bench --bench-levels 50,200,500 \
        --bench-out artifacts/controlplane_r6.json                 # bench
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from tf_operator_tpu.api.types import (
    ObjectMeta,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    SchedulingSpec,
    TPUJob,
    TPUJobSpec,
    TopologySpec,
)
from tf_operator_tpu.api.types import _to_jsonable

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The r5 baseline this round's tentpole is measured against
# (BASELINE.md "500 concurrent" row): 189.4 jobs/min, submit 60.8 s.
R5_BASELINE_500 = 189.4

# The r6 single-tenant throughput the fleet-scheduler round must not
# regress by more than 10% (artifacts/controlplane_r6.json, 500 level).
R6_BASELINE_500 = 429.1


def build_job(
    name: str,
    workers: int,
    steps: int,
    entrypoint: str,
    topology: str,
    cpu_env: bool,
    namespace: str = "default",
    queue: str = "",
    priority: str = "",
    chips: int = 0,
    sleep_s: float = 0.0,
    workload_extra: dict = None,
    env_extra: dict = None,
) -> TPUJob:
    env = {}
    if cpu_env:
        env = {
            "JAX_PLATFORMS": "cpu",
            "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "",
        }
    env.update(env_extra or {})
    template = ProcessTemplate(entrypoint=entrypoint, env=env,
                               chips_per_process=chips)
    workload = {"dim": 16, "steps": steps}
    if sleep_s:
        workload["sleep_s"] = sleep_s
    workload.update(workload_extra or {})
    spec = TPUJobSpec(
        replica_specs={ReplicaType.WORKER: ReplicaSpec(replicas=workers, template=template)},
        workload=workload,
    )
    if topology:
        spec.topology = TopologySpec(slice_type=topology)
    if queue or priority:
        spec.scheduling = SchedulingSpec(queue=queue, priority_class=priority)
    return TPUJob(metadata=ObjectMeta(name=name, namespace=namespace), spec=spec)


def wait_for_terminal(client, jobs, timeout: float, t0: float) -> dict:
    """Poll the job list until every submitted job is terminal (or the
    deadline passes); returns the load report the --wait path prints.
    One LIST per round (not a GET per job): polling must not load the
    very server whose throughput is being measured, and one transient
    HTTP error must not abort the test."""
    terminal = {"Done", "Failed"}
    pending = {j.metadata.name for j in jobs}
    done: dict = {}
    deadline = time.time() + timeout
    while pending and time.time() < deadline:
        try:
            listed = client.list("default")
        except Exception:
            time.sleep(0.5)
            continue
        for j in listed:
            name = j.metadata.name
            if name in pending:
                phase = j.status.phase().value
                if phase in terminal:
                    done[name] = phase
                    pending.discard(name)
        if pending:
            time.sleep(0.5)
    wall_s = time.perf_counter() - t0
    succeeded = sum(1 for v in done.values() if v == "Done")
    return {
        "metric": "controller_jobs_per_min",
        "value": round(len(done) / wall_s * 60.0, 1) if wall_s else 0.0,
        "unit": "jobs/min",
        "jobs": len(jobs),
        "succeeded": succeeded,
        "failed": len(done) - succeeded,
        "unfinished": len(pending),
        "wall_s": round(wall_s, 2),
    }


# ---- --bench: the control-plane scale oracle ----------------------------


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _histogram_quantile(buckets, total: int, q: float) -> float:
    """Estimate a quantile (seconds) from cumulative Prometheus buckets
    [(le_seconds, cumulative_count)] by linear interpolation within the
    containing bucket — the standard histogram_quantile() estimate."""
    if total <= 0:
        return 0.0
    rank = q * total
    prev_le, prev_cum = 0.0, 0
    for le, cum in buckets:
        if cum >= rank:
            span = cum - prev_cum
            frac = (rank - prev_cum) / span if span else 1.0
            return prev_le + (le - prev_le) * frac
        prev_le, prev_cum = le, cum
    return prev_le  # rank beyond the last finite bucket: clamp


def _parse_histogram(text: str, family: str) -> tuple:
    """Extract ([(le_seconds, cumulative)], count) for one unlabeled
    Prometheus histogram family from exposition text."""
    import re

    buckets = []
    total = 0
    for line in text.splitlines():
        m = re.match(rf'{family}_bucket\{{le="([^"]+)"\}} (\d+)', line)
        if m:
            le = m.group(1)
            if le != "+Inf":
                buckets.append((float(le), int(m.group(2))))
            continue
        m = re.match(rf"{family}_count (\d+)", line)
        if m:
            total = int(m.group(1))
    return buckets, total


def _scrape_sync_latency(server: str) -> dict:
    """Read the reconcile-latency + TTFS histograms from /metrics →
    p50/p99 ms. TTFS (submit→first-step, trace-span-derived) is the
    cross-component number the whole framework is graded on."""
    import urllib.request

    with urllib.request.urlopen(server + "/metrics", timeout=10) as resp:
        text = resp.read().decode()
    buckets, total = _parse_histogram(text, "tpujob_sync_duration_seconds")
    out = {
        "syncs": total,
        "sync_p50_ms": round(_histogram_quantile(buckets, total, 0.5) * 1e3, 2),
        "sync_p99_ms": round(_histogram_quantile(buckets, total, 0.99) * 1e3, 2),
    }
    tb, tn = _parse_histogram(text, "tpujob_time_to_first_step_seconds")
    out["ttfs_jobs"] = tn
    out["ttfs_p50_ms"] = round(_histogram_quantile(tb, tn, 0.5) * 1e3, 1)
    out["ttfs_p99_ms"] = round(_histogram_quantile(tb, tn, 0.99) * 1e3, 1)
    # r11 cold/warm split: the reconciler folds TTFS into a second family
    # keyed on the first-step span's warm attribute (warm worker slot
    # and/or compile-cache hit). Both populations reported whenever they
    # have samples — the classic no-op bench lands everything in cold.
    for pop in ("cold", "warm"):
        pb, pn = _parse_histogram(
            text, f"tpujob_time_to_first_step_{pop}_seconds"
        )
        if pn:
            out[f"ttfs_{pop}_jobs"] = pn
            out[f"ttfs_{pop}_p50_ms"] = round(
                _histogram_quantile(pb, pn, 0.5) * 1e3, 1
            )
            out[f"ttfs_{pop}_p99_ms"] = round(
                _histogram_quantile(pb, pn, 0.99) * 1e3, 1
            )
    # Async-checkpoint overlap receipt (r8): per-accepted-save step-loop
    # stall, folded from workload save-stall spans at job terminal. Zero
    # samples (bench workloads without checkpointing) is normal — omit.
    sb, sn = _parse_histogram(text, "tpujob_checkpoint_save_stall_seconds")
    if sn:
        out["save_stalls"] = sn
        out["save_stall_p50_ms"] = round(
            _histogram_quantile(sb, sn, 0.5) * 1e3, 2
        )
        out["save_stall_p99_ms"] = round(
            _histogram_quantile(sb, sn, 0.99) * 1e3, 2
        )
    # Goodput accounting (r13): the per-job goodput ratio gauge (mean over
    # jobs that reported one) and the per-cause lost-seconds counters.
    ratios = _parse_labeled_gauges(text, "tpujob_goodput_ratio")
    if ratios:
        out["goodput_jobs"] = len(ratios)
        out["goodput_ratio"] = round(sum(ratios) / len(ratios), 4)
    lost = _parse_cause_counters(text, "tpujob_lost_seconds_total")
    if lost:
        out["lost_seconds"] = {k: round(v, 3) for k, v in sorted(lost.items())}
    # Hang plane (r15): declared-hang count plus the hang-downtime
    # histogram (declaration-backdated span widths, closed at recovered
    # gang-RUNNING). Zero hangs is the healthy bench case — report
    # hangs_total: 0 and omit the downtime quantile (no samples).
    out["hangs_total"] = _parse_counter(text, "tpujob_hangs_total")
    hb, hn = _parse_histogram(text, "tpujob_hang_downtime_seconds")
    if hn:
        out["hang_downtime_p50_ms"] = round(
            _histogram_quantile(hb, hn, 0.5) * 1e3, 1
        )
    return out


def _parse_labeled_gauges(text: str, family: str) -> list:
    """All sample values of one labeled gauge family from exposition text."""
    import re

    return [
        float(m.group(1))
        for line in text.splitlines()
        for m in [re.match(rf"{family}\{{[^}}]*\}} (\S+)", line)]
        if m
    ]


def _parse_counter(text: str, family: str) -> int:
    """Value of one unlabeled counter family (0 when absent)."""
    import re

    for line in text.splitlines():
        m = re.match(rf"{family} (\S+)", line)
        if m:
            return int(float(m.group(1)))
    return 0


def _parse_cause_counters(text: str, family: str) -> dict:
    """{cause: value} for a counter family labeled with cause="..."."""
    import re

    out: dict = {}
    for line in text.splitlines():
        m = re.match(rf'{family}\{{[^}}]*cause="([^"]+)"[^}}]*\}} (\S+)', line)
        if m:
            out[m.group(1)] = out.get(m.group(1), 0.0) + float(m.group(2))
    return out


def _start_operator(args, tag: str, extra=()):
    """Deploy a fresh operator daemon for one bench level; returns
    (popen, server_url, workdir, log_path) once /healthz answers."""
    import subprocess
    import tempfile
    import urllib.request

    port = _free_port()
    server = f"http://127.0.0.1:{port}"
    workdir = tempfile.mkdtemp(prefix=f"tpujob-bench-{tag}-")
    log_path = os.path.join(workdir, "operator.log")
    cmd = [
        sys.executable, "-m", "tf_operator_tpu.cli.operator",
        "--port", str(port),
        "--log-dir", os.path.join(workdir, "process-logs"),
        "--backend", args.bench_backend,
        *extra,
    ]
    with open(log_path, "ab") as log:
        operator = subprocess.Popen(
            cmd, stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True, cwd=REPO_ROOT,
        )
    deadline = time.time() + 30
    while True:
        try:
            with urllib.request.urlopen(server + "/healthz", timeout=2):
                break
        except OSError:
            if operator.poll() is not None or time.time() > deadline:
                _stop_operator(operator, workdir, keep=True)
                raise RuntimeError(
                    f"operator never became healthy; see {log_path}"
                )
            time.sleep(0.2)
    return operator, server, workdir, log_path


def _stop_operator(operator, workdir: str, keep: bool = False) -> None:
    import shutil
    import signal
    import subprocess

    if operator.poll() is None:
        operator.send_signal(signal.SIGTERM)
        try:
            operator.wait(timeout=15)
        except subprocess.TimeoutExpired:
            operator.kill()
            operator.wait()
    if not keep:
        shutil.rmtree(workdir, ignore_errors=True)


def _bench_level(n_jobs: int, args) -> dict:
    """One bench level: fresh operator daemon → submit n_jobs no-op jobs
    → wait terminal → scrape latency → tear down."""
    from tf_operator_tpu.dashboard.client import TPUJobClient

    operator, server, workdir, log_path = _start_operator(args, str(n_jobs))
    try:
        jobs = [
            build_job(
                f"bench{n_jobs}-{i}", args.workers, args.steps,
                "tf_operator_tpu.workloads.noop:main", args.topology, True,
            )
            for i in range(n_jobs)
        ]
        client = TPUJobClient(server)
        t0 = time.perf_counter()
        for job in jobs:
            client.create(job)
        submit_s = time.perf_counter() - t0
        report = wait_for_terminal(client, jobs, args.timeout, t0)
        latency = _scrape_sync_latency(server)
        row = {
            "jobs": n_jobs,
            "jobs_per_min": report["value"],
            "succeeded": report["succeeded"],
            "failed": report["failed"],
            "unfinished": report["unfinished"],
            "submit_s": round(submit_s, 2),
            "wall_s": report["wall_s"],
            **latency,
        }
        print(json.dumps(row), flush=True)
        return row
    finally:
        _stop_operator(operator, workdir)


def run_bench(args) -> int:
    levels = [int(s) for s in str(args.bench_levels).split(",") if s.strip()]
    rows = [_bench_level(n, args) for n in levels]
    artifact = {
        "metric": "controlplane_bench",
        "unit": "jobs/min",
        "backend": args.bench_backend,
        "workers_per_job": args.workers,
        "payload": "tf_operator_tpu.workloads.noop:main",
        "levels": rows,
        "baseline_r5_jobs_per_min_500": R5_BASELINE_500,
    }
    line = json.dumps(artifact)
    print(line)
    if args.bench_out:
        os.makedirs(os.path.dirname(args.bench_out) or ".", exist_ok=True)
        with open(args.bench_out, "w") as f:
            f.write(line + "\n")
    # Correctness gate (the CI stage's contract): every job at every
    # level must have Succeeded.
    bad = [
        r for r in rows
        if r["failed"] or r["unfinished"] or r["succeeded"] != r["jobs"]
    ]
    return 1 if bad else 0


# ---- --bench-ttfs: the sub-second time-to-first-step oracle (r11) -------


def _wait_gauge(server: str, name: str, want: float, timeout: float) -> bool:
    """Poll /metrics until gauge ``name`` >= want (pool-warm sync point)."""
    import re
    import urllib.request

    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(server + "/metrics", timeout=5) as r:
                m = re.search(rf"^{name} ([0-9.e+-]+)$",
                              r.read().decode(), re.MULTILINE)
            if m and float(m.group(1)) >= want:
                return True
        except OSError:
            pass
        time.sleep(0.25)
    return False


def _ttfs_submit_wave(client, jobs, timeout: float, inflight: int) -> dict:
    """Submit jobs with a bounded in-flight window (repeat-submit shape:
    a stream of submissions, not one thundering batch — 100 concurrent
    gangs would measure control-plane queueing, not TTFS) and wait until
    every job is terminal."""
    t0 = time.perf_counter()
    pending = list(jobs)
    live: list = []
    done: dict = {}
    deadline = time.time() + timeout
    while (pending or live) and time.time() < deadline:
        while pending and len(live) < inflight:
            job = pending.pop(0)
            client.create(job)
            live.append(job.metadata.name)
        try:
            listed = {j.metadata.name: j for j in client.list("default")}
        except Exception:
            time.sleep(0.2)
            continue
        for name in list(live):
            j = listed.get(name)
            if j is not None and j.status.phase().value in ("Done", "Failed"):
                done[name] = j.status.phase().value
                live.remove(name)
        if pending or live:
            time.sleep(0.1)
    succeeded = sum(1 for v in done.values() if v == "Done")
    return {
        "jobs": len(jobs),
        "succeeded": succeeded,
        "failed": len(done) - succeeded,
        "unfinished": len(jobs) - len(done),
        "wall_s": round(time.perf_counter() - t0, 2),
    }


def _ttfs_wave(tag: str, args, machinery: bool, keyer, seed: bool = False) -> dict:
    """One TTFS wave on a fresh operator: submit ``--bench-ttfs-jobs``
    single-process modeled-compile jobs (workloads/compiled.py) with a
    bounded in-flight window, wait terminal, scrape the TTFS split.
    ``machinery`` toggles the whole r11 stack (cachesvc + AOT-at-
    admission + warm pool); ``keyer(i)`` names each job's compile key —
    unique per job = every submission cold-compiles a fresh program,
    constant = repeat submissions of the same workload."""
    from tf_operator_tpu.dashboard.client import TPUJobClient

    extra = ()
    if machinery:
        extra = (
            "--compile-cache",
            "--aot-workers", "4",
            "--warm-pool", str(args.bench_ttfs_inflight),
        )
    operator, server, workdir, log_path = _start_operator(
        args, f"ttfs-{tag}", extra=extra
    )
    try:
        if machinery:
            # Measure steady state, not pool bring-up: a production host
            # agent warms its pool at agent start, long before any job
            # arrives. Wait for the warm-idle gauge to report full.
            _wait_gauge(server, "tpujob_warmpool_warm_idle",
                        args.bench_ttfs_inflight, timeout=60.0)
        # Hermetic local tier: point cached_compile's directory inside the
        # wave's workdir so no state leaks across waves or bench runs
        # (JAX_PLATFORMS=cpu keeps enable() from touching jax itself).
        cache_dir = os.path.join(workdir, "compile-cache")
        jobs = [
            build_job(
                f"ttfs-{tag}-{i}", 1, 0,
                "tf_operator_tpu.workloads.compiled:main", "", True,
                workload_extra={"aot": {
                    "key": keyer(i),
                    "compile_ms": args.bench_compile_ms,
                }},
                env_extra={"JAX_COMPILATION_CACHE_DIR": cache_dir},
            )
            for i in range(args.bench_ttfs_jobs)
        ]
        client = TPUJobClient(server)
        if seed:
            # Repeat-submit semantics: the measured jobs re-submit a
            # workload the fleet has already compiled once. Run one seed
            # job with the same key to terminal, outside the timed wave.
            seed_job = build_job(
                f"ttfs-{tag}-seed", 1, 0,
                "tf_operator_tpu.workloads.compiled:main", "", True,
                workload_extra={"aot": {
                    "key": keyer(0),
                    "compile_ms": args.bench_compile_ms,
                }},
                env_extra={"JAX_COMPILATION_CACHE_DIR": cache_dir},
            )
            client.create(seed_job)
            wait_for_terminal(client, [seed_job], args.timeout,
                              time.perf_counter())
        report = _ttfs_submit_wave(
            client, jobs, args.timeout, args.bench_ttfs_inflight
        )
        latency = _scrape_sync_latency(server)
        import urllib.request

        with urllib.request.urlopen(server + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
        row = {
            "wave": tag,
            "machinery": machinery,
            **report,
            **latency,
            "aot_kicked": _scrape_counter(
                text, "tpujob_aot_compiles_kicked_total"),
            "aot_published": _scrape_counter(
                text, "tpujob_aot_compiles_published_total"),
        }
        print(json.dumps(row), flush=True)
        return row
    finally:
        _stop_operator(operator, workdir)


def run_ttfs_bench(args) -> int:
    """Three waves, each on a fresh operator (same-host A/B, the r7
    precedent for honest regression calls):

    - ``baseline``: machinery OFF, unique compile keys — the pre-change
      cold population (every job pays spawn + modeled compile serially).
    - ``cold``: the full r11 stack ON, unique compile keys — first
      submission of a never-seen program; the speedup mechanisms are
      AOT-at-admission (compile overlaps scheduling + spawn; the gang
      member waits out the compile *intent* instead of recompiling) and
      the warm worker pool (no cold fork/imports).
    - ``warm``: stack ON, every job shares ONE key — repeat submissions;
      after the first publish, every job is a pure cache hit.

    Gates (the r11 acceptance): warm p50 under the bound; cold p50 at or
    under ``--bench-ttfs-cold-factor`` x the same-host baseline p50; and
    zero cache-integrity failures surfaced as job failures — every job
    in every wave must end Done (a corrupt/dead-cachesvc path degrades
    to local compile by design, so any Failed job is a real defect)."""
    nonce = f"{os.getpid()}-{int(time.time())}"
    waves = [
        _ttfs_wave("baseline", args, False, lambda i: f"b-{nonce}-{i}"),
        _ttfs_wave("cold", args, True, lambda i: f"c-{nonce}-{i}"),
        _ttfs_wave("warm", args, True, lambda i: f"w-{nonce}", seed=True),
    ]
    base, cold, warm = waves
    warm_p50 = warm.get("ttfs_warm_p50_ms", warm.get("ttfs_p50_ms", 0.0))
    artifact = {
        "metric": "ttfs_bench",
        "unit": "ms",
        "backend": args.bench_backend,
        "jobs_per_wave": args.bench_ttfs_jobs,
        "inflight": args.bench_ttfs_inflight,
        "modeled_compile_ms": args.bench_compile_ms,
        "payload": "tf_operator_tpu.workloads.compiled:main",
        "waves": waves,
        "pre_cold_p50_ms": base.get("ttfs_p50_ms", 0.0),
        "cold_p50_ms": cold.get("ttfs_p50_ms", 0.0),
        "warm_p50_ms": warm_p50,
        "warm_bound_ms": args.bench_ttfs_warm_bound_ms,
        "cold_factor_bound": args.bench_ttfs_cold_factor,
    }
    line = json.dumps(artifact)
    print(line)
    if args.bench_out:
        os.makedirs(os.path.dirname(args.bench_out) or ".", exist_ok=True)
        with open(args.bench_out, "w") as f:
            f.write(line + "\n")
    ok = True
    for w in waves:
        if w["failed"] or w["unfinished"] or w["succeeded"] != w["jobs"]:
            print(f"FAIL: wave {w['wave']}: not every job Succeeded "
                  "(cache-integrity or degradation surfaced as a job "
                  "failure)", file=sys.stderr)
            ok = False
    if warm_p50 >= args.bench_ttfs_warm_bound_ms:
        print(f"FAIL: warm TTFS p50 {warm_p50}ms >= bound "
              f"{args.bench_ttfs_warm_bound_ms}ms", file=sys.stderr)
        ok = False
    bound = args.bench_ttfs_cold_factor * artifact["pre_cold_p50_ms"]
    if artifact["cold_p50_ms"] > bound:
        print(f"FAIL: cold TTFS p50 {artifact['cold_p50_ms']}ms > "
              f"{args.bench_ttfs_cold_factor} x baseline "
              f"{artifact['pre_cold_p50_ms']}ms = {bound:.1f}ms",
              file=sys.stderr)
        ok = False
    return 0 if ok else 1


# ---- --bench-elastic: the elastic-gang resize oracle (r12) --------------


def run_elastic_bench(args) -> int:
    """The r12 elasticity receipt: drive the seeded kill/return schedule
    through the elastic chaos soak (``chaos/soak.py``) and report one
    JSON line — resize downtime p50/p99, tokens/s before/during/after
    the shrink, and the hard gates the CI ``elastic-soak`` stage rides
    on: zero full gang restarts, bit-identical eval after the re-grow
    vs an uninterrupted run at the same token count, and at least one
    resize restored from a peer depot rather than disk."""
    from tf_operator_tpu.chaos.soak import (
        elastic_artifact,
        run_elastic_soak,
        run_grow_beyond_spec_probe,
    )

    result = run_elastic_soak(
        seed=args.seed,
        kills=args.bench_elastic_kills,
        workers=args.workers,
        total_windows=args.bench_elastic_windows,
        timeout=args.timeout,
        device_state=args.bench_elastic_device_state,
        preempt_during_resize=args.bench_elastic_preempt_during_resize,
        queue_quota=(
            args.workers if args.bench_elastic_preempt_during_resize else 0
        ),
    )
    artifact = elastic_artifact(result, args.seed)
    violations = result.check()
    if args.bench_elastic_grow_beyond_spec:
        # r19 probe: the same receipt line grows a grow_beyond_spec
        # section — world past spec on loaned in-quota chips, cleanly
        # first-reclaimed under injected queue pressure.
        grow = run_grow_beyond_spec_probe(
            seed=args.seed, timeout=args.timeout
        )
        artifact["grow_beyond_spec"] = {
            "spec_world": grow.spec_world,
            "elastic_max_world": grow.max_world,
            "grew_to": grow.grew_to,
            "overspec_seen": grow.overspec_seen,
            "resize_history": grow.resize_history,
            "quota_violations": grow.quota_violations,
            "pass": not grow.check(),
        }
        violations += grow.check()
        artifact["pass"] = not violations
    line = json.dumps(artifact)
    print(line)
    if args.bench_out:
        os.makedirs(os.path.dirname(args.bench_out) or ".", exist_ok=True)
        with open(args.bench_out, "w") as f:
            f.write(line + "\n")
    for v in violations:
        print(f"FAIL: {v}", file=sys.stderr)
    return 1 if violations else 0


# ---- --bench-tenants: the multi-tenant fleet-scheduler oracle (r7) ------


def _parse_labeled_histogram(text: str, family: str, match=None) -> tuple:
    """([(le_seconds, cumulative)], count) for a LABELED histogram family,
    summing across every series whose labels include ``match``."""
    import re

    line_re = re.compile(rf"{family}_(bucket|count)\{{([^}}]*)\}} ([0-9.eE+-]+)")
    buckets: dict = {}
    total = 0
    for line in text.splitlines():
        m = line_re.match(line)
        if not m:
            continue
        kind, labelstr, val = m.groups()
        labels = dict(re.findall(r'(\w+)="([^"]*)"', labelstr))
        if match and any(labels.get(k) != v for k, v in match.items()):
            continue
        if kind == "bucket":
            le = labels.get("le", "")
            if le and le != "+Inf":
                buckets[float(le)] = buckets.get(float(le), 0) + int(float(val))
        else:
            total += int(float(val))
    return sorted(buckets.items()), total


def _scrape_counter(text: str, family: str) -> int:
    import re

    total = 0
    for line in text.splitlines():
        m = re.match(rf"{family}(?:\{{[^}}]*\}})? ([0-9.eE+-]+)", line)
        if m:
            total += int(float(m.group(1)))
    return total


def _create_sched_objects(client, tenants: int, quota_chips: int) -> None:
    """High/low PriorityClasses plus one Queue per tenant namespace —
    created BEFORE any job so admission sees the quota from job one."""
    from tf_operator_tpu.sched.objects import PriorityClass, Queue, QueueSpec

    for name, value in (("high", 100), ("low", 0)):
        client.create_object(PriorityClass(
            metadata=ObjectMeta(name=name, namespace="default"), value=value,
        ))
    for i in range(tenants):
        client.create_object(Queue(
            metadata=ObjectMeta(name="main", namespace=f"tenant{i}"),
            spec=QueueSpec(quota_chips=quota_chips),
        ))


def _preemption_probe(client, args) -> dict:
    """The warm-resume receipt, run against the live benched operator:
    a one-job-quota namespace holds a low-priority sleeper; a high-
    priority submission must preempt it (victim restart cause
    ``preemption``, preemption_count not restart_count) and the victim
    must still finish after the high job releases the quota."""
    from tf_operator_tpu.sched.objects import Queue, QueueSpec

    chips, workers = args.bench_chips, args.workers
    demand = chips * workers
    client.create_object(Queue(
        metadata=ObjectMeta(name="main", namespace="probe"),
        spec=QueueSpec(quota_chips=demand),  # exactly one job fits
    ))
    mk = lambda name, prio, sleep: build_job(
        name, workers, 0, "tf_operator_tpu.workloads.noop:main", "", True,
        namespace="probe", queue="main", priority=prio,
        chips=chips, sleep_s=sleep,
    )
    out = {"ok": False, "error": ""}
    try:
        client.create(mk("victim", "low", 12.0))
        deadline = time.time() + 30
        while time.time() < deadline:
            if client.get_job("probe", "victim").status.phase().value == "Running":
                break
            time.sleep(0.25)
        else:
            out["error"] = "victim never started running"
            return out

        t_high = time.time()
        client.create(mk("preemptor", "high", 1.0))
        high = client.wait_for_job("probe", "preemptor", timeout=60)
        out["high_wait_s"] = round(time.time() - t_high, 2)
        if high.status.phase().value != "Done":
            out["error"] = f"preemptor finished {high.status.phase().value}"
            return out

        victim = client.wait_for_job("probe", "victim", timeout=90)
        out.update(
            victim_phase=victim.status.phase().value,
            preemption_count=victim.status.preemption_count,
            restart_count=victim.status.restart_count,
            last_restart_cause=victim.status.last_restart_cause,
        )
        if victim.status.phase().value != "Done":
            out["error"] = "victim did not finish after preemption"
        elif victim.status.preemption_count < 1:
            out["error"] = "victim was never preempted"
        elif victim.status.restart_count != 0:
            out["error"] = "preemption was charged to restart_count/backoff"
        elif victim.status.last_restart_cause != "preemption":
            out["error"] = (
                f"restart cause {victim.status.last_restart_cause!r}, "
                "expected 'preemption'"
            )
        elif out["high_wait_s"] > args.bench_preempt_wait_bound:
            out["error"] = (
                f"high-priority admission took {out['high_wait_s']}s "
                f"(bound {args.bench_preempt_wait_bound}s)"
            )
        else:
            out["ok"] = True
    except Exception as exc:  # probe failures fail the bench, not crash it
        out["error"] = f"{type(exc).__name__}: {exc}"
    return out


def _sched_bench_level(n_jobs: int, args) -> dict:
    """One multi-tenant level: fresh operator (sharded reconciler) →
    Queues/PriorityClasses → n_jobs spread over the tenants with the
    high/low priority mix → wait terminal while polling per-tenant
    running demand against quota → queue-wait + preemption metrics."""
    import urllib.request

    from tf_operator_tpu.dashboard.client import TPUJobClient

    tenants = args.bench_tenants
    shards = str(max(2, min(tenants, 4)))
    operator, server, workdir, log_path = _start_operator(
        args, f"sched{n_jobs}",
        extra=("--threadiness", shards, "--reconcile-shards", shards),
    )
    try:
        client = TPUJobClient(server)
        _create_sched_objects(client, tenants, args.bench_quota_chips)

        n_high = max(1, int(n_jobs * args.bench_priority_mix))
        jobs = [
            build_job(
                f"sb{n_jobs}-{i}", args.workers, 0,
                "tf_operator_tpu.workloads.noop:main", "", True,
                namespace=f"tenant{i % tenants}", queue="main",
                priority="high" if i < n_high else "low",
                chips=args.bench_chips,
            )
            for i in range(n_jobs)
        ]
        t0 = time.perf_counter()
        for job in jobs:
            client.create(job)
        submit_s = time.perf_counter() - t0

        # Wait loop doubling as the quota oracle: each poll, sum the chips
        # of LIVE Process objects per tenant namespace — the store-side
        # ground truth of chip occupancy (job phases lag the handoff; a
        # preemption victim can still read Running one status-write after
        # its gang is gone). The peak must never exceed the tenant
        # queue's quota_chips: the two-phase preemption handoff releases
        # the victim's quota only once its gang is observably gone, so
        # victim and preemptor processes never coexist in a snapshot.
        pending = {(j.metadata.namespace, j.metadata.name) for j in jobs}
        done: dict = {}
        peak = {f"tenant{i}": 0 for i in range(tenants)}
        deadline = time.time() + args.timeout
        while pending and time.time() < deadline:
            try:
                listed = client.list(None)
                for i in range(tenants):
                    ns = f"tenant{i}"
                    live = sum(
                        max(p.spec.chips, 0)
                        for p in client.list_objects("Process", ns)
                        if not p.is_finished()
                    )
                    peak[ns] = max(peak[ns], live)
            except Exception:
                time.sleep(0.5)
                continue
            for j in listed:
                k = (j.metadata.namespace, j.metadata.name)
                if k in pending and j.status.phase().value in ("Done", "Failed"):
                    done[k] = j.status.phase().value
                    pending.discard(k)
            if pending:
                time.sleep(0.5)
        wall_s = time.perf_counter() - t0

        probe = _preemption_probe(client, args)

        with urllib.request.urlopen(server + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
        qb, qn = _parse_labeled_histogram(text, "tpujob_queue_wait_seconds")
        hb, hn = _parse_labeled_histogram(
            text, "tpujob_queue_wait_seconds", match={"priority": "high"}
        )
        quota_violations = [
            {"tenant": ns, "peak_chips": used,
             "quota_chips": args.bench_quota_chips}
            for ns, used in sorted(peak.items())
            if used > args.bench_quota_chips
        ]
        per_tenant = {}
        for i in range(tenants):
            ns = f"tenant{i}"
            t_done = [v for k, v in done.items() if k[0] == ns]
            per_tenant[ns] = {
                "jobs": sum(1 for j in jobs if j.metadata.namespace == ns),
                "succeeded": sum(1 for v in t_done if v == "Done"),
                "jobs_per_min": round(len(t_done) / wall_s * 60.0, 1) if wall_s else 0.0,
                "peak_chips": peak.get(ns, 0),
            }
        succeeded = sum(1 for v in done.values() if v == "Done")
        row = {
            "jobs": n_jobs,
            "tenants": tenants,
            "priority_mix": args.bench_priority_mix,
            "quota_chips": args.bench_quota_chips,
            "jobs_per_min": round(len(done) / wall_s * 60.0, 1) if wall_s else 0.0,
            "succeeded": succeeded,
            "failed": len(done) - succeeded,
            "unfinished": len(pending),
            "submit_s": round(submit_s, 2),
            "wall_s": round(wall_s, 2),
            "queue_waits": qn,
            "queue_wait_p50_ms": round(_histogram_quantile(qb, qn, 0.5) * 1e3, 1),
            "queue_wait_p99_ms": round(_histogram_quantile(qb, qn, 0.99) * 1e3, 1),
            "queue_wait_high_p99_ms": round(_histogram_quantile(hb, hn, 0.99) * 1e3, 1),
            "preemptions_requested": _scrape_counter(
                text, "tpujob_preemptions_requested_total"
            ),
            "quota_violations": quota_violations,
            "per_tenant": per_tenant,
            "probe": probe,
        }
        print(json.dumps(row), flush=True)
        return row
    finally:
        _stop_operator(operator, workdir)


def run_sched_bench(args) -> int:
    levels = [int(s) for s in str(args.bench_levels).split(",") if s.strip()]
    rows = [_sched_bench_level(n, args) for n in levels]
    single = None
    if args.bench_single_level:
        single = _bench_level(args.bench_single_level, args)
    artifact = {
        "metric": "sched_bench",
        "unit": "jobs/min",
        "backend": args.bench_backend,
        "tenants": args.bench_tenants,
        "priority_mix": args.bench_priority_mix,
        "quota_chips": args.bench_quota_chips,
        "workers_per_job": args.workers,
        "payload": "tf_operator_tpu.workloads.noop:main",
        "levels": rows,
        "single_tenant": single,
        "single_tenant_floor": args.bench_single_floor,
        "baseline_r6_jobs_per_min_500": R6_BASELINE_500,
    }
    line = json.dumps(artifact)
    print(line)
    if args.bench_out:
        os.makedirs(os.path.dirname(args.bench_out) or ".", exist_ok=True)
        with open(args.bench_out, "w") as f:
            f.write(line + "\n")
    # The CI contract: every job Succeeded, no tenant ever observed over
    # its chip quota, the preemption probe's receipts all held, and the
    # single-tenant control stays above the regression floor (absolute
    # jobs/min via --bench-single-floor; the checked-in r6 number was
    # captured on a faster host, so an absolute gate against it would
    # fail at the seed commit too — regression calls need a same-host
    # A/B, which is how the r7 artifact's floor was chosen).
    ok = True
    for r in rows:
        if r["failed"] or r["unfinished"] or r["succeeded"] != r["jobs"]:
            print(f"FAIL: level {r['jobs']}: not every job Succeeded", file=sys.stderr)
            ok = False
        if r["quota_violations"]:
            print(f"FAIL: level {r['jobs']}: quota exceeded: "
                  f"{r['quota_violations']}", file=sys.stderr)
            ok = False
        if not r["probe"].get("ok"):
            print(f"FAIL: level {r['jobs']}: preemption probe: "
                  f"{r['probe'].get('error')}", file=sys.stderr)
            ok = False
    if single is not None:
        floor = args.bench_single_floor
        if single["failed"] or single["unfinished"]:
            print("FAIL: single-tenant control: not every job Succeeded",
                  file=sys.stderr)
            ok = False
        elif floor and single["jobs_per_min"] < floor:
            print(f"FAIL: single-tenant control {single['jobs_per_min']} "
                  f"jobs/min under the floor {floor:.1f}", file=sys.stderr)
            ok = False
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpujob-genjob")
    p.add_argument("--nr-jobs", type=int, default=1)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--steps", type=int, default=2)
    p.add_argument("--prefix", default="genjob")
    p.add_argument("--entrypoint", default="tf_operator_tpu.workloads.smoke:main")
    p.add_argument("--topology", default="", help="slice type, e.g. v5p-32")
    p.add_argument("--no-cpu-env", action="store_true",
                   help="don't inject the CPU-platform env (run on real TPU)")
    p.add_argument("--out-dir", default=None, help="write one JSON spec per job")
    p.add_argument("--submit", action="store_true", help="submit to the operator")
    p.add_argument("--server", default="http://127.0.0.1:8080")
    p.add_argument("--wait", action="store_true",
                   help="after --submit, wait for every job to reach a "
                        "terminal state and print a JSON load report "
                        "(jobs/min, success count) — the controller-scale "
                        "oracle for the reference's O(100)-job design target")
    p.add_argument("--timeout", type=float, default=900.0)
    p.add_argument("--cleanup", action="store_true",
                   help="delete the generated jobs after the report")
    p.add_argument("--bench", action="store_true",
                   help="self-contained control-plane bench: per level in "
                        "--bench-levels, deploy a fresh operator, submit "
                        "that many concurrent no-op jobs, report jobs/min "
                        "+ p50/p99 sync latency as one JSON line; exit "
                        "nonzero unless every job Succeeded")
    p.add_argument("--bench-levels", default="50,200,500",
                   help="comma-separated concurrent-job counts")
    p.add_argument("--bench-out", default=None,
                   help="also write the bench JSON line to this path "
                        "(the artifacts/controlplane_r*.json format)")
    p.add_argument("--bench-backend", choices=("native", "local"),
                   default="native",
                   help="process backend for the benched operator "
                        "(native = C++ supervisor, the deploy default)")
    p.add_argument("--bench-tenants", type=int, default=0,
                   help="with --bench: >0 switches to the multi-tenant "
                        "fleet-scheduler bench — jobs spread over N tenant "
                        "namespaces, each with a quota'd Queue, mixed "
                        "high/low PriorityClasses, quota/preemption oracles")
    p.add_argument("--bench-priority-mix", type=float, default=0.2,
                   help="fraction of bench jobs submitted at high priority")
    p.add_argument("--bench-quota-chips", type=int, default=32,
                   help="per-tenant Queue chip quota (bench jobs hold "
                        "workers x --bench-chips chips while admitted)")
    p.add_argument("--bench-chips", type=int, default=4,
                   help="chips_per_process each bench job requests")
    p.add_argument("--bench-preempt-wait-bound", type=float, default=60.0,
                   help="max seconds the probe's high-priority job may wait "
                        "for admission via preemption before the bench "
                        "fails (covers the victim's full graceful drain "
                        "plus sync latency on a loaded control plane)")
    p.add_argument("--bench-single-level", type=int, default=0,
                   help="also run one classic single-tenant level as the "
                        "no-fleet-overhead throughput control")
    p.add_argument("--bench-single-floor", type=float, default=0.0,
                   help="fail unless the single-tenant control clears this "
                        "many jobs/min (0 = correctness-only; pick the "
                        "floor from a same-host baseline run, not from an "
                        "artifact captured on different hardware)")
    p.add_argument("--bench-ttfs", action="store_true",
                   help="run the r11 time-to-first-step bench: three waves "
                        "(baseline / cold-with-machinery / warm repeat-"
                        "submit), each on a fresh operator; gates warm p50 "
                        "and the cold-vs-baseline ratio")
    p.add_argument("--bench-ttfs-jobs", type=int, default=100,
                   help="jobs per TTFS wave")
    p.add_argument("--bench-compile-ms", type=int, default=600,
                   help="modeled XLA compile cost each cache miss pays "
                        "(workloads/compiled.py)")
    p.add_argument("--bench-ttfs-warm-bound-ms", type=float, default=1000.0,
                   help="fail if the warm wave's warm-population TTFS p50 "
                        "is at or above this (the sub-second headline)")
    p.add_argument("--bench-ttfs-cold-factor", type=float, default=0.5,
                   help="fail if the cold wave's TTFS p50 exceeds this "
                        "fraction of the same-host baseline p50")
    p.add_argument("--bench-ttfs-inflight", type=int, default=4,
                   help="bounded submission window (and warm-pool size): "
                        "repeat-submit is a stream, not one batch")
    p.add_argument("--bench-elastic", action="store_true",
                   help="run the r12 elastic-gang resize bench: seeded "
                        "kill/return schedule through the elastic chaos "
                        "soak; one JSON line with resize downtime p50/p99 "
                        "and tokens/s before/during/after the shrink; "
                        "exits nonzero unless zero full restarts, "
                        "bit-identical eval, and >=1 peer-depot restore")
    p.add_argument("--bench-elastic-kills", type=int, default=2,
                   help="kill/return events in the elastic schedule")
    p.add_argument("--bench-elastic-windows", type=int, default=400,
                   help="total data windows the elastic workload consumes")
    p.add_argument("--bench-elastic-device-state", action="store_true",
                   help="carry a real device param/opt pytree through "
                        "every resize (train/reshard.py); hardens the "
                        "gate to bit-identical final params vs an "
                        "uninterrupted run")
    p.add_argument("--bench-elastic-preempt-during-resize",
                   action="store_true",
                   help="stamp a fleet preemption mid-shrink (r19 "
                        "composition probe): the drain must defer to the "
                        "post-resize epoch, under a store-audited Queue")
    p.add_argument("--bench-elastic-grow-beyond-spec", action="store_true",
                   help="also run the r19 grow-beyond-spec probe: world "
                        "past spec on loaned in-quota chips, cleanly "
                        "first-reclaimed under injected queue pressure")
    p.add_argument("--seed", type=int, default=12,
                   help="schedule seed for --bench-elastic")
    args = p.parse_args(argv)

    if args.bench_elastic:
        if args.workers < 3:
            args.workers = 3  # need a chief + >=2 killable members
        if args.timeout > 300.0:
            args.timeout = 150.0  # soak bound, not the submit default
        return run_elastic_bench(args)
    if args.bench_ttfs:
        return run_ttfs_bench(args)
    if args.bench:
        if args.bench_tenants > 0:
            return run_sched_bench(args)
        return run_bench(args)

    jobs = [
        build_job(
            f"{args.prefix}-{i}", args.workers, args.steps, args.entrypoint,
            args.topology, not args.no_cpu_env,
        )
        for i in range(args.nr_jobs)
    ]

    if args.out_dir:
        os.makedirs(args.out_dir, exist_ok=True)
        for job in jobs:
            path = os.path.join(args.out_dir, f"{job.metadata.name}.json")
            with open(path, "w") as f:
                json.dump(_to_jsonable(job.to_dict()), f, indent=2)
        print(f"wrote {len(jobs)} specs to {args.out_dir}")

    if args.submit:
        from tf_operator_tpu.dashboard.client import TPUJobClient

        client = TPUJobClient(args.server)
        t0 = time.perf_counter()
        for job in jobs:
            client.create(job)
        submit_s = time.perf_counter() - t0
        print(f"submitted {len(jobs)} jobs to {args.server} in {submit_s:.2f}s")

        if args.wait:
            report = wait_for_terminal(client, jobs, args.timeout, t0)
            report["submit_s"] = round(submit_s, 2)
            print(json.dumps(report))
            if args.cleanup:
                for job in jobs:
                    try:
                        client.delete("default", job.metadata.name)
                    except Exception:
                        pass
            if report["unfinished"] or report["succeeded"] != len(jobs):
                return 1
    elif not args.out_dir:
        for job in jobs:
            print(json.dumps(_to_jsonable(job.to_dict())))
    return 0


if __name__ == "__main__":
    sys.exit(main())
