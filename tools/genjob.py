"""TPUJob load generator.

Reference parity: hack/genjob/genjob.go — templated job generation for
controller load/gang-scheduling experiments (``--nr-tfjobs``,
``--scheduler-name``); here ``--nr-jobs`` with optional direct submission
so one command can put O(100) concurrent jobs on the operator (the
reference's design scale target, tf_job_design_doc.md:24-26).

Usage:
    python -m tools.genjob --nr-jobs 20 --out-dir /tmp/jobs        # write specs
    python -m tools.genjob --nr-jobs 20 --submit --server http://… # submit
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tf_operator_tpu.api.types import (
    ObjectMeta,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    TPUJob,
    TPUJobSpec,
    TopologySpec,
)
from tf_operator_tpu.api.types import _to_jsonable


def build_job(
    name: str,
    workers: int,
    steps: int,
    entrypoint: str,
    topology: str,
    cpu_env: bool,
) -> TPUJob:
    env = {}
    if cpu_env:
        env = {
            "JAX_PLATFORMS": "cpu",
            "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "",
        }
    template = ProcessTemplate(entrypoint=entrypoint, env=env)
    spec = TPUJobSpec(
        replica_specs={ReplicaType.WORKER: ReplicaSpec(replicas=workers, template=template)},
        workload={"dim": 16, "steps": steps},
    )
    if topology:
        spec.topology = TopologySpec(slice_type=topology)
    return TPUJob(metadata=ObjectMeta(name=name), spec=spec)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpujob-genjob")
    p.add_argument("--nr-jobs", type=int, default=1)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--steps", type=int, default=2)
    p.add_argument("--prefix", default="genjob")
    p.add_argument("--entrypoint", default="tf_operator_tpu.workloads.smoke:main")
    p.add_argument("--topology", default="", help="slice type, e.g. v5p-32")
    p.add_argument("--no-cpu-env", action="store_true",
                   help="don't inject the CPU-platform env (run on real TPU)")
    p.add_argument("--out-dir", default=None, help="write one JSON spec per job")
    p.add_argument("--submit", action="store_true", help="submit to the operator")
    p.add_argument("--server", default="http://127.0.0.1:8080")
    p.add_argument("--wait", action="store_true",
                   help="after --submit, wait for every job to reach a "
                        "terminal state and print a JSON load report "
                        "(jobs/min, success count) — the controller-scale "
                        "oracle for the reference's O(100)-job design target")
    p.add_argument("--timeout", type=float, default=900.0)
    p.add_argument("--cleanup", action="store_true",
                   help="delete the generated jobs after the report")
    args = p.parse_args(argv)

    jobs = [
        build_job(
            f"{args.prefix}-{i}", args.workers, args.steps, args.entrypoint,
            args.topology, not args.no_cpu_env,
        )
        for i in range(args.nr_jobs)
    ]

    if args.out_dir:
        os.makedirs(args.out_dir, exist_ok=True)
        for job in jobs:
            path = os.path.join(args.out_dir, f"{job.metadata.name}.json")
            with open(path, "w") as f:
                json.dump(_to_jsonable(job.to_dict()), f, indent=2)
        print(f"wrote {len(jobs)} specs to {args.out_dir}")

    if args.submit:
        import time

        from tf_operator_tpu.dashboard.client import TPUJobClient

        client = TPUJobClient(args.server)
        t0 = time.perf_counter()
        for job in jobs:
            client.create(job)
        submit_s = time.perf_counter() - t0
        print(f"submitted {len(jobs)} jobs to {args.server} in {submit_s:.2f}s")

        if args.wait:
            terminal = {"Done", "Failed"}
            pending = {j.metadata.name for j in jobs}
            done: dict = {}
            deadline = time.time() + args.timeout
            while pending and time.time() < deadline:
                # One LIST per round (not a GET per job): polling must not
                # load the very server whose throughput is being measured,
                # and one transient HTTP error must not abort the test.
                try:
                    listed = client.list("default")
                except Exception:
                    time.sleep(0.5)
                    continue
                for j in listed:
                    name = j.metadata.name
                    if name in pending:
                        phase = j.status.phase().value
                        if phase in terminal:
                            done[name] = phase
                            pending.discard(name)
                if pending:
                    time.sleep(0.5)
            wall_s = time.perf_counter() - t0
            succeeded = sum(1 for v in done.values() if v == "Done")
            print(json.dumps({
                "metric": "controller_jobs_per_min",
                "value": round(len(done) / wall_s * 60.0, 1),
                "unit": "jobs/min",
                "jobs": len(jobs),
                "succeeded": succeeded,
                "failed": len(done) - succeeded,
                "unfinished": len(pending),
                "submit_s": round(submit_s, 2),
                "wall_s": round(wall_s, 2),
            }))
            if args.cleanup:
                for job in jobs:
                    try:
                        client.delete("default", job.metadata.name)
                    except Exception:
                        pass
            if pending or succeeded != len(jobs):
                return 1
    elif not args.out_dir:
        for job in jobs:
            print(json.dumps(_to_jsonable(job.to_dict())))
    return 0


if __name__ == "__main__":
    sys.exit(main())
