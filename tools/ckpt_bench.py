"""Async-checkpoint overlap probe (r3, VERDICT #9 done-bar).

Measures the steady-state step time of a gpt-small training loop WITH
periodic async orbax checkpointing vs WITHOUT, through the exact
production path (WorkloadCheckpointer.run_loop — the same warmup/timed
protocol the workloads use). Async saves pay only the device->host
transfer inside save(); serialization overlaps subsequent steps, so the
with-checkpointing step time should be ~equal to the clean loop
(delta ~0 at bench scale). ``--sync`` additionally measures the r2
blocking behavior for contrast.

    python -m tools.ckpt_bench [--steps 30] [--every 5] [--sync]
        [--backend auto|npy|orbax]

r8: also reports the per-save caller stall (p50/p99 over the accepted
saves, from WorkloadCheckpointer.save_stalls) and its ratio to the
step time — the tentpole's "save stall < 1 step-time" receipt.
``--backend npy`` exercises the chunked async npy drain specifically.

Prints one JSON line per mode plus the overhead summary.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)


def _pctile(xs, q: float) -> float:
    """Nearest-rank percentile over a small sample (no numpy needed)."""
    s = sorted(xs)
    return s[min(len(s) - 1, int(q * len(s)))]


def run_mode(mode: str, steps: int, every: int, tmpdir: str,
             backend: str = "auto") -> float:
    import jax

    from tf_operator_tpu.models.transformer import (
        init_transformer,
        lm_loss,
        preset,
        transformer_logical_axes,
    )
    from tf_operator_tpu.parallel import build_mesh
    from tf_operator_tpu.train.checkpoint import CheckpointManager, WorkloadCheckpointer
    from tf_operator_tpu.train.trainer import Trainer, TrainerConfig

    shutil.rmtree(tmpdir, ignore_errors=True)
    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = preset(
        "gpt-small" if on_tpu else "tiny",
        max_seq=512 if on_tpu else 64,
        attn_impl="flash" if on_tpu else "dense",
    )
    mesh = build_mesh({"dp": jax.device_count()})
    trainer = Trainer(
        mesh,
        loss_fn=lambda p, tok, e: lm_loss(p, tok, cfg, mesh=mesh),
        init_fn=lambda k: init_transformer(k, cfg),
        logical_axes=transformer_logical_axes(cfg),
        config=TrainerConfig(optimizer="adamw", learning_rate=1e-4),
    )
    batch = 32 if on_tpu else jax.device_count()
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (batch, cfg.max_seq), 0, cfg.vocab),
        trainer.batch_sharding,
    )
    wl = {} if mode == "none" else {
        "checkpoint_dir": tmpdir, "checkpoint_every": every,
        "checkpoint_backend": backend,
    }
    ckpt = WorkloadCheckpointer(wl)
    if mode == "sync":
        # swap the manager for a blocking one (the r2 default); close the
        # async manager first or its background machinery leaks alongside
        ckpt.manager.close()
        ckpt.manager = CheckpointManager(
            tmpdir, backend=backend, async_save=False
        )
    _, loss, timed, step_s = ckpt.run_loop(
        trainer, jax.random.PRNGKey(0), tokens, steps
    )
    out = {
        "metric": f"ckpt_{mode}_step_s", "value": round(step_s, 5),
        "timed_steps": timed, "loss": round(float(loss), 4),
        "checkpoint_every": every if mode != "none" else 0,
    }
    if ckpt.save_stalls:
        # The tentpole receipt: how long the step loop was actually
        # blocked per accepted save, vs the step time it hides behind.
        out["save_stall_p50_s"] = round(_pctile(ckpt.save_stalls, 0.5), 5)
        out["save_stall_p99_s"] = round(_pctile(ckpt.save_stalls, 0.99), 5)
        if step_s:
            out["stall_over_step"] = round(
                _pctile(ckpt.save_stalls, 0.5) / step_s, 3
            )
    print(json.dumps(out), flush=True)
    return step_s


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--every", type=int, default=5)
    p.add_argument("--sync", action="store_true",
                   help="also measure the blocking (async_save=False) mode")
    p.add_argument("--backend", choices=("auto", "npy", "orbax"),
                   default="auto",
                   help="checkpoint backend (npy = chunked async drain)")
    args = p.parse_args(argv)

    from tf_operator_tpu.train.compile_cache import enable as enable_compile_cache

    enable_compile_cache()
    base = tempfile.mkdtemp(prefix="ckpt-bench-")
    try:
        clean = run_mode("none", args.steps, args.every,
                         os.path.join(base, "a"), args.backend)
        asyn = run_mode("async", args.steps, args.every,
                        os.path.join(base, "b"), args.backend)
        out = {
            "metric": "async_ckpt_overhead_pct",
            "value": round(100 * (asyn / clean - 1), 2),
        }
        if args.sync:
            syn = run_mode("sync", args.steps, args.every,
                           os.path.join(base, "c"), args.backend)
            out["sync_overhead_pct"] = round(100 * (syn / clean - 1), 2)
        print(json.dumps(out))
    finally:
        shutil.rmtree(base, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
