"""Templated install bundle: render/install parameterized TPUJob specs.

The helm-chart analogue (reference: `examples/tf_job/` — Chart.yaml +
values.yaml + templates/tf_job.yaml rendered by `helm install
--set image=...`). This substrate has no helm/k8s, so the bundle is a
directory of `string.Template` JSON templates plus a `bundle.json`
manifest carrying default values:

    deploy/bundle/
      bundle.json            # name/version + default values
      templates/*.json.tmpl  # ${var}-parameterized TPUJob specs

Usage (helm-verb parity):

    python -m tools.bundle render  [--bundle DIR] [--set k=v ...]
    python -m tools.bundle install --server http://op:8080 --set name=myjob \
        [--set preset=llama2-7b ...] [--auth-token-file f]
    python -m tools.bundle values  [--bundle DIR]   # show defaults

`render` prints the substituted spec (validated through the real
TPUJob.from_dict + admission defaulting/validation — a bundle cannot
produce a spec the API would reject); `install` submits it.
"""

from __future__ import annotations

import argparse
import json
import os
import string
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BUNDLE = os.path.join(_REPO_ROOT, "deploy", "bundle")


def load_bundle(bundle_dir: str) -> dict:
    manifest_path = os.path.join(bundle_dir, "bundle.json")
    tdir = os.path.join(bundle_dir, "templates")
    if not os.path.exists(manifest_path) or not os.path.isdir(tdir):
        raise SystemExit(
            f"{bundle_dir} is not a bundle (needs bundle.json + templates/)"
        )
    with open(manifest_path) as f:
        manifest = json.load(f)
    templates = {}
    for name in sorted(os.listdir(tdir)):
        if name.endswith(".tmpl"):
            with open(os.path.join(tdir, name)) as f:
                templates[name[: -len(".tmpl")]] = f.read()
    if not templates:
        raise SystemExit(f"no *.tmpl templates under {tdir}")
    manifest["templates"] = templates
    return manifest


def render(bundle_dir: str, overrides: dict) -> dict:
    """Returns {template_name: validated spec dict}. Unknown override keys
    fail loudly (a typo'd --set silently ignored would deploy defaults)."""
    from tf_operator_tpu.api import ValidationError, set_defaults, validate_job
    from tf_operator_tpu.api.v1alpha1 import parse_job

    manifest = load_bundle(bundle_dir)
    values = dict(manifest.get("values", {}))
    unknown = set(overrides) - set(values)
    if unknown:
        raise SystemExit(
            f"unknown value(s) {sorted(unknown)}; bundle defines {sorted(values)}"
        )
    values.update(overrides)
    out = {}
    for name, text in manifest["templates"].items():
        try:
            doc = json.loads(string.Template(text).substitute(values))
        except KeyError as exc:
            raise SystemExit(f"{name}: template var {exc} has no value")
        except json.JSONDecodeError as exc:
            raise SystemExit(f"{name}: rendered template is not valid JSON: {exc}")
        # The rendered spec goes through the REAL admission path so a
        # bundle can't ship something the API would bounce.
        try:
            job = parse_job(doc)
            set_defaults(job)
            validate_job(job)
        except (ValidationError, ValueError, KeyError, TypeError) as exc:
            raise SystemExit(f"{name}: rendered spec rejected: {exc}")
        out[name] = job.to_dict()
    return out


def _parse_set(pairs) -> dict:
    out = {}
    for pair in pairs or []:
        k, sep, v = pair.partition("=")
        if not sep:
            raise SystemExit(f"--set expects key=value, got {pair!r}")
        out[k.strip()] = v
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    for name in ("render", "install", "values"):
        sp = sub.add_parser(name)
        sp.add_argument("--bundle", default=DEFAULT_BUNDLE)
        if name in ("render", "install"):
            sp.add_argument("--set", action="append", dest="sets", metavar="k=v")
        if name == "install":
            sp.add_argument("--server", required=True)
            sp.add_argument("--auth-token-file", default=None)
    args = p.parse_args(argv)

    if args.cmd == "values":
        print(json.dumps(load_bundle(args.bundle).get("values", {}), indent=2))
        return 0

    rendered = render(args.bundle, _parse_set(getattr(args, "sets", None)))
    if args.cmd == "render":
        # one JSON document on stdout, always parseable: a single-template
        # bundle prints its spec bare, multi-template prints {name: spec}
        if len(rendered) == 1:
            print(json.dumps(next(iter(rendered.values())), indent=2))
        else:
            print(json.dumps(rendered, indent=2))
        return 0

    from tf_operator_tpu.api.types import TPUJob
    from tf_operator_tpu.dashboard.client import TPUJobClient
    from tf_operator_tpu.utils.auth import resolve_token

    client = TPUJobClient(
        args.server, token=resolve_token(token_file=args.auth_token_file)
    )
    for name, doc in rendered.items():
        created = client.create(TPUJob.from_dict(doc))
        print(f"{name}: tpujob {created.key()} created (uid {created.metadata.uid})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
