"""Release builder.

Reference parity: py/release.py — clone-at-green, build artifact, publish
(GCB + helm there). The TPU-native artifact is a versioned source tarball
(git archive of HEAD) whose smoke test proves it is self-contained: extract
to a clean dir, import the package, compile the native supervisor, run a
unit probe — all from the artifact, never from the working tree.

Usage:
    python -m tools.release build [--out-dir dist]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tarfile
import tempfile
import time

import tf_operator_tpu

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def git_sha() -> str:
    from tf_operator_tpu.utils.version import git_sha as _sha

    # honor_env=False: the manifest must name the HEAD git-archive packs,
    # not a TPUJOB_GIT_SHA baked into the surrounding environment.
    return _sha(length=12, honor_env=False) or "unknown"


def build(args) -> int:
    os.makedirs(args.out_dir, exist_ok=True)
    version = tf_operator_tpu.__version__
    sha = git_sha()
    name = f"tf-operator-tpu-{version}+{sha}"
    tarball = os.path.join(args.out_dir, f"{name}.tar.gz")

    r = subprocess.run(
        ["git", "archive", "--format=tar.gz", f"--prefix={name}/",
         "-o", tarball, "HEAD"],
        cwd=REPO_ROOT, capture_output=True, text=True,
    )
    if r.returncode != 0:
        print(f"git archive failed: {r.stderr}", file=sys.stderr)
        return 1

    manifest = {
        "name": name,
        "version": version,
        "git_sha": sha,
        "built_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "artifact": os.path.basename(tarball),
    }
    with open(os.path.join(args.out_dir, f"{name}.json"), "w") as f:
        json.dump(manifest, f, indent=2)

    if not args.skip_smoke:
        rc = smoke_test(tarball, name)
        if rc != 0:
            return rc
    print(f"release ok: {tarball}")
    return 0


def smoke_test(tarball: str, name: str) -> int:
    """Prove the artifact is self-contained (py/release.py's build-then-test
    discipline): extract, import, native build, tiny API round trip."""
    tmp = tempfile.mkdtemp(prefix="tpujob-release-")
    try:
        with tarfile.open(tarball) as tf:
            tf.extractall(tmp, filter="data")
        root = os.path.join(tmp, name)
        probe = (
            "import tf_operator_tpu, json;"
            "from tf_operator_tpu.api.types import TPUJob;"
            "from tf_operator_tpu.runtime.native import ensure_built;"
            "ensure_built();"
            "from tests.test_api_types import make_job;"
            "j = make_job();"
            "assert TPUJob.from_dict(j.to_dict()).to_dict() == j.to_dict();"
            "print('artifact smoke ok', tf_operator_tpu.__version__)"
        )
        env = dict(os.environ, PYTHONPATH=root)
        r = subprocess.run(
            [sys.executable, "-c", probe], cwd=root, env=env,
            capture_output=True, text=True, timeout=300,
        )
        sys.stdout.write(r.stdout)
        if r.returncode != 0:
            print(f"artifact smoke FAILED:\n{r.stderr}", file=sys.stderr)
            return 1
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpujob-release")
    p.add_argument("command", choices=("build",))
    p.add_argument("--out-dir", default=os.path.join(REPO_ROOT, "dist"))
    p.add_argument("--skip-smoke", action="store_true")
    args = p.parse_args(argv)
    return {"build": build}[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
