"""Operator image builder.

Reference parity: ``py/build_and_push_image.py`` (176 LoC) +
``build/images/tf_operator/build_and_push.py`` — stage a build context,
derive the tag from the git sha, invoke the container builder, optionally
push. Here the context is the release archive (tools/release.py), the
Dockerfile is ``build/Dockerfile``, and when no container runtime exists
(this dev image has none) ``--dry-run`` emits the exact commands, keeping
the tool testable hermetically — the same posture as the reference's GCB
path, which also only *drives* an external builder.

Usage:
    python -m tools.build_image [--registry REG] [--tag TAG] [--push]
                                [--dry-run] [--context-dir DIR]
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import tarfile
import tempfile

from tools.release import REPO_ROOT, git_sha


def find_builder() -> str | None:
    for b in ("docker", "podman"):
        if shutil.which(b):
            return b
    return None


def stage_context(context_dir: str) -> str:
    """Materialize a clean build context: git archive of HEAD + Dockerfile
    at its root (the reference stages into a scratch dir the same way).
    An existing context dir is wiped first — stale files from an earlier
    commit must not ship in the image."""
    if os.path.isdir(context_dir):
        shutil.rmtree(context_dir)
    os.makedirs(context_dir, exist_ok=True)
    tar_path = os.path.join(context_dir, "src.tar")
    r = subprocess.run(
        ["git", "archive", "--format=tar", "-o", tar_path, "HEAD"],
        cwd=REPO_ROOT, capture_output=True, text=True,
    )
    if r.returncode != 0:
        raise RuntimeError(f"git archive failed: {r.stderr}")
    with tarfile.open(tar_path) as tf:
        tf.extractall(context_dir, filter="data")
    os.unlink(tar_path)
    shutil.copy(
        os.path.join(REPO_ROOT, "build", "Dockerfile"),
        os.path.join(context_dir, "Dockerfile"),
    )
    return context_dir


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="build_image")
    p.add_argument("--registry", default="local",
                   help="image registry/repo prefix (reference: GCR project)")
    p.add_argument("--tag", default=None,
                   help="image tag; default v<sha> like the reference")
    p.add_argument("--push", action="store_true")
    p.add_argument("--dry-run", action="store_true",
                   help="stage the context and print the commands only")
    p.add_argument("--context-dir", default=None)
    args = p.parse_args(argv)

    tag = args.tag or f"v-{git_sha()}"
    image = f"{args.registry}/tf-operator-tpu:{tag}"
    ctx = args.context_dir or tempfile.mkdtemp(prefix="tpujob-image-")
    stage_context(ctx)

    builder = find_builder()
    cmds = [[builder or "docker", "build", "-t", image, ctx]]
    if args.push:
        cmds.append([builder or "docker", "push", image])

    if args.dry_run or builder is None:
        if builder is None and not args.dry_run:
            print("no container runtime found; dry run:", file=sys.stderr)
        print(f"context: {ctx}")
        for c in cmds:
            print("$ " + " ".join(c))
        return 0

    for c in cmds:
        r = subprocess.run(c)
        if r.returncode != 0:
            return r.returncode
    print(f"built {image}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
