"""Headline benchmark: ResNet-50 training throughput on the attached TPU.

Prints ONE JSON line:
  {"metric": "resnet50_images_per_sec_per_chip", "value": N,
   "unit": "images/sec/chip", "vs_baseline": MFU/0.50, ...}

The reference publishes no numbers (BASELINE.md); the driver-supplied north
star is ResNet-50 at >=50% MFU, so ``vs_baseline`` is achieved-MFU / 0.50 —
1.0 means the target is met.

Extra diagnostic fields beyond the required four are included (mfu,
step_time, batch, device) for the record; consumers key on the first four.

``BENCH_MODEL=bert`` (or any transformer preset name) benches the LM
training path instead — flash-attention transformer, tokens/sec/chip,
same single-JSON-line contract.

MFU basis (changed r3): LM rows report ``mfu_attn`` (6ND + the 12·L·t·d
attention matmul term — the honest number at long context) and
``mfu_6nd`` (parameter-only, comparable to BENCH_r01/r02 rows and
scaling-law tables). ``mfu``/``vs_baseline`` follow mfu_attn from r3 on —
comparing them against pre-r3 archives across an accounting boundary
over-reads the gain by the attention fraction (~6% at t=512, ~2x at
t=8192 on gpt-small); use mfu_6nd for those diffs.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def run_timed_steps(trainer, state, pull, steps: int, stream: bool,
                    step_hint_s: float = 0.0):
    """The one timed-region protocol both benches share: optional device
    loop (BENCH_DEVICE_LOOP=K: K steps per compiled call, 0 disables; the
    K-step program compiles OUTSIDE the timed region), profiler capture
    outside the timing, one host fetch at the end. Returns
    (state, metrics, steps_run, step_s).

    The device loop exists to amortize per-step dispatch (~5 ms through
    the remote tunnel) — a win for small-step models (gpt-small, 10 ms
    steps: +7%) but a measured LOSS for big ones (gqa-2048, 0.6 s steps:
    the K-step scan's carry copies cost 6.3%, r4). Unless
    BENCH_DEVICE_LOOP is set explicitly, the loop auto-disables when the
    caller's warmup-measured step time exceeds 100 ms, where dispatch is
    <1% and the scan only costs."""
    import time

    from tf_operator_tpu.train.profile import profile_ctx

    k_env = os.environ.get("BENCH_DEVICE_LOOP")
    k = min(int(k_env if k_env is not None else "10"), steps)
    if k_env is None and step_hint_s > 0.1:
        k = 0
    device_loop = k > 1 and not stream
    full, rem = divmod(steps, k) if device_loop else (0, steps)
    if device_loop:
        # compile the K-step program OUTSIDE the timed region (the
        # single-step program is already warm from the caller's warmup)
        state, metrics = trainer.multi_step(state, pull(), k)
        _ = float(metrics["loss"])
    with profile_ctx(os.environ.get("BENCH_PROFILE")):
        t0 = time.perf_counter()
        for _ in range(full):
            state, metrics = trainer.multi_step(state, pull(), k)
        for _ in range(rem):  # BENCH_STEPS is honored exactly
            state, metrics = trainer.step(state, pull())
        _ = float(metrics["loss"])
        step_s = (time.perf_counter() - t0) / steps
    return state, metrics, steps, step_s


def start_precompile(trainer, batch_spec):
    """Kick off the background step compile (r4 submit overlap) — called
    BEFORE batch staging so the step program's trace+compile+upload
    overlaps the batch upload AND the init phase. BENCH_OVERLAP=0
    restores the serial path for A/B."""
    if os.environ.get("BENCH_OVERLAP", "1") != "1":
        return None
    if os.environ.get("BENCH_FUSED_SUBMIT", "0") == "1":
        return None
    return trainer.precompile_step_async(batch_spec)


def run_first_step(trainer, pull, breakdown, t_submit, pre=None):
    """Submit-phase protocol shared by both benches: the split
    init-then-step path by default (two programs, phase-timed, with the
    step program compiling on ``pre``'s background thread — r3 measured
    the two phases strictly serialized at 5.0 s + 9.9 s), or the fused
    single-program path under BENCH_FUSED_SUBMIT=1 (Trainer.init_and_step
    — one executable upload; measured no net win through this tunnel, see
    BASELINE.md submit section). Returns (state, metrics). float() forces
    a host fetch — plain block_until_ready does not synchronize through
    the remote TPU tunnel."""
    import jax

    if os.environ.get("BENCH_FUSED_SUBMIT", "0") == "1":
        state, metrics = trainer.init_and_step(jax.random.PRNGKey(0), pull())
        _ = float(metrics["loss"])
        breakdown["fused_init_first_step_s"] = round(
            time.perf_counter() - t_submit - breakdown["stage_batch_dispatch_s"], 2
        )
    else:
        t0 = time.perf_counter()
        state = trainer.init(jax.random.PRNGKey(0))
        breakdown["init_dispatch_s"] = round(time.perf_counter() - t0, 2)
        if pre is not None:
            t0 = time.perf_counter()
            pre.join()
            breakdown["step_compile_join_s"] = round(time.perf_counter() - t0, 2)
        t0 = time.perf_counter()
        state, metrics = trainer.step(state, pull())
        _ = float(metrics["loss"])
        breakdown["first_step_s"] = round(time.perf_counter() - t0, 2)
    return state, metrics


def bench_lm(model: str) -> None:
    """Transformer pretraining throughput (BASELINE.json BERT/Llama configs)."""
    from tf_operator_tpu.train.compile_cache import enable as enable_compile_cache

    cache_dir = enable_compile_cache()

    import jax

    from tf_operator_tpu.models.transformer import (
        init_transformer,
        lm_loss,
        preset,
        transformer_logical_axes,
    )
    from tf_operator_tpu.parallel import build_mesh
    from tf_operator_tpu.train.metrics import (
        mfu,
        transformer_train_flops,
        transformer_train_flops_exact,
    )
    from tf_operator_tpu.train.trainer import Trainer, TrainerConfig

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    n_chips = jax.device_count()
    name = {"bert": "bert-base", "gpt": "gpt-small"}.get(model, model)

    batch = int(os.environ.get("BENCH_BATCH", "32" if on_tpu else str(n_chips)))
    seq = int(os.environ.get("BENCH_SEQ", "512" if on_tpu else "128"))
    steps = int(os.environ.get("BENCH_STEPS", "30" if on_tpu else "4"))
    attn = os.environ.get("BENCH_ATTN", "flash" if on_tpu else "dense")
    # Remat matters: full remat frees enough HBM for 2x the batch (b=32
    # w/ remat: 36.5% MFU vs b=16 w/o: 34.0% on v5e — no-remat b=32 OOMs
    # even with the fused loss, 19.2G/15.75G). "dots" (selective
    # checkpointing) measured *worse* than full remat here (35.8%) — the
    # +3.6G of saved dot outputs cost more in scheduling than the saved
    # recompute, so full remat stays the default. BENCH_REMAT=1|0|full|none|dots.
    remat_env = os.environ.get("BENCH_REMAT", "1")
    remat = {"1": True, "0": False, "full": True, "none": False}.get(
        remat_env, remat_env
    )

    # BENCH_ACCUM=K: gradient accumulation over K microbatches — the
    # north-star d>=2048 configs need it to fit adamw state + activations
    # in one chip's HBM (tools/memplan sizes the combination).
    accum = int(os.environ.get("BENCH_ACCUM", "1"))

    overrides = {}
    # BENCH_CF: MoE capacity factor (expert rows = cf·k·T; FLOP padding
    # scales with it, as does drop_frac — see BASELINE.md MoE rows).
    if os.environ.get("BENCH_CF"):
        overrides["capacity_factor"] = float(os.environ["BENCH_CF"])
    # BENCH_MOE_DISPATCH=ragged: padding-free grouped-matmul experts (r5).
    if os.environ.get("BENCH_MOE_DISPATCH"):
        overrides["moe_dispatch"] = os.environ["BENCH_MOE_DISPATCH"]
    cfg = preset(name, max_seq=seq, attn_impl=attn, remat=remat, **overrides)
    mesh = build_mesh({"dp": n_chips})

    def loss_fn(params, tokens, extra):
        del extra
        return lm_loss(params, tokens, cfg, mesh=mesh)

    trainer = Trainer(
        mesh,
        loss_fn=loss_fn,
        init_fn=lambda k: init_transformer(k, cfg),
        logical_axes=transformer_logical_axes(cfg),
        config=TrainerConfig(optimizer="adamw", learning_rate=1e-4,
                             grad_accum=accum, fast_init_rng=True),
    )
    # BENCH_DATA=stream: feed every step a fresh host batch through the
    # prefetching DeviceLoader instead of one resident device batch —
    # stream ≈ fixed is the proof the input pipeline stays off the step's
    # critical path.
    stream = os.environ.get("BENCH_DATA", "fixed") == "stream"
    loader = None
    if stream:
        # Built BEFORE t_submit: synthetic-data generation must not skew
        # the submit→first-step comparison against fixed mode.
        from tf_operator_tpu.train.data import DeviceLoader, SyntheticTokens

        loader = DeviceLoader(
            SyntheticTokens(batch, n=4 * batch, seq_len=seq, vocab=cfg.vocab),
            trainer.batch_sharding,
        )

        def pull():
            return next(loader)["tokens"]

    t_submit = time.perf_counter()
    breakdown = {}
    pre = start_precompile(
        trainer, jax.ShapeDtypeStruct((batch, seq), "int32")
    )
    if not stream:
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab),
            trainer.batch_sharding,
        )

        def pull():
            return tokens

    breakdown["stage_batch_dispatch_s"] = round(time.perf_counter() - t_submit, 2)
    try:
        state, metrics = run_first_step(trainer, pull, breakdown, t_submit, pre)
        first_step_s = time.perf_counter() - t_submit
        # 5 warmup steps, one fetch: the hint carries the fixed ~70-100 ms
        # tunnel sync divided by 5 (≤20 ms) — at 2 steps the sync term
        # alone could push a 44 ms step past the 100 ms loop-disable
        # threshold and flip the headline protocol run-to-run.
        t_warm = time.perf_counter()
        for _ in range(5):
            state, metrics = trainer.step(state, pull())
        _ = float(metrics["loss"])
        warm_step_s = (time.perf_counter() - t_warm) / 5

        state, metrics, steps, step_s = run_timed_steps(
            trainer, state, pull, steps, stream, step_hint_s=warm_step_s
        )
    finally:
        if loader is not None:
            loader.close()

    params = cfg.n_params()
    tokens_per_step = batch * seq
    # active params: for top-1 MoE only one expert's FLOPs count per token.
    # Two MFU readings (VERDICT r2 #3): mfu_6nd is the parameter-only rule
    # (comparable to scaling-law tables); mfu_attn adds the attention
    # matmul term (12·L·t·d per token) and is the honest number at long
    # context — the headline mfu/vs_baseline use it.
    flops_6nd = transformer_train_flops(cfg.n_active_params(), tokens_per_step)
    flops_exact = transformer_train_flops_exact(
        cfg.n_active_params(), tokens_per_step, cfg.n_layers, cfg.d_model, seq
    )
    achieved_6nd = mfu(flops_6nd, step_s, n_chips)
    achieved = mfu(flops_exact, step_s, n_chips)
    print(
        json.dumps(
            {
                "metric": f"{name}_tokens_per_sec_per_chip",
                "value": round(tokens_per_step / step_s / n_chips, 1),
                "unit": "tokens/sec/chip",
                "vs_baseline": round(achieved / 0.50, 4),
                "mfu": round(achieved, 4),
                "mfu_attn": round(achieved, 4),
                "mfu_6nd": round(achieved_6nd, 4),
                "step_time_s": round(step_s, 5),
                "batch": batch,
                "seq_len": seq,
                "grad_accum": accum,
                "attn": attn,
                "n_params": params,
                "n_chips": n_chips,
                "device": getattr(dev, "device_kind", dev.platform),
                "submit_to_first_step_s": round(first_step_s, 2),
                "submit_breakdown": breakdown,
                "compile_cache": bool(cache_dir),
                "loss": round(float(metrics["loss"]), 4),
            }
        )
    )


def bench_resnet_bn_ab() -> None:
    """Same-INVOCATION A/B of the BN stats-gradient modes (VERDICT r3
    #3): var and exact trainers built side by side, timed regions
    interleaved var/exact/var/exact on the same chip minutes apart — the
    receipt chip-day variance cannot fake. One JSON line with both."""
    from tf_operator_tpu.train.compile_cache import enable as enable_compile_cache

    enable_compile_cache()

    import dataclasses

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.resnet import ResNetConfig, init_resnet, resnet_forward
    from tf_operator_tpu.train.metrics import mfu, resnet_train_flops
    from tf_operator_tpu.train.trainer import Trainer, TrainerConfig
    from tf_operator_tpu.parallel import build_mesh

    dev = jax.devices()[0]
    n_chips = jax.device_count()
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    image_size = int(os.environ.get("BENCH_IMAGE", "224"))
    steps = int(os.environ.get("BENCH_STEPS", "15"))
    mesh = build_mesh({"dp": n_chips})

    def make_trainer(mode):
        cfg = dataclasses.replace(
            ResNetConfig.resnet50(), bn_stats_stop_gradient=mode
        )

        def loss_fn(params, batch_data, st):
            images, labels = batch_data
            logits, new_state = resnet_forward(params, st, images, cfg, train=True)
            logp = jax.nn.log_softmax(logits)
            loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
            return loss, new_state

        return Trainer(
            mesh,
            loss_fn=loss_fn,
            init_fn=lambda k: init_resnet(k, cfg),
            config=TrainerConfig(optimizer="sgd", learning_rate=0.1,
                                 grad_clip=None, fast_init_rng=True),
        ), cfg

    arms = {}
    images = labels = None
    for mode in ("var", False):
        name = "var" if mode == "var" else "exact"
        trainer, cfg = make_trainer(mode)
        if images is None:
            images = jax.device_put(
                jax.random.normal(
                    jax.random.PRNGKey(1), (batch, image_size, image_size, 3)
                ),
                trainer.batch_sharding,
            )
            labels = jax.device_put(
                jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 1000),
                trainer.batch_sharding,
            )
        state = trainer.init(jax.random.PRNGKey(0))
        for _ in range(3):  # compile + warm
            state, m = trainer.step(state, (images, labels))
        _ = float(m["loss"])
        arms[name] = {"trainer": trainer, "state": state, "cfg": cfg,
                      "times": []}
    # interleave: var, exact, var, exact — same chip, minutes apart
    for _ in range(2):
        for name in ("var", "exact"):
            a = arms[name]
            t0 = time.perf_counter()
            st = a["state"]
            for _ in range(steps):
                st, m = a["trainer"].step(st, (images, labels))
            _ = float(m["loss"])
            a["state"] = st
            a["times"].append((time.perf_counter() - t0) / steps)
    fwd_flops = arms["var"]["cfg"].flops_per_image(image_size)
    train_flops = resnet_train_flops(fwd_flops, batch)
    out = {
        "metric": "resnet50_bn_ab_step_time_s",
        "value": round(min(arms["var"]["times"]), 5),
        "unit": "s/step (var mode, best of interleaved runs)",
        "vs_baseline": round(
            min(arms["exact"]["times"]) / min(arms["var"]["times"]), 4),
        "interleave_order": "var,exact,var,exact",
        "n_chips": n_chips,
        "batch": batch,
        "device": getattr(dev, "device_kind", dev.platform),
    }
    for name in ("var", "exact"):
        ts = arms[name]["times"]
        out[f"{name}_step_time_s"] = [round(t, 5) for t in ts]
        out[f"{name}_mfu"] = round(mfu(train_flops, min(ts), n_chips), 4)
    print(json.dumps(out))


def bench_submit_ab() -> None:
    """Same-SESSION submit→first-step repeats (r5, VERDICT r4 #5): the
    r4 driver capture (11.01 s) contradicted the documented 8.4-9.3 s
    range, and tunnel throughput varies 2-3x run to run — so the claim
    needs the spread, pinned minutes apart on the same chip, not a
    single draw. Runs BENCH_SUBMIT_AB child bench processes (fresh
    interpreter each — submit latency includes imports and trace) and
    prints ONE JSON line with every draw + min/median/max. BENCH_MODEL
    picks the config (resnet50 default)."""
    import statistics
    import subprocess

    n = int(os.environ.get("BENCH_SUBMIT_AB", "4"))
    env = dict(os.environ, BENCH_STEPS="1", BENCH_NORTHSTAR="0",
               BENCH_SUBMIT_AB="0")
    draws, breakdowns = [], []
    for _ in range(n):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=560,
        )
        if proc.returncode != 0 or not proc.stdout.strip():
            # surface the child's failure instead of an opaque
            # IndexError — tunnel drops are exactly what the A/B probes
            sys.exit(
                f"submit A/B child failed rc={proc.returncode}:\n"
                + proc.stderr[-2000:]
            )
        row = json.loads(proc.stdout.strip().splitlines()[-1])
        draws.append(row["submit_to_first_step_s"])
        breakdowns.append(row.get("submit_breakdown", {}))
    print(json.dumps({
        "metric": "submit_to_first_step_s_ab",
        "value": round(statistics.median(draws), 2),
        "unit": "s (median of same-session draws)",
        "vs_baseline": round(8.0 / statistics.median(draws), 4),
        "model": os.environ.get("BENCH_MODEL", "resnet50"),
        "draws": draws,
        "min": min(draws),
        "max": max(draws),
        "breakdowns": breakdowns,
    }))


def main() -> None:
    if os.environ.get("BENCH_SUBMIT_AB", "0") not in ("0", ""):
        bench_submit_ab()
        return
    if os.environ.get("BENCH_BN_AB", "0") == "1":
        bench_resnet_bn_ab()
        return
    model = os.environ.get("BENCH_MODEL", "resnet50").lower()
    if model not in ("resnet50", "resnet"):
        from tf_operator_tpu.models.transformer import PRESETS

        known = {"bert", "gpt", *PRESETS}
        if model not in known:
            sys.exit(
                f"unknown BENCH_MODEL {model!r}; choose resnet50 or one of: "
                + ", ".join(sorted(known))
            )
        bench_lm(model)
        return
    from tf_operator_tpu.train.compile_cache import enable as enable_compile_cache

    cache_dir = enable_compile_cache()

    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.resnet import ResNetConfig, init_resnet, resnet_forward
    from tf_operator_tpu.train.metrics import mfu, resnet_train_flops
    from tf_operator_tpu.train.trainer import Trainer, TrainerConfig
    from tf_operator_tpu.parallel import build_mesh

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    n_chips = jax.device_count()

    batch = int(os.environ.get("BENCH_BATCH", "128" if on_tpu else "16"))
    image_size = int(os.environ.get("BENCH_IMAGE", "224" if on_tpu else "64"))
    steps = int(os.environ.get("BENCH_STEPS", "30" if on_tpu else "4"))
    # 5 warmup steps: the loop-disable hint divides the fixed ~70-100 ms
    # tunnel sync across them — at 2, that term alone could push the
    # 44 ms ResNet step past the 100 ms threshold (see bench_lm).
    warmup = 5

    cfg = ResNetConfig.resnet50()
    # BN-stats levers (BASELINE.md "BN decomposition"). Default is the
    # config default — "var" since r3 (stop the variance gradient only:
    # ~+5 MFU pts (37.4% vs 31-32% exact), accuracy-validated on real data).
    # "exact"/"1" restores exact BN, "0" stops both stats gradients
    # (diverges at lr 0.1 on synthetic — measurement only). BENCH_FUSED_1X1=1
    # routes 1x1 convs through the Pallas fused matmul+stats kernel
    # (measured SLOWER than XLA convs — the documented negative result).
    import dataclasses

    sg_env = os.environ.get("BENCH_BN_STATS_GRAD", "var")
    if sg_env == "0":
        cfg = dataclasses.replace(cfg, bn_stats_stop_gradient=True)
    elif sg_env in ("1", "exact"):
        cfg = dataclasses.replace(cfg, bn_stats_stop_gradient=False)
    elif sg_env == "var":
        cfg = dataclasses.replace(cfg, bn_stats_stop_gradient="var")
    else:
        # a typo'd value silently landing on the (faster) var default
        # would corrupt an intended exact-BN measurement by +5 MFU pts
        sys.exit(f"unknown BENCH_BN_STATS_GRAD={sg_env!r}; use exact|1|0|var")
    if os.environ.get("BENCH_FUSED_1X1", "0") == "1":
        cfg = dataclasses.replace(cfg, fused_1x1=True)
    mesh = build_mesh({"dp": n_chips})

    def init_fn(key):
        return init_resnet(key, cfg)

    def loss_fn(params, batch_data, state):
        images, labels = batch_data
        logits, new_state = resnet_forward(params, state, images, cfg, train=True)
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
        return loss, new_state

    trainer = Trainer(
        mesh,
        loss_fn=loss_fn,
        init_fn=init_fn,
        config=TrainerConfig(optimizer="sgd", learning_rate=0.1, grad_clip=None,
                             fast_init_rng=True),
    )
    # BENCH_DATA=stream: fresh host batches through the prefetching
    # DeviceLoader (77 MB/step at b=128/224²) — stream ≈ fixed proves the
    # input pipeline overlaps the step instead of serializing on it.
    stream = os.environ.get("BENCH_DATA", "fixed") == "stream"
    loader = None
    if stream:
        # Built BEFORE t_submit (data generation isn't submit latency).
        from tf_operator_tpu.train.data import DeviceLoader, SyntheticImages

        loader = DeviceLoader(
            SyntheticImages(
                batch, n=4 * batch, image_size=image_size,
                num_classes=cfg.num_classes,
            ),
            trainer.batch_sharding,
        )

        def pull():
            b = next(loader)
            return b["image"], b["label"]

    t_submit = time.perf_counter()
    breakdown = {}
    pre = start_precompile(
        trainer,
        (
            jax.ShapeDtypeStruct((batch, image_size, image_size, 3), "float32"),
            jax.ShapeDtypeStruct((batch,), "int32"),
        ),
    )

    if not stream:
        # Staged FIRST: device_put dispatches the (77 MB at b=128) upload
        # asynchronously so it streams while the fused program traces.
        images = jax.device_put(
            jax.random.normal(jax.random.PRNGKey(1), (batch, image_size, image_size, 3)),
            trainer.batch_sharding,
        )
        labels = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, cfg.num_classes),
            trainer.batch_sharding,
        )

        def pull():
            return images, labels

    breakdown["stage_batch_dispatch_s"] = round(time.perf_counter() - t_submit, 2)
    try:
        state, metrics = run_first_step(trainer, pull, breakdown, t_submit, pre)
        first_step_s = time.perf_counter() - t_submit
        t_warm = time.perf_counter()
        for _ in range(warmup):
            state, metrics = trainer.step(state, pull())
        _ = float(metrics["loss"])
        warm_step_s = (time.perf_counter() - t_warm) / warmup

        # Timed region: steps dispatched back-to-back (donation chains them
        # on device), ONE sync at the end — per-step host syncs would
        # serialize on tunnel RTT and measure latency, not throughput.
        state, metrics, steps, step_s = run_timed_steps(
            trainer, state, pull, steps, stream, step_hint_s=warm_step_s
        )
    finally:
        if loader is not None:
            loader.close()
    images_per_sec = batch / step_s
    images_per_sec_per_chip = images_per_sec / n_chips
    fwd_flops = cfg.flops_per_image(image_size)
    train_flops = resnet_train_flops(fwd_flops, batch)
    achieved_mfu = mfu(train_flops, step_s, n_chips)

    # Measured v5e ceilings (BASELINE.md "roofline decomposition", measured
    # via tools/roofline --mode conv + the frozen-stats ablation): the
    # conv-only (BN-free) network fwd+bwd sustains 45.3% of peak; the full
    # step with BN statistics FROZEN (everything XLA can fuse, stats
    # barrier removed) reaches 39.4%. vs_ceiling judges the exact-BN step
    # against the latter — the achievable-step ceiling.
    ceiling = float(os.environ.get("BENCH_CEILING", "0.394")) if on_tpu else None

    out = {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(images_per_sec_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(achieved_mfu / 0.50, 4),
        "mfu": round(achieved_mfu, 4),
        "step_time_s": round(step_s, 5),
        "batch": batch,
        "image_size": image_size,
        "n_chips": n_chips,
        "device": getattr(dev, "device_kind", dev.platform),
        "submit_to_first_step_s": round(first_step_s, 2),
        "submit_breakdown": breakdown,
        "compile_cache": bool(cache_dir),
        "loss": round(float(metrics["loss"]), 4),
    }
    if ceiling:
        out["ceiling_mfu"] = ceiling
        out["vs_ceiling"] = round(achieved_mfu / ceiling, 4)
    if on_tpu and os.environ.get("BENCH_NORTHSTAR", "1") != "0":
        out["northstar_lm"] = _northstar_row()
    print(json.dumps(out))


def _northstar_row():
    """Run the north-star-shape LM bench (gqa-2048: d_model=2048 GQA,
    the regime the 50%-MFU target presumes — BASELINE.md "north-star
    shapes") as a subprocess and return its parsed JSON row, condensed.
    A subprocess so its 15.7 GB HBM plan starts from an empty chip
    rather than fighting the ResNet run's live buffers; any failure is
    reported in-band instead of sinking the headline."""
    import subprocess

    # Pin every measurement-affecting knob: the row must be THE
    # canonical north-star config even when the parent run was invoked
    # with stream/profile/remat overrides meant for the ResNet headline.
    env = dict(
        os.environ,
        BENCH_MODEL="gqa-2048",
        BENCH_BATCH="6",
        BENCH_SEQ="2048",
        BENCH_STEPS="20",
        BENCH_NORTHSTAR="0",
        BENCH_ATTN="flash",
        # r5: selective remat — save the post-attention residual stream
        # (tools/rematsweep winner: 57.3% exact / 50.9% 6ND vs full
        # remat's 55.9/49.6 at the same max-fit batch)
        BENCH_REMAT="save_mid",
        BENCH_DATA="fixed",
        BENCH_ACCUM="1",
    )
    env.pop("BENCH_PROFILE", None)  # parent+child tracing one dir collide
    env.pop("BENCH_DEVICE_LOOP", None)  # auto-disables at this step size
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=560,
        )
        if proc.returncode != 0:
            return {"error": f"rc={proc.returncode}: {proc.stderr[-300:]}"}
        row = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as exc:  # noqa: BLE001 — diagnostic row, never fatal
        return {"error": f"{type(exc).__name__}: {exc}"[:300]}
    row.pop("submit_breakdown", None)
    return row


if __name__ == "__main__":
    main()
