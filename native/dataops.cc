// Native host-side input-pipeline ops for tf_operator_tpu.
//
// The reference operator has no data plane at all (SURVEY.md §2: the user
// container owns input); this framework's workload library does, and its
// augmentation (train/data.py augment_images) is per-example branchy
// memory work — exactly what a compiled loop with threads does well while
// the DeviceLoader's prefetch thread hides it behind the step. The
// randomness (crop offsets, flip flags) stays in numpy so the Python
// fallback and this path produce bit-identical outputs from one RNG
// stream; this library only does the deterministic gather:
//
//   pad-crop: output row y of image i reads padded row y+dy[i], i.e.
//   source row y+dy[i]-pad (zero outside [0,h)); columns likewise — the
//   overlapping segment is one memcpy, the borders are memset.
//   flip: reverse the row's pixels (pixel = c*elem bytes) during the
//   final write, so flipped images cost no extra pass.
//
// Layout contract: images are C-contiguous [b, h, w, pixel_bytes] where
// pixel_bytes folds trailing channel dims and element size (any dtype —
// the op is pure byte movement). Threads split the batch.

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

namespace {

struct AugmentArgs {
  const uint8_t* in;
  uint8_t* out;
  int64_t b, h, w, pixel;  // pixel = bytes per pixel
  int64_t pad;
  const int32_t* dy;
  const int32_t* dx;
  const uint8_t* flip;
};

void augment_range(const AugmentArgs& a, int64_t i0, int64_t i1,
                   std::vector<uint8_t>* rowbuf) {
  const int64_t row_bytes = a.w * a.pixel;
  rowbuf->resize(static_cast<size_t>(row_bytes));
  uint8_t* tmp = rowbuf->data();
  for (int64_t i = i0; i < i1; ++i) {
    const uint8_t* img = a.in + i * a.h * row_bytes;
    uint8_t* dst_img = a.out + i * a.h * row_bytes;
    const int64_t dy = a.pad ? a.dy[i] : 0;
    const int64_t dx = a.pad ? a.dx[i] : 0;
    const bool flip = a.flip && a.flip[i];
    // source col for out col x is x + dx - pad; valid out cols:
    const int64_t x_lo = std::max<int64_t>(0, a.pad - dx);
    const int64_t x_hi = std::min<int64_t>(a.w, a.w + a.pad - dx);
    for (int64_t y = 0; y < a.h; ++y) {
      const int64_t ys = y + dy - a.pad;
      uint8_t* dst = dst_img + y * row_bytes;
      if (ys < 0 || ys >= a.h || x_hi <= x_lo) {
        std::memset(dst, 0, static_cast<size_t>(row_bytes));
        continue;
      }
      const uint8_t* src_row = img + ys * row_bytes;
      uint8_t* row = flip ? tmp : dst;
      if (x_lo > 0) std::memset(row, 0, static_cast<size_t>(x_lo * a.pixel));
      std::memcpy(row + x_lo * a.pixel,
                  src_row + (x_lo + dx - a.pad) * a.pixel,
                  static_cast<size_t>((x_hi - x_lo) * a.pixel));
      if (x_hi < a.w)
        std::memset(row + x_hi * a.pixel, 0,
                    static_cast<size_t>((a.w - x_hi) * a.pixel));
      if (flip) {
        for (int64_t x = 0; x < a.w; ++x)
          std::memcpy(dst + x * a.pixel, tmp + (a.w - 1 - x) * a.pixel,
                      static_cast<size_t>(a.pixel));
      }
    }
  }
}

}  // namespace

extern "C" {

// Random-crop (from virtual zero padding) + horizontal flip. dy/dx are
// per-image offsets in [0, 2*pad] (ignored when pad == 0; may be null);
// flip is a per-image 0/1 mask (null = no flips). n_threads <= 0 picks
// hardware concurrency. Returns 0 on success, nonzero on bad arguments.
int tpuj_augment(const void* in, void* out, int64_t b, int64_t h, int64_t w,
                 int64_t pixel_bytes, int64_t pad, const int32_t* dy,
                 const int32_t* dx, const uint8_t* flip, int n_threads) {
  if (!in || !out || b < 0 || h <= 0 || w <= 0 || pixel_bytes <= 0 || pad < 0)
    return 1;
  if (pad > 0 && (!dy || !dx)) return 2;
  AugmentArgs a{static_cast<const uint8_t*>(in), static_cast<uint8_t*>(out),
                b, h, w, pixel_bytes, pad, dy, dx, flip};
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  int nt = n_threads > 0 ? n_threads : (hw > 0 ? hw : 1);
  nt = static_cast<int>(std::min<int64_t>(nt, std::max<int64_t>(b, 1)));
  if (nt <= 1) {
    std::vector<uint8_t> buf;
    augment_range(a, 0, b, &buf);
    return 0;
  }
  std::vector<std::thread> threads;
  threads.reserve(static_cast<size_t>(nt));
  const int64_t chunk = (b + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    const int64_t i0 = t * chunk;
    const int64_t i1 = std::min<int64_t>(b, i0 + chunk);
    if (i0 >= i1) break;
    threads.emplace_back([&a, i0, i1]() {
      std::vector<uint8_t> buf;
      augment_range(a, i0, i1, &buf);
    });
  }
  for (auto& th : threads) th.join();
  return 0;
}

}  // extern "C"
