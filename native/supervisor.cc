// tpujob native process supervisor.
//
// The compiled half of the runtime's kubelet analogue: spawn (fork/execve
// with setsid + log redirection), monitor (waitpid with a thread-safe
// completion registry), and kill (process-group signals with a
// grace-then-SIGKILL escalation). The Go reference delegates all of this to
// the kubelet and only *observes* container termination states
// (pkg/trainer/replicas.go:310-363, pkg/controller.v2/pod_control.go:54-165);
// on a bare TPU host this library IS the container runtime.
//
// Exit codes are normalized to the shell/k8s convention the exit-code
// taxonomy (pkg/util/train/train_util.go:18-53) is written against:
// 0-255 for normal exits, 128+signal for signal deaths (so SIGKILL -> 137,
// SIGTERM -> 143), never Python's negative-returncode convention.
//
// Thread model: any number of embedding-process threads may call any
// function on any pid concurrently. waitpid(2) reaps exactly once; the
// registry makes wait/poll idempotent afterwards (the losing racer reads
// the winner's recorded status).

#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <stdint.h>
#include <string.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

#include <mutex>
#include <unordered_map>

namespace {

struct Entry {
  bool done = false;
  int code = 0;
};

std::mutex g_mu;
std::unordered_map<long, Entry> g_procs;

int normalize(int status) {
  if (WIFEXITED(status)) return WEXITSTATUS(status);
  if (WIFSIGNALED(status)) return 128 + WTERMSIG(status);
  return 255;  // stopped/continued can't reach here (no WUNTRACED)
}

int record(long pid, int status) {
  std::lock_guard<std::mutex> l(g_mu);
  Entry& e = g_procs[pid];
  e.done = true;
  e.code = normalize(status);
  return e.code;
}

bool lookup(long pid, int* code) {
  std::lock_guard<std::mutex> l(g_mu);
  auto it = g_procs.find(pid);
  if (it != g_procs.end() && it->second.done) {
    *code = it->second.code;
    return true;
  }
  return false;
}

void sleep_ms(long ms) {
  struct timespec ts;
  ts.tv_sec = ms / 1000;
  ts.tv_nsec = (ms % 1000) * 1000000L;
  nanosleep(&ts, nullptr);
}

}  // namespace

extern "C" {

// Spawn argv with envp. The child setsid()s (it owns a fresh process group,
// so supervisor signals never leak in and group kills take the whole
// subtree), redirects stdout+stderr to log_path when given (append mode —
// the kubelet-log analogue the dashboard serves), and chdir()s to workdir
// when given. Returns the pid, or -errno on failure — including exec
// failure, which is reported synchronously through a CLOEXEC pipe instead
// of surfacing as a mysterious exit-127 child.
long tpuj_spawn(const char* const* argv, const char* const* envp,
                const char* workdir, const char* log_path) {
  int logfd = -1;
  if (log_path && log_path[0]) {
    logfd = open(log_path, O_WRONLY | O_CREAT | O_APPEND, 0644);
    if (logfd < 0) return -(long)errno;
  }
  int ep[2];
  if (pipe2(ep, O_CLOEXEC) != 0) {
    int e = errno;
    if (logfd >= 0) close(logfd);
    return -(long)e;
  }
  pid_t pid = fork();
  if (pid < 0) {
    int e = errno;
    if (logfd >= 0) close(logfd);
    close(ep[0]);
    close(ep[1]);
    return -(long)e;
  }
  if (pid == 0) {
    // Child: async-signal-safe calls only until execve.
    setsid();
    if (logfd >= 0) {
      dup2(logfd, 1);
      dup2(logfd, 2);
      close(logfd);
    }
    if (workdir && workdir[0] && chdir(workdir) != 0) {
      int e = errno;
      ssize_t ignored = write(ep[1], &e, sizeof e);
      (void)ignored;
      _exit(127);
    }
    execve(argv[0], const_cast<char* const*>(argv),
           const_cast<char* const*>(envp));
    int e = errno;
    ssize_t ignored = write(ep[1], &e, sizeof e);
    (void)ignored;
    _exit(127);
  }
  if (logfd >= 0) close(logfd);
  close(ep[1]);
  int child_errno = 0;
  ssize_t n;
  do {
    n = read(ep[0], &child_errno, sizeof child_errno);
  } while (n < 0 && errno == EINTR);
  close(ep[0]);
  if (n > 0) {  // exec (or chdir) failed in the child
    int status;
    while (waitpid(pid, &status, 0) < 0 && errno == EINTR) {
    }
    return -(long)child_errno;
  }
  std::lock_guard<std::mutex> l(g_mu);
  g_procs.emplace((long)pid, Entry{});
  return (long)pid;
}

// Blocking wait. Returns the normalized exit code; idempotent (a second
// waiter — or a waiter racing tpuj_terminate — reads the recorded status).
// Returns -ECHILD for a pid this supervisor never spawned.
int tpuj_wait(long pid) {
  int code;
  if (lookup(pid, &code)) return code;
  for (;;) {
    int status;
    pid_t r = waitpid((pid_t)pid, &status, 0);
    if (r == (pid_t)pid) return record(pid, status);
    if (r < 0 && errno == EINTR) continue;
    if (r < 0 && errno == ECHILD) {
      // Another thread won the waitpid race; its record() is imminent.
      for (int i = 0; i < 2000; ++i) {
        if (lookup(pid, &code)) return code;
        sleep_ms(5);
      }
    }
    return -ECHILD;
  }
}

// Nonblocking poll: 1 = exited (*code_out set), 0 = still running,
// negative errno on error.
int tpuj_poll(long pid, int* code_out) {
  int code;
  if (lookup(pid, &code)) {
    *code_out = code;
    return 1;
  }
  int status;
  pid_t r = waitpid((pid_t)pid, &status, WNOHANG);
  if (r == 0) return 0;
  if (r == (pid_t)pid) {
    *code_out = record(pid, status);
    return 1;
  }
  if (errno == ECHILD && lookup(pid, &code)) {  // racing waiter recorded it
    *code_out = code;
    return 1;
  }
  return -(int)errno;
}

// Signal the child's process group (the whole subtree — a training harness
// that forked data-loader children must not leave orphans). No-op once the
// child is recorded dead.
int tpuj_signal(long pid, int sig) {
  int code;
  if (lookup(pid, &code)) return 0;
  if (kill((pid_t)-pid, sig) == 0) return 0;
  if (errno == ESRCH && kill((pid_t)pid, sig) == 0) return 0;
  return -(int)errno;
}

// Graceful stop: SIGTERM, poll up to grace_ms, escalate to SIGKILL.
// Returns the final normalized exit code (143 for a clean SIGTERM death,
// 137 after escalation), or negative errno.
int tpuj_terminate(long pid, int grace_ms) {
  int rc = tpuj_signal(pid, SIGTERM);
  if (rc < 0 && rc != -ESRCH) return rc;
  long waited = 0;
  int code;
  while (waited < grace_ms) {
    int r = tpuj_poll(pid, &code);
    if (r == 1) return code;
    if (r == -ECHILD) {
      // A concurrent tpuj_wait won the waitpid race and its record() has
      // not committed yet; tpuj_wait's registry-poll path resolves it.
      // Returning the raw -ECHILD here would be consumed as an "exit
      // code" and poison the caller's view of a recycled pid.
      return tpuj_wait(pid);
    }
    if (r < 0) return r;
    sleep_ms(10);
    waited += 10;
  }
  tpuj_signal(pid, SIGKILL);
  return tpuj_wait(pid);
}

// Kill whatever remains of the child's process GROUP, regardless of the
// leader's registry state. Used after the leader has been reaped: setsid
// group members (forked data loaders etc.) survive their leader, and the
// pod semantic is that they must not — a dead leader means a dead gang
// member, and its whole local process tree goes with it. ESRCH (group
// fully gone — the common case) is success.
int tpuj_kill_group(long pid, int sig) {
  if (kill((pid_t)-pid, sig) == 0) return 0;
  return errno == ESRCH ? 0 : -(int)errno;
}

// Drop a reaped pid's registry slot (call after the exit code has been
// consumed; pids recycle, so a stale done-entry could lie about a future
// child that happens to get the same pid).
void tpuj_forget(long pid) {
  std::lock_guard<std::mutex> l(g_mu);
  g_procs.erase(pid);
}

// Registry size (spawned and not yet forgotten) — leak oracle for tests.
int tpuj_tracked_count() {
  std::lock_guard<std::mutex> l(g_mu);
  return (int)g_procs.size();
}

}  // extern "C"
