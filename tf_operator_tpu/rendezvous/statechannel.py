"""Peer state-transfer channel: host-level shard depot + warm-restore client.

The reference operator restarts a failed gang and lets the workload reload
its checkpoint from shared storage — at flagship scale that disk round-trip
IS the MTTR floor (BASELINE r4/r5: ~400 s for 11.6 GB of state). This
module is the restore-side half of the async-checkpoint work: a restarted
gang member pulls the committed host-side shard bytes directly from a
surviving peer instead of touching disk at all.

Why host-level and not gang-level: a gang restart in this operator deletes
and recreates EVERY member — no gang process survives to serve its shards.
The :class:`ShardDepot` therefore lives next to the :class:`HostAgent`
(runtime/agent.py), which outlives gang teardowns; the workload pushes each
COMMITTED checkpoint step to its local depot over loopback
(``TPUJOB_PEER_DEPOT``), and a recreated member is handed the depot
endpoints of live hosts by the controller (``TPUJOB_RESTORE_PEERS``, next
to the existing warm-restart env).

Wire protocol (stdlib HTTP, no new deps):

- ``GET  /depot/v1/steps?ns=&job=``                → ``{"steps": [int]}``
  (committed steps only — an in-flight push is invisible)
- ``GET  /depot/v1/files?ns=&job=&step=``          → ``{"files": {rel: sha256}}``
- ``GET  /depot/v1/shard?ns=&job=&step=&file=``    → raw bytes
  (+ ``X-Shard-SHA256`` trailer-by-header for end-to-end verification)
- ``PUT  /depot/v1/shard?ns=&job=&step=&file=``    → stage one file
- ``POST /depot/v1/commit?ns=&job=&step=``         → staged → committed

Commit ordering mirrors the on-disk contract (train/checkpoint.py): a
step is served only after its commit POST, and a fetched step materializes
on the restorer's disk with the commit-marker file (``manifest.json`` /
orbax markers) written LAST — so a fetch torn by a dying peer can never
become a resume point; the caller falls back to the next peer, then disk.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("tpujob.statechannel")

# Files that mark a step directory COMMITTED (train/checkpoint.py): written
# last on fetch so a torn download is never discoverable as a resume point.
COMMIT_MARKER_FILES = ("manifest.json", "_CHECKPOINT_METADATA", "commit_success.txt")

_MAX_SHARD_BYTES = 1 << 31  # sanity bound on a single served file


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


_LOOPBACK_HOSTS = ("127.0.0.1", "localhost", "::1")


def _safe_join(root: str, rel: str) -> str:
    """Join a PEER-SUPPLIED relative path under ``root``, refusing any
    form that would escape it (absolute paths, ``..`` components, or
    anything whose normalized join lands outside root). The depot wire
    protocol is unauthenticated, so a compromised or buggy peer's listing
    must never be able to direct writes outside the fetch temp dir."""
    if not rel or os.path.isabs(rel) or "\\" in rel:
        raise ValueError(f"unsafe relpath from peer: {rel!r}")
    root_abs = os.path.abspath(root)
    full = os.path.abspath(os.path.join(root_abs, rel))
    if os.path.commonpath([root_abs, full]) != root_abs:
        raise ValueError(f"unsafe relpath from peer: {rel!r}")
    return full


class ShardDepot:
    """In-memory, host-lifetime store of committed checkpoint shards.

    One per host agent. Holds the last ``keep`` committed steps per
    (namespace, job) in host RAM — the state a surviving host can hand a
    restarted gang without any disk round-trip. Not durable by design:
    durability is the disk checkpoint's job; the depot is purely the warm
    path, and losing it degrades a restore to disk, never to data loss.

    Staged-but-uncommitted bytes are bounded: a workload dying mid-push
    (the exact crash this system exists for) must not pin a checkpoint's
    worth of RAM in the host-lifetime agent forever. Orphaned staging is
    pruned when a newer step commits for the same (ns, job), and total
    staged bytes are capped at ``max_staged_bytes`` (oldest-touched push
    evicted first; an evicted push's commit returns 409 and the workload
    degrades to the disk path — never to data loss).
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        keep: int = 2,
        max_staged_bytes: int = 8 << 30,
    ) -> None:
        self.keep = int(keep)
        self.max_staged_bytes = int(max_staged_bytes)
        self._lock = threading.Lock()
        # (ns, job) -> {step: {relpath: bytes}} — committed, servable.
        self._committed: Dict[Tuple[str, str], Dict[int, Dict[str, bytes]]] = {}
        # (ns, job) -> {step: writing world size} parsed from the pushed
        # manifest at commit time (0 = untagged/legacy push). Served on the
        # steps listing so an elastic restorer can skip steps written by a
        # different world WITHOUT downloading them (r12).
        self._worlds: Dict[Tuple[str, str], Dict[int, int]] = {}
        # (ns, job, step) -> {relpath: bytes} — staged by PUTs, invisible
        # until the commit POST promotes it.
        self._staging: Dict[Tuple[str, str, int], Dict[str, bytes]] = {}
        self._staged_bytes = 0
        # key -> last-touch sequence number: the staging-cap eviction order.
        self._stage_seq = 0
        self._stage_touch: Dict[Tuple[str, str, int], int] = {}
        depot = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: D102 — silence stdlib
                log.debug("depot %s " + fmt, self.client_address[0], *args)

            def _q(self):
                parsed = urllib.parse.urlparse(self.path)
                return parsed.path, dict(urllib.parse.parse_qsl(parsed.query))

            def _reply(self, code: int, body: bytes = b"", headers=()):
                self.send_response(code)
                for k, v in headers:
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _json(self, obj) -> None:
                self._reply(200, json.dumps(obj).encode(),
                            [("Content-Type", "application/json")])

            def do_GET(self):
                path, q = self._q()
                ns, jobname = q.get("ns", "default"), q.get("job", "")
                if path == "/depot/v1/steps":
                    self._json({
                        "steps": depot.steps(ns, jobname),
                        "worlds": {
                            str(s): w
                            for s, w in depot.step_worlds(ns, jobname).items()
                        },
                    })
                elif path == "/depot/v1/files":
                    files = depot.files(ns, jobname, int(q.get("step", "0")))
                    if files is None:
                        self._reply(404)
                    else:
                        self._json({"files": files})
                elif path == "/depot/v1/shard":
                    data = depot.shard(
                        ns, jobname, int(q.get("step", "0")), q.get("file", "")
                    )
                    if data is None:
                        self._reply(404)
                    else:
                        self._reply(200, data, [
                            ("Content-Type", "application/octet-stream"),
                            ("X-Shard-SHA256", _sha256(data)),
                        ])
                else:
                    self._reply(404)

            def do_PUT(self):
                path, q = self._q()
                if path != "/depot/v1/shard":
                    self._reply(404)
                    return
                n = int(self.headers.get("Content-Length", "0"))
                if n < 0 or n > _MAX_SHARD_BYTES:
                    self._reply(413)
                    return
                data = self.rfile.read(n)
                depot.stage(
                    q.get("ns", "default"), q.get("job", ""),
                    int(q.get("step", "0")), q.get("file", ""), data,
                )
                self._reply(200)

            def do_POST(self):
                path, q = self._q()
                if path != "/depot/v1/commit":
                    self._reply(404)
                    return
                ok = depot.commit(
                    q.get("ns", "default"), q.get("job", ""),
                    int(q.get("step", "0")),
                )
                self._reply(200 if ok else 409)

        if host not in _LOOPBACK_HOSTS:
            # The depot protocol carries no authentication: a non-loopback
            # bind serves (and accepts) checkpoint bytes to anything that
            # can reach the port. Deployments doing this must restrict it
            # at the network layer (the k8s manifests scope it to the
            # pod network).
            log.warning(
                "shard depot binding non-loopback %s: the depot HTTP "
                "protocol is unauthenticated — restrict access at the "
                "network layer", host,
            )
        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"shard-depot-{self.port}",
        )
        self._thread.start()

    # -- depot-side operations (also callable in-process) ------------------

    def stage(self, ns: str, job: str, step: int, relpath: str, data: bytes) -> None:
        with self._lock:
            key = (ns, job, int(step))
            files = self._staging.setdefault(key, {})
            prev = files.get(relpath)
            if prev is not None:
                self._staged_bytes -= len(prev)
            files[relpath] = data
            self._staged_bytes += len(data)
            self._stage_seq += 1
            self._stage_touch[key] = self._stage_seq
            # Enforce the staging cap: evict the longest-untouched push
            # first (an abandoned one by construction — a live push keeps
            # touching its key); the push being appended to is evicted
            # only if it alone exceeds the cap.
            while self._staged_bytes > self.max_staged_bytes and self._staging:
                victim = min(
                    (k for k in self._staging if k != key),
                    key=self._stage_touch.__getitem__,
                    default=key,
                )
                log.warning(
                    "staged bytes over cap (%d > %d): evicting staged push %s",
                    self._staged_bytes, self.max_staged_bytes, victim,
                )
                self._drop_staging_locked(victim)
                if victim == key:
                    break

    def _drop_staging_locked(self, key: Tuple[str, str, int]) -> None:
        files = self._staging.pop(key, None)
        self._stage_touch.pop(key, None)
        if files:
            self._staged_bytes -= sum(len(d) for d in files.values())

    def commit(self, ns: str, job: str, step: int) -> bool:
        """Promote a staged step to committed/servable; prune beyond keep.

        Also prunes any staging left at or below the committed step for
        the same (ns, job): those are orphans of pushes that died mid-PUT
        — a newer step committing proves the workload moved on, and
        without the prune each orphan pins its bytes in the host-lifetime
        agent's RAM forever."""
        step = int(step)
        with self._lock:
            files = self._staging.pop((ns, job, step), None)
            if not files:
                return False
            self._stage_touch.pop((ns, job, step), None)
            self._staged_bytes -= sum(len(d) for d in files.values())
            for key in [
                k for k in self._staging
                if k[0] == ns and k[1] == job and k[2] <= step
            ]:
                log.warning("pruning orphaned staged push %s (superseded)", key)
                self._drop_staging_locked(key)
            per_job = self._committed.setdefault((ns, job), {})
            per_job[step] = files
            # Record the writing world size from the pushed npy manifest
            # (r12): best-effort — an unparsable or orbax-marker-only push
            # is simply untagged (0), never a commit failure.
            world = 0
            manifest = files.get("manifest.json")
            if manifest is not None:
                try:
                    world = int(json.loads(manifest.decode()).get("world_size", 0) or 0)
                except (ValueError, UnicodeDecodeError, AttributeError):
                    world = 0
            worlds = self._worlds.setdefault((ns, job), {})
            worlds[step] = world
            for old in sorted(per_job)[: max(0, len(per_job) - self.keep)]:
                del per_job[old]
                worlds.pop(old, None)
        return True

    def steps(self, ns: str, job: str) -> List[int]:
        with self._lock:
            return sorted(self._committed.get((ns, job), {}))

    def step_worlds(self, ns: str, job: str) -> Dict[int, int]:
        """{committed step: writing world size} (0 = untagged push)."""
        with self._lock:
            return dict(self._worlds.get((ns, job), {}))

    def files(self, ns: str, job: str, step: int) -> Optional[Dict[str, str]]:
        with self._lock:
            fs = self._committed.get((ns, job), {}).get(int(step))
            if fs is None:
                return None
            return {rel: _sha256(data) for rel, data in fs.items()}

    def shard(self, ns: str, job: str, step: int, relpath: str) -> Optional[bytes]:
        with self._lock:
            return self._committed.get((ns, job), {}).get(int(step), {}).get(relpath)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


class DepotClient:
    """Workload-side client: push committed steps up, pull warm state down.

    Every method is best-effort and returns None/False/[] on any transport
    or integrity failure — a peer dying mid-transfer must degrade to the
    next restore source, never crash the restoring workload."""

    def __init__(self, timeout: float = 10.0) -> None:
        self.timeout = timeout

    # -- transport helpers -------------------------------------------------

    def _get(self, base: str, path: str, q: Dict[str, str]):
        url = f"{base}{path}?{urllib.parse.urlencode(q)}"
        return urllib.request.urlopen(url, timeout=self.timeout)  # noqa: S310

    def _json(self, base: str, path: str, q: Dict[str, str]):
        with self._get(base, path, q) as resp:
            return json.loads(resp.read().decode())

    # -- push (serving side feed) -----------------------------------------

    def push_step(self, depot_url: str, ns: str, job: str, step: int,
                  step_dir: str) -> bool:
        """Upload one COMMITTED on-disk step directory to a depot, then
        commit it there. Caller must only push after the local disk commit
        (the on_commit seam in CheckpointManager guarantees that)."""
        try:
            for root, _dirs, names in os.walk(step_dir):
                for name in names:
                    full = os.path.join(root, name)
                    rel = os.path.relpath(full, step_dir)
                    with open(full, "rb") as f:
                        data = f.read()
                    q = {"ns": ns, "job": job, "step": str(step), "file": rel}
                    url = f"{depot_url}/depot/v1/shard?{urllib.parse.urlencode(q)}"
                    req = urllib.request.Request(url, data=data, method="PUT")
                    with urllib.request.urlopen(req, timeout=self.timeout):  # noqa: S310
                        pass
            q = {"ns": ns, "job": job, "step": str(step)}
            url = f"{depot_url}/depot/v1/commit?{urllib.parse.urlencode(q)}"
            req = urllib.request.Request(url, data=b"", method="POST")
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:  # noqa: S310
                return resp.status == 200
        except (OSError, urllib.error.URLError, ValueError) as exc:
            log.warning("depot push of step %d to %s failed: %s", step, depot_url, exc)
            return False

    # -- pull (restore side) ----------------------------------------------

    def steps(self, depot_url: str, ns: str, job: str) -> List[int]:
        try:
            return [int(s) for s in
                    self._json(depot_url, "/depot/v1/steps", {"ns": ns, "job": job})["steps"]]
        except (OSError, urllib.error.URLError, ValueError, KeyError):
            return []

    def step_worlds(self, depot_url: str, ns: str, job: str) -> Dict[int, int]:
        """{committed step: writing world size} from a peer's listing;
        {} on any failure or a pre-r12 peer that doesn't serve worlds."""
        try:
            body = self._json(depot_url, "/depot/v1/steps", {"ns": ns, "job": job})
            return {int(s): int(w) for s, w in (body.get("worlds") or {}).items()}
        except (OSError, urllib.error.URLError, ValueError, KeyError, AttributeError):
            return {}

    def best_peer(self, peers: List[str], ns: str, job: str,
                  expect_world_size: Optional[int] = None) -> Tuple[Optional[str], int]:
        """(depot_url, step) of the highest committed step across peers;
        (None, 0) when no peer holds anything. Dead peers are skipped.

        With ``expect_world_size`` set (elastic restore, r12), steps whose
        advertised writing world size is tagged AND differs are skipped —
        a shard set sharded for a different world is not a warm-restore
        source for this one. Untagged steps (0 / pre-r12 peer) pass; the
        manifest check in fetch_step and the restore-time refusal in
        CheckpointManager remain the authoritative gates."""
        best_url, best_step = None, 0
        for url in peers:
            steps = self.steps(url, ns, job)
            if expect_world_size and steps:
                worlds = self.step_worlds(url, ns, job)
                steps = [
                    s for s in steps
                    if not worlds.get(s) or worlds[s] == int(expect_world_size)
                ]
            if steps and steps[-1] > best_step:
                best_url, best_step = url, steps[-1]
        return best_url, best_step

    def fetch_step(self, depot_url: str, ns: str, job: str, step: int,
                   dest_root: str,
                   expect_world_size: Optional[int] = None) -> Optional[str]:
        """Materialize a peer's committed step as a COMMITTED step
        directory under ``dest_root`` (the restorer's checkpoint dir), so
        the ordinary disk-restore path loads it bit-identically.

        Integrity + commit ordering: every file is verified against the
        peer's sha256 before landing, data files are written to a temp dir
        first, commit-marker files (COMMIT_MARKER_FILES) are written LAST,
        and the temp dir is atomically renamed into place — a peer dying
        mid-transfer leaves an unfinished temp dir, never a resume point.
        Returns the final step path, or None on any failure (caller falls
        back to the next peer, then disk)."""
        import shutil

        step = int(step)
        final = os.path.join(dest_root, f"step_{step}")
        if os.path.exists(os.path.join(final, "manifest.json")):
            return final  # disk already holds this committed step
        q = {"ns": ns, "job": job, "step": str(step)}
        tmp = os.path.join(dest_root, f".peerfetch_step_{step}_{os.getpid()}")
        try:
            listing = self._json(depot_url, "/depot/v1/files", q)["files"]
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            # Validate EVERY peer-supplied relpath before any byte lands:
            # a listing entry like '../../x' must fail the whole fetch
            # (fall back to the next source), not write outside tmp.
            for rel in listing:
                _safe_join(tmp, rel)
            markers = [r for r in listing if os.path.basename(r) in COMMIT_MARKER_FILES]
            data_files = [r for r in listing if r not in markers]
            if not markers:
                log.warning("peer %s step %d has no commit marker; refusing",
                            depot_url, step)
                shutil.rmtree(tmp, ignore_errors=True)
                return None
            for rel in data_files + markers:  # markers strictly last
                with self._get(depot_url, "/depot/v1/shard", {**q, "file": rel}) as resp:
                    data = resp.read()
                    want = resp.headers.get("X-Shard-SHA256", "")
                if want and _sha256(data) != want:
                    raise ValueError(f"sha256 mismatch on {rel}")
                if expect_world_size and os.path.basename(rel) == "manifest.json":
                    # Elastic restore (r12): verify the writing world size
                    # tag before this fetch can become a resume point. A
                    # mismatch degrades to the next source, loudly.
                    saved = int(json.loads(data.decode()).get("world_size", 0) or 0)
                    if saved and saved != int(expect_world_size):
                        raise ValueError(
                            f"step {step} written by world {saved}, "
                            f"expected {int(expect_world_size)}"
                        )
                full = _safe_join(tmp, rel)
                os.makedirs(os.path.dirname(full), exist_ok=True)
                with open(full, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
            os.makedirs(dest_root, exist_ok=True)
            try:
                os.rename(tmp, final)
            except OSError:
                shutil.rmtree(tmp, ignore_errors=True)  # lost a race; theirs won
            return final
        except (OSError, urllib.error.URLError, ValueError, KeyError) as exc:
            log.warning("peer fetch of step %d from %s failed: %s — falling back",
                        step, depot_url, exc)
            shutil.rmtree(tmp, ignore_errors=True)
            return None


def choose_restore_source(
    peers: List[str], ns: str, job: str, disk_step: int,
    client: Optional[DepotClient] = None,
    expect_world_size: Optional[int] = None,
) -> Tuple[str, Optional[str], int]:
    """The restore-source decision order (docs/design.md §4.9):

    1. **peer** — some live depot holds a committed step >= the newest
       complete step on disk (and > 0): pull from that peer; no disk read.
    2. **disk** — otherwise (no peers, peers behind disk, peers dead).

    Returns ``(source, depot_url, step)`` where source is "peer" or
    "disk"; for disk the url is None and step is ``disk_step``. A peer
    strictly BEHIND disk is never chosen — restoring older state than the
    controller-declared resume step would violate monotonic resume."""
    client = client or DepotClient()
    url, peer_step = client.best_peer(peers, ns, job,
                                      expect_world_size=expect_world_size)
    if url is not None and peer_step > 0 and peer_step >= disk_step:
        return "peer", url, peer_step
    return "disk", None, disk_step
