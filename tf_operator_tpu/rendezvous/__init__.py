"""Rendezvous: how launched processes learn who they are and where to meet.

Replaces the reference's TF_CONFIG generator + consumer pair
(controller.v2/controller_tensorflow.go:49-112 on the produce side,
examples/tf_sample/tf_sample/tf_smoke.py:88-110 on the consume side). On TPU
the whole cluster-spec map collapses to three values — coordinator address,
process count, process id — because intra-slice topology is hardware and XLA
collectives need no address book (SURVEY.md §5 "communication backend").
"""

from tf_operator_tpu.rendezvous.context import JobContext, RetryableFailure  # noqa: F401
from tf_operator_tpu.rendezvous.env import (  # noqa: F401
    ENV_CHIPS,
    ENV_COORDINATOR_ADDRESS,
    ENV_ENTRYPOINT,
    ENV_JOB_NAME,
    ENV_MESH_AXES,
    ENV_NAMESPACE,
    ENV_NUM_PROCESSES,
    ENV_PORT,
    ENV_PROCESS_ID,
    ENV_REPLICA_INDEX,
    ENV_REPLICA_TYPE,
    ENV_WORKLOAD,
    identity_env,
)
