"""JobContext: the workload-facing identity/rendezvous object.

What ``TF_CONFIG`` parsing is to a reference workload
(examples/tf_sample/tf_sample/tf_smoke.py:88-96), ``JobContext.from_env()``
is to a TPU workload — except there is no cluster-spec map to interpret:
the context carries coordinator coordinates, this process's rank, the
logical mesh axes, and the free-form workload config.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List

from tf_operator_tpu.rendezvous.env import (
    ENV_API_SERVER,
    ENV_CHECKPOINT_DIR,
    ENV_CHIPS,
    ENV_COORDINATOR_ADDRESS,
    ENV_DCN_MESH_AXES,
    ENV_ENTRYPOINT,
    ENV_JOB_NAME,
    ENV_MESH_AXES,
    ENV_NAMESPACE,
    ENV_NUM_PROCESSES,
    ENV_PEER_DEPOT,
    ENV_PORT,
    ENV_PROCESS_ID,
    ENV_REPLICA_INDEX,
    ENV_REPLICA_TYPE,
    ENV_RESIZE_EPOCH,
    ENV_RESTORE_PEERS,
    ENV_RESUME_STEP,
    ENV_TRACE_ID,
    ENV_WORKLOAD,
)


class RetryableFailure(Exception):
    """Raise from a workload to request a restart: the harness exits with
    the user-defined retryable code 138 (train_util.go:18-53 semantics)."""


@dataclass
class JobContext:
    job_name: str = ""
    namespace: str = "default"
    replica_type: str = "Worker"
    replica_index: int = 0
    process_id: int = 0
    num_processes: int = 1
    coordinator_address: str = ""
    mesh_axes: Dict[str, int] = field(default_factory=dict)
    dcn_mesh_axes: Dict[str, int] = field(default_factory=dict)
    workload: Dict[str, Any] = field(default_factory=dict)
    chips: int = 0
    port: int = 0  # rendezvous port (nonzero on the coordinator process)
    entrypoint: str = ""
    # Warm-restart contract (rendezvous/env.py): > 0 means the controller
    # recreated this gang after a restart with checkpoints on disk — the
    # trainer resumes from latest_step(); streams fast-forward past
    # resume_step batches. 0 on a cold first incarnation.
    resume_step: int = 0
    checkpoint_dir: str = ""
    # Peer warm-restore contract (rendezvous/statechannel.py): this host's
    # shard-depot URL (push committed checkpoint shards here) and the live
    # hosts' depot URLs a restarted member may pull warm state from before
    # touching disk. Both empty when the deployment runs without depots.
    peer_depot: str = ""
    restore_peers: List[str] = field(default_factory=list)
    # Elastic-gang contract (r12): the job's resize epoch at this
    # process's creation. Nonzero means this process joined an elastic
    # gang mid-resize — the LIVE membership/world size lives in the job
    # status (poll_resize_directive), never in this frozen env snapshot.
    resize_epoch: int = 0
    # Trace context (obs/): the job's trace id (its uid), injected by the
    # controller so workload-recorded spans (first-step, checkpoint
    # save/restore) join the controller/scheduler/agent timeline.
    trace_id: str = ""

    @staticmethod
    def from_env(env: Dict[str, str] | None = None) -> "JobContext":
        e = env if env is not None else os.environ
        return JobContext(
            job_name=e.get(ENV_JOB_NAME, ""),
            namespace=e.get(ENV_NAMESPACE, "default"),
            replica_type=e.get(ENV_REPLICA_TYPE, "Worker"),
            replica_index=int(e.get(ENV_REPLICA_INDEX, "0") or 0),
            process_id=int(e.get(ENV_PROCESS_ID, "0") or 0),
            num_processes=int(e.get(ENV_NUM_PROCESSES, "1") or 1),
            coordinator_address=e.get(ENV_COORDINATOR_ADDRESS, ""),
            mesh_axes=json.loads(e.get(ENV_MESH_AXES, "{}") or "{}"),
            dcn_mesh_axes=json.loads(e.get(ENV_DCN_MESH_AXES, "{}") or "{}"),
            workload=json.loads(e.get(ENV_WORKLOAD, "{}") or "{}"),
            chips=int(e.get(ENV_CHIPS, "0") or 0),
            port=int(e.get(ENV_PORT, "0") or 0),
            entrypoint=e.get(ENV_ENTRYPOINT, ""),
            resume_step=int(e.get(ENV_RESUME_STEP, "0") or 0),
            checkpoint_dir=e.get(ENV_CHECKPOINT_DIR, ""),
            peer_depot=e.get(ENV_PEER_DEPOT, ""),
            restore_peers=json.loads(e.get(ENV_RESTORE_PEERS, "[]") or "[]"),
            resize_epoch=int(e.get(ENV_RESIZE_EPOCH, "0") or 0),
            trace_id=e.get(ENV_TRACE_ID, ""),
        )

    # -- hang forensics (r15, obs/blackbox.py) ----------------------------

    def install_stackdump_hook(self) -> str:
        """Install the SIGUSR2 → all-thread-stack-dump hook (faulthandler)
        the hang plane's stack sweep relies on: when the reconciler
        declares the gang HUNG, each HostAgent delivers SIGUSR2 to its
        wedged members and reads back the file this hook writes.

        Known limit (docs/design.md §6.3): faulthandler dumps PYTHON
        frames from the signal handler — a rank wedged inside a native
        extension (a real collective blocks in C++) still dumps, because
        faulthandler is C-level and async-signal-safe, but the stack shows
        the Python frame that CALLED into the extension, not the native
        frames below it. That is exactly the forensic we need: which
        collective, from where.

        Returns the dump-file path, or "" when no ENV_STACKDUMP_DIR was
        injected (not running under an agent) or installation failed —
        never raises; a missing hook degrades the postmortem, not the
        workload."""
        import faulthandler
        import signal

        from tf_operator_tpu.rendezvous.env import (
            ENV_STACKDUMP_DIR,
            stackdump_path,
        )

        dump_dir = os.environ.get(ENV_STACKDUMP_DIR, "")
        if not dump_dir or not hasattr(faulthandler, "register"):
            return ""
        try:
            os.makedirs(dump_dir, exist_ok=True)
            path = stackdump_path(
                dump_dir, self.namespace, self.job_name,
                self.replica_type, self.replica_index,
            )
            f = open(path, "w")  # noqa: SIM115 — faulthandler holds the fd
            faulthandler.register(signal.SIGUSR2, file=f, all_threads=True)
            # Keep the file object alive for the process lifetime:
            # faulthandler writes to the raw fd, and a GC'd file object
            # would close it out from under the handler.
            self._stackdump_file = f
            return path
        except Exception:  # noqa: BLE001 — forensics must never block launch
            return ""

    # -- device plane helpers (used by workloads after rendezvous) --------

    def initialize_distributed(self) -> None:
        """Join the gang via jax.distributed (no-op for 1-process jobs).
        Replaces tf.train.Server bring-up (tf_smoke.py:98-110). Also turns
        on the persistent compilation cache so gang restarts (the recovery
        path) and repeat submissions skip XLA recompilation."""
        from tf_operator_tpu.train.compile_cache import enable as _enable_cache

        _enable_cache()
        if self.num_processes <= 1:
            return
        import jax

        jax.distributed.initialize(
            coordinator_address=self.coordinator_address,
            num_processes=self.num_processes,
            process_id=self.process_id,
        )

    def build_mesh(self):
        """Build the jax.sharding.Mesh declared by the job topology over the
        global device set. Empty mesh_axes ⇒ one data-parallel axis over all
        devices. With dcn_mesh_axes set, builds a hybrid multi-slice mesh
        (DCN factors outermost per axis — parallel.mesh.build_hybrid_mesh)."""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        if self.dcn_mesh_axes:
            from tf_operator_tpu.parallel.mesh import build_hybrid_mesh

            return build_hybrid_mesh(self.mesh_axes, self.dcn_mesh_axes)
        devices = np.asarray(jax.devices())
        axes = self.mesh_axes or {"dp": devices.size}
        names = tuple(axes.keys())
        sizes = tuple(axes.values())
        return Mesh(devices.reshape(sizes), names)

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0

    # -- tracing (obs/) ----------------------------------------------------

    def record_span(
        self,
        op: str,
        start: float,
        end: float,
        attrs: Dict[str, str] | None = None,
        name: str | None = None,
    ) -> bool:
        """Record one span into the job's timeline through the operator
        API (ENV_API_SERVER + ENV_TRACE_ID, both controller-injected).
        Component ``trainer``. Best effort by design: tracing must never
        fail a training step — returns False when nothing was recorded
        (no API server / no trace context / transport failure)."""
        base = os.environ.get(ENV_API_SERVER, "")
        if not base or not self.trace_id or not self.job_name:
            return False
        from tf_operator_tpu.obs.spans import COMPONENT_TRAINER, SpanRecorder
        from tf_operator_tpu.runtime.remote_store import RemoteStore

        full_attrs = {"rank": str(self.process_id), **(attrs or {})}
        recorder = SpanRecorder(RemoteStore(base), component=COMPONENT_TRAINER)
        return (
            recorder.record(
                self.namespace, self.job_name, self.trace_id, op,
                start, end, attrs=full_attrs, name=name,
            )
            is not None
        )

    def mark_first_step(self, step: int = 0) -> bool:
        """Mark the job's first training step (the TTFS boundary). Every
        rank may call this — the deterministic gang-wide span name means
        the store keeps exactly the earliest mark.

        The span also carries the r11 warm/cold classification the
        reconciler splits TTFS on: warm="1" when this process ran from a
        pre-warmed slot (ENV_WARM_SLOT) or any compile-cache tier hit,
        plus the compile-cache counters and the remote tier's health
        (``cache_degraded`` — a dead cachesvc is a span attribute, never
        a job failure)."""
        from tf_operator_tpu.obs.spans import first_step_span_name

        attrs = {"step": str(step), "track": "first-step"}
        try:
            from tf_operator_tpu.rendezvous.env import ENV_WARM_SLOT
            from tf_operator_tpu.train import compile_cache

            stats = compile_cache.stats()
            hits = stats.get("local_hits", 0) + stats.get("remote_hits", 0)
            warm_slot = os.environ.get(ENV_WARM_SLOT, "") == "1"
            attrs["warm"] = "1" if (warm_slot or hits > 0) else "0"
            attrs["warm_slot"] = "1" if warm_slot else "0"
            attrs["cache_local_hits"] = str(stats.get("local_hits", 0))
            attrs["cache_remote_hits"] = str(stats.get("remote_hits", 0))
            attrs["cache_misses"] = str(stats.get("misses", 0))
            if stats.get("remote_dead"):
                attrs["cache_degraded"] = "1"
        except Exception:  # noqa: BLE001 — classification must never block TTFS
            pass
        now = time.time()
        return self.record_span(
            "first-step", now, now, attrs=attrs,
            name=first_step_span_name(self.job_name, self.trace_id),
        )

    def record_save_stall(self, step: int, start: float, end: float) -> bool:
        """Record the step-loop stall one accepted checkpoint save caused
        (the async pipeline's overlap receipt: span width = staging copy,
        NOT the device→host fetch or disk write, which run behind it).
        The reconciler folds these into the
        ``tpujob_checkpoint_save_stall_seconds`` histogram at terminal."""
        return self.record_span(
            "checkpoint-save-stall", start, end,
            attrs={"step": str(step), "track": "checkpoint"},
        )

    def record_restore(
        self, source: str, step: int, start: float, end: float
    ) -> bool:
        """Record one warm restore with its source ("peer" when state was
        pulled from a surviving host's shard depot, "disk" otherwise) —
        folded into ``tpujob_restore_seconds{source}`` at terminal, and
        the span the chaos soak reads effective recovery downtime from."""
        return self.record_span(
            "restore", start, end,
            attrs={
                "source": source, "step": str(step), "track": "checkpoint",
            },
        )

    def record_resize(
        self, direction: str, epoch: int, start: float, end: float
    ) -> bool:
        """Record the trainer-side half of one resize: the span from the
        member noticing the directive to completing its re-carve/re-shard
        at the barrier step. The controller's ``resize`` span (opened at
        the resize decision) measures control-plane downtime; this one
        measures the data-plane boundary cost."""
        return self.record_span(
            "resize-boundary", start, end,
            attrs={
                "direction": direction, "epoch": str(epoch),
                "track": "resize",
            },
        )

    # -- live telemetry (obs/telemetry.py) ---------------------------------

    def telemetry(
        self,
        flush_every: int = 10,
        tokens_per_step: float = 0.0,
        flops_per_step: float = 0.0,
        n_chips: int = 0,
        host: str = "",
        profile_root: str = "",
    ):
        """Build this rank's :class:`~tf_operator_tpu.obs.telemetry.StepTelemetry`
        reporter. The workload calls ``rep.step(duration_s, ...)`` once per
        step and ``rep.close()`` at exit; every ``flush_every`` steps one
        compact batch ships through the operator API into the job's
        telemetry ring. Without an API server (ENV_API_SERVER unset) or
        when it dies mid-run, the reporter degrades to local-only
        accounting — a telemetry failure is never a job failure; the gap
        surfaces as ``degraded`` on the next delivered batch and a
        ``telemetry-degraded`` span attribute at close (PR 11 contract).

        Flush boundaries double as the on-demand-profiling poll point:
        rank 0 checks ``status.profile_directive`` and wraps the next N
        steps in ``profile_ctx``, reporting the capture back as a
        ``profile-capture`` span + directive ack."""
        from tf_operator_tpu.obs.telemetry import StepTelemetry, TelemetryRecorder

        base = os.environ.get(ENV_API_SERVER, "")
        recorder = None
        if base and self.trace_id and self.job_name:
            from tf_operator_tpu.runtime.remote_store import RemoteStore

            recorder = TelemetryRecorder(RemoteStore(base))
        chief = self.process_id == 0
        rep = StepTelemetry(
            recorder,
            namespace=self.namespace,
            job_name=self.job_name,
            trace_id=self.trace_id,
            rank=self.process_id,
            host=host or os.environ.get("HOSTNAME", ""),
            flush_every=flush_every,
            tokens_per_step=tokens_per_step,
            flops_per_step=flops_per_step,
            n_chips=n_chips or self.chips or 1,
            start_step=self.resume_step,
            poll_directive=self.poll_profile_directive if chief else None,
            on_capture=self._report_profile_capture if chief else None,
            profile_root=profile_root
            or (os.path.join(self.checkpoint_dir, "profile")
                if self.checkpoint_dir else ""),
        )
        return rep

    def close_telemetry(self, rep) -> None:
        """Final flush; if any batches were lost to API unreachability,
        leave the degradation receipt as a span attribute."""
        if rep is None:  # telemetry() returns None outside the operator
            return
        try:
            degraded = bool(rep.degraded)
            rep.close()
            if degraded:
                now = time.time()
                self.record_span(
                    "telemetry", now, now,
                    attrs={"telemetry_degraded": "1", "track": "telemetry"},
                )
        except Exception:  # noqa: BLE001 — teardown is never fatal
            pass

    # -- on-demand profiling directive (same protocol as resize) -----------

    def poll_profile_directive(self) -> Dict[str, Any] | None:
        """Fetch the job's live profile directive ({"epoch", "steps",
        "dir", ...}; None when never requested or the API is unreachable).
        Workers compare ``epoch`` against the last epoch they captured."""
        base = os.environ.get(ENV_API_SERVER, "")
        if not base or not self.job_name:
            return None
        from tf_operator_tpu.api.types import KIND_TPUJOB
        from tf_operator_tpu.runtime.remote_store import RemoteStore

        try:
            job = RemoteStore(base).get(KIND_TPUJOB, self.namespace, self.job_name)
        except Exception:  # noqa: BLE001 — polling must never kill a step
            return None
        if job is None:
            return None
        directive = dict(job.status.profile_directive or {})
        return directive or None

    def _report_profile_capture(self, epoch: int, steps: int, path: str) -> None:
        """Chief-only capture receipt: a ``profile-capture`` span carrying
        the xplane directory, plus ``completed_epoch``/``xplane`` acked
        back into the directive so `tpujob profile` can see it landed."""
        now = time.time()
        self.record_span(
            "profile-capture", now, now,
            attrs={
                "xplane": str(path), "epoch": str(epoch),
                "steps": str(steps), "track": "profile",
            },
        )
        base = os.environ.get(ENV_API_SERVER, "")
        if not base or not self.job_name:
            return
        from tf_operator_tpu.api.types import KIND_TPUJOB
        from tf_operator_tpu.runtime.remote_store import RemoteStore
        from tf_operator_tpu.runtime.store import update_with_retry_loop

        def mutate(job):
            cur = job.status.profile_directive or {}
            if int(cur.get("epoch", 0)) != int(epoch):
                return False  # a newer request superseded this capture
            job.status.profile_directive = {
                **cur, "completed_epoch": int(epoch), "xplane": str(path),
            }

        try:
            update_with_retry_loop(
                RemoteStore(base), KIND_TPUJOB, self.namespace, self.job_name,
                mutate, transient_timeout=30.0,
            )
        except Exception:  # noqa: BLE001 — the span is the primary receipt
            pass

    # -- checkpoint-cadence directive (r16, same protocol as profiling) ----

    def poll_checkpoint_cadence_directive(self) -> Dict[str, Any] | None:
        """Fetch the job's live checkpoint-cadence directive ({"epoch",
        "checkpoint_every", ...}; None when the autopilot has never
        retuned the cadence or the API is unreachable). The chief
        compares ``epoch`` against the last epoch it applied and acts
        exactly once per epoch, at a step boundary."""
        base = os.environ.get(ENV_API_SERVER, "")
        if not base or not self.job_name:
            return None
        from tf_operator_tpu.api.types import KIND_TPUJOB
        from tf_operator_tpu.runtime.remote_store import RemoteStore

        try:
            job = RemoteStore(base).get(KIND_TPUJOB, self.namespace, self.job_name)
        except Exception:  # noqa: BLE001 — polling must never kill a step
            return None
        if job is None:
            return None
        directive = dict(job.status.checkpoint_cadence_directive or {})
        return directive or None

    def ack_checkpoint_cadence(self, epoch: int, step: int) -> None:
        """Chief-only apply receipt: ``applied_epoch``/``applied_step``
        acked back into the directive (refusing a superseded epoch), so
        the autopilot knows its last directive landed before proposing
        the next one."""
        base = os.environ.get(ENV_API_SERVER, "")
        if not base or not self.job_name:
            return
        from tf_operator_tpu.api.types import KIND_TPUJOB
        from tf_operator_tpu.runtime.remote_store import RemoteStore
        from tf_operator_tpu.runtime.store import update_with_retry_loop

        def mutate(job):
            cur = job.status.checkpoint_cadence_directive or {}
            if int(cur.get("epoch", 0)) != int(epoch):
                return False  # a newer directive superseded this apply
            job.status.checkpoint_cadence_directive = {
                **cur, "applied_epoch": int(epoch), "applied_step": int(step),
            }

        try:
            update_with_retry_loop(
                RemoteStore(base), KIND_TPUJOB, self.namespace, self.job_name,
                mutate, transient_timeout=30.0,
            )
        except Exception:  # noqa: BLE001 — the next poll re-offers the epoch
            pass

    # -- elastic resize barrier (r12) --------------------------------------
    #
    # The controller offers survivors a new world size by writing a resize
    # directive into the job status (reconciler._resize_gang). The env of
    # a running process is frozen, so the directive — polled through the
    # operator API — is the only live channel. The chief (lowest surviving
    # rank) publishes barrier fields (boundary offset etc.) back into the
    # SAME directive via the optimistic status update the evaluator's
    # report_eval_metrics already uses; non-chief members poll until the
    # barrier fields appear. All methods are best-effort reads/writes over
    # ENV_API_SERVER and degrade to None/False without it.

    def poll_resize_directive(self) -> Dict[str, Any] | None:
        """Fetch the job's live resize directive (None when the gang runs
        at spec size, the API is unreachable, or no API is configured).
        Members compare ``directive["epoch"]`` against the last epoch they
        acted on; a higher epoch means a resize is pending."""
        base = os.environ.get(ENV_API_SERVER, "")
        if not base or not self.job_name:
            return None
        from tf_operator_tpu.api.types import KIND_TPUJOB
        from tf_operator_tpu.runtime.remote_store import RemoteStore

        try:
            job = RemoteStore(base).get(KIND_TPUJOB, self.namespace, self.job_name)
        except Exception:  # noqa: BLE001 — polling must never kill a step
            return None
        if job is None:
            return None
        directive = dict(job.status.resize_directive or {})
        return directive or None

    def publish_resize_barrier(
        self, epoch: int, fields: Dict[str, Any]
    ) -> bool:
        """Chief-only: merge barrier fields (e.g. ``boundary_offset``,
        ``orphans``, ``completed``) into the directive for ``epoch``. The
        write is an optimistic read-modify-write; it refuses (returns
        False) if the directive moved to a NEWER epoch underneath us — a
        second resize superseded this barrier and the chief must re-poll
        rather than clobber it."""
        base = os.environ.get(ENV_API_SERVER, "")
        if not base or not self.job_name:
            return False
        from tf_operator_tpu.api.types import KIND_TPUJOB
        from tf_operator_tpu.runtime.remote_store import RemoteStore
        from tf_operator_tpu.runtime.store import update_with_retry_loop

        stale = []

        def mutate(job):
            cur = job.status.resize_directive or {}
            if int(cur.get("epoch", 0)) != int(epoch):
                stale.append(True)
                return False
            job.status.resize_directive = {**cur, **fields}

        try:
            out = update_with_retry_loop(
                RemoteStore(base), KIND_TPUJOB, self.namespace, self.job_name,
                mutate, transient_timeout=30.0,
            )
        except Exception:  # noqa: BLE001 — barrier publish retries upstream
            return False
        return out is not None and not stale

    # -- result reporting --------------------------------------------------

    def report_eval_metrics(self, step: int, metrics: Dict[str, float]) -> bool:
        """Write evaluator scores into TPUJobStatus.eval_metrics through the
        operator API (ENV_API_SERVER, injected by the controller). The
        write is an optimistic read-modify-write against the job object —
        stale-version races with the reconciler's status writer retry, and
        a newer step already reported by another evaluator wins. Best
        effort by design: scoring must not die because the operator is
        mid-restart (returns False when nothing was written)."""
        from tf_operator_tpu.rendezvous.env import ENV_API_SERVER

        base = os.environ.get(ENV_API_SERVER, "")
        if not base or not self.job_name:
            return False
        from tf_operator_tpu.api.types import KIND_TPUJOB
        from tf_operator_tpu.runtime.remote_store import RemoteStore
        from tf_operator_tpu.runtime.store import update_with_retry_loop

        import time as _time

        def mutate(job):
            if int(job.status.eval_metrics.get("step", -1)) > step:
                return False  # a newer checkpoint was already scored
            job.status.eval_metrics = {
                "step": int(step),
                "metrics": {str(k): float(v) for k, v in metrics.items()},
                "time": _time.time(),
            }

        try:
            out = update_with_retry_loop(
                RemoteStore(base), KIND_TPUJOB, self.namespace, self.job_name,
                mutate, transient_timeout=30.0,
            )
        except Exception:  # noqa: BLE001 — reporting is never fatal to eval
            return False
        return out is not None
