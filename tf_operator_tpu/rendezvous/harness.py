"""In-process harness: what actually runs inside each launched process.

The analogue of the user container's entry script in the reference: where
tf_smoke.py reads TF_CONFIG and starts a tf.train.Server
(examples/tf_sample/tf_sample/tf_smoke.py:77-110), this harness reads the
TPUJOB_* contract, resolves the declared ``pkg.module:fn`` entrypoint, and
calls ``fn(ctx)``. Exit-code contract (consumed by the controller's
restart policies, utils/exit_codes.py):

- 0    — workload returned normally
- 138  — workload raised RetryableFailure (please restart me)
- 1    — workload raised any other exception (permanent)
- 2    — the harness itself could not resolve/launch the entrypoint
"""

from __future__ import annotations

import importlib
import logging
import sys
import time
import traceback

from tf_operator_tpu.rendezvous.context import JobContext, RetryableFailure
from tf_operator_tpu.utils.exit_codes import USER_RETRYABLE_CODE

log = logging.getLogger("tpujob.harness")


def resolve_entrypoint(spec: str):
    module_name, sep, fn_name = spec.partition(":")
    if not sep or not module_name or not fn_name:
        raise ValueError(f"entrypoint must look like 'pkg.module:fn', got {spec!r}")
    module = importlib.import_module(module_name)
    try:
        return getattr(module, fn_name)
    except AttributeError as exc:
        raise ValueError(f"module {module_name!r} has no attribute {fn_name!r}") from exc


def main(argv=None) -> int:
    del argv
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s [%(levelname)s] %(message)s",
        stream=sys.stderr,
    )
    ctx = JobContext.from_env()
    # Hang forensics (r15): arm SIGUSR2 → all-thread stack dump before the
    # workload runs, so a stack sweep can read a wedged gang even when the
    # wedge is inside the entrypoint's very first step.
    dump_path = ctx.install_stackdump_hook()
    if dump_path:
        log.info("stack-dump hook armed: SIGUSR2 -> %s", dump_path)
    if not ctx.entrypoint:
        log.error("no TPUJOB_ENTRYPOINT set")
        return 2
    try:
        fn = resolve_entrypoint(ctx.entrypoint)
    except Exception:
        log.error("failed to resolve entrypoint %r:\n%s", ctx.entrypoint, traceback.format_exc())
        return 2

    log.info(
        "starting %s: job=%s/%s role=%s[%d] rank=%d/%d coordinator=%s",
        ctx.entrypoint, ctx.namespace, ctx.job_name, ctx.replica_type,
        ctx.replica_index, ctx.process_id, ctx.num_processes, ctx.coordinator_address,
    )
    if ctx.resume_step:
        # Warm restart (rendezvous/env.py contract): the controller saw
        # checkpoints at creation; the trainer resumes from latest_step().
        log.info("warm restart: controller-declared resume step %d", ctx.resume_step)
    if ctx.resize_epoch:
        # Elastic join (rendezvous/env.py contract): this process was
        # created into a resized gang — the live membership is in the job
        # status directive, NOT this env snapshot.
        log.info(
            "elastic join: controller-declared resize epoch %d "
            "(directive in job status is authoritative)", ctx.resize_epoch,
        )

    # Trace (obs/): one trainer-component span per workload run, whatever
    # the workload is — the timeline shows entrypoint-entry -> exit with
    # the outcome, even for workloads that never mark a first step.
    t0 = time.time()

    def _span(outcome: str) -> None:
        ctx.record_span(
            "workload", t0, time.time(),
            attrs={
                "outcome": outcome,
                "entrypoint": ctx.entrypoint,
                "track": f"workload {ctx.replica_type}/{ctx.replica_index}",
            },
            name=f"{ctx.job_name}-{ctx.trace_id[:8]}-workload-"
                 f"{ctx.replica_type.lower()}-{ctx.replica_index}-"
                 f"{int(t0 * 1e3) % 100000:05d}",
        )

    try:
        fn(ctx)
    except RetryableFailure as exc:
        log.warning("workload requested retry: %s", exc)
        _span("retryable")
        return USER_RETRYABLE_CODE
    except SystemExit as exc:
        if exc.code is None:
            _span("ok")
            return 0
        if isinstance(exc.code, int):
            _span("ok" if exc.code == 0 else f"exit:{exc.code}")
            return exc.code
        log.error("workload exited: %s", exc.code)
        _span("error")
        return 1
    except KeyboardInterrupt:
        # SIGINT is infrastructure eviction: re-raise so the interpreter
        # exits 130, which the taxonomy classifies as retryable — returning
        # 1 here would turn every preemption into a permanent failure.
        _span("preempted")
        raise
    except Exception as exc:
        if _is_infrastructure_error(exc):
            # A peer died / the coordination service went away. The peer's
            # own exit decides permanence; THIS process must report
            # retryable, or the first surviving peer to be observed would
            # convert a retryable preemption into a permanent job failure.
            log.warning("distributed runtime failure (retryable):\n%s", traceback.format_exc())
            _span("infra-retryable")
            return USER_RETRYABLE_CODE
        log.error("workload failed:\n%s", traceback.format_exc())
        _span("error")
        return 1
    _span("ok")
    return 0


_INFRA_ERROR_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "coordination service",
    "CoordinationService",
    "heartbeat",
    "peer",
    "failed to connect",
)


def _is_infrastructure_error(exc: BaseException) -> bool:
    """Heuristic: errors surfaced by the distributed runtime when a peer or
    the coordination service disappears — retryable, not workload bugs."""
    if type(exc).__name__ in ("XlaRuntimeError", "JaxRuntimeError"):
        msg = str(exc)
        return any(marker in msg for marker in _INFRA_ERROR_MARKERS)
    return False


if __name__ == "__main__":
    sys.exit(main())
