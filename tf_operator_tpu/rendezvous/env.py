"""The process environment contract (TF_CONFIG analogue).

The reference serializes a cluster-spec map + task identity into one JSON
env var, ``TF_CONFIG`` (controller.v2/controller_tensorflow.go:49-84). The
TPU-native contract is flat env vars in two groups:

Identity (injected by the backend from ``ProcessSpec`` — every launched
process gets these even if the controller adds nothing):

- ``TPUJOB_ENTRYPOINT``      — "pkg.module:fn" the harness resolves and calls
- ``TPUJOB_NAME``            — owning job name
- ``TPUJOB_NAMESPACE``       — owning job namespace
- ``TPUJOB_REPLICA_TYPE``    — Coordinator / Worker / Evaluator
- ``TPUJOB_REPLICA_INDEX``   — index within the replica set (task_index
                               analogue, replicas.go:121-136)
- ``TPUJOB_PORT``            — rendezvous port (meaningful on coordinator)
- ``TPUJOB_CHIPS``           — TPU chips this process drives

Rendezvous (computed by the controller, consumed by
``jax.distributed.initialize`` in the harness):

- ``TPUJOB_COORDINATOR_ADDRESS`` — "host:port" of process 0
- ``TPUJOB_NUM_PROCESSES``       — total process count in the gang
- ``TPUJOB_PROCESS_ID``          — this process's rank
- ``TPUJOB_MESH_AXES``           — JSON {"axis": size, ...} logical mesh
- ``TPUJOB_DCN_MESH_AXES``       — JSON per-axis cross-slice (DCN) factors
- ``TPUJOB_WORKLOAD``            — JSON passthrough of spec.workload
"""

from __future__ import annotations

from typing import Dict, TYPE_CHECKING

if TYPE_CHECKING:  # avoid a runtime cycle with runtime.objects
    from tf_operator_tpu.runtime.objects import ProcessSpec

ENV_ENTRYPOINT = "TPUJOB_ENTRYPOINT"
ENV_JOB_NAME = "TPUJOB_NAME"
ENV_NAMESPACE = "TPUJOB_NAMESPACE"
ENV_REPLICA_TYPE = "TPUJOB_REPLICA_TYPE"
ENV_REPLICA_INDEX = "TPUJOB_REPLICA_INDEX"
ENV_PORT = "TPUJOB_PORT"
ENV_CHIPS = "TPUJOB_CHIPS"

ENV_COORDINATOR_ADDRESS = "TPUJOB_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "TPUJOB_NUM_PROCESSES"
ENV_PROCESS_ID = "TPUJOB_PROCESS_ID"
ENV_MESH_AXES = "TPUJOB_MESH_AXES"
ENV_DCN_MESH_AXES = "TPUJOB_DCN_MESH_AXES"
ENV_WORKLOAD = "TPUJOB_WORKLOAD"
# Operator API base URL (the store-over-HTTP surface): lets workloads
# report results back through the API — e.g. the Evaluator replica writing
# eval scores into TPUJobStatus.eval_metrics.
ENV_API_SERVER = "TPUJOB_API_SERVER"

# Warm-restart contract (controller → recreated gang). When the job's
# workload declares a checkpoint_dir, every created gang member gets:
#
# - ``TPUJOB_CHECKPOINT_DIR`` — the job's checkpoint directory
# - ``TPUJOB_RESUME_STEP``    — latest checkpointed step at creation time
#                               (0 on the first, cold incarnation)
#
# The trainer's authoritative resume point stays ``latest_step()`` read
# from the directory itself (a checkpoint may land between creation and
# restore); the env is the controller's declaration that this incarnation
# is a warm restart — workloads use it to fast-forward data streams, and
# soak/chaos harnesses assert on it without parsing logs.
ENV_CHECKPOINT_DIR = "TPUJOB_CHECKPOINT_DIR"
ENV_RESUME_STEP = "TPUJOB_RESUME_STEP"

# Peer warm-restore contract (rendezvous/statechannel.py), stamped next to
# the warm-restart env above:
#
# - ``TPUJOB_PEER_DEPOT``    — this HOST's shard-depot URL (injected by the
#                              host agent's backend, not the controller): the
#                              loopback endpoint a workload pushes committed
#                              checkpoint shards to, so they survive gang
#                              teardown.
# - ``TPUJOB_RESTORE_PEERS`` — JSON list of live hosts' depot URLs (stamped
#                              by the controller on every created gang
#                              member): the candidate warm-restore sources a
#                              restarted member pulls state from before
#                              falling back to disk.
ENV_PEER_DEPOT = "TPUJOB_PEER_DEPOT"
ENV_RESTORE_PEERS = "TPUJOB_RESTORE_PEERS"

# Elastic-gang contract (r12), stamped next to the warm-restart env above:
#
# - ``TPUJOB_RESIZE_EPOCH`` — the job's monotonic resize epoch at the
#                             moment this process was created (0 on a
#                             never-resized gang). A nonzero value is the
#                             controller's declaration that this process
#                             joins an elastic gang mid-resize (a re-grown
#                             member, or a member created into a shrunk
#                             world) — it must read the live resize
#                             directive from the job status
#                             (JobContext.poll_resize_directive) before
#                             carving data or joining the barrier, because
#                             the env of SURVIVING members is frozen at
#                             their creation: the directive in the job
#                             object, not the env, is the live truth.
ENV_RESIZE_EPOCH = "TPUJOB_RESIZE_EPOCH"

# Sub-second TTFS contract (r11, cachesvc/ + runtime/warmpool.py):
#
# - ``TPUJOB_COMPILE_CACHE`` — the fleet compile-cache service URL
#                              (stamped by the controller on every created
#                              gang member): compile_cache.enable() turns
#                              its hardened cache I/O into a read-through/
#                              write-back remote tier against it. Unset =
#                              the PR 10 local-only path.
# - ``TPUJOB_WARM_SLOT``     — "1" when this process was handed a
#                              pre-warmed runtime slot by the host agent's
#                              warm pool instead of a cold spawn (set by
#                              the warm child on itself, never by the
#                              controller): workloads surface it on the
#                              compile-cache span so the bench can split
#                              TTFS into warm/cold populations.
ENV_COMPILE_CACHE = "TPUJOB_COMPILE_CACHE"
ENV_WARM_SLOT = "TPUJOB_WARM_SLOT"

# Trace context (obs/): the job's trace id — its uid — injected by the
# controller into every created gang member (alongside the warm-restart
# env above) so spans recorded by the agent/backend and by the workload
# itself (``JobContext.record_span`` / ``mark_first_step`` over
# ENV_API_SERVER) land in the SAME per-job timeline the controller and
# scheduler write into. Stable across gang restarts: the timeline spans
# the job, not one incarnation.
ENV_TRACE_ID = "TPUJOB_TRACE_ID"

# Hang forensics (r15, obs/blackbox.py): directory where the harness's
# faulthandler hook writes all-thread stack dumps when the host agent
# delivers SIGUSR2 during a stack sweep. Injected by the HOST AGENT's
# backend (like TPUJOB_PEER_DEPOT — the path is host-local, the
# controller cannot know it); the harness writes one file per process,
# ``{namespace}_{process-name}.stack``, which the agent reads back and
# ships through the store/API seam. Unset = no hook installed (a plain
# SIGUSR2 then kills the process — the default disposition).
ENV_STACKDUMP_DIR = "TPUJOB_STACKDUMP_DIR"


def stackdump_path(
    dump_dir: str, namespace: str, job_name: str,
    replica_type: str, replica_index: int,
) -> str:
    """The per-process stack-dump file BOTH sides of the SIGUSR2 contract
    compute independently: the harness writes here when the signal lands,
    the host agent reads here after delivering it. Mirrors the backend's
    log-path sanitization (basename() forecloses traversal via crafted
    names; validation also rejects them at admission)."""
    import os as _os

    return _os.path.join(
        dump_dir,
        f"{_os.path.basename(namespace)}_{_os.path.basename(job_name)}"
        f"-{replica_type.lower()}-{int(replica_index)}.stack",
    )


def identity_env(spec: "ProcessSpec", namespace: str) -> Dict[str, str]:
    """Identity env derived from a ProcessSpec; the backend injects this so
    a launched harness can always resolve its entrypoint and identity."""
    return {
        ENV_ENTRYPOINT: spec.entrypoint,
        ENV_JOB_NAME: spec.job_name,
        ENV_NAMESPACE: namespace,
        ENV_REPLICA_TYPE: spec.replica_type,
        ENV_REPLICA_INDEX: str(spec.replica_index),
        ENV_PORT: str(spec.port),
        ENV_CHIPS: str(spec.chips),
    }
