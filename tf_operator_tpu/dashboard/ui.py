"""Single-page dashboard UI (served at /ui).

Reference parity: dashboard/frontend/src/** — the React components JobList,
JobDetail (with pod list + logs), CreateJob (+ CreateReplicaSpec,
EnvVarCreator), and the namespace selector (services.js API client). Here a
dependency-free vanilla-JS SPA with hash routing over the same REST API;
all dynamic content is inserted via textContent so object fields are never
interpreted as HTML.

Routes: #/jobs  #/job/<ns>/<name>  #/create  #/events  #/fleet
"""

UI_HTML = r"""<!doctype html>
<html><head><meta charset="utf-8"><title>TPUJob dashboard</title>
<style>
 :root{--fg:#1a1a1a;--muted:#667;--line:#ddd;--bg:#fafafa;--card:#fff;
       --ok:#0a7d32;--bad:#c0392b;--run:#1a6fb5;--warn:#b26a00}
 body{font-family:system-ui,sans-serif;margin:0;background:var(--bg);color:var(--fg)}
 header{display:flex;align-items:center;gap:1rem;padding:.7rem 1.2rem;
        background:#222;color:#eee}
 header h1{font-size:16px;margin:0;font-weight:600}
 header a{color:#bcd;text-decoration:none;font-size:14px;padding:.2rem .5rem;border-radius:4px}
 header a.active{background:#444;color:#fff}
 main{padding:1rem 1.2rem;max-width:1100px;margin:0 auto}
 table{border-collapse:collapse;width:100%;background:var(--card);font-size:13.5px}
 th,td{border:1px solid var(--line);padding:5px 9px;text-align:left;vertical-align:top}
 th{background:#f0f0f0;font-weight:600}
 .Done,.Succeeded,.phase-Done{color:var(--ok)}
 .Failed,.phase-Failed{color:var(--bad)}
 .Running,.phase-Running{color:var(--run)}
 .CleanUp,.Restarting{color:var(--warn)}
 .muted{color:var(--muted)} .mono{font-family:ui-monospace,monospace;font-size:12.5px}
 button{font:inherit;padding:.25rem .7rem;border:1px solid #aaa;border-radius:4px;
        background:#fff;cursor:pointer} button:hover{background:#f0f0f0}
 button.danger{color:var(--bad);border-color:var(--bad)}
 select,input,textarea{font:inherit;padding:.25rem .4rem;border:1px solid #bbb;border-radius:4px}
 .card{background:var(--card);border:1px solid var(--line);border-radius:6px;
       padding:.8rem 1rem;margin-bottom:1rem}
 .card h2{font-size:15px;margin:.1rem 0 .6rem}
 .row{display:flex;gap:1rem;flex-wrap:wrap;align-items:center;margin-bottom:.6rem}
 pre.logs{background:#111;color:#dfe;padding:.7rem;border-radius:6px;max-height:420px;
          overflow:auto;font-size:12px;white-space:pre-wrap}
 .kv{display:grid;grid-template-columns:max-content 1fr;gap:.15rem .9rem;font-size:13.5px}
 .kv b{font-weight:600}
 .err{color:var(--bad);white-space:pre-wrap;font-size:13px}
 label{font-size:13px;color:var(--muted);display:block}
 .replica{border:1px dashed #ccc;border-radius:6px;padding:.6rem;margin:.4rem 0}
 textarea{width:100%;min-height:180px}
 svg.spark{background:#f7f7f7;border:1px solid var(--line);border-radius:3px}
</style></head>
<body>
<header>
 <h1>TPUJob</h1>
 <a href="#/jobs" data-nav="jobs">Jobs</a>
 <a href="#/create" data-nav="create">Create</a>
 <a href="#/events" data-nav="events">Events</a>
 <a href="#/fleet" data-nav="fleet">Fleet</a>
 <span style="flex:1"></span>
 <select id="nsSel" title="namespace"><option value="">all namespaces</option></select>
</header>
<main id="main"></main>
<script>
'use strict';
const $main = document.getElementById('main');
const $ns = document.getElementById('nsSel');
let timer = null;

function el(tag, attrs, ...children){
  const e = document.createElement(tag);
  for (const [k,v] of Object.entries(attrs||{})){
    if (k === 'class') e.className = v;
    else if (k.startsWith('on')) e.addEventListener(k.slice(2), v);
    else e.setAttribute(k, v);
  }
  for (const c of children)
    e.appendChild(typeof c === 'string' ? document.createTextNode(c) : c);
  return e;
}
async function api(path, opts){
  const r = await fetch(path, opts);
  const ctype = r.headers.get('Content-Type') || '';
  const body = ctype.includes('json') ? await r.json() : await r.text();
  if (!r.ok) throw new Error((body && body.error) || (r.status + ' ' + r.statusText));
  return body;
}
function age(ts){
  if (!ts) return '';
  let s = Math.max(0, (Date.now()/1000) - ts);
  if (s < 90) return Math.round(s) + 's';
  if (s < 5400) return Math.round(s/60) + 'm';
  return (s/3600).toFixed(1) + 'h';
}
function fmtTime(ts){ return ts ? new Date(ts*1000).toLocaleString() : ''; }
// Inline SVG sparkline over a numeric series (newest telemetry window last).
function spark(values, color){
  const W = 160, H = 34, P = 2;
  const svg = document.createElementNS('http://www.w3.org/2000/svg','svg');
  svg.setAttribute('width', W); svg.setAttribute('height', H);
  svg.setAttribute('class','spark');
  if (values.length){
    const mx = Math.max(...values), mn = Math.min(...values);
    const span = (mx - mn) || 1;
    const pts = values.map((v,i)=>{
      const x = values.length > 1 ? P + i*(W-2*P)/(values.length-1) : W/2;
      const y = H - P - (v - mn)*(H-2*P)/span;
      return x.toFixed(1)+','+y.toFixed(1);
    }).join(' ');
    const pl = document.createElementNS('http://www.w3.org/2000/svg','polyline');
    pl.setAttribute('points', pts);
    pl.setAttribute('fill','none');
    pl.setAttribute('stroke', color||'#1a6fb5');
    pl.setAttribute('stroke-width','1.5');
    svg.appendChild(pl);
  }
  return svg;
}
function qns(){ return $ns.value ? ('?namespace=' + encodeURIComponent($ns.value)) : ''; }

async function refreshNamespaces(){
  try{
    const d = await api('/api/namespaces');
    const cur = $ns.value;
    while ($ns.options.length > 1) $ns.remove(1);
    for (const n of d.items) $ns.appendChild(el('option', {value:n}, n));
    $ns.value = cur;
  }catch(e){/* header stays */}
}
$ns.addEventListener('change', route);

// ---- job list --------------------------------------------------------------
async function viewJobs(){
  const d = await api('/api/tpujob' + qns());
  const tbody = el('tbody');
  for (const j of d.items){
    const reps = Object.entries(j.spec.replica_specs||{})
      .map(([k,v])=>k+':'+v.replicas).join(' ');
    const conds = (j.status.conditions||[]).filter(c=>c.status).map(c=>c.type).join(', ');
    // Serve jobs report live request counts over the eval_metrics channel.
    const m = (j.status.eval_metrics||{}).metrics||{};
    const reqs = (m.requests_total===undefined) ? '' :
      (m.requests_completed||0)+'/'+(m.requests_total||0)
      + ((m.requests_active||0) ? ' ('+m.requests_active+' active)' : '');
    const link = el('a', {href:'#/job/'+j.metadata.namespace+'/'+j.metadata.name},
                    j.metadata.name);
    const del = el('button', {class:'danger', onclick: async (ev)=>{
      ev.preventDefault();
      if (!confirm('Delete '+j.metadata.namespace+'/'+j.metadata.name+'?')) return;
      await api('/api/tpujob/'+j.metadata.namespace+'/'+j.metadata.name, {method:'DELETE'});
      route();
    }}, 'delete');
    tbody.appendChild(el('tr', null,
      el('td', null, j.metadata.namespace), el('td', null, link),
      el('td', {class:'phase-'+j.phase}, j.phase||''),
      el('td', null, reps),
      el('td', null, String(j.status.restart_count||0)),
      el('td', null, reqs),
      el('td', {class:'muted'}, conds),
      el('td', {class:'muted'}, age(j.metadata.creation_timestamp)),
      el('td', null, del)));
  }
  render(el('div', null, el('table', null,
    el('thead', null, el('tr', null, ...['Namespace','Name','Phase','Replicas',
      'Restarts','Requests','Conditions','Age',''].map(h=>el('th',null,h)))), tbody)));
}

// ---- job detail ------------------------------------------------------------
async function viewJob(ns, name){
  let d;
  try{ d = await api('/api/tpujob/'+ns+'/'+name); }
  catch(e){ return render(el('div',{class:'err'}, String(e.message))); }
  const j = d.job;
  const root = el('div');

  const kv = el('div', {class:'kv'});
  const pairs = [
    ['Phase', j.phase||''], ['Created', fmtTime(j.metadata.creation_timestamp)],
    ['Started', fmtTime(j.status.start_time)],
    ['Completed', fmtTime(j.status.completion_time)],
    ['Gang restarts', String(j.status.restart_count||0)
       + (j.status.preemption_count ? ' (+'+j.status.preemption_count+' preempted)' : '')
       + (j.status.last_restart_cause ? ' — last: '+j.status.last_restart_cause : '')],
    // world_size 0 = never resized (spec-derived gang size applies)
    ['World', (j.status.world_size ? String(j.status.world_size) : 'spec')
       + (j.status.resize_epoch ? ' @ resize epoch '+j.status.resize_epoch : '')
       + (j.status.resize_count ? ' ('+j.status.resize_count+' resizes)' : '')],
    ['Slice', j.spec.topology.slice_type ||
       (j.spec.topology.num_hosts+'x'+j.spec.topology.chips_per_host+' chips')],
    ['Mesh', JSON.stringify(j.spec.topology.mesh_axes||{})],
    ['UID', j.metadata.uid],
  ];
  for (const [k,v] of pairs){ kv.appendChild(el('b',null,k)); kv.appendChild(el('span',null,v)); }
  root.appendChild(el('div',{class:'card'},
    el('h2',null, ns+'/'+name),
    kv));

  const ctb = el('tbody');
  for (const c of (j.status.conditions||[]))
    ctb.appendChild(el('tr', null,
      el('td',{class:c.type}, c.type), el('td',null,String(c.status)),
      el('td',null,c.reason||''), el('td',{class:'muted'},c.message||''),
      el('td',{class:'muted'}, fmtTime(c.last_transition_time))));
  root.appendChild(el('div',{class:'card'}, el('h2',null,'Conditions'),
    el('table',null, el('thead',null, el('tr',null,
      ...['Type','Status','Reason','Message','Transition'].map(h=>el('th',null,h)))), ctb)));

  const rtb = el('tbody');
  for (const [rt, rs] of Object.entries(j.status.replica_statuses||{}))
    rtb.appendChild(el('tr',null, el('td',null,rt),
      el('td',null,String(rs.active)), el('td',null,String(rs.succeeded)),
      el('td',null,String(rs.failed))));
  root.appendChild(el('div',{class:'card'}, el('h2',null,'Replica status'),
    el('table',null, el('thead',null, el('tr',null,
      ...['Type','Active','Succeeded','Failed'].map(h=>el('th',null,h)))), rtb)));

  // Elastic resize audit (r12): the append-only shrink/grow history.
  if ((j.status.resize_history||[]).length){
    const ztb = el('tbody');
    for (const r of j.status.resize_history)
      ztb.appendChild(el('tr',null, el('td',null,String(r.epoch)),
        el('td',null,r.direction||''), el('td',null,String(r.world_size)),
        el('td',null,r.cause||''), el('td',{class:'muted'}, fmtTime(r.time))));
    root.appendChild(el('div',{class:'card'}, el('h2',null,'Resize history'),
      el('table',null, el('thead',null, el('tr',null,
        ...['Epoch','Direction','World','Cause','Time'].map(h=>el('th',null,h)))), ztb)));
  }

  // Goodput autopilot (r16): active cadence + the last executed
  // decision with its justifying numbers (the status mirror of the
  // authoritative autopilot-decision span).
  if (j.status.autopilot && Object.keys(j.status.autopilot).length){
    const a = j.status.autopilot, last = a.last_decision||{};
    const akv = el('div',{class:'kv'});
    const apairs = [
      ['Decisions', String(a.decisions_total||0)],
      ['Checkpoint every', String(a.active_checkpoint_every||0)+' steps'],
      ['Last decision', (last.kind||'?')+': '+(last.action||'?')],
      ['At', fmtTime(last.time)],
    ];
    for (const [k,v] of apairs){ akv.appendChild(el('b',null,k)); akv.appendChild(el('span',null,v)); }
    root.appendChild(el('div',{class:'card'}, el('h2',null,'Autopilot'), akv));
  }

  // Hang forensics (r15): a declared hang is the headline — stuck step +
  // seconds-since-progress, not stale tokens/s.
  if (j.status.hang_state && Object.keys(j.status.hang_state).length){
    const h = j.status.hang_state;
    const ago = h.since ? Math.max(0, Date.now()/1000 - h.since).toFixed(0)+'s' : '?';
    const hkv = el('div',{class:'kv'});
    const hpairs = [
      ['Stuck at step', String(h.stuck_step!==undefined ? h.stuck_step : '?')],
      ['No progress for', ago],
      ['Last moving ranks', JSON.stringify(h.last_moving_ranks||[])],
      ['Declared', fmtTime(h.time)],
    ];
    for (const [k,v] of hpairs){ hkv.appendChild(el('b',null,k)); hkv.appendChild(el('span',null,v)); }
    root.appendChild(el('div',{class:'card'}, el('h2',null,'HUNG'), hkv));
  }
  // Postmortem link: rendered only when a bundle is actually frozen
  // (the route 404s otherwise — loud for tools, absent for the UI).
  try{
    const pm = await api('/api/tpujob/'+ns+'/'+name+'/postmortem');
    root.appendChild(el('div',{class:'card'}, el('h2',null,'Postmortem'),
      el('div',null,
        'frozen: '+pm.reason+', '+(pm.stackdumps||[]).length+' rank stack dump(s) — ',
        el('a',{href:'/api/tpujob/'+ns+'/'+name+'/postmortem'}, 'bundle JSON'),
        el('span',{class:'muted'}, '  (tar: tpujob debug '+ns+' '+name+')'))));
  }catch(err){/* no postmortem frozen — the card simply stays absent */}

  // Live step telemetry (r13): sparklines over the per-rank ring batches
  // plus the gang summary and goodput decomposition.
  try{
    const t = await api('/api/tpujob/'+ns+'/'+name+'/telemetry');
    if ((t.batches||[]).length){
      const s = t.summary||{}, g = t.goodput||{};
      const bySeq = {};
      for (const b of t.batches){
        const k = b.seq;
        if (!bySeq[k]) bySeq[k] = {tok:0, mfu:0, n:0};
        bySeq[k].tok += (b.tokens_per_s||0);
        bySeq[k].mfu += (b.mfu||0); bySeq[k].n += 1;
      }
      const seqs = Object.keys(bySeq).map(Number).sort((a,b)=>a-b);
      const tok = seqs.map(k=>bySeq[k].tok);
      const mfu = seqs.map(k=>bySeq[k].mfu/(bySeq[k].n||1));
      const tkv = el('div',{class:'kv'});
      const spread = s.spread ? s.spread.toFixed(2)+'x' : '';
      const tpairs = [
        ['Tokens/s', (s.tokens_per_s||0).toLocaleString(undefined,{maximumFractionDigits:1})],
        ['MFU', (s.mfu||0).toFixed(3)],
        ['Step', String(s.last_step||0) + ' (ranks: '+(s.ranks||0)+')'],
        ['Step-time spread', spread],
      ];
      if (g.goodput_ratio !== undefined){
        const lost = Object.entries(g.lost_s||{}).filter(([,v])=>v>0)
          .map(([c,v])=>c+': '+v.toFixed(1)+'s').join('  ');
        tpairs.push(['Goodput', g.goodput_ratio.toFixed(3) + (lost? '  ('+lost+')':'')]);
      }
      if (s.degraded) tpairs.push(['Degraded', 'some ranks report local-only']);
      for (const [k,v] of tpairs){ tkv.appendChild(el('b',null,k)); tkv.appendChild(el('span',null,v)); }
      root.appendChild(el('div',{class:'card'}, el('h2',null,'Telemetry'),
        tkv,
        el('div',{class:'row'},
          el('span',null, el('label',null,'tokens/s'), spark(tok,'#1a6fb5')),
          el('span',null, el('label',null,'MFU'), spark(mfu,'#0a7d32')))));
    }
  }catch(err){/* telemetry is best-effort; the card simply stays absent */}

  // Evaluator-reported scores (TPUJobStatus.eval_metrics).
  const em = j.status.eval_metrics||{};
  if (em.step !== undefined){
    const etb = el('tbody');
    for (const [k,v] of Object.entries(em.metrics||{}))
      etb.appendChild(el('tr',null, el('td',null,k),
        el('td',null, (typeof v==='number')? v.toFixed(4): String(v))));
    root.appendChild(el('div',{class:'card'},
      el('h2',null,'Eval (checkpoint step '+em.step+', '+fmtTime(em.time)+')'),
      el('table',null, el('thead',null, el('tr',null,
        ...['Metric','Value'].map(h=>el('th',null,h)))), etb)));
  }

  const logsPre = el('pre', {class:'logs', style:'display:none'});
  const ptb = el('tbody');
  for (const p of (d.processes||[])){
    const st = p.status||{};
    const exit = (st.exit_code===null||st.exit_code===undefined)?'':String(st.exit_code);
    const logBtn = el('button', {onclick: async ()=>{
      logsPre.style.display = '';
      logsPre.textContent = '(loading '+p.metadata.name+' logs…)';
      try{
        logsPre.textContent = await api('/api/process/'+ns+'/'+p.metadata.name+'/logs');
      }catch(e){ logsPre.textContent = 'error: '+e.message; }
    }}, 'logs');
    ptb.appendChild(el('tr',null,
      el('td',{class:'mono'},p.metadata.name),
      el('td',null,p.spec.replica_type), el('td',null,String(p.spec.replica_index)),
      el('td',{class:st.phase},st.phase||''), el('td',null,exit),
      el('td',{class:'muted'},st.reason||''), el('td',null,logBtn)));
  }
  root.appendChild(el('div',{class:'card'}, el('h2',null,'Processes'),
    el('table',null, el('thead',null, el('tr',null,
      ...['Name','Type','Index','Phase','Exit','Reason',''].map(h=>el('th',null,h)))), ptb),
    logsPre));

  const etb = el('tbody');
  try{
    const evs = await api('/api/events?namespace='+encodeURIComponent(ns));
    const mine = (e)=>{const n = e.involved_name||'';
      return n === name || n.startsWith(name+'-');};
    for (const e of evs.items.filter(mine).slice(-30).reverse())
      etb.appendChild(el('tr',null,
        el('td',{class:e.type==='Warning'?'Failed':'muted'},e.type),
        el('td',null,e.reason||''), el('td',{class:'muted'},e.message||''),
        el('td',{class:'muted'},age(e.metadata.creation_timestamp)+' ago')));
  }catch(err){}
  root.appendChild(el('div',{class:'card'}, el('h2',null,'Events'),
    el('table',null, el('thead',null, el('tr',null,
      ...['Type','Reason','Message','Age'].map(h=>el('th',null,h)))), etb)));
  render(root);
}

// ---- create ----------------------------------------------------------------
function replicaBlock(rt, entry, n){
  const b = el('div', {class:'replica'});
  b.appendChild(el('div',{class:'row'},
    el('span',null, el('label',null,'role'),
      el('select',{'data-f':'rtype'},
        ...['Worker','Coordinator','Evaluator'].map(v=>{
          const o = el('option',{value:v},v); if (v===rt) o.selected = true; return o;}))),
    el('span',null, el('label',null,'replicas'),
      el('input',{'data-f':'replicas',type:'number',min:'0',value:String(n),style:'width:5rem'})),
    el('span',null, el('label',null,'entrypoint (pkg.module:fn)'),
      el('input',{'data-f':'entrypoint',value:entry,style:'width:22rem',class:'mono'})),
    el('span',null, el('label',null,'restart policy'),
      el('select',{'data-f':'rp'}, ...['','ExitCode','Always','OnFailure','Never']
        .map(v=>el('option',{value:v}, v||'(default)')))),
    el('button',{onclick:(e)=>{e.preventDefault(); b.remove();}},'remove role')));
  b.appendChild(el('div',{class:'row'},
    el('span',{style:'flex:1'},
      el('label',null,'env (KEY=VALUE per line)'),
      el('textarea',{'data-f':'env',style:'min-height:3.2rem'})),
    el('span',{style:'flex:1'},
      el('label',null,'args (one per line)'),
      el('textarea',{'data-f':'args',style:'min-height:3.2rem',class:'mono'}))));
  return b;
}
// Mesh axes as structured name x size rows (dp/tp/cp/pp/ep/fsdp — the
// parallel.mesh vocabulary) instead of a raw JSON field.
function meshAxisRow(name, size){
  const r = el('span',{class:'axisrow'},
    el('select',{'data-f':'axname'},
      ...['dp','fsdp','tp','cp','pp','ep'].map(v=>{
        const o = el('option',{value:v},v); if (v===name) o.selected = true; return o;})),
    el('input',{'data-f':'axsize',type:'number',min:'1',value:String(size),style:'width:4rem'}),
    el('button',{onclick:(e)=>{e.preventDefault(); r.remove();}},'x'));
  return r;
}

// Known workload entrypoints -> sensible template (the reference's
// CreateJob form hardcoded its image defaults the same way).
const WORKLOADS = {
  'smoke (every-device op check)': {entry:'tf_operator_tpu.workloads.smoke:main', wl:{dim:64}},
  'mnist (idx data_dir or synthetic)': {entry:'tf_operator_tpu.workloads.mnist:main', wl:{epochs:10, batch_size:128}},
  'lm (transformer pretrain)': {entry:'tf_operator_tpu.workloads.lm:main', wl:{preset:'tiny', steps:10, batch_size:8, seq_len:128}},
  'resnet (image classification)': {entry:'tf_operator_tpu.workloads.resnet:main', wl:{steps:10, batch_size:32}},
  'eval (checkpoint scorer)': {entry:'tf_operator_tpu.workloads.eval:main', wl:{preset:'tiny', checkpoint_dir:'/tmp/ckpt'}},
  'serve (continuous-batching inference)': {entry:'tf_operator_tpu.workloads.serve:main', wl:{preset:'tiny', requests:8, kv_page_size:16, kv_pool_pages:64, max_slots:4}},
  'custom': {entry:'', wl:{}},
};

function viewCreate(){
  const errBox = el('div',{class:'err'});
  const nameIn = el('input',{value:'job-'+Math.random().toString(36).slice(2,7)});
  const nsIn = el('input',{value:$ns.value||'default'});
  const sliceIn = el('input',{value:'',placeholder:'e.g. v5e-8'});
  const hostsIn = el('input',{type:'number',min:'1',value:'1',style:'width:5rem'});
  const chipsIn = el('input',{type:'number',min:'0',value:'0',style:'width:5rem'});
  const axes = el('span');
  const addAxis = el('button',{onclick:(e)=>{e.preventDefault();
    axes.appendChild(meshAxisRow('dp',1));}},'+ axis');
  const wlIn = el('textarea',{style:'min-height:4rem',class:'mono'});
  wlIn.value = '{}';
  const reps = el('div');
  reps.appendChild(replicaBlock('Worker','tf_operator_tpu.workloads.smoke:main',2));
  const addBtn = el('button',{onclick:(e)=>{e.preventDefault();
    reps.appendChild(replicaBlock('Worker','',1));}},'+ add role');
  const wlSel = el('select',{onchange:()=>{
    const w = WORKLOADS[wlSel.value]; if (!w) return;
    wlIn.value = JSON.stringify(w.wl, null, 1);
    const first = reps.querySelector('[data-f=entrypoint]');
    if (first && w.entry) first.value = w.entry;
  }}, ...Object.keys(WORKLOADS).map(k=>el('option',{value:k},k)));

  const jsonArea = el('textarea',{class:'mono'});
  function buildSpec(){
    const replica_specs = {};
    for (const b of reps.querySelectorAll('.replica')){
      const f = (sel)=>b.querySelector('[data-f='+sel+']');
      const env = {};
      for (const line of f('env').value.split('\n').map(s=>s.trim()).filter(Boolean)){
        const i = line.indexOf('='); if (i>0) env[line.slice(0,i)] = line.slice(i+1);
      }
      const spec = {replicas: Number(f('replicas').value),
        template: {entrypoint: f('entrypoint').value, env,
                   args: f('args').value.split('\n').map(s=>s.trim()).filter(Boolean)}};
      if (f('rp').value) spec.restart_policy = f('rp').value;
      replica_specs[f('rtype').value] = spec;
    }
    const mesh = {};
    for (const r of axes.querySelectorAll('.axisrow')){
      const n = r.querySelector('[data-f=axname]').value;
      if (mesh[n] !== undefined) throw new Error('mesh axes: duplicate axis '+n);
      const v = Number(r.querySelector('[data-f=axsize]').value);
      if (!Number.isInteger(v) || v < 1)
        throw new Error('mesh axes: '+n+' needs an integer size >= 1');
      mesh[n] = v;
    }
    let wl = {};
    try{ wl = JSON.parse(wlIn.value||'{}'); }catch(e){ throw new Error('workload: '+e.message); }
    return {metadata:{name:nameIn.value, namespace:nsIn.value},
      spec:{replica_specs,
        topology:{slice_type:sliceIn.value, num_hosts:Number(hostsIn.value),
                  chips_per_host:Number(chipsIn.value), mesh_axes:mesh},
        workload: wl}};
  }
  async function submit(body){
    errBox.textContent = '';
    try{
      const out = await api('/api/tpujob', {method:'POST',
        headers:{'Content-Type':'application/json'}, body: JSON.stringify(body)});
      location.hash = '#/job/'+out.metadata.namespace+'/'+out.metadata.name;
    }catch(e){ errBox.textContent = e.message; }
  }
  render(el('div', null,
    el('div',{class:'card'}, el('h2',null,'Create TPUJob'),
      el('div',{class:'row'},
        el('span',null, el('label',null,'name'), nameIn),
        el('span',null, el('label',null,'namespace'), nsIn)),
      el('div',{class:'row'},
        el('span',null, el('label',null,'slice type'), sliceIn),
        el('span',null, el('label',null,'hosts'), hostsIn),
        el('span',null, el('label',null,'chips/host'), chipsIn),
        el('span',null, el('label',null,'mesh axes'), axes, addAxis)),
      el('div',{class:'row'},
        el('span',null, el('label',null,'workload'), wlSel)),
      el('label',null,'workload config (JSON, passed to every process)'), wlIn,
      reps, addBtn, el('span',null,' '),
      el('button',{onclick:(e)=>{e.preventDefault();
        try{ submit(buildSpec()); }catch(err){ errBox.textContent = err.message; }}},
        'Submit'),
      el('span',null,' '),
      el('button',{onclick:(e)=>{e.preventDefault();
        try{ jsonArea.value = JSON.stringify(buildSpec(), null, 2); }
        catch(err){ errBox.textContent = err.message; }}}, 'Form → JSON'),
      errBox),
    el('div',{class:'card'}, el('h2',null,'Raw JSON'),
      jsonArea,
      el('div',{class:'row'},
        el('button',{onclick:(e)=>{e.preventDefault();
          try{ submit(JSON.parse(jsonArea.value)); }
          catch(err){ errBox.textContent = err.message; }}}, 'Submit JSON')))));
}

// ---- events ----------------------------------------------------------------
async function viewEvents(){
  const d = await api('/api/events' + qns());
  const tb = el('tbody');
  for (const e of d.items.slice(-200).reverse())
    tb.appendChild(el('tr',null,
      el('td',{class:e.type==='Warning'?'Failed':'muted'},e.type),
      el('td',null,e.metadata.namespace),
      el('td',{class:'mono'},e.involved_name||''),
      el('td',null,e.reason||''), el('td',{class:'muted'},e.message||''),
      el('td',{class:'muted'},age(e.metadata.creation_timestamp)+' ago')));
  render(el('table',null, el('thead',null, el('tr',null,
    ...['Type','Namespace','Object','Reason','Message','Age'].map(h=>el('th',null,h)))), tb));
}

// ---- fleet -----------------------------------------------------------------
// Cross-job ledger view (obs/ledger.py): rollups over every job that ever
// reached a terminal, durable across operator restarts and job GC.
async function viewFleet(){
  let s, h;
  try{
    s = await api('/api/fleet/summary');
    h = await api('/api/fleet/hosts');
  }catch(e){ return render(el('div',{class:'err'},
    'fleet ledger unavailable: '+String(e.message||e))); }
  const root = el('div');

  const kv = el('div',{class:'kv'});
  const phases = Object.entries(s.phases||{}).map(([p,n])=>p+': '+n).join('  ');
  const pairs = [
    ['Jobs folded', String(s.jobs||0) + '  (' + phases + ')'],
    ['Failures', String(s.failures||0)],
    ['Fleet MTBF', s.mtbf_s!==null && s.mtbf_s!==undefined ? s.mtbf_s.toFixed(1)+'s' : 'none observed'],
    ['Goodput mean', (s.goodput_mean||0).toFixed(3)],
  ];
  if (s.compile_cache){
    const c = s.compile_cache;
    pairs.push(['Compile cache', 'hits '+(c.hits||0)+', misses '+(c.misses||0)
      +', miss rate '+((c.miss_rate||0)*100).toFixed(1)+'%'
      +', evictions '+(c.evictions||0)+', intents '+(c.intents||0)]);
  }
  for (const [k,v] of pairs){ kv.appendChild(el('b',null,k)); kv.appendChild(el('span',null,v)); }
  const hist = Object.entries(s.goodput_hist||{})
    .map(([b,n])=>b+': '+n).join('   ');
  root.appendChild(el('div',{class:'card'}, el('h2',null,'Fleet'), kv,
    el('div',{class:'muted',style:'margin-top:.4rem'},'goodput histogram  '+hist)));

  const qtb = el('tbody');
  for (const [q, v] of Object.entries(s.queues||{}))
    qtb.appendChild(el('tr',null, el('td',null,q||'(default)'),
      el('td',null,String(v.jobs)), el('td',null,String(v.failures)),
      el('td',null, v.mtbf_s!==null && v.mtbf_s!==undefined ? v.mtbf_s.toFixed(1)+'s' : '-'),
      el('td',null,(v.goodput_mean||0).toFixed(3)),
      el('td',null,(v.save_stall_s||0).toFixed(3)+'s')));
  root.appendChild(el('div',{class:'card'}, el('h2',null,'Queues'),
    el('table',null, el('thead',null, el('tr',null,
      ...['Queue','Jobs','Failures','MTBF','Goodput','Save stall'].map(x=>el('th',null,x)))), qtb)));

  const ctb = el('tbody');
  for (const [c, v] of Object.entries(s.causes||{}))
    ctb.appendChild(el('tr',null, el('td',null,c),
      el('td',null,String(v.incidents)), el('td',null,(v.lost_s||0).toFixed(1)+'s'),
      el('td',null,(v.lost_p50_s||0).toFixed(1)+'s'),
      el('td',null,(v.lost_p90_s||0).toFixed(1)+'s'),
      el('td',null,(v.lost_p99_s||0).toFixed(1)+'s')));
  root.appendChild(el('div',{class:'card'}, el('h2',null,'Downtime by cause'),
    el('table',null, el('thead',null, el('tr',null,
      ...['Cause','Incidents','Lost','p50','p90','p99'].map(x=>el('th',null,x)))), ctb)));

  const htb = el('tbody');
  for (const [host, v] of Object.entries(h.hosts||{}))
    htb.appendChild(el('tr',null, el('td',{class:'mono'},host),
      el('td',null,String(v.jobs)),
      el('td',{class:v.incident_jobs? 'Failed':''},String(v.incident_jobs)),
      el('td',null,String(v.failures)),
      el('td',{class:'muted'},age(v.last_end_ts)+' ago')));
  root.appendChild(el('div',{class:'card'}, el('h2',null,'Hosts'),
    el('table',null, el('thead',null, el('tr',null,
      ...['Host','Jobs','Incident jobs','Failures','Last seen'].map(x=>el('th',null,x)))), htb)));
  render(root);
}

// ---- router ----------------------------------------------------------------
function render(node){ $main.innerHTML=''; $main.appendChild(node); }
function setNav(which){
  for (const a of document.querySelectorAll('header a'))
    a.classList.toggle('active', a.dataset.nav === which);
}
async function route(){
  if (timer) clearTimeout(timer);
  refreshNamespaces();
  const h = location.hash || '#/jobs';
  const parts = h.slice(2).split('/');
  try{
    if (parts[0] === 'job' && parts.length >= 3){ setNav('jobs'); await viewJob(parts[1], parts.slice(2).join('/')); }
    else if (parts[0] === 'create'){ setNav('create'); viewCreate(); return; } // no auto-refresh while editing
    else if (parts[0] === 'events'){ setNav('events'); await viewEvents(); }
    else if (parts[0] === 'fleet'){ setNav('fleet'); await viewFleet(); }
    else { setNav('jobs'); await viewJobs(); }
  }catch(e){ render(el('div',{class:'err'}, String(e.message||e))); }
  timer = setTimeout(route, 3000);
}
window.addEventListener('hashchange', route);
route();
</script></body></html>
"""
