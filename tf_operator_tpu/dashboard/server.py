"""Threaded HTTP server exposing the store: REST API + static UI.

Routes (reference: dashboard/backend/handler/api_handler.go:74-113):

- GET    /api/tpujob                      — list jobs (?namespace=)
- POST   /api/tpujob                      — submit a job (JSON body)
- GET    /api/tpujob/{ns}/{name}          — job detail + processes + endpoints
- DELETE /api/tpujob/{ns}/{name}          — delete job (controller GCs children)
- GET    /api/process/{ns}/{name}/logs    — process logs (kubelet-log analogue)
- GET    /api/events?namespace=           — events (the test oracle surface)
- GET    /api/namespaces                  — namespaces in use
- GET    /ui                              — single-page app (dashboard/ui.py):
  job list/detail with processes+logs+events, create form, events view —
  the reference React frontend's JobList/JobDetail/CreateJob surface
- GET    /healthz                         — liveness
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from tf_operator_tpu.api.types import (
    KIND_ENDPOINT,
    KIND_EVENT,
    KIND_PROCESS,
    KIND_TPUJOB,
    LABEL_JOB_NAME,
    TPUJob,
)
from tf_operator_tpu.api import set_defaults, validate_job, ValidationError
from tf_operator_tpu.api.types import _to_jsonable
from tf_operator_tpu.runtime.process_backend import LocalProcessControl
from tf_operator_tpu.runtime.store import AlreadyExistsError, NotFoundError, Store

from tf_operator_tpu.dashboard.ui import UI_HTML as _UI_HTML

_JOB_RE = re.compile(r"^/api/tpujob/([^/]+)/([^/]+)$")
_LOGS_RE = re.compile(r"^/api/process/([^/]+)/([^/]+)/logs$")


class _Handler(BaseHTTPRequestHandler):
    server_version = "tpujob-dashboard/0.1"
    store: Store = None  # set by server factory
    metrics = None  # ControllerMetrics, set by server factory when wired

    # silence default request logging
    def log_message(self, fmt, *args):
        del fmt, args

    # -- helpers ----------------------------------------------------------

    def _json(self, code: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, message: str) -> None:
        self._json(code, {"error": message})

    def _job_payload(self, job: TPUJob) -> dict:
        d = job.to_dict()
        d["phase"] = job.status.phase().value
        return d

    # -- GET --------------------------------------------------------------

    def do_GET(self):  # noqa: N802 (stdlib casing)
        url = urlparse(self.path)
        q = parse_qs(url.query)
        ns = q.get("namespace", [None])[0]
        path = url.path

        if path == "/healthz":
            return self._json(200, {"ok": True})
        if path == "/metrics":
            if self.metrics is None:
                return self._error(404, "metrics not wired (no controller)")
            body = self.metrics.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path in ("/", "/ui"):
            body = _UI_HTML.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path == "/api/tpujob":
            jobs = self.store.list(KIND_TPUJOB, namespace=ns)
            return self._json(200, {"items": [self._job_payload(j) for j in jobs]})
        if path == "/api/namespaces":
            spaces = sorted({j.metadata.namespace for j in self.store.list(KIND_TPUJOB)})
            return self._json(200, {"items": spaces})
        if path == "/api/events":
            evs = self.store.list(KIND_EVENT, namespace=ns)
            return self._json(200, {"items": [_to_jsonable(e) for e in evs]})

        m = _JOB_RE.match(path)
        if m:
            ns, name = m.groups()
            try:
                job = self.store.get(KIND_TPUJOB, ns, name)
            except NotFoundError:
                return self._error(404, f"tpujob {ns}/{name} not found")
            procs = self.store.list(
                KIND_PROCESS, namespace=ns, label_selector={LABEL_JOB_NAME: name}
            )
            eps = self.store.list(
                KIND_ENDPOINT, namespace=ns, label_selector={LABEL_JOB_NAME: name}
            )
            return self._json(
                200,
                {
                    "job": self._job_payload(job),
                    "processes": [_to_jsonable(p) for p in procs],
                    "endpoints": [_to_jsonable(e) for e in eps],
                },
            )

        m = _LOGS_RE.match(path)
        if m:
            ns, name = m.groups()
            try:
                proc = self.store.get(KIND_PROCESS, ns, name)
            except NotFoundError:
                return self._error(404, f"process {ns}/{name} not found")
            log_path = proc.metadata.annotations.get(LocalProcessControl.LOG_ANNOTATION)
            if not log_path:
                return self._error(404, "no logs captured for this process")
            try:
                with open(log_path, "rb") as f:
                    # Tail the last 1MB without reading the whole file.
                    import os as _os

                    f.seek(0, _os.SEEK_END)
                    size = f.tell()
                    f.seek(max(0, size - 1024 * 1024))
                    data = f.read()
            except OSError as exc:
                return self._error(500, str(exc))
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return

        self._error(404, f"no route {path}")

    # -- POST / DELETE -----------------------------------------------------

    def do_POST(self):  # noqa: N802
        if urlparse(self.path).path != "/api/tpujob":
            return self._error(404, "POST only at /api/tpujob")
        length = int(self.headers.get("Content-Length", 0))
        try:
            data = json.loads(self.rfile.read(length) or b"{}")
            # Dual API generations (SURVEY.md §0): list-based v1alpha1
            # documents are converted, map-based ones decode directly.
            from tf_operator_tpu.api.v1alpha1 import parse_job

            job = parse_job(data)
            set_defaults(job)
            validate_job(job)
        except (ValueError, ValidationError, KeyError, TypeError) as exc:
            return self._error(400, f"invalid job: {exc}")
        # Namespace auto-create semantics (api_handler.go:178-218) are
        # implicit: namespaces exist by use.
        try:
            created = self.store.create(job)
        except AlreadyExistsError as exc:
            return self._error(409, str(exc))
        self._json(201, self._job_payload(created))

    def do_DELETE(self):  # noqa: N802
        m = _JOB_RE.match(urlparse(self.path).path)
        if not m:
            return self._error(404, "DELETE only at /api/tpujob/{ns}/{name}")
        ns, name = m.groups()
        try:
            self.store.delete(KIND_TPUJOB, ns, name)
        except NotFoundError:
            return self._error(404, f"tpujob {ns}/{name} not found")
        self._json(200, {"deleted": f"{ns}/{name}"})


class DashboardServer:
    def __init__(
        self, store: Store, host: str = "127.0.0.1", port: int = 8080, metrics=None
    ) -> None:
        handler = type(
            "BoundHandler", (_Handler,), {"store": store, "metrics": metrics}
        )
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="dashboard", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
