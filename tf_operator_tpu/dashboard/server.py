"""Threaded HTTP server exposing the store: REST API + static UI.

Routes (reference: dashboard/backend/handler/api_handler.go:74-113):

- GET    /api/tpujob                      — list jobs (?namespace=)
- POST   /api/tpujob                      — submit a job (JSON body)
- GET    /api/tpujob/{ns}/{name}          — job detail + processes + endpoints
- DELETE /api/tpujob/{ns}/{name}          — delete job (controller GCs children)
- GET    /api/tpujob/{ns}/{name}/trace    — the job's lifecycle trace as
  Chrome trace-event JSON (Perfetto-loadable; obs/export.py)
- GET    /api/tpujob/{ns}/{name}/telemetry — the job's live telemetry ring
  (per-rank step batches + gang summary + goodput decomposition)
- GET    /api/tpujob/{ns}/{name}/postmortem — the frozen hang/failure
  bundle + shipped per-rank stack dumps (404 LOUDLY when never frozen or
  GC'd with the job — never an empty tar)
- POST   /api/tpujob/{ns}/{name}/profile  — publish an on-demand profile
  directive (body: {"steps": N, "dir": path?}); the chief captures the
  next N steps and acks with a profile-capture span
- GET    /api/process/{ns}/{name}/logs    — process logs (kubelet-log analogue)
- GET    /api/events?namespace=           — events (the test oracle surface)
- GET    /api/namespaces                  — namespaces in use
- GET    /ui                              — single-page app (dashboard/ui.py):
  job list/detail with processes+logs+events, create form, events view —
  the reference React frontend's JobList/JobDetail/CreateJob surface
- GET    /healthz                         — liveness
- GET    /metrics                         — Prometheus text (when wired)

Generic object API (the remote-store seam; clients: runtime/remote_store.py):

- GET    /api/v1/{kind}?namespace=        — list raw objects of a kind
- POST   /api/v1/{kind}                   — create (body: serialized object)
- GET    /api/v1/{kind}/{ns}/{name}       — get
- PUT    /api/v1/{kind}/{ns}/{name}?check_version=1 — update (409 on stale)
- DELETE /api/v1/{kind}/{ns}/{name}       — delete
- GET    /api/v1/watch?kinds=A,B          — JSON-lines stream of watch
  events (existing objects replayed as ADDED first — list+watch contract)

Auth (utils.auth, r3): constructed with ``auth_token``, the server
requires ``Authorization: Bearer <token>`` on every mutating route and on
the whole /api/v1 surface (the machine seam); human read routes
(/ui, job reads, events, logs, /metrics, /healthz) stay open by default.
``auth_reads`` (r4, ``--auth-reads``) extends the same bearer to every
read route except /healthz — full reference parity, where Kubernetes
auth covers ALL API access (pkg/util/k8sutil/k8sutil.go:53-77) and the
dashboard talks to the authenticated apiserver
(dashboard/backend/client/manager.go:13-45).
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, unquote, urlparse

from tf_operator_tpu.api.types import (
    KIND_ENDPOINT,
    KIND_EVENT,
    KIND_PRIORITY_CLASS,
    KIND_PROCESS,
    KIND_QUEUE,
    KIND_TPUJOB,
    LABEL_JOB_NAME,
    TPUJob,
)
from tf_operator_tpu.api import set_defaults, validate_job, ValidationError
from tf_operator_tpu.api.validation import validate_priority_class, validate_queue
from tf_operator_tpu.api.types import _to_jsonable
from tf_operator_tpu.runtime.process_backend import LocalProcessControl
from tf_operator_tpu.runtime.serialize import KNOWN_KINDS, from_doc, to_doc
from tf_operator_tpu.runtime.store import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    Store,
)

from tf_operator_tpu.dashboard.ui import UI_HTML as _UI_HTML

_JOB_RE = re.compile(r"^/api/tpujob/([^/]+)/([^/]+)$")
_TRACE_RE = re.compile(r"^/api/tpujob/([^/]+)/([^/]+)/trace$")
_TELEMETRY_RE = re.compile(r"^/api/tpujob/([^/]+)/([^/]+)/telemetry$")
_PROFILE_RE = re.compile(r"^/api/tpujob/([^/]+)/([^/]+)/profile$")
_POSTMORTEM_RE = re.compile(r"^/api/tpujob/([^/]+)/([^/]+)/postmortem$")
_LOGS_RE = re.compile(r"^/api/process/([^/]+)/([^/]+)/logs$")
_OBJ_KIND_RE = re.compile(r"^/api/v1/([A-Za-z]+)$")
_OBJ_RE = re.compile(r"^/api/v1/([A-Za-z]+)/([^/]+)/([^/]+)$")


def _decode_segments(m):
    """Percent-decode matched path segments for the JOB routes, rejecting
    any whose decoded form is empty or contains '/' — job namespace/name
    pairs circulate as "ns/name" STRING keys (workqueue, expectations), so
    a %2F-smuggled slash would make distinct jobs collide there. Returns
    None → 400. The generic /api/v1 object routes deliberately stay
    permissive: the store keys on (kind, ns, name) TUPLES, so slashes in
    generic object names are unambiguous — and that round-trip is pinned
    by test_names_with_reserved_characters_round_trip."""
    segs = tuple(unquote(g) for g in m.groups())
    if any(not s or "/" in s for s in segs):
        return None
    return segs


class _Handler(BaseHTTPRequestHandler):
    server_version = "tpujob-dashboard/0.1"
    store: Store = None  # set by server factory
    metrics = None  # ControllerMetrics, set by server factory when wired
    ledger = None  # FleetLedger (obs/ledger.py), set by factory when wired
    watch_ping_interval: float = 15.0  # idle keep-alive period on watches
    auth_token: Optional[str] = None  # shared secret; None = open server
    auth_reads: bool = False  # r4 --auth-reads: bearer on EVERY route but /healthz

    # silence default request logging
    def log_message(self, fmt, *args):
        del fmt, args

    # -- helpers ----------------------------------------------------------

    def _authorized(self) -> bool:
        """Bearer-token check (utils.auth): mutating routes and the whole
        /api/v1 machine surface call this; no-op when no token is
        configured. On failure a 401 has already been written."""
        if self.auth_token is None:
            return True
        from tf_operator_tpu.utils.auth import check_bearer

        if check_bearer(self.headers.get("Authorization"), self.auth_token):
            return True
        self._error(401, "unauthorized")
        return False

    def _json(self, code: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, message: str) -> None:
        self._json(code, {"error": message})

    def _job_payload(self, job: TPUJob, api_version: str = "") -> dict:
        if api_version == "v1alpha1":
            # v1alpha1-generation read surface: list-shaped replica specs +
            # the phase/state status block (v1alpha1/types.go:106-160).
            from tf_operator_tpu.api.v1alpha1 import to_v1alpha1

            return to_v1alpha1(job)
        d = job.to_dict()
        d["phase"] = job.status.phase().value
        return d

    # -- GET --------------------------------------------------------------

    def do_GET(self):  # noqa: N802 (stdlib casing)
        url = urlparse(self.path)
        q = parse_qs(url.query)
        ns = q.get("namespace", [None])[0]
        path = url.path

        if path == "/healthz":
            # liveness stays open even under --auth-reads: probes carry
            # no data and a dead-token probe loop would mask real outages
            return self._json(200, {"ok": True})
        # Full-surface auth (r4, --auth-reads): the reference rides
        # Kubernetes auth for EVERY API access, reads included
        # (/root/reference/pkg/util/k8sutil/k8sutil.go:53-77; the
        # dashboard talks to the authenticated apiserver,
        # dashboard/backend/client/manager.go:13-45). With auth_reads the
        # same bearer gates job reads, events, logs, /metrics and the UI
        # — training logs and eval metrics are not public data in the HA
        # topology this server advertises.
        if self.auth_reads and not self._authorized():
            return
        if path == "/metrics":
            if self.metrics is None:
                return self._error(404, "metrics not wired (no controller)")
            body = self.metrics.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path in ("/", "/ui"):
            body = _UI_HTML.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        # ?api_version=v1alpha1 on job reads serves the older generation's
        # shape (list replica specs + phase/state status block).
        api_version = q.get("api_version", [""])[0]
        if path == "/api/tpujob":
            jobs = self.store.list(KIND_TPUJOB, namespace=ns)
            return self._json(
                200, {"items": [self._job_payload(j, api_version) for j in jobs]}
            )
        if path == "/api/namespaces":
            spaces = sorted({j.metadata.namespace for j in self.store.list(KIND_TPUJOB)})
            return self._json(200, {"items": spaces})
        if path == "/api/events":
            evs = self.store.list(KIND_EVENT, namespace=ns)
            return self._json(200, {"items": [_to_jsonable(e) for e in evs]})
        # Fleet ledger rollups (r18): computed from the durable record
        # set, not the store — they survive job GC and operator death.
        # Serialized with sort_keys so the acceptance's byte-identical
        # before/after-recovery comparison is about content, not dict
        # ordering.
        if path in ("/api/fleet/summary", "/api/fleet/hosts"):
            if self.ledger is None:
                return self._error(404, "fleet ledger not wired (--ledger-dir)")
            payload = (
                self.ledger.summary()
                if path == "/api/fleet/summary"
                else {"hosts": self.ledger.hosts()}
            )
            body = json.dumps(payload, sort_keys=True).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return

        m = _TRACE_RE.match(path)
        if m:
            segs = _decode_segments(m)
            if segs is None:
                return self._error(400, "invalid name in path (empty or contains '/')")
            tns, tname = segs
            from tf_operator_tpu.obs.export import to_chrome_trace
            from tf_operator_tpu.obs.spans import job_trace

            try:
                job = self.store.get(KIND_TPUJOB, tns, tname)
            except NotFoundError:
                job = None
            spans = job_trace(self.store, tns, tname)
            if job is None and not spans:
                return self._error(404, f"no trace for tpujob {tns}/{tname}")
            return self._json(200, to_chrome_trace(spans, job=job))

        m = _TELEMETRY_RE.match(path)
        if m:
            segs = _decode_segments(m)
            if segs is None:
                return self._error(400, "invalid name in path (empty or contains '/')")
            tns, tname = segs
            from tf_operator_tpu.obs.spans import job_trace
            from tf_operator_tpu.obs.telemetry import (
                goodput_decomposition,
                job_telemetry,
                telemetry_summary,
            )

            try:
                job = self.store.get(KIND_TPUJOB, tns, tname)
            except NotFoundError:
                job = None
            batches = job_telemetry(self.store, tns, tname)
            if job is None and not batches:
                return self._error(404, f"no telemetry for tpujob {tns}/{tname}")
            spans = job_trace(self.store, tns, tname)
            submit = job.metadata.creation_timestamp if job else 0.0
            end = (job.status.completion_time if job else None) or time.time()
            return self._json(
                200,
                {
                    "job": f"{tns}/{tname}",
                    "batches": [to_doc(b) for b in batches],
                    "summary": telemetry_summary(batches),
                    "goodput": goodput_decomposition(spans, batches, submit, end),
                },
            )

        m = _POSTMORTEM_RE.match(path)
        if m:
            segs = _decode_segments(m)
            if segs is None:
                return self._error(400, "invalid name in path (empty or contains '/')")
            pns, pname = segs
            from tf_operator_tpu.obs.blackbox import (
                job_stackdumps,
                load_postmortem,
            )

            bundle = load_postmortem(self.store, pns, pname)
            if bundle is None:
                # LOUD by design: a GC'd job's forensics are gone with it,
                # and a live job without a bundle has nothing frozen yet —
                # neither case may read as an empty-but-successful result.
                try:
                    self.store.get(KIND_TPUJOB, pns, pname)
                    detail = "job exists but no postmortem has been frozen"
                except NotFoundError:
                    detail = (
                        "job deleted — forensics are GC'd with the job"
                    )
                return self._error(
                    404, f"no postmortem for tpujob {pns}/{pname} ({detail})"
                )
            dumps = job_stackdumps(self.store, pns, pname)
            return self._json(
                200,
                {
                    "job": f"{pns}/{pname}",
                    "reason": bundle.reason,
                    "frozen_at": bundle.time,
                    "truncated": bundle.truncated,
                    "bundle": bundle.payload,
                    "stackdumps": [
                        {
                            "rank": d.rank, "epoch": d.epoch,
                            "host": d.payload.get("host", ""),
                            "truncated": d.truncated,
                            "text": d.payload.get("text", ""),
                        }
                        for d in dumps
                    ],
                },
            )

        m = _JOB_RE.match(path)
        if m:
            # Path segments arrive percent-encoded (RemoteStore quotes
            # them); decode before they become store keys.
            segs = _decode_segments(m)
            if segs is None:
                return self._error(400, "invalid name in path (empty or contains '/')")
            ns, name = segs
            try:
                job = self.store.get(KIND_TPUJOB, ns, name)
            except NotFoundError:
                return self._error(404, f"tpujob {ns}/{name} not found")
            procs = self.store.list(
                KIND_PROCESS, namespace=ns, label_selector={LABEL_JOB_NAME: name}
            )
            eps = self.store.list(
                KIND_ENDPOINT, namespace=ns, label_selector={LABEL_JOB_NAME: name}
            )
            return self._json(
                200,
                {
                    "job": self._job_payload(job, api_version),
                    "processes": [_to_jsonable(p) for p in procs],
                    "endpoints": [_to_jsonable(e) for e in eps],
                },
            )

        # The generic object API (including the watch stream) is the
        # machine seam — all consumers are token-capable, so the whole
        # surface authenticates, reads included.
        if path.startswith("/api/v1/") and not self._authorized():
            return

        if path == "/api/v1/watch":
            kinds = [k for k in (q.get("kinds", [""])[0]).split(",") if k]
            bad = [k for k in kinds if k not in KNOWN_KINDS]
            if bad:
                return self._error(400, f"unknown kinds {bad}")
            return self._stream_watch(kinds or None)

        m = _OBJ_KIND_RE.match(path)
        if m:
            kind = m.group(1)
            if kind not in KNOWN_KINDS:
                return self._error(404, f"unknown kind {kind}")
            # ?label=k=v (repeatable): server-side selector so remote
            # consumers don't transfer the whole collection to filter it.
            selector = {}
            for pair in q.get("label", []):
                k, sep, v = pair.partition("=")
                if sep:
                    selector[k] = v
            items = self.store.list(
                kind, namespace=ns, label_selector=selector or None
            )
            return self._json(200, {"items": [to_doc(o) for o in items]})

        m = _OBJ_RE.match(path)
        if m:
            kind, ons, name = map(unquote, m.groups())
            if kind not in KNOWN_KINDS:
                return self._error(404, f"unknown kind {kind}")
            try:
                return self._json(200, to_doc(self.store.get(kind, ons, name)))
            except NotFoundError:
                return self._error(404, f"{kind} {ons}/{name} not found")

        m = _LOGS_RE.match(path)
        if m:
            segs = _decode_segments(m)
            if segs is None:
                return self._error(400, "invalid name in path (empty or contains '/')")
            ns, name = segs
            try:
                proc = self.store.get(KIND_PROCESS, ns, name)
            except NotFoundError:
                return self._error(404, f"process {ns}/{name} not found")
            log_path = proc.metadata.annotations.get(LocalProcessControl.LOG_ANNOTATION)
            if not log_path:
                return self._error(404, "no logs captured for this process")
            try:
                with open(log_path, "rb") as f:
                    # Tail the last 1MB without reading the whole file.
                    import os as _os

                    f.seek(0, _os.SEEK_END)
                    size = f.tell()
                    f.seek(max(0, size - 1024 * 1024))
                    data = f.read()
            except OSError as exc:
                return self._error(500, str(exc))
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return

        self._error(404, f"no route {path}")

    def _stream_watch(self, kinds) -> None:
        """Chunk the store's watch stream as JSON lines until the client
        disconnects. Existing objects replay as ADDED first (the store's
        list+watch contract), so a reconnecting agent reconverges.

        The watch is registered with the server so stop() can end it:
        otherwise server_close()'s handler-thread join would block forever
        on a stream whose client is idle."""
        with self._watch_lock:
            if self._watch_closed.is_set():
                return self._error(503, "server shutting down")
            w = self.store.watch(kinds=kinds)
            # Replay boundary: everything queued at watch creation is the
            # existing-object replay; a SYNCED marker after it lets remote
            # consumers reconcile away objects deleted while they were
            # disconnected (deletions are never replayed).
            replay_n = w.queue.qsize()
            self._active_watches.add(w)
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()
            sent = 0
            if replay_n == 0:
                self.wfile.write(b'{"type": "SYNCED"}\n')
                self.wfile.flush()
            # Poll with a timeout instead of blocking forever: an idle
            # period writes a PING, so a silently-dead client (power loss,
            # no FIN) fails the write and this handler+watch get reaped
            # instead of leaking until the next real event.
            # Batched delivery: drain everything already queued and write
            # it as ONE buffered chunk with one flush — during a burst
            # (gang create, resync) the per-event write+flush syscalls
            # were the stream's dominant cost. The queue is also the
            # watch's backpressure bound: draining it promptly keeps the
            # store from closing this watch as overflowed.
            import queue as _queue

            stopped = False
            while not stopped:
                try:
                    ev = w.queue.get(timeout=self.watch_ping_interval)
                except Exception:
                    self.wfile.write(b'{"type": "PING"}\n')
                    self.wfile.flush()
                    continue
                chunk = bytearray()
                while True:
                    if ev is None:
                        stopped = True  # watch stopped; send what we have
                        break
                    chunk += json.dumps(
                        {"type": ev.type.value, "kind": ev.obj.kind, "object": to_doc(ev.obj)}
                    ).encode()
                    chunk += b"\n"
                    sent += 1
                    if sent == replay_n:
                        chunk += b'{"type": "SYNCED"}\n'
                    try:
                        ev = w.queue.get_nowait()
                    except _queue.Empty:
                        break
                if chunk:
                    self.wfile.write(chunk)
                    self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away
        finally:
            w.stop()
            with self._watch_lock:
                self._active_watches.discard(w)

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length) or b"{}")

    # -- POST / PUT / DELETE ----------------------------------------------

    def do_PUT(self):  # noqa: N802
        if not self._authorized():
            return
        url = urlparse(self.path)
        m = _OBJ_RE.match(url.path)
        if not m:
            return self._error(404, "PUT only at /api/v1/{kind}/{ns}/{name}")
        kind, ns, name = map(unquote, m.groups())
        if kind not in KNOWN_KINDS:
            return self._error(404, f"unknown kind {kind}")
        check = parse_qs(url.query).get("check_version", ["0"])[0] == "1"
        try:
            obj = from_doc(kind, self._read_body())
        except (ValueError, KeyError, TypeError) as exc:
            return self._error(400, f"invalid {kind}: {exc}")
        if (obj.metadata.namespace, obj.metadata.name) != (ns, name):
            return self._error(400, "body identity does not match URL")
        try:
            return self._json(200, to_doc(self.store.update(obj, check_version=check)))
        except NotFoundError:
            return self._error(404, f"{kind} {ns}/{name} not found")
        except ConflictError as exc:
            return self._json(409, {"error": str(exc), "code": "conflict"})

    def do_POST(self):  # noqa: N802
        if not self._authorized():
            return
        path = urlparse(self.path).path
        m = _OBJ_KIND_RE.match(path)
        if m:
            kind = m.group(1)
            if kind not in KNOWN_KINDS:
                return self._error(404, f"unknown kind {kind}")
            try:
                obj = from_doc(kind, self._read_body())
                if kind == KIND_TPUJOB:
                    # The generic path must not be a validation bypass:
                    # same defaulting + admission as the /api/tpujob route.
                    set_defaults(obj)
                    validate_job(obj)
                elif kind == KIND_QUEUE:
                    validate_queue(obj)
                elif kind == KIND_PRIORITY_CLASS:
                    validate_priority_class(obj)
            except (ValueError, ValidationError, KeyError, TypeError) as exc:
                return self._error(400, f"invalid {kind}: {exc}")
            try:
                return self._json(201, to_doc(self.store.create(obj)))
            except AlreadyExistsError as exc:
                return self._json(409, {"error": str(exc), "code": "already_exists"})
        m = _PROFILE_RE.match(path)
        if m:
            # On-demand profiling: bump the monotonic profile-directive
            # epoch on status (same protocol as resize_directive — the
            # chief observes the new epoch at its next flush boundary,
            # wraps N steps in profile_ctx, and acks completed_epoch).
            segs = _decode_segments(m)
            if segs is None:
                return self._error(400, "invalid name in path (empty or contains '/')")
            pns, pname = segs
            try:
                body = self._read_body()
            except (ValueError, TypeError) as exc:
                return self._error(400, f"invalid body: {exc}")
            try:
                steps = int(body.get("steps", 0))
            except (ValueError, TypeError):
                return self._error(400, "steps must be an integer")
            if steps <= 0:
                return self._error(400, "steps must be > 0")
            prof_dir = str(body.get("dir", "") or "")
            issued = {}

            def arm(job):
                cur = job.status.profile_directive or {}
                issued.clear()
                issued.update(
                    {
                        "epoch": int(cur.get("epoch", 0)) + 1,
                        "steps": steps,
                        "dir": prof_dir,
                        "time": time.time(),
                    }
                )
                job.status.profile_directive = dict(issued)

            if not self.store.update_with_retry(KIND_TPUJOB, pns, pname, arm):
                return self._error(404, f"tpujob {pns}/{pname} not found")
            return self._json(200, {"profile_directive": issued})
        if path != "/api/tpujob":
            return self._error(404, "POST only at /api/tpujob or /api/v1/{kind}")
        length = int(self.headers.get("Content-Length", 0))
        try:
            data = json.loads(self.rfile.read(length) or b"{}")
            # Dual API generations (SURVEY.md §0): list-based v1alpha1
            # documents are converted, map-based ones decode directly.
            from tf_operator_tpu.api.v1alpha1 import parse_job

            job = parse_job(data)
            set_defaults(job)
            validate_job(job)
        except (ValueError, ValidationError, KeyError, TypeError) as exc:
            return self._error(400, f"invalid job: {exc}")
        # Namespace auto-create semantics (api_handler.go:178-218) are
        # implicit: namespaces exist by use.
        try:
            created = self.store.create(job)
        except AlreadyExistsError as exc:
            return self._error(409, str(exc))
        self._json(201, self._job_payload(created))

    def do_DELETE(self):  # noqa: N802
        if not self._authorized():
            return
        path = urlparse(self.path).path
        m = _OBJ_RE.match(path)
        if m:
            kind, ns, name = map(unquote, m.groups())
            if kind not in KNOWN_KINDS:
                return self._error(404, f"unknown kind {kind}")
            try:
                self.store.delete(kind, ns, name)
            except NotFoundError:
                return self._error(404, f"{kind} {ns}/{name} not found")
            return self._json(200, {"deleted": f"{kind}/{ns}/{name}"})
        m = _JOB_RE.match(path)
        if not m:
            return self._error(404, "DELETE at /api/tpujob/{ns}/{name} or /api/v1/{kind}/{ns}/{name}")
        segs = _decode_segments(m)
        if segs is None:
            return self._error(400, "invalid name in path (empty or contains '/')")
        ns, name = segs
        try:
            self.store.delete(KIND_TPUJOB, ns, name)
        except NotFoundError:
            return self._error(404, f"tpujob {ns}/{name} not found")
        self._json(200, {"deleted": f"{ns}/{name}"})


class _BoundedThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a bounded handler-thread count.

    The stock server spawns one unbounded thread per connection — under a
    submit burst (500 sequential creates, plus pollers, plus long-lived
    watch streams) that is an unbounded thread population on the store's
    lock. ``max_workers`` caps concurrently-served connections; the
    accept loop blocks on the semaphore once saturated, which is
    backpressure on clients (their connects queue in the listen backlog)
    instead of memory/thread growth in the operator. Watch streams hold a
    permit for their lifetime — size the bound above the expected agent
    count (default 64 ≫ any tested topology)."""

    def __init__(self, addr, handler, max_workers: int = 64):
        self._permits = threading.BoundedSemaphore(max_workers)
        super().__init__(addr, handler)

    def process_request(self, request, client_address):
        self._permits.acquire()
        try:
            super().process_request(request, client_address)
        except BaseException:
            self._permits.release()
            raise

    def process_request_thread(self, request, client_address):
        try:
            super().process_request_thread(request, client_address)
        finally:
            self._permits.release()


class DashboardServer:
    def __init__(
        self,
        store: Store,
        host: str = "127.0.0.1",
        port: int = 8080,
        metrics=None,
        watch_ping_interval: float = 15.0,
        auth_token: Optional[str] = None,
        auth_reads: bool = False,
        max_workers: int = 64,
        ledger=None,
    ) -> None:
        """``auth_token``: shared secret (utils.auth) required on mutating
        routes and the /api/v1 surface; None serves anonymously (tests,
        localhost dev). ``auth_reads`` (r4): extend the bearer check to
        every read route except /healthz — reference-parity with
        Kubernetes auth covering all API access. Requesting auth_reads
        without a token is refused loudly (r5, ADVICE r4): silently
        serving an open server is the exact hole the flag exists to
        close — the CLI guard in cli/operator.py only covers CLI
        callers."""
        if auth_reads and not auth_token:
            raise ValueError(
                "auth_reads=True requires auth_token — without a token the "
                "server would serve every read anonymously"
            )
        self._watches: set = set()
        self._watch_closed = threading.Event()
        handler = type(
            "BoundHandler",
            (_Handler,),
            {
                "store": store,
                "metrics": metrics,
                "ledger": ledger,
                "watch_ping_interval": watch_ping_interval,
                "auth_token": auth_token,
                "auth_reads": bool(auth_reads),
                "_active_watches": self._watches,
                "_watch_lock": threading.Lock(),
                "_watch_closed": self._watch_closed,
            },
        )
        self.httpd = _BoundedThreadingHTTPServer(
            (host, port), handler, max_workers=max_workers
        )
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="dashboard", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        # End live watch streams first: server_close() joins handler
        # threads, and a stream whose client is idle never unblocks on
        # its own (the sentinel from Watch.stop() does). The closed flag
        # forecloses the register-after-snapshot race: registration under
        # the same lock refuses once set.
        self._watch_closed.set()
        for w in list(self._watches):
            w.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
