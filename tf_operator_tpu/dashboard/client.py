"""Python client for the TPUJob REST API.

Reference parity: py/tf_job_client.py — CRD CRUD via CustomObjectsApi plus
``wait_for_job`` polling phase (v1alpha1) / conditions (v1alpha2)
(tf_job_client.py:21-161). Stdlib-only (urllib), no requests dependency.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from tf_operator_tpu.api.types import TPUJob


class TPUJobApiError(RuntimeError):
    def __init__(self, code: int, message: str) -> None:
        super().__init__(f"HTTP {code}: {message}")
        self.code = code


class TPUJobClient:
    def __init__(self, base_url: str, timeout: float = 10.0,
                 token: Optional[str] = None) -> None:
        """``token``: bearer secret for an auth-enabled operator; defaults
        to the ambient credential ($TPUJOB_AUTH_TOKEN / token file)."""
        from tf_operator_tpu.utils.auth import resolve_token

        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.token = token if token is not None else resolve_token()

    # -- raw ---------------------------------------------------------------

    def _request(self, method: str, path: str, body: Optional[dict] = None):
        from tf_operator_tpu.utils.auth import bearer_headers

        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json", **bearer_headers(self.token)}
        req = urllib.request.Request(
            self.base_url + path,
            data=data,
            method=method,
            headers=headers,
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                raw = resp.read()
                ctype = resp.headers.get("Content-Type", "")
        except urllib.error.HTTPError as exc:
            try:
                message = json.loads(exc.read()).get("error", str(exc))
            except Exception:
                message = str(exc)
            raise TPUJobApiError(exc.code, message) from None
        if raw and "application/json" in ctype:
            return json.loads(raw)
        return raw.decode(errors="replace")

    # -- CRUD --------------------------------------------------------------

    def create(self, job: TPUJob) -> TPUJob:
        out = self._request("POST", "/api/tpujob", job.to_dict())
        out.pop("phase", None)
        return TPUJob.from_dict(out)

    def list(self, namespace: Optional[str] = None) -> List[TPUJob]:
        q = f"?namespace={namespace}" if namespace else ""
        items = self._request("GET", f"/api/tpujob{q}")["items"]
        return [TPUJob.from_dict({k: v for k, v in d.items() if k != "phase"}) for d in items]

    def get(self, namespace: str, name: str) -> Dict[str, Any]:
        """Full detail: {"job": ..., "processes": [...], "endpoints": [...]}."""
        return self._request("GET", f"/api/tpujob/{namespace}/{name}")

    def get_job(self, namespace: str, name: str) -> TPUJob:
        d = self.get(namespace, name)["job"]
        d.pop("phase", None)
        return TPUJob.from_dict(d)

    def delete(self, namespace: str, name: str) -> None:
        self._request("DELETE", f"/api/tpujob/{namespace}/{name}")

    # -- generic objects (the /api/v1 machine seam) ------------------------

    def create_object(self, obj) -> Dict[str, Any]:
        """Create any serializable object (Queue, PriorityClass, Host, ...)
        through the generic kind API; the server runs per-kind validation."""
        from tf_operator_tpu.runtime.serialize import to_doc

        return self._request("POST", f"/api/v1/{obj.kind}", to_doc(obj))

    def list_objects(self, kind: str, namespace: Optional[str] = None) -> List[Any]:
        from tf_operator_tpu.runtime.serialize import from_doc

        q = f"?namespace={namespace}" if namespace else ""
        items = self._request("GET", f"/api/v1/{kind}{q}")["items"]
        return [from_doc(kind, d) for d in items]

    def trace(self, namespace: str, name: str) -> Dict[str, Any]:
        """The job's lifecycle trace as Chrome trace-event JSON
        (Perfetto-loadable: traceEvents + derived timings in otherData)."""
        return self._request("GET", f"/api/tpujob/{namespace}/{name}/trace")

    def telemetry(self, namespace: str, name: str) -> Dict[str, Any]:
        """Live step telemetry: {"job", "batches", "summary", "goodput"} —
        per-rank ring batches plus the gang summary (tokens/s, MFU,
        step-time spread) and the goodput decomposition."""
        return self._request("GET", f"/api/tpujob/{namespace}/{name}/telemetry")

    def postmortem(self, namespace: str, name: str) -> Dict[str, Any]:
        """The job's frozen postmortem: {"job", "reason", "frozen_at",
        "bundle", "stackdumps"}. Raises TPUJobApiError(404) when nothing
        was ever frozen OR the job (and its forensics) was GC'd — callers
        must surface that loudly, never as an empty result."""
        return self._request(
            "GET", f"/api/tpujob/{namespace}/{name}/postmortem"
        )

    def profile(self, namespace: str, name: str, steps: int,
                profile_dir: str = "") -> Dict[str, Any]:
        """Publish an on-demand profile directive: the chief wraps the
        next ``steps`` steps in profile_ctx and acks with a
        profile-capture span carrying the xplane path."""
        body: Dict[str, Any] = {"steps": int(steps)}
        if profile_dir:
            body["dir"] = profile_dir
        return self._request(
            "POST", f"/api/tpujob/{namespace}/{name}/profile", body
        )

    def logs(self, namespace: str, process_name: str) -> str:
        raw = self._request("GET", f"/api/process/{namespace}/{process_name}/logs")
        return raw if isinstance(raw, str) else raw.decode(errors="replace")

    def events(self, namespace: Optional[str] = None) -> List[dict]:
        q = f"?namespace={namespace}" if namespace else ""
        return self._request("GET", f"/api/events{q}")["items"]

    def fleet_summary(self) -> Dict[str, Any]:
        """The fleet ledger rollup (obs/ledger.py): per-queue MTBF and
        goodput, per-cause downtime percentiles, incident counts —
        computed from the durable cross-job record set, so it survives
        job GC and operator restarts. 404 when no ledger is wired."""
        return self._request("GET", "/api/fleet/summary")

    def fleet_hosts(self) -> Dict[str, Any]:
        """Per-host ledger view: {"hosts": {host: {jobs, incident_jobs,
        failures, last_end_ts}}}."""
        return self._request("GET", "/api/fleet/hosts")

    # -- waiting (tf_job_client.py:104-161) --------------------------------

    def wait_for_job(
        self,
        namespace: str,
        name: str,
        timeout: float = 600.0,
        poll: float = 1.0,
        target_phases: tuple = ("Done", "Failed"),
    ) -> TPUJob:
        deadline = time.time() + timeout
        while True:
            job = self.get_job(namespace, name)
            if job.status.phase().value in target_phases:
                return job
            if time.time() > deadline:
                raise TimeoutError(
                    f"tpujob {namespace}/{name} not in {target_phases} after {timeout}s; "
                    f"phase={job.status.phase().value}"
                )
            time.sleep(poll)

    def wait_for_delete(self, namespace: str, name: str, timeout: float = 60.0) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                self.get(namespace, name)
            except TPUJobApiError as exc:
                if exc.code == 404:
                    return
                raise
            time.sleep(0.5)
        raise TimeoutError(f"tpujob {namespace}/{name} still present after {timeout}s")
