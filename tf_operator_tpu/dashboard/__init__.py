"""Dashboard: REST read/write API + minimal web UI.

Reference parity: dashboard/backend (go-restful API at /tfjobs/api/...,
api_handler.go:74-113) and the React frontend, collapsed into one
threaded HTTP server over the store. The API doubles as the framework's
remote apiserver surface: the submit CLI and the Python client speak it.
"""

from tf_operator_tpu.dashboard.server import DashboardServer  # noqa: F401
from tf_operator_tpu.dashboard.client import TPUJobClient  # noqa: F401
