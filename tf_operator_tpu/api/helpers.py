"""API helpers: owner references + admin accelerator/runtime injection.

Reference parity: ``pkg/apis/tensorflow/helper/helpers.go`` — ``AsOwner``
(:36-47) and ``ConfigureAcceleratorsForTFJobSpec`` (:50-104), where an
admin-supplied ControllerConfig (loaded from a YAML file by the daemon,
``cmd/tf-operator/app/server.go:138-156``) maps an accelerator resource
name (e.g. ``alpha.kubernetes.io/nvidia-gpu``) to hostPath volumes and env
vars injected into matching containers.

TPU-native shape: processes, not containers, so "volumes" become library
directories prepended to ``LD_LIBRARY_PATH`` and plain env vars (the way
libtpu/driver paths reach a JAX process). Matching pivots on the job's
slice type (``v5p-32`` matches config key ``v5p``) instead of container
resource limits — chip kind is the resource on a TPU cluster.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tf_operator_tpu.api.types import KIND_TPUJOB, TPUJob

# Accelerator key that matches any slice type (admin catch-all).
MATCH_ANY = "*"


def as_owner(job: TPUJob) -> Dict[str, str]:
    """Owner-reference fields for a child of ``job`` (AsOwner,
    helpers.go:36-47 — there BlockOwnerDeletion/Controller flags, here the
    uid/kind/name triple the adoption machinery pivots on)."""
    return {
        "owner_uid": job.metadata.uid,
        "owner_kind": KIND_TPUJOB,
        "owner_name": job.metadata.name,
    }


@dataclass
class AcceleratorConfig:
    """Injection recipe for one chip kind (AcceleratorConfig,
    v1alpha1/types.go:175-204: Volumes + EnvVars)."""

    env: Dict[str, str] = field(default_factory=dict)
    # Directories prepended (in order) to LD_LIBRARY_PATH — the hostPath
    # volume analogue for an OS-process runtime.
    library_paths: List[str] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Dict) -> "AcceleratorConfig":
        return AcceleratorConfig(
            env={str(k): str(v) for k, v in d.get("env", {}).items()},
            library_paths=[str(p) for p in d.get("library_paths", [])],
        )


@dataclass
class ControllerConfig:
    """Admin-level operator configuration (ControllerConfig,
    v1alpha1/types.go:175-204), keyed by chip kind."""

    accelerators: Dict[str, AcceleratorConfig] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: Dict) -> "ControllerConfig":
        return ControllerConfig(
            accelerators={
                str(k): AcceleratorConfig.from_dict(v)
                for k, v in d.get("accelerators", {}).items()
            }
        )

    @staticmethod
    def load(path: str) -> "ControllerConfig":
        """Read a JSON (or, if PyYAML is present, YAML) config file
        (readControllerConfig, server.go:138-156)."""
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
        try:
            data = json.loads(text)
        except json.JSONDecodeError:
            try:
                import yaml  # type: ignore
            except ImportError as exc:
                raise ValueError(
                    f"{path}: not valid JSON and PyYAML unavailable"
                ) from exc
            data = yaml.safe_load(text)
        if not isinstance(data, dict):
            raise ValueError(f"{path}: expected a mapping at top level")
        return ControllerConfig.from_dict(data)

    def match(self, slice_type: str) -> Optional[AcceleratorConfig]:
        """Longest-prefix match of slice type against accelerator keys
        ('v5p-32' prefers key 'v5p-32' over 'v5p' over '*') — the
        resource-limit matching loop of helpers.go:50-104 recast for
        slice types."""
        best: Tuple[int, Optional[AcceleratorConfig]] = (-1, None)
        for key, cfg in self.accelerators.items():
            if key == MATCH_ANY:
                if best[0] < 0:
                    best = (0, cfg)
            elif slice_type == key or slice_type.startswith(key + "-"):
                if len(key) > best[0]:
                    best = (len(key), cfg)
        return best[1]


def accelerator_env(
    config: Optional[ControllerConfig],
    slice_type: str,
    base_ld_library_path: str = "",
) -> Dict[str, str]:
    """Env-var injection for a process of a job on ``slice_type``.

    Returns the admin env plus a merged LD_LIBRARY_PATH. Injected values
    are *defaults*: callers layer user template env and rendezvous
    identity on top (the reference appends admin volumes/env to the
    container; user-specified values keep precedence here, which is the
    safer direction for env maps)."""
    if config is None:
        return {}
    accel = config.match(slice_type)
    if accel is None:
        return {}
    env = dict(accel.env)
    if accel.library_paths:
        merged = ":".join(accel.library_paths)
        base = base_ld_library_path or os.environ.get("LD_LIBRARY_PATH", "")
        env["LD_LIBRARY_PATH"] = f"{merged}:{base}" if base else merged
    return env
