"""TPUJob API types.

Reference parity (capabilities, not code): pkg/apis/tensorflow/v1alpha2/types.go
(map-based ``TFReplicaSpecs``, RestartPolicy incl. ExitCode, conditions-based
status with per-replica Active/Succeeded/Failed counters) plus the v1alpha1
phase enum retained as a derived view (pkg/apis/tensorflow/v1alpha1/types.go
phases Creating/Running/CleanUp/Failed/Done).

TPU-first deltas from the reference:

- Replica roles are COORDINATOR / WORKER / EVALUATOR. There is no PS role —
  SPMD over a TPU slice has no parameter servers (the reference's PS/MASTER
  topology, v1alpha1/types.go:80-84, collapses into a single multi-controller
  program). COORDINATOR is the chief analogue (v1alpha2/types.go:94-112);
  when absent, worker 0 carries coordinator semantics, matching the
  chief-absent ⇒ worker-0 rule of controller_status.go:39-120.
- The spec carries a ``TopologySpec`` (slice type / mesh axes) because gang
  placement on TPU means atomic slice provisioning, not a PodDisruptionBudget
  hack (pkg/trainer/training.go:450-511).
- Processes, not pods: a ``ProcessTemplate`` names a Python entrypoint
  (``pkg.module:fn``) instead of a container image; the runtime substrate
  launches OS processes (or records intended launches in tests).

Everything is a plain dataclass with ``to_dict``/``from_dict`` so objects can
cross the store/CLI/REST boundaries as JSON, the way CRDs cross the apiserver.
"""

from __future__ import annotations

import copy
import dataclasses
import enum
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

API_GROUP = "tpujob.tf-operator-tpu.dev"
API_VERSION = "v1"
KIND_TPUJOB = "TPUJob"
KIND_PROCESS = "Process"
KIND_ENDPOINT = "Endpoint"
KIND_EVENT = "Event"
KIND_HOST = "Host"
KIND_LEASE = "Lease"
KIND_SPAN = "Span"
KIND_TELEMETRY = "Telemetry"
# Fleet-scheduler object kinds (sched/): cluster-level priority classes and
# per-namespace admission queues with chip/job quotas. Like Spans, they ride
# the generic store/API seam (runtime/serialize.py registers decoders).
KIND_PRIORITY_CLASS = "PriorityClass"
KIND_QUEUE = "Queue"
# Forensics objects (obs/blackbox.py, r15): per-rank stack-dump shipments
# and the frozen per-job postmortem bundle. Ride the generic store/API
# seam like Spans/Telemetry and are GC'd with the owning job.
KIND_POSTMORTEM = "Postmortem"

# Serving job classes (SchedulingSpec.job_class, r10): "serving" marks a
# latency-sensitive decode workload — the fleet scheduler gives it a high
# default priority so it preempts training without PriorityClass setup.
JOB_CLASS_TRAINING = "training"
JOB_CLASS_SERVING = "serving"

# Default port the coordinator's jax.distributed service listens on
# (replaces the reference's TF gRPC port 2222, v1alpha1/types.go:30).
DEFAULT_COORDINATOR_PORT = 8476

# Label keys stamped on every managed object (reference: genLabels,
# controller.v2/controller_helper.go:53-58 and trainer labels incl.
# task_index, pkg/trainer/replicas.go:121-136).
LABEL_GROUP = "group_name"
LABEL_JOB_NAME = "tpu_job_name"
LABEL_REPLICA_TYPE = "replica_type"
LABEL_REPLICA_INDEX = "replica_index"

DEFAULT_NAMESPACE = "default"


class ReplicaType(str, enum.Enum):
    """Typed replica roles (reference: v1alpha2/types.go:94-112)."""

    COORDINATOR = "Coordinator"
    WORKER = "Worker"
    EVALUATOR = "Evaluator"

    def __str__(self) -> str:  # labels / names want the bare value
        return self.value


class RestartPolicy(str, enum.Enum):
    """Restart behavior for a replica set (reference: v1alpha2/types.go:79-92).

    EXIT_CODE keeps the reference's most distinctive policy: on failure the
    controller consults the exit-code taxonomy (utils/exit_codes.py) and
    restarts only retryable failures (controller_pod.go:77-92).
    """

    ALWAYS = "Always"
    ON_FAILURE = "OnFailure"
    NEVER = "Never"
    EXIT_CODE = "ExitCode"


class JobPhase(str, enum.Enum):
    """Coarse phase view (reference: v1alpha1/types.go:106-116).

    Derived from conditions; kept for v1alpha1-style clients and the CLI.
    """

    NONE = ""
    CREATING = "Creating"
    # Admitted-pending: the job waits in the fleet scheduler's admission
    # queue (over quota, or no capacity) instead of hot-looping placement.
    QUEUED = "Queued"
    RUNNING = "Running"
    CLEANUP = "CleanUp"
    FAILED = "Failed"
    DONE = "Done"


class ConditionType(str, enum.Enum):
    """Job conditions (reference: v1alpha2/types.go:167-196)."""

    CREATED = "Created"
    # Waiting in the fleet scheduler's admission queue (sched/): over the
    # queue's quota or unplaceable on current capacity. Cleared on admission.
    QUEUED = "Queued"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


class CleanupPolicy(str, enum.Enum):
    """What to do with processes when the job finishes.

    Reference analogue: CleanPodPolicy. ALL tears down every process,
    RUNNING only still-running ones, NONE keeps them for debugging.
    """

    ALL = "All"
    RUNNING = "Running"
    NONE = "None"


@dataclass
class ObjectMeta:
    """Object identity + bookkeeping (reference: k8s ObjectMeta subset used
    by the operator: name/namespace/uid/labels/ownerReferences/resourceVersion).
    """

    name: str = ""
    namespace: str = DEFAULT_NAMESPACE
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: int = 0
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    # Owner reference by uid: the adoption/orphaning machinery
    # (controller_pod.go:222-258) pivots on this.
    owner_uid: Optional[str] = None
    owner_kind: Optional[str] = None
    owner_name: Optional[str] = None

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class ProcessTemplate:
    """Template for worker processes (reference: PodTemplateSpec + the
    requirement that the trained container be named "tensorflow",
    validation/validation.go:26-79 — here the analogue is a resolvable
    ``entrypoint`` of the form ``package.module:function``).
    """

    entrypoint: str = ""  # "pkg.module:fn" — called as fn(ctx) in-process
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    # Resources per process: how many TPU chips this process drives.
    chips_per_process: int = 0  # 0 ⇒ defaulted from topology
    # Working directory for launched processes (real backend only).
    workdir: Optional[str] = None


@dataclass
class ReplicaSpec:
    """One replica set (reference: v1alpha2 TFReplicaSpec, types.go:45-78)."""

    replicas: Optional[int] = None  # defaulted to 1 (defaults.go:57-61)
    template: ProcessTemplate = field(default_factory=ProcessTemplate)
    restart_policy: Optional[RestartPolicy] = None  # defaulted per role
    port: Optional[int] = None  # coordinator rendezvous port (defaults.go:33-55)


@dataclass
class TopologySpec:
    """TPU slice topology — the gang-placement unit.

    Either a named slice (``slice_type='v5p-32'``) or explicit counts. The
    reference approximated gang placement with a PodDisruptionBudget
    (training.go:450-511); on TPU the slice itself is the atomic unit, so
    topology is part of the job spec.
    """

    slice_type: str = ""  # e.g. "v5p-32"; informational if explicit counts set
    num_hosts: int = 1
    chips_per_host: int = 0  # 0 ⇒ discover from backend at admission
    # Logical mesh axis sizes over the slice's devices, e.g.
    # {"dp": 2, "fsdp": 2, "tp": 2}. Empty ⇒ pure DP over all chips.
    mesh_axes: Dict[str, int] = field(default_factory=dict)
    # Multi-slice (cross-DCN) factors per axis: each named axis's total
    # size becomes mesh_axes[a] * dcn_mesh_axes[a], with the DCN factor as
    # the axis's outer block (parallel.mesh.build_hybrid_mesh). Keep DCN
    # factors on dp/pp — tp/cp collectives must stay on ICI.
    dcn_mesh_axes: Dict[str, int] = field(default_factory=dict)

    def total_chips(self) -> int:
        return self.num_hosts * self.chips_per_host


@dataclass
class RunPolicy:
    """Job-level execution policy (reference: backoff consts
    pkg/controller/controller.go:59-61 + CleanPodPolicy + activeDeadline).
    """

    cleanup_policy: CleanupPolicy = CleanupPolicy.RUNNING
    active_deadline_seconds: Optional[float] = None
    backoff_limit: Optional[int] = None  # max retryable restarts before Failed
    # Gang semantics: on TPU, one process dying severs the slice's SPMD
    # program, so the default is whole-gang restart (SURVEY.md §7 hard part b)
    # rather than the reference's per-pod restart.
    gang_restart: bool = True
    scheduler_name: str = ""  # opaque hint, mirrors SchedulerName v1alpha1/types.go:48-63
    # Per-job node-lost detection window: a host whose agent has not
    # heartbeat within this many seconds is treated as lost for THIS job's
    # processes and placements. None ⇒ the controller-wide default
    # (runtime/scheduler.py DEFAULT_HEARTBEAT_TTL). Latency-sensitive jobs
    # tighten it; jobs on flaky networks loosen it instead of eating
    # spurious gang restarts.
    heartbeat_ttl_seconds: Optional[float] = None
    # Elastic gangs (r12): opt-in shrink/re-grow on member loss instead of
    # full gang restart. Only honored for dp/fsdp-only meshes (tp/pp/ep
    # shard the model program itself — losing a rank there severs the SPMD
    # program and a full restart is the only sound recovery); the
    # reconciler falls back to _restart_gang whenever the mesh, the lost
    # member (the coordinator anchors rendezvous), or survivor count makes
    # a resize unsound.
    elastic: bool = False
    # Hang detection window (r15): the gang is declared HUNG when NO rank
    # has advanced past its last reported step for this many seconds while
    # host heartbeats stay live (the silent wedged-collective failure the
    # exit taxonomy can never see — no process exits). None ⇒ watchdog
    # disabled for this job; the straggler median-rule still runs. Must be
    # comfortably larger than the workload's telemetry flush interval or
    # slow-but-moving jobs would be shot.
    hang_timeout_seconds: Optional[float] = None
    # Goodput autopilot (r16): opt-in per-job knob for the fleet
    # controller that turns telemetry into policy (autopilot/). None ⇒
    # disabled (the default: no job gets auto-tuned without asking).
    # Recognized keys, all optional:
    #   {"enabled": bool (default True when the dict is present),
    #    "cooldown_s": float        — min seconds between actions per kind,
    #    "confirm_ticks": int       — consecutive agreeing ticks to act,
    #    "min_checkpoint_every": int, "max_checkpoint_every": int
    #                               — Young/Daly cadence clamps (steps),
    #    "cadence": bool, "migrate": bool, "warmpool": bool
    #                               — per-actuator gates (default True)}.
    autopilot: Optional[Dict[str, Any]] = None


@dataclass
class SchedulingSpec:
    """Fleet-scheduler knobs (sched/): which admission queue this job joins
    and which PriorityClass orders it there. Both are names resolved at
    admission time — a missing Queue means "no quota" and a missing
    PriorityClass means priority 0, so jobs submitted before the objects
    exist still run (kube-scheduler's optional schedulerName spirit).

    ``job_class`` (r10) declares WHAT the job is, not where it queues:
    "serving" jobs are latency-sensitive decode loops that default to a
    high effective priority (sched/fleet.py SERVING_DEFAULT_PRIORITY)
    so they preempt training for capacity without any PriorityClass
    setup — the victim drains and warm-resumes through the ordinary
    preemption lifecycle, and backfills when the serve job finishes. An
    explicit priority_class always wins over the class default."""

    queue: str = ""  # Queue name in the job's namespace; "" ⇒ unqueued
    priority_class: str = ""  # PriorityClass name; "" ⇒ priority 0
    job_class: str = ""  # "" | JOB_CLASS_TRAINING | JOB_CLASS_SERVING
    # Grow-beyond-spec (r19): the largest world size the fleet scheduler
    # may offer this ELASTIC job when idle in-quota chips exist. 0 ⇒ the
    # spec-derived gang size is the ceiling (no over-spec growth). Offers
    # come strictly after every queued admission (backfill never starves
    # the admission queue) and over-spec members are the FIRST thing
    # reclaimed under any quota pressure — the job shrinks back to spec
    # through the ordinary resize protocol, never charged to backoff.
    elastic_max_world: int = 0


@dataclass
class TPUJobSpec:
    """Desired state (reference: v1alpha2 TFJobSpec, types.go:45-54)."""

    replica_specs: Dict[ReplicaType, ReplicaSpec] = field(default_factory=dict)
    topology: TopologySpec = field(default_factory=TopologySpec)
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    scheduling: SchedulingSpec = field(default_factory=SchedulingSpec)
    # Free-form workload config passed through to every process's context
    # (hyperparameters etc.) — the data plane reads it, the control plane
    # never interprets it, preserving the reference's strict control/data
    # split (tf_job_design_doc.md:96-98).
    workload: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Condition:
    """Status condition (reference: TFJobCondition, v1alpha2/types.go:152-166)."""

    type: ConditionType = ConditionType.CREATED
    status: bool = True
    reason: str = ""
    message: str = ""
    last_update_time: float = 0.0
    last_transition_time: float = 0.0


@dataclass
class ReplicaStatus:
    """Per-replica-set counters (reference: TFReplicaStatus, v1alpha2
    types.go:135-149)."""

    active: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class TPUJobStatus:
    """Observed state (reference: TFJobStatus, v1alpha2/types.go:114-133)."""

    conditions: List[Condition] = field(default_factory=list)
    replica_statuses: Dict[ReplicaType, ReplicaStatus] = field(default_factory=dict)
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    last_reconcile_time: Optional[float] = None
    # Monotonic count of failure-caused gang restarts (feeds backoff_limit).
    restart_count: int = 0
    # Monotonic count of preemption-caused gang restarts (host drained /
    # SIGTERM eviction). Deliberately NOT counted against backoff_limit:
    # being evicted is infrastructure's doing, not the workload's.
    preemption_count: int = 0
    # Cause of the most recent gang restart: "preemption" |
    # "retryable-failure" | "node-lost" ("" before any restart) — lets
    # status surfaces report preempted vs failed restarts distinctly.
    # Elastic jobs (r12) additionally report "resize_shrink"/"resize_grow"
    # here, but resizes increment resize_count, never restart_count.
    last_restart_cause: str = ""
    # Elastic-gang state (r12). resize_epoch is the monotonic barrier
    # counter stamped into the gang env (TPUJOB_RESIZE_EPOCH) and into
    # every resize directive; world_size is the CURRENT gang size (0 ⇒
    # never resized: the spec-derived size applies). resize_count mirrors
    # restart_count for resizes and is deliberately NOT charged against
    # backoff_limit (same rule as preemptions: losing a member is
    # infrastructure's doing, not the workload's).
    resize_epoch: int = 0
    resize_count: int = 0
    world_size: int = 0
    # The live resize directive the controller offers the survivors:
    # {"epoch": int, "direction": "shrink"|"grow", "world_size": int,
    #  "members": [process names, rank order], "time": ts} plus any
    # barrier fields the chief publishes back (boundary/offset/ack). Empty
    # when the gang runs at spec size with no resize in flight.
    resize_directive: Dict[str, Any] = field(default_factory=dict)
    # Bounded audit of resizes: the last RESIZE_HISTORY_KEEP entries of
    # [{"epoch", "direction", "world_size", "cause", "time"}] — the
    # dashboard/CLI surface for "visibly degraded". Older entries fold
    # into resize_history_folded (a count) so a long elastic soak cannot
    # grow the job status without limit; total resizes for display =
    # resize_history_folded + len(resize_history).
    resize_history: List[Dict[str, Any]] = field(default_factory=list)
    resize_history_folded: int = 0
    # Grow-beyond-spec (r19): how many EXTRA worker indices beyond the
    # spec replica count the fleet has grown this gang by. The gang's
    # target membership is spec + overspec_workers; decremented only by
    # a quota reclaim (a failure-shrink keeps the target so the
    # symmetric re-grow can restore it).
    overspec_workers: int = 0
    # Latest evaluator-reported scores, written by the Evaluator replica
    # through the API (workloads/eval.py → JobContext.report_eval_metrics):
    # {"step": int, "metrics": {name: value}, "time": ts}. The reference
    # surfaced replica *status* per role (controller_status.go:136-154) but
    # gave eval *results* no queryable home; here `tpujob get` and the
    # dashboard read them from the job object.
    eval_metrics: Dict[str, Any] = field(default_factory=dict)
    # On-demand profiling directive (same monotonic-epoch protocol as
    # resize_directive): the CLI/API publishes {"epoch": int, "steps": int,
    # "dir": path, "time": ts}; the chief wraps the next N steps in
    # profile_ctx and publishes back {"completed_epoch": int,
    # "xplane": path}. Empty when no capture has ever been requested.
    profile_directive: Dict[str, Any] = field(default_factory=dict)
    # Hang plane (r15). hang_count mirrors restart_count for hang-caused
    # gang restarts; hangs ARE charged against backoff_limit under
    # ON_FAILURE/EXIT_CODE (a wedged collective is the workload's doing
    # until proven otherwise) via the ordinary restart_count bump.
    hang_count: int = 0
    # Live watchdog verdict: {"stuck_step": int, "since": ts,
    # "last_moving_ranks": [ranks that reported the newest window],
    # "time": ts}. Present only while a hang is declared-but-unrecovered;
    # cleared when the gang restarts or progress resumes.
    hang_state: Dict[str, Any] = field(default_factory=dict)
    # Stack-sweep directive (same monotonic-epoch protocol as
    # profile_directive): the reconciler publishes {"epoch": int,
    # "dir": path, "time": ts} when it declares a hang; each HostAgent
    # SIGUSR2s its wedged members exactly once per epoch and publishes
    # back acks under "acks": {rank: stack_file_path}. Empty when no
    # sweep has ever been requested.
    stackdump_directive: Dict[str, Any] = field(default_factory=dict)
    # Checkpoint-cadence directive (r16, same monotonic-epoch protocol as
    # profile_directive): the autopilot publishes {"epoch": int,
    # "checkpoint_every": int, "time": ts} when Young/Daly says the
    # interval should move; the chief applies it at the next step
    # boundary and acks back {"applied_epoch": int, "applied_step": int}.
    # Empty when the cadence has never been retuned.
    checkpoint_cadence_directive: Dict[str, Any] = field(default_factory=dict)
    # Autopilot receipt surface (r16), reconciler-authored: {"last_decision":
    # {"kind", "action", "time", ...inputs}, "decisions_total": int,
    # "active_checkpoint_every": int} — what `tpujob top` and the
    # dashboard job view show. Empty while the autopilot is disabled or
    # has never acted. The authoritative receipts are the
    # autopilot-decision spans; this is the at-a-glance mirror.
    autopilot: Dict[str, Any] = field(default_factory=dict)

    def phase(self) -> JobPhase:
        """Derived v1alpha1-style phase (v1alpha1/types.go:106-116).

        CleanUp is the reference's "job decided, children not yet torn
        down" window: a terminal condition with replicas still active
        reports CleanUp until GC empties the active counters."""
        latest: Optional[Condition] = None
        for cond in self.conditions:
            if cond.status:
                latest = cond
        if latest is None:
            return JobPhase.NONE
        if latest.type in (ConditionType.SUCCEEDED, ConditionType.FAILED) and any(
            rs.active > 0 for rs in self.replica_statuses.values()
        ):
            return JobPhase.CLEANUP
        return {
            ConditionType.CREATED: JobPhase.CREATING,
            ConditionType.QUEUED: JobPhase.QUEUED,
            ConditionType.RUNNING: JobPhase.RUNNING,
            ConditionType.RESTARTING: JobPhase.RUNNING,
            ConditionType.SUCCEEDED: JobPhase.DONE,
            ConditionType.FAILED: JobPhase.FAILED,
        }[latest.type]


@dataclass
class TPUJob:
    """The job object (reference: TFJob, v1alpha2/types.go:28-43)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TPUJobSpec = field(default_factory=TPUJobSpec)
    status: TPUJobStatus = field(default_factory=TPUJobStatus)
    kind: str = KIND_TPUJOB

    def key(self) -> str:
        return self.metadata.key()

    def deepcopy(self) -> "TPUJob":
        return copy.deepcopy(self)

    # ---- serialization -------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return _to_jsonable(self)

    @staticmethod
    def from_dict(data: Dict[str, Any]) -> "TPUJob":
        return _tpujob_from_dict(data)


def now() -> float:
    return time.time()


# ---------------------------------------------------------------------------
# JSON (de)serialization. dataclasses.asdict handles the encode side except
# enum keys; the decode side rebuilds the typed tree.
# ---------------------------------------------------------------------------


def _to_jsonable(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _to_jsonable(getattr(obj, f.name)) for f in dataclasses.fields(obj)}
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, dict):
        return {str(k.value if isinstance(k, enum.Enum) else k): _to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(v) for v in obj]
    return obj


def _tpujob_from_dict(data: Dict[str, Any]) -> TPUJob:
    meta = ObjectMeta(**data.get("metadata", {}))
    spec_d = data.get("spec", {})
    replica_specs: Dict[ReplicaType, ReplicaSpec] = {}
    for rtype_s, rs in spec_d.get("replica_specs", {}).items():
        rs = dict(rs)
        tmpl = ProcessTemplate(**rs.pop("template", {}))
        rp = rs.pop("restart_policy", None)
        replica_specs[ReplicaType(rtype_s)] = ReplicaSpec(
            template=tmpl,
            restart_policy=RestartPolicy(rp) if rp else None,
            **rs,
        )
    topo = TopologySpec(**spec_d.get("topology", {}))
    run_d = dict(spec_d.get("run_policy", {}))
    cp = run_d.pop("cleanup_policy", None)
    if cp is not None:  # null ⇒ fall back to the dataclass default
        run_d["cleanup_policy"] = CleanupPolicy(cp)
    run = RunPolicy(**run_d)
    spec = TPUJobSpec(
        replica_specs=replica_specs,
        topology=topo,
        run_policy=run,
        scheduling=SchedulingSpec(**spec_d.get("scheduling", {})),
        workload=spec_d.get("workload", {}),
    )
    status_d = data.get("status", {})
    conditions = [
        Condition(
            type=ConditionType(c["type"]),
            status=bool(c.get("status", True)),
            reason=c.get("reason", ""),
            message=c.get("message", ""),
            last_update_time=c.get("last_update_time", 0.0),
            last_transition_time=c.get("last_transition_time", 0.0),
        )
        for c in status_d.get("conditions", [])
    ]
    replica_statuses = {
        ReplicaType(k): ReplicaStatus(**v) for k, v in status_d.get("replica_statuses", {}).items()
    }
    status = TPUJobStatus(
        conditions=conditions,
        replica_statuses=replica_statuses,
        start_time=status_d.get("start_time"),
        completion_time=status_d.get("completion_time"),
        last_reconcile_time=status_d.get("last_reconcile_time"),
        restart_count=status_d.get("restart_count", 0),
        preemption_count=status_d.get("preemption_count", 0),
        last_restart_cause=status_d.get("last_restart_cause", ""),
        eval_metrics=status_d.get("eval_metrics", {}) or {},
        resize_epoch=status_d.get("resize_epoch", 0),
        resize_count=status_d.get("resize_count", 0),
        world_size=status_d.get("world_size", 0),
        resize_directive=status_d.get("resize_directive", {}) or {},
        resize_history=list(status_d.get("resize_history", []) or []),
        resize_history_folded=status_d.get("resize_history_folded", 0),
        overspec_workers=status_d.get("overspec_workers", 0),
        profile_directive=status_d.get("profile_directive", {}) or {},
        hang_count=status_d.get("hang_count", 0),
        hang_state=status_d.get("hang_state", {}) or {},
        stackdump_directive=status_d.get("stackdump_directive", {}) or {},
        checkpoint_cadence_directive=(
            status_d.get("checkpoint_cadence_directive", {}) or {}
        ),
        autopilot=status_d.get("autopilot", {}) or {},
    )
    return TPUJob(metadata=meta, spec=spec, status=status, kind=data.get("kind", KIND_TPUJOB))
