"""Defaulting for TPUJob specs.

Reference parity: pkg/apis/tensorflow/v1alpha2/defaults.go (setDefaultPort
:33-55, setDefaultReplicas :57-61, SetDefaults_TFJob :64-69). Defaulting is
idempotent and runs on every reconcile after DeepCopy, matching
controller.v2/controller.go:357-361.
"""

from __future__ import annotations

from tf_operator_tpu.api.types import (
    DEFAULT_COORDINATOR_PORT,
    JOB_CLASS_SERVING,
    ReplicaType,
    RestartPolicy,
    TPUJob,
    TPUJobSpec,
)


def set_defaults(job: TPUJob) -> TPUJob:
    """Apply defaults in place and return the job (idempotent)."""
    set_spec_defaults(job.spec)
    return job


def set_spec_defaults(spec: TPUJobSpec) -> None:
    for rtype, rs in spec.replica_specs.items():
        if rs.replicas is None:
            rs.replicas = 1
        if rs.port is None:
            rs.port = DEFAULT_COORDINATOR_PORT
        if rs.restart_policy is None:
            # Evaluators are side observers — restart them on failure.
            # Coordinator/worker failures default to EXIT_CODE so the
            # taxonomy (utils/exit_codes.py) decides, the reference's most
            # battle-tested policy (controller_pod.go:77-92).
            if rtype is ReplicaType.EVALUATOR:
                rs.restart_policy = RestartPolicy.ON_FAILURE
            else:
                rs.restart_policy = RestartPolicy.EXIT_CODE
    # A job running the serve workload IS a serving job (r10): default the
    # class so the fleet scheduler's latency-sensitive priority applies
    # without the submitter having to know the scheduling vocabulary. An
    # explicit job_class (any value, incl. "training") is left alone.
    if not spec.scheduling.job_class and any(
        rs.template.entrypoint.startswith("tf_operator_tpu.workloads.serve")
        for rs in spec.replica_specs.values()
    ):
        spec.scheduling.job_class = JOB_CLASS_SERVING
