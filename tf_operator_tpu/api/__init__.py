"""API layer: TPUJob spec/status types, defaulting, validation.

Reference parity: pkg/apis/tensorflow/v1alpha2 (map-based replica specs,
conditions-based status) with a v1alpha1 compatibility view (list-based
specs, phase-based status) in ``compat``.
"""

from tf_operator_tpu.api.types import (  # noqa: F401
    Condition,
    ConditionType,
    JobPhase,
    ObjectMeta,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaStatus,
    ReplicaType,
    RestartPolicy,
    RunPolicy,
    CleanupPolicy,
    TopologySpec,
    TPUJob,
    TPUJobSpec,
    TPUJobStatus,
)
from tf_operator_tpu.api.defaults import set_defaults  # noqa: F401
from tf_operator_tpu.api.validation import ValidationError, validate_job, validate_spec  # noqa: F401
