"""v1alpha1-compatible job spec: list-based replica specs + conversion.

Reference parity: the repo carries TWO coexisting API generations
(SURVEY.md §0) — v1alpha1 (``pkg/apis/tensorflow/v1alpha1/types.go:40-160``:
``ReplicaSpecs []*TFReplicaSpec`` with ``TFReplicaType`` per entry, a
``TerminationPolicy`` naming the chief, and a job-level ``RuntimeId``) and
v1alpha2 (map-based). The primary API here (api/types.py) is the
v1alpha2-shaped one; this module accepts the older list shape and converts,
so v1alpha1-style job documents keep working — the same compatibility story
the reference's dual controllers provide.

Wire format accepted::

    {"api_version": "v1alpha1",
     "metadata": {...},
     "spec": {"replica_specs": [
         {"replica_type": "Coordinator"|"Worker"|"Evaluator"
                          |"MASTER"|"CHIEF"|"PS"|"WORKER"|"EVALUATOR",
          "replicas": 2, "template": {...}, "port": 8476,
          "restart_policy": "ExitCode"},
        ...],
      "termination_policy": {"chief": {"replica_name": "WORKER",
                                        "replica_index": 0}},
      "topology": {...}, "run_policy": {...}, "workload": {...}}}

Reference-role mapping (v1alpha1/types.go:80-84): MASTER/CHIEF →
Coordinator, WORKER → Worker, EVALUATOR → Evaluator. PS is rejected — SPMD
has no parameter servers (SURVEY.md §7: the PS role *collapses*); jobs that
carried PS replicas must drop them, and the error says so explicitly.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List

from tf_operator_tpu.api.types import (
    JobPhase,
    ObjectMeta,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    RunPolicy,
    TopologySpec,
    TPUJob,
    TPUJobSpec,
)
from tf_operator_tpu.api.validation import ValidationError

API_VERSION_V1ALPHA1 = "v1alpha1"

# v1alpha1 replica-type vocabulary → TPU-native roles.
_ROLE_MAP = {
    "MASTER": ReplicaType.COORDINATOR,
    "CHIEF": ReplicaType.COORDINATOR,
    "COORDINATOR": ReplicaType.COORDINATOR,
    "WORKER": ReplicaType.WORKER,
    "EVALUATOR": ReplicaType.EVALUATOR,
}


def is_v1alpha1(data: Dict[str, Any]) -> bool:
    """A document is v1alpha1-shaped if it says so or if its replica_specs
    is a list (the generation-defining difference)."""
    if data.get("api_version") == API_VERSION_V1ALPHA1:
        return True
    rs = data.get("spec", {}).get("replica_specs")
    return isinstance(rs, list)


def convert_v1alpha1(data: Dict[str, Any]) -> TPUJob:
    """Convert a v1alpha1-shaped dict into the primary TPUJob type.

    Raises ValidationError for PS replicas, duplicate roles, and unknown
    replica types — conversion failures must be loud, not lossy.
    """
    spec_d = data.get("spec", {})
    entries = spec_d.get("replica_specs", [])
    if not isinstance(entries, list):
        raise ValidationError("v1alpha1 spec.replica_specs must be a list")

    replica_specs: Dict[ReplicaType, ReplicaSpec] = {}
    for i, entry in enumerate(entries):
        raw_type = str(
            entry.get("replica_type", entry.get("tpu_replica_type", ""))
        ).upper()
        if raw_type == "PS":
            raise ValidationError(
                "v1alpha1 PS replicas have no TPU equivalent: SPMD training "
                "has no parameter servers — drop the PS replica set and let "
                "data parallelism shard the batch (SURVEY.md §2.3)"
            )
        role = _ROLE_MAP.get(raw_type)
        if role is None:
            raise ValidationError(
                f"replica_specs[{i}]: unknown replica_type {raw_type!r}"
            )
        if role in replica_specs:
            raise ValidationError(
                f"replica_specs[{i}]: duplicate role {role.value} "
                f"(v1alpha1 lists may not repeat a type)"
            )
        entry = dict(entry)
        entry.pop("replica_type", None)
        entry.pop("tpu_replica_type", None)
        try:
            tmpl = ProcessTemplate(**entry.pop("template", {}))
            rp = entry.pop("restart_policy", None)
            replica_specs[role] = ReplicaSpec(
                template=tmpl,
                restart_policy=RestartPolicy(rp) if rp else None,
                **entry,
            )
        except (TypeError, ValueError) as exc:
            # Loud, typed failures: unknown keys / bad values must surface
            # as ValidationError, the error the CLI/REST surfaces render.
            raise ValidationError(f"replica_specs[{i}]: {exc}") from exc

    # TerminationPolicy (v1alpha1/types.go:48-63): the chief designation.
    # Coordinator-present already means chief; otherwise only the default
    # (worker 0) is expressible in the new API — reject anything else
    # rather than silently changing which process decides job success.
    term = spec_d.get("termination_policy") or {}
    chief = term.get("chief") or {}
    if chief:
        cname = str(chief.get("replica_name", "")).upper()
        try:
            cidx = int(chief.get("replica_index", 0))
        except (TypeError, ValueError) as exc:
            raise ValidationError(
                f"termination_policy chief replica_index "
                f"{chief.get('replica_index')!r} is not an integer"
            ) from exc
        crole = _ROLE_MAP.get(cname)
        if crole is None:
            raise ValidationError(f"termination_policy chief {cname!r} unknown")
        if crole is ReplicaType.COORDINATOR:
            if ReplicaType.COORDINATOR not in replica_specs:
                raise ValidationError(
                    f"termination_policy: chief {cname!r} named but the job "
                    "declares no coordinator/master replica set"
                )
        elif not (crole is ReplicaType.WORKER and cidx == 0
                  and ReplicaType.COORDINATOR not in replica_specs):
            raise ValidationError(
                "termination_policy: only the coordinator (or worker 0 when "
                "no coordinator exists) can be chief in the TPU-native API"
            )

    meta_d = dict(data.get("metadata", {}))
    # v1alpha1 carried a job-level RuntimeId (types.go:48-63); preserve it
    # as an annotation for traceability.
    runtime_id = spec_d.get("runtime_id")
    annotations = dict(meta_d.get("annotations", {}))
    if runtime_id:
        annotations["tpujob.v1alpha1/runtime-id"] = str(runtime_id)
    meta_d["annotations"] = annotations
    try:
        meta = ObjectMeta(**meta_d)
    except TypeError as exc:
        raise ValidationError(f"metadata: {exc}") from exc

    from tf_operator_tpu.api.types import _tpujob_from_dict

    # Reuse the primary decoder for topology/run_policy/workload by
    # building a v1-shaped dict around the converted replica specs.
    shell = {
        "metadata": {},
        "spec": {
            "topology": spec_d.get("topology", {}),
            "run_policy": spec_d.get("run_policy", {}),
            "workload": spec_d.get("workload", {}),
        },
    }
    try:
        job = _tpujob_from_dict(copy.deepcopy(shell))
    except (TypeError, ValueError, KeyError) as exc:
        raise ValidationError(f"v1alpha1 spec: {exc}") from exc
    job.metadata = meta
    job.spec.replica_specs = replica_specs
    return job


def parse_job(data: Dict[str, Any]) -> TPUJob:
    """Decode either API generation: v1alpha1 documents are converted,
    anything else goes through the primary decoder."""
    if is_v1alpha1(data):
        return convert_v1alpha1(data)
    return TPUJob.from_dict(data)


def _v1alpha1_role(role: ReplicaType) -> str:
    return "MASTER" if role is ReplicaType.COORDINATOR else role.value.upper()


def to_v1alpha1(job: TPUJob) -> Dict[str, Any]:
    """Down-convert for v1alpha1-generation clients (round-trip surface).

    Status maps to the v1alpha1 shape (v1alpha1/types.go:106-160): the
    phase enum (Creating/Running/CleanUp/Failed/Done) derived from
    conditions + active counters, a coarse ``state``
    (Running/Succeeded/Failed), a ``reason`` from the deciding condition,
    and per-replica ``replicas_states`` counters — so a v1alpha1
    generation client polling a converted job sees the same lifecycle it
    saw from the reference's v1alpha1 trainer state machine."""
    entries: List[Dict[str, Any]] = []
    for role, rs in job.spec.replica_specs.items():
        d = {
            "replica_type": _v1alpha1_role(role),
            "replicas": rs.replicas,
            "template": {
                "entrypoint": rs.template.entrypoint,
                "args": list(rs.template.args),
                "env": dict(rs.template.env),
                "chips_per_process": rs.template.chips_per_process,
                "workdir": rs.template.workdir,
            },
        }
        if rs.restart_policy is not None:
            d["restart_policy"] = rs.restart_policy.value
        if rs.port is not None:
            d["port"] = rs.port
        entries.append(d)
    out = job.to_dict()
    out["api_version"] = API_VERSION_V1ALPHA1
    out["spec"]["replica_specs"] = entries

    phase = job.status.phase()
    state = {
        JobPhase.DONE: "Succeeded",
        JobPhase.FAILED: "Failed",
        JobPhase.CLEANUP: "Running",
        JobPhase.RUNNING: "Running",
        JobPhase.CREATING: "Running",
        JobPhase.NONE: "",
    }[phase]
    reason = ""
    for cond in job.status.conditions:
        if cond.status:
            reason = cond.reason or reason
    replica_statuses = [
        {
            "tpu_replica_type": _v1alpha1_role(role),
            # Counters drain as children are GC'd; a fully-drained replica
            # set inherits the job-level state rather than claiming Running.
            "state": (
                "Failed" if rs.failed
                else "Succeeded" if rs.succeeded and not rs.active
                else "Running" if rs.active
                else state
            ),
            "replicas_states": {
                "Running": rs.active,
                "Succeeded": rs.succeeded,
                "Failed": rs.failed,
            },
        }
        for role, rs in job.status.replica_statuses.items()
    ]
    out["status"] = {
        "phase": phase.value,
        "state": state,
        "reason": reason,
        "replica_statuses": replica_statuses,
    }
    return out
