"""TPUJob spec validation.

Reference parity: pkg/apis/tensorflow/validation/validation.go:26-79, which
requires a non-empty replica map, valid replica types, a template per
replica, a non-nil port, and the trained container to be named "tensorflow".
The TPU-native analogues are below; mesh/topology consistency checks are new
(the reference had no notion of device topology).
"""

from __future__ import annotations

import math
import re

from tf_operator_tpu.api.types import (
    JOB_CLASS_SERVING,
    JOB_CLASS_TRAINING,
    ReplicaType,
    TPUJob,
    TPUJobSpec,
)

# DNS-1123-label shape, like k8s object names: also forecloses path
# traversal in log paths and HTML injection in the dashboard.
_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_MAX_NAME = 63


class ValidationError(ValueError):
    """Raised when a TPUJob spec is invalid (reference: field.ErrorList)."""


def _validate_dns_label(value: str, field: str) -> None:
    if not value:
        raise ValidationError(f"{field} is required")
    if len(value) > _MAX_NAME or not _NAME_RE.match(value):
        raise ValidationError(
            f"{field} must be a lowercase DNS label (a-z, 0-9, '-'), got {value!r}"
        )


def validate_job(job: TPUJob) -> None:
    _validate_dns_label(job.metadata.name, "metadata.name")
    _validate_dns_label(job.metadata.namespace, "metadata.namespace")
    validate_spec(job.spec)


def validate_spec(spec: TPUJobSpec) -> None:
    if not spec.replica_specs:
        raise ValidationError("spec.replica_specs must not be empty")
    if not (
        ReplicaType.COORDINATOR in spec.replica_specs
        or ReplicaType.WORKER in spec.replica_specs
    ):
        # Job state is chief-driven (coordinator, else worker-0 —
        # controller_status.go:39-120 semantics); a job with neither would
        # sit in Created forever, so reject it at admission.
        raise ValidationError(
            "spec.replica_specs needs a Coordinator or Worker replica "
            "(job completion is chief-driven; Evaluator-only jobs have no chief)"
        )

    for rtype, rs in spec.replica_specs.items():
        if not isinstance(rtype, ReplicaType):
            raise ValidationError(f"unknown replica type {rtype!r}")
        prefix = f"spec.replica_specs[{rtype.value}]"
        if rs.replicas is not None and rs.replicas < 1:
            raise ValidationError(f"{prefix}.replicas must be >= 1")
        if rs.port is not None and not (0 < rs.port < 65536):
            raise ValidationError(f"{prefix}.port must be a valid port")
        # The reference demands the training container be named "tensorflow"
        # (validation.go:63-75); our analogue is a resolvable entrypoint.
        tmpl = rs.template
        if not tmpl.entrypoint:
            raise ValidationError(f"{prefix}.template.entrypoint is required")
        module, sep, func = tmpl.entrypoint.partition(":")
        if not sep or not module or not func:
            raise ValidationError(
                f"{prefix}.template.entrypoint must look like 'pkg.module:fn', "
                f"got {tmpl.entrypoint!r}"
            )
        if tmpl.chips_per_process < 0:
            raise ValidationError(f"{prefix}.template.chips_per_process must be >= 0")

    sched = spec.scheduling
    # Queue/PriorityClass references are resolved at admission, so only
    # their SHAPE is validated here (a missing object is legal: quota and
    # priority are opt-in); the names feed store keys and the dashboard.
    if sched.queue:
        _validate_dns_label(sched.queue, "spec.scheduling.queue")
    if sched.priority_class:
        _validate_dns_label(
            sched.priority_class, "spec.scheduling.priority_class"
        )
    job_class = getattr(sched, "job_class", "")
    if job_class not in ("", JOB_CLASS_TRAINING, JOB_CLASS_SERVING):
        raise ValidationError(
            f"spec.scheduling.job_class must be '', "
            f"'{JOB_CLASS_TRAINING}' or '{JOB_CLASS_SERVING}', "
            f"got {job_class!r}"
        )

    # Serve workloads (r10): the KV page geometry is capacity the engine
    # preallocates at startup — a bad value OOMs or deadlocks the decode
    # loop at runtime, so reject it at submission where the message can
    # still name the field.
    is_serve = job_class == JOB_CLASS_SERVING or any(
        rs.template.entrypoint.startswith("tf_operator_tpu.workloads.serve")
        for rs in spec.replica_specs.values()
    )
    if is_serve:
        wl = spec.workload or {}
        page = wl.get("kv_page_size", 16)
        pool = wl.get("kv_pool_pages", 64)
        slots = wl.get("max_slots", 4)
        if not isinstance(page, int) or page < 1:
            raise ValidationError(
                f"spec.workload.kv_page_size must be an int >= 1 tokens "
                f"(got {page!r}) — the paged KV cache cannot address "
                f"zero-token pages"
            )
        if not isinstance(pool, int) or pool < 1:
            raise ValidationError(
                f"spec.workload.kv_pool_pages must be an int >= 1 "
                f"(got {pool!r}) — a zero-page pool can hold no KV state, "
                f"so no request could ever be admitted"
            )
        if not isinstance(slots, int) or slots < 1:
            raise ValidationError(
                f"spec.workload.max_slots must be an int >= 1 (got {slots!r})"
            )

    rp = spec.run_policy
    if rp.heartbeat_ttl_seconds is not None and rp.heartbeat_ttl_seconds <= 0:
        raise ValidationError(
            "spec.run_policy.heartbeat_ttl_seconds must be > 0 "
            "(omit it to use the controller default)"
        )
    if rp.backoff_limit is not None and rp.backoff_limit < 0:
        raise ValidationError("spec.run_policy.backoff_limit must be >= 0")

    coord = spec.replica_specs.get(ReplicaType.COORDINATOR)
    if coord is not None and coord.replicas not in (None, 1):
        # Exactly one coordinator, like the chief (v1alpha2/types.go:105-108).
        raise ValidationError("spec.replica_specs[Coordinator].replicas must be 1")

    _validate_topology(spec)


def validate_queue(queue) -> None:
    """Queue admission checks (dashboard POST seam, like validate_job)."""
    _validate_dns_label(queue.metadata.name, "metadata.name")
    _validate_dns_label(queue.metadata.namespace, "metadata.namespace")
    if queue.spec.quota_chips < 0:
        raise ValidationError("spec.quota_chips must be >= 0 (0 = unlimited)")
    if queue.spec.max_running_jobs < 0:
        raise ValidationError("spec.max_running_jobs must be >= 0 (0 = unlimited)")


def validate_priority_class(pc) -> None:
    _validate_dns_label(pc.metadata.name, "metadata.name")
    if not isinstance(pc.value, int) or isinstance(pc.value, bool):
        raise ValidationError("value must be an integer")


def _validate_topology(spec: TPUJobSpec) -> None:
    topo = spec.topology
    if topo.num_hosts < 1:
        raise ValidationError("spec.topology.num_hosts must be >= 1")
    if topo.chips_per_host < 0:
        raise ValidationError("spec.topology.chips_per_host must be >= 0")
    if topo.dcn_mesh_axes and not topo.mesh_axes:
        # The "empty mesh_axes => pure DP over all chips" default cannot be
        # combined with DCN factors (build_hybrid_mesh would default every
        # ICI axis to 1); require the per-slice mesh to be explicit.
        raise ValidationError(
            "spec.topology.dcn_mesh_axes requires explicit mesh_axes "
            "(the per-slice ICI mesh)"
        )
    for axis, size in topo.dcn_mesh_axes.items():
        if size < 1:
            raise ValidationError(
                f"spec.topology.dcn_mesh_axes[{axis!r}] must be >= 1"
            )
        if axis in ("tp", "cp"):
            raise ValidationError(
                f"spec.topology.dcn_mesh_axes[{axis!r}]: tensor/context axes "
                "must stay on ICI (put DCN factors on dp/fsdp/pp)"
            )
    if topo.mesh_axes:
        for axis, size in topo.mesh_axes.items():
            if size < 1:
                raise ValidationError(f"spec.topology.mesh_axes[{axis!r}] must be >= 1")
        if topo.chips_per_host:
            # With dcn factors, mesh_axes describe the per-slice (ICI) mesh
            # and the product of both must cover the full topology.
            mesh_size = math.prod(topo.mesh_axes.values()) * math.prod(
                topo.dcn_mesh_axes.values() or [1]
            )
            total = topo.total_chips()
            if mesh_size != total:
                raise ValidationError(
                    f"mesh axes {topo.mesh_axes} x dcn {topo.dcn_mesh_axes or {}} "
                    f"multiply to {mesh_size} but topology has {total} chips"
                )
