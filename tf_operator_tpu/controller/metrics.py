"""Controller telemetry: Prometheus-text /metrics for the operator.

The reference registers client-go reflector/workqueue metrics via blank
imports but exposes no endpoint and no custom metrics
(cmd/tf-operator/main.go:26-27; SURVEY.md §5 "tracing/profiling: none").
This is the first-class version: counters maintained by the reconciler,
plus store/queue-derived gauges computed at scrape time, rendered in the
Prometheus text exposition format at ``GET /metrics`` on the dashboard
server.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from tf_operator_tpu.api.types import (
    KIND_HOST,
    KIND_PROCESS,
    KIND_QUEUE,
    KIND_TPUJOB,
)
from tf_operator_tpu.sched.objects import job_demand


class ControllerMetrics:
    """Thread-safe counter registry + scrape-time gauge renderer."""

    COUNTER_HELP = {
        "tpujob_syncs_total": "Reconcile sync attempts.",
        "tpujob_sync_errors_total": "Reconcile syncs that raised (requeued).",
        "tpujob_gang_restarts_total": "Gang restarts executed.",
        "tpujob_processes_created_total": "Child processes created.",
        "tpujob_processes_deleted_total": "Child processes deleted.",
        "tpujob_node_lost_total": "Processes declared lost (host/agent gone).",
        "tpujob_controller_restarts_total": (
            "Controller restarts that recovered state from the durable "
            "store (WAL + snapshot) and re-adopted live jobs."
        ),
        "tpujob_preemptions_requested_total": (
            "Preempt-by-priority victim drains requested by the fleet "
            "scheduler."
        ),
    }

    LABELED_HELP = {
        "tpujob_gang_restarts_by_cause_total": (
            "Gang restarts by cause (preemption / retryable-failure / "
            "node-lost / oom)."
        ),
    }

    # Lifecycle-latency histograms derived from trace-span boundaries
    # (obs/): the reconciler observes them as it records the spans, so
    # /metrics and the exported trace always agree. Buckets span "local
    # no-op job" (tens of ms) through "real slice bring-up" (minutes).
    LIFECYCLE_BUCKETS = (
        0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
    )
    # Sub-step-time latencies (the async save stall target is < 1
    # step-time, i.e. milliseconds on real steps): LIFECYCLE_BUCKETS'
    # 50 ms floor would collapse the whole distribution into bucket 0.
    FINE_BUCKETS = (
        0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
        0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    )
    HIST_HELP = {
        "tpujob_time_to_scheduled_seconds": (
            "Submit -> gang placement decided (the scheduled span's end)."
        ),
        "tpujob_time_to_first_step_seconds": (
            "Submit -> first training step (TTFS; the first-step span "
            "reported by the workload)."
        ),
        "tpujob_restart_downtime_seconds": (
            "Gang restart decided -> gang RUNNING again (MTTR), by "
            "restart cause."
        ),
        "tpujob_queue_wait_seconds": (
            "Fleet-scheduler admission wait (queued span: parked in "
            "QUEUED -> admitted), by queue and priority class."
        ),
        "tpujob_checkpoint_save_stall_seconds": (
            "Step-loop stall per accepted async checkpoint save (the "
            "staging copy; device->host fetch and disk write overlap "
            "training behind it)."
        ),
        "tpujob_restore_seconds": (
            "Warm-restore wall time by source (peer = pulled from a "
            "surviving host's shard depot; disk = orbax/npy read)."
        ),
    }
    # Histogram families measuring sub-step-time latencies use the fine
    # bucket ladder; everything else stays on the lifecycle ladder.
    HIST_BUCKETS = {
        "tpujob_checkpoint_save_stall_seconds": FINE_BUCKETS,
    }

    # Reconcile-latency histogram bounds (seconds). Healthy syncs on the
    # indexed store sit in the first few buckets; the tail buckets are
    # where the pre-index O(population) scans lived — the knee's signature.
    SYNC_BUCKETS = (
        0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
        0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    )
    # Raw-sample cap for quantile estimation (the bench's p50/p99 oracle);
    # a 500-job run produces ~10-20k syncs, well under it.
    MAX_SYNC_SAMPLES = 200_000

    def __init__(self, store=None, queue=None) -> None:
        self.store = store
        self.queue = queue
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {k: 0.0 for k in self.COUNTER_HELP}
        # (name, (("label","value"), ...)) -> count
        self._labeled: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        self._sync_seconds_sum = 0.0
        self._sync_seconds_count = 0
        self._sync_bucket_counts = [0] * (len(self.SYNC_BUCKETS) + 1)  # +Inf
        self._sync_samples: List[float] = []
        # Deterministic decimation state: once the sample list hits
        # MAX_SYNC_SAMPLES it is thinned to every 2nd sample and the
        # keep-stride doubles, so quantiles keep tracking the WHOLE run
        # (the old behavior froze them at the first 200k syncs).
        self._sync_sample_stride = 1
        self._sync_observations = 0
        # (name, (("label","value"), ...)) -> [bucket_counts, sum, count]
        self._hists: Dict[
            Tuple[str, Tuple[Tuple[str, str], ...]], list
        ] = {}

    # -- writers (reconciler) ---------------------------------------------

    def inc(
        self, name: str, n: float = 1.0, labels: Optional[Dict[str, str]] = None
    ) -> None:
        if labels:
            key = (name, tuple(sorted(labels.items())))
            with self._lock:
                self._labeled[key] = self._labeled.get(key, 0.0) + n
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + n

    def observe_sync(self, seconds: float, error: bool) -> None:
        with self._lock:
            self._counters["tpujob_syncs_total"] += 1
            if error:
                self._counters["tpujob_sync_errors_total"] += 1
            self._sync_seconds_sum += seconds
            self._sync_seconds_count += 1
            i = 0
            while i < len(self.SYNC_BUCKETS) and seconds > self.SYNC_BUCKETS[i]:
                i += 1
            self._sync_bucket_counts[i] += 1
            # Keep-every-Nth with doubling stride: every observation has a
            # deterministic fate, the kept set always covers the whole run,
            # and memory stays bounded at MAX_SYNC_SAMPLES.
            if self._sync_observations % self._sync_sample_stride == 0:
                self._sync_samples.append(seconds)
                if len(self._sync_samples) >= self.MAX_SYNC_SAMPLES:
                    self._sync_samples = self._sync_samples[::2]
                    self._sync_sample_stride *= 2
            self._sync_observations += 1

    def observe_hist(
        self, name: str, seconds: float, labels: Optional[Dict[str, str]] = None
    ) -> None:
        """Observe one value into a lifecycle-latency histogram family
        (HIST_HELP). Label sets create their series on first use."""
        key = (name, tuple(sorted((labels or {}).items())))
        bounds = self._buckets_for(name)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = [[0] * (len(bounds) + 1), 0.0, 0]
                self._hists[key] = h
            i = 0
            while i < len(bounds) and seconds > bounds[i]:
                i += 1
            h[0][i] += 1
            h[1] += seconds
            h[2] += 1

    @classmethod
    def _buckets_for(cls, name: str) -> tuple:
        return cls.HIST_BUCKETS.get(name, cls.LIFECYCLE_BUCKETS)

    def sync_latency_quantiles(self, qs=(0.5, 0.99)) -> Dict[float, float]:
        """Empirical sync-latency quantiles from the raw samples (the
        bench artifact's p50/p99 source — exact, unlike bucket
        interpolation). Empty history returns 0s."""
        with self._lock:
            samples = sorted(self._sync_samples)
        if not samples:
            return {q: 0.0 for q in qs}
        return {
            q: samples[min(len(samples) - 1, int(q * len(samples)))]
            for q in qs
        }

    # -- scrape -----------------------------------------------------------

    def render(self) -> str:
        out: List[str] = []
        with self._lock:
            counters = dict(self._counters)
            labeled = dict(self._labeled)
            s_sum, s_count = self._sync_seconds_sum, self._sync_seconds_count
            buckets = list(self._sync_bucket_counts)
            hists = {
                k: [list(v[0]), v[1], v[2]] for k, v in self._hists.items()
            }
        # .17g: %g's 6 significant digits would freeze a counter past ~1e6
        # (consecutive increments render identically and rate() reads 0).
        for name, value in sorted(counters.items()):
            help_text = self.COUNTER_HELP.get(name, name)
            out.append(f"# HELP {name} {help_text}")
            out.append(f"# TYPE {name} counter")
            out.append(f"{name} {value:.17g}")
        # Labeled counters: one HELP/TYPE block per family, samples sorted
        # by label set so scrapes are stable.
        for name in sorted({k[0] for k in labeled}):
            out.append(f"# HELP {name} {self.LABELED_HELP.get(name, name)}")
            out.append(f"# TYPE {name} counter")
            for (n, lbls), value in sorted(labeled.items()):
                if n != name:
                    continue
                rendered = _render_labels(lbls)
                out.append(f"{name}{{{rendered}}} {value:.17g}")
        # Lifecycle-latency histograms (trace-span-derived): one
        # HELP/TYPE block per family, one bucket series per label set.
        for name in sorted({k[0] for k in hists}):
            out.append(f"# HELP {name} {self.HIST_HELP.get(name, name)}")
            out.append(f"# TYPE {name} histogram")
            bounds = self._buckets_for(name)
            for (n, lbls), (bkts, h_sum, h_count) in sorted(hists.items()):
                if n != name:
                    continue
                base = _render_labels(lbls)
                sep = "," if base else ""
                cum = 0
                for le, cnt in zip(bounds, bkts):
                    cum += cnt
                    out.append(
                        f'{name}_bucket{{{base}{sep}le="{le:g}"}} {cum}'
                    )
                out.append(f'{name}_bucket{{{base}{sep}le="+Inf"}} {h_count}')
                suffix = f"{{{base}}}" if base else ""
                out.append(f"{name}_sum{suffix} {h_sum:.17g}")
                out.append(f"{name}_count{suffix} {h_count}")
        # Reconcile latency as a HISTOGRAM (r6): the knee was inferred
        # from throughput before; the tail buckets make it observable.
        out.append("# HELP tpujob_sync_duration_seconds Reconcile sync wall time.")
        out.append("# TYPE tpujob_sync_duration_seconds histogram")
        cum = 0
        for le, n in zip(self.SYNC_BUCKETS, buckets):
            cum += n
            out.append(f'tpujob_sync_duration_seconds_bucket{{le="{le:g}"}} {cum}')
        out.append(f'tpujob_sync_duration_seconds_bucket{{le="+Inf"}} {s_count}')
        out.append(f"tpujob_sync_duration_seconds_sum {s_sum:.17g}")
        out.append(f"tpujob_sync_duration_seconds_count {s_count}")

        if self.queue is not None:
            out.append("# HELP tpujob_workqueue_depth Keys waiting in the workqueue.")
            out.append("# TYPE tpujob_workqueue_depth gauge")
            out.append(f"tpujob_workqueue_depth {self.queue.depth()}")

        if self.store is not None:
            out.extend(self._store_gauges())
            out.extend(self._list_cost_counters())
        return "\n".join(out) + "\n"

    def _list_cost_counters(self) -> List[str]:
        """Store list-cost counters (Store.list_stats): scanned tracking
        returned is the index doing its job; scanned diverging from
        returned means some selector is falling back to a wide scan —
        the exact regression the store-index tests pin."""
        stats_fn = getattr(self.store, "list_stats", None)
        if stats_fn is None:
            return []
        stats = stats_fn()
        out = []
        help_ = {
            "calls": "Store.list calls served.",
            "scanned": "Index candidates visited across all Store.list calls.",
            "returned": "Objects returned across all Store.list calls.",
        }
        for k in ("calls", "scanned", "returned"):
            name = f"tpujob_store_list_{k}_total"
            out.append(f"# HELP {name} {help_[k]}")
            out.append(f"# TYPE {name} counter")
            out.append(f"{name} {stats[k]}")
        return out

    def _store_gauges(self) -> List[str]:
        out: List[str] = []
        jobs: Dict[str, int] = {}
        for j in self.store.list(KIND_TPUJOB):
            phase = _job_phase(j)
            jobs[phase] = jobs.get(phase, 0) + 1
        out.append("# HELP tpujob_jobs Jobs in the store by phase.")
        out.append("# TYPE tpujob_jobs gauge")
        for phase, n in sorted(jobs.items()):
            out.append(f'tpujob_jobs{{phase="{phase}"}} {n}')

        procs: Dict[str, int] = {}
        for p in self.store.list(KIND_PROCESS):
            procs[p.status.phase.value] = procs.get(p.status.phase.value, 0) + 1
        out.append("# HELP tpujob_processes Processes in the store by phase.")
        out.append("# TYPE tpujob_processes gauge")
        for phase, n in sorted(procs.items()):
            out.append(f'tpujob_processes{{phase="{phase}"}} {n}')

        hosts = self.store.list(KIND_HOST)
        if hosts:
            ready = sum(1 for h in hosts if h.status.phase.value == "Ready")
            draining = sum(1 for h in hosts if h.status.phase.value == "Draining")
            out.append("# HELP tpujob_hosts Registered hosts.")
            out.append("# TYPE tpujob_hosts gauge")
            out.append(f'tpujob_hosts{{ready="true"}} {ready}')
            out.append(f'tpujob_hosts{{ready="false"}} {len(hosts) - ready}')
            out.append(
                "# HELP tpujob_hosts_draining Hosts under a preemption "
                "notice (DRAINING)."
            )
            out.append("# TYPE tpujob_hosts_draining gauge")
            out.append(f"tpujob_hosts_draining {draining}")

        queues = self.store.list(KIND_QUEUE)
        if queues:
            # Per-queue quota gauges, recomputed from the store at scrape
            # time (not from the fleet scheduler's in-memory usage) so the
            # numbers survive a controller restart and double as the
            # quota-overshoot oracle the sched bench polls.
            used: Dict[tuple, int] = {}
            for j in self.store.list(KIND_TPUJOB):
                qname = j.spec.scheduling.queue
                # Only chip-holding phases count against the queue: a job
                # holds its quota from gang-create (Creating) until its
                # terminal classification releases it, so Done/Failed jobs
                # awaiting GC and parked Queued jobs must not inflate used.
                if not qname or _job_phase(j) not in ("Creating", "Running", "CleanUp"):
                    continue
                k = (j.metadata.namespace, qname)
                used[k] = used.get(k, 0) + job_demand(j)
            for help_text, name in (
                ("Queue chip quota (0 = unlimited).", "tpujob_queue_quota_chips"),
                ("Chips held by admitted jobs in the queue.", "tpujob_queue_used_chips"),
                ("Quota headroom (quota - used; unlimited renders -1).", "tpujob_queue_free_chips"),
            ):
                out.append(f"# HELP {name} {help_text}")
                out.append(f"# TYPE {name} gauge")
                for q in queues:
                    k = (q.metadata.namespace, q.metadata.name)
                    quota = q.spec.quota_chips
                    u = used.get(k, 0)
                    value = {
                        "tpujob_queue_quota_chips": quota,
                        "tpujob_queue_used_chips": u,
                        "tpujob_queue_free_chips": (quota - u) if quota else -1,
                    }[name]
                    out.append(
                        f'{name}{{namespace="{_escape_label_value(k[0])}",'
                        f'queue="{_escape_label_value(k[1])}"}} {value}'
                    )
        return out


def _escape_label_value(v: str) -> str:
    """Prometheus text-exposition label-value escaping: backslash, double
    quote and newline must be escaped or the whole scrape is unparseable
    (one restart message with a quote used to poison /metrics)."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _render_labels(lbls: Tuple[Tuple[str, str], ...]) -> str:
    return ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in lbls)


def _job_phase(job) -> str:
    try:
        return job.status.phase().value
    except Exception:
        return "Unknown"
