"""Conditions status engine.

Reference parity: pkg/controller.v2/controller_status.go — newCondition /
setCondition / filterOutCondition (:157-215) plus the replica-status counters
(:136-154). Semantics preserved:

- setting a condition updates an existing one of the same type in place
  (bumping transition time only when status flips);
- setting Running filters out Restarting (and vice versa) — they are
  mutually exclusive "currently" conditions;
- Succeeded/Failed are terminal; once either is true the job is finished.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from tf_operator_tpu.api.types import (
    Condition,
    ConditionType,
    ReplicaStatus,
    ReplicaType,
    TPUJobStatus,
)
from tf_operator_tpu.runtime.objects import Process, ProcessPhase

_EXCLUSIVE = {
    # Queued is a "currently" condition too: a job admitted to run is no
    # longer waiting in the fleet-scheduler queue, and vice versa.
    ConditionType.RUNNING: {ConditionType.RESTARTING, ConditionType.QUEUED},
    ConditionType.RESTARTING: {ConditionType.RUNNING},
    ConditionType.QUEUED: {ConditionType.RUNNING},
}


def new_condition(ctype: ConditionType, reason: str, message: str) -> Condition:
    now = time.time()
    return Condition(
        type=ctype,
        status=True,
        reason=reason,
        message=message,
        last_update_time=now,
        last_transition_time=now,
    )


def get_condition(status: TPUJobStatus, ctype: ConditionType) -> Optional[Condition]:
    for c in status.conditions:
        if c.type is ctype:
            return c
    return None


def has_condition(status: TPUJobStatus, ctype: ConditionType) -> bool:
    c = get_condition(status, ctype)
    return c is not None and c.status


def is_finished(status: TPUJobStatus) -> bool:
    return has_condition(status, ConditionType.SUCCEEDED) or has_condition(
        status, ConditionType.FAILED
    )


def set_condition(status: TPUJobStatus, cond: Condition) -> None:
    """Insert/update ``cond``, dropping mutually-exclusive conditions
    (controller_status.go setCondition + filterOutCondition)."""
    drop = _EXCLUSIVE.get(cond.type, set())
    status.conditions = [c for c in status.conditions if c.type not in drop]
    existing = get_condition(status, cond.type)
    if existing is not None:
        if existing.status == cond.status and existing.reason == cond.reason:
            existing.message = cond.message
            existing.last_update_time = cond.last_update_time
            return
        cond.last_transition_time = (
            existing.last_transition_time
            if existing.status == cond.status
            else cond.last_transition_time
        )
        status.conditions = [c for c in status.conditions if c.type is not cond.type]
    status.conditions.append(cond)


def clear_condition(status: TPUJobStatus, ctype: ConditionType) -> bool:
    """Drop all conditions of ``ctype`` (filterOutCondition analogue);
    phase() falls back to the latest remaining True condition. Returns
    True when something was removed."""
    before = len(status.conditions)
    status.conditions = [c for c in status.conditions if c.type is not ctype]
    return len(status.conditions) != before


def initialize_replica_statuses(status: TPUJobStatus, rtypes) -> None:
    """Zero the counters for each replica type (controller_status.go:136-141)."""
    status.replica_statuses = {ReplicaType(rt): ReplicaStatus() for rt in rtypes}


def update_replica_status(status: TPUJobStatus, rtype: ReplicaType, process: Process) -> None:
    """Fold one observed process into the counters
    (controller_status.go:143-154: pod phase → Active/Succeeded/Failed)."""
    rs = status.replica_statuses.setdefault(rtype, ReplicaStatus())
    if process.status.phase in (ProcessPhase.RUNNING, ProcessPhase.PENDING):
        rs.active += 1
    elif process.status.phase is ProcessPhase.SUCCEEDED:
        rs.succeeded += 1
    elif process.status.phase is ProcessPhase.FAILED:
        rs.failed += 1


def replica_counts(status: TPUJobStatus) -> Dict[str, int]:
    totals = {"active": 0, "succeeded": 0, "failed": 0}
    for rs in status.replica_statuses.values():
        totals["active"] += rs.active
        totals["succeeded"] += rs.succeeded
        totals["failed"] += rs.failed
    return totals
