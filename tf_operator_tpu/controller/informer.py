"""Shared informer: a local cache fed by store watches + event handlers.

Reference parity: client-go SharedInformerFactory as wired by the operator
(pkg/client/informers/externalversions/factory.go, and the unstructured
variant pkg/util/unstructured/informer.go:25-62). The informer consumes the
store's list+watch stream on a background thread, maintains a read-only
cache (the lister), and dispatches add/update/delete callbacks — the same
callbacks that do expectations bookkeeping and enqueue job keys in the
reference (controller_pod.go:285-412).
"""

from __future__ import annotations

import copy
import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from tf_operator_tpu.runtime.store import Store, WatchEventType

log = logging.getLogger(__name__)

Handler = Callable[[Any], None]
UpdateHandler = Callable[[Any, Any], None]


class Informer:
    """Caches one kind; dispatches handlers serially on the watch thread
    (client-go delivers each informer's events in order, same here)."""

    def __init__(self, store: Store, kind: str) -> None:
        self._store = store
        self.kind = kind
        self._lock = threading.RLock()
        self._cache: Dict[Tuple[str, str], Any] = {}  # (ns, name) -> obj
        self._on_add: List[Handler] = []
        self._on_update: List[UpdateHandler] = []
        self._on_delete: List[Handler] = []
        self._thread: Optional[threading.Thread] = None
        self._watch = None
        self._synced = threading.Event()
        # Permanent watch failure (rejected credentials): reason string.
        # has_synced() raises on it so cache-sync waiters fail fast.
        self.failed: Optional[str] = None

    # -- registration (before run) ---------------------------------------

    def add_event_handler(
        self,
        on_add: Optional[Handler] = None,
        on_update: Optional[UpdateHandler] = None,
        on_delete: Optional[Handler] = None,
    ) -> None:
        if on_add:
            self._on_add.append(on_add)
        if on_update:
            self._on_update.append(on_update)
        if on_delete:
            self._on_delete.append(on_delete)

    # -- lister (reference: pkg/client/listers) ---------------------------

    def get(self, namespace: str, name: str) -> Optional[Any]:
        with self._lock:
            obj = self._cache.get((namespace, name))
            return copy.deepcopy(obj) if obj is not None else None

    def list(
        self, namespace: Optional[str] = None, label_selector: Optional[Dict[str, str]] = None
    ) -> List[Any]:
        with self._lock:
            out = []
            for (ns, _), obj in self._cache.items():
                if namespace is not None and ns != namespace:
                    continue
                if label_selector and not all(
                    obj.metadata.labels.get(k) == v for k, v in label_selector.items()
                ):
                    continue
                out.append(copy.deepcopy(obj))
            out.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
            return out

    def has_synced(self) -> bool:
        if self.failed is not None:
            raise RuntimeError(
                f"informer {self.kind} watch failed permanently: {self.failed}"
            )
        return self._synced.is_set()

    def seed(self, objs) -> None:
        """Populate the cache directly without a watch — for tests that
        drive syncs deterministically (the reference's tests inject into
        informer indexers the same way, controller_test.go:44-70)."""
        with self._lock:
            for obj in objs:
                meta = obj.metadata
                self._cache[(meta.namespace, meta.name)] = copy.deepcopy(obj)
        self._synced.set()

    # -- lifecycle --------------------------------------------------------

    def run(self) -> None:
        """Start consuming the watch on a daemon thread."""
        if self._thread is not None:
            return
        self._watch = self._store.watch(kinds=[self.kind])
        # The watch replays existing objects as ADDED before live events, so
        # draining it keeps cache population and handler dispatch in order.
        self._thread = threading.Thread(
            target=self._loop, name=f"informer-{self.kind}", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        from tf_operator_tpu.runtime.remote_store import UnauthorizedError

        assert self._watch is not None
        try:
            if hasattr(self._watch, "queue"):
                self._loop_local()
            else:
                self._loop_remote()
        except UnauthorizedError as exc:
            # Permanent credential rejection: record it and unblock sync
            # waiters LOUDLY (has_synced raises) rather than letting the
            # thread die silently behind a green /healthz.
            self.failed = str(exc)
            log.critical("informer %s: store credentials rejected (%s)",
                         self.kind, exc)
            self._synced.set()

    def _loop_local(self) -> None:
        # Synced once the replayed backlog drains: either the queue empties
        # after a dispatch or the first 50ms poll comes up empty.
        import queue as _queue

        while True:
            try:
                ev = self._watch.queue.get(timeout=0.05)
            except _queue.Empty:
                self._synced.set()
                continue
            if ev is None:
                self._synced.set()
                return
            self._dispatch(ev)
            if self._watch.queue.empty():
                self._synced.set()

    def _loop_remote(self) -> None:
        """RemoteWatch consumption (the HA --store-server controller): an
        auto-reconnecting ITERABLE that brackets each (re)connect's replay
        with REPLAY_START/SYNCED control events instead of exposing a
        queue. On SYNCED the cache reconciles against the replayed set —
        deletions that happened while disconnected are never replayed, so
        anything cached but absent from the replay gets a synthetic
        DELETED (the informer-side analogue of the agent's orphan reap)."""
        from tf_operator_tpu.runtime.store import WatchEvent

        replay_seen: Optional[set] = None
        for ev in self._watch:
            if ev.type is WatchEventType.REPLAY_START:
                replay_seen = set()
                continue
            if ev.type is WatchEventType.SYNCED:
                if replay_seen is not None:
                    with self._lock:
                        stale = [
                            (k, obj) for k, obj in self._cache.items()
                            if k not in replay_seen
                        ]
                    for _, obj in stale:
                        self._dispatch(WatchEvent(WatchEventType.DELETED, obj))
                replay_seen = None
                self._synced.set()
                continue
            if replay_seen is not None:
                meta = ev.obj.metadata
                key = (meta.namespace, meta.name)
                replay_seen.add(key)
                # DeltaFIFO rule: a re-list ADD for an object we already
                # cache is a MODIFIED, not a new ADDED — replay ADDs would
                # otherwise re-fire creation_observed on the expectations
                # cache and let a concurrent sync trust a stale view (the
                # exact staleness the expectations machinery guards).
                if ev.type is WatchEventType.ADDED and key in self._cache:
                    ev = WatchEvent(WatchEventType.MODIFIED, ev.obj)
            self._dispatch(ev)
        self._synced.set()

    def _dispatch(self, ev) -> None:
        meta = ev.obj.metadata
        key = (meta.namespace, meta.name)
        with self._lock:
            old = self._cache.get(key)
            if ev.type is WatchEventType.DELETED:
                self._cache.pop(key, None)
            else:
                self._cache[key] = ev.obj
        try:
            if ev.type is WatchEventType.ADDED:
                for h in self._on_add:
                    h(ev.obj)
            elif ev.type is WatchEventType.MODIFIED:
                for h in self._on_update:
                    h(old, ev.obj)
            else:
                for h in self._on_delete:
                    h(ev.obj)
        except Exception:  # a handler bug must not kill the watch thread
            log.exception("informer handler failed for %s %s", self.kind, key)

    def stop(self) -> None:
        if self._watch is not None:
            self._watch.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
