"""Shared informer: a local cache fed by store watches + event handlers.

Reference parity: client-go SharedInformerFactory as wired by the operator
(pkg/client/informers/externalversions/factory.go, and the unstructured
variant pkg/util/unstructured/informer.go:25-62). The informer consumes the
store's list+watch stream on a background thread, maintains a read-only
cache (the lister), and dispatches add/update/delete callbacks — the same
callbacks that do expectations bookkeeping and enqueue job keys in the
reference (controller_pod.go:285-412).

r6 scale notes: one loop consumes both the in-process Watch and the
RemoteWatch — both now frame replays with REPLAY_START/SYNCED control
events, so reconnect reconciliation (replay ADD of a cached key ⇒
MODIFIED; cached key absent from the replay ⇒ synthetic DELETED) is a
single code path. The lister is indexed like the store: per namespace
and per indexed-label value (the job-name label), so ``list`` visits —
and deepcopies — only the selected set, not the whole cache
(`_claim_processes` calls it once per job sync; a flat scan made every
resync pass O(jobs²)). A local watch closed by the store for overflow
(consumer fell DEFAULT_WATCH_QUEUE_SIZE events behind) is transparently
re-subscribed, with the replay markers driving cache reconciliation.
"""

from __future__ import annotations

import copy
import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from tf_operator_tpu.runtime.store import (
    INDEXED_LABELS,
    Store,
    WatchEvent,
    WatchEventType,
)

log = logging.getLogger(__name__)

Handler = Callable[[Any], None]
UpdateHandler = Callable[[Any, Any], None]


class Informer:
    """Caches one kind; dispatches handlers serially on the watch thread
    (client-go delivers each informer's events in order, same here)."""

    def __init__(self, store: Store, kind: str) -> None:
        self._store = store
        self.kind = kind
        self._lock = threading.RLock()
        self._cache: Dict[Tuple[str, str], Any] = {}  # (ns, name) -> obj
        # Lister indices (mirror the store's): ns -> keys, and
        # (label_key, label_value) -> keys for the indexed labels.
        self._by_ns: Dict[str, set] = {}
        self._by_label: Dict[Tuple[str, str], set] = {}
        self._on_add: List[Handler] = []
        self._on_update: List[UpdateHandler] = []
        self._on_delete: List[Handler] = []
        self._thread: Optional[threading.Thread] = None
        self._watch = None
        self._synced = threading.Event()
        self._stopped = False
        # Permanent watch failure (rejected credentials): reason string.
        # has_synced() raises on it so cache-sync waiters fail fast.
        self.failed: Optional[str] = None

    # -- registration (before run) ---------------------------------------

    def add_event_handler(
        self,
        on_add: Optional[Handler] = None,
        on_update: Optional[UpdateHandler] = None,
        on_delete: Optional[Handler] = None,
    ) -> None:
        if on_add:
            self._on_add.append(on_add)
        if on_update:
            self._on_update.append(on_update)
        if on_delete:
            self._on_delete.append(on_delete)

    # -- lister (reference: pkg/client/listers) ---------------------------

    def get(self, namespace: str, name: str) -> Optional[Any]:
        with self._lock:
            obj = self._cache.get((namespace, name))
            return copy.deepcopy(obj) if obj is not None else None

    def list(
        self, namespace: Optional[str] = None, label_selector: Optional[Dict[str, str]] = None
    ) -> List[Any]:
        with self._lock:
            keys = None
            residual = dict(label_selector) if label_selector else None
            if residual:
                for lk in INDEXED_LABELS:
                    if lk in residual:
                        keys = self._by_label.get((lk, residual.pop(lk)), set())
                        break
            if keys is None:
                keys = (
                    self._by_ns.get(namespace, set())
                    if namespace is not None
                    else self._cache.keys()
                )
            out = []
            for key in keys:
                obj = self._cache.get(key)
                if obj is None:
                    continue
                if namespace is not None and key[0] != namespace:
                    continue
                if residual and not all(
                    obj.metadata.labels.get(k) == v for k, v in residual.items()
                ):
                    continue
                out.append(copy.deepcopy(obj))
            out.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
            return out

    def has_synced(self) -> bool:
        if self.failed is not None:
            raise RuntimeError(
                f"informer {self.kind} watch failed permanently: {self.failed}"
            )
        return self._synced.is_set()

    def seed(self, objs) -> None:
        """Populate the cache directly without a watch — for tests that
        drive syncs deterministically (the reference's tests inject into
        informer indexers the same way, controller_test.go:44-70)."""
        with self._lock:
            for obj in objs:
                meta = obj.metadata
                self._cache_put((meta.namespace, meta.name), copy.deepcopy(obj))
        self._synced.set()

    # -- cache + index maintenance (callers hold _lock) -------------------

    def _label_keys(self, obj: Any) -> List[Tuple[str, str]]:
        labels = obj.metadata.labels or {}
        return [(lk, labels[lk]) for lk in INDEXED_LABELS if lk in labels]

    def _cache_put(self, key: Tuple[str, str], obj: Any) -> None:
        old = self._cache.get(key)
        if old is not None:
            for b in self._label_keys(old):
                bucket = self._by_label.get(b)
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        del self._by_label[b]
        self._cache[key] = obj
        self._by_ns.setdefault(key[0], set()).add(key)
        for b in self._label_keys(obj):
            self._by_label.setdefault(b, set()).add(key)

    def _cache_pop(self, key: Tuple[str, str]) -> None:
        old = self._cache.pop(key, None)
        if old is None:
            return
        bucket = self._by_ns.get(key[0])
        if bucket is not None:
            bucket.discard(key)
            if not bucket:
                del self._by_ns[key[0]]
        for b in self._label_keys(old):
            lbucket = self._by_label.get(b)
            if lbucket is not None:
                lbucket.discard(key)
                if not lbucket:
                    del self._by_label[b]

    # -- lifecycle --------------------------------------------------------

    def run(self) -> None:
        """Start consuming the watch on a daemon thread."""
        if self._thread is not None:
            return
        self._watch = self._subscribe()
        self._thread = threading.Thread(
            target=self._loop, name=f"informer-{self.kind}", daemon=True
        )
        self._thread.start()

    def _subscribe(self):
        try:
            # In-process store: ask for the replay markers so the one
            # replay-reconciling loop below serves local watches too.
            return self._store.watch(kinds=[self.kind], mark_replay=True)
        except TypeError:
            # Store-compatible object without mark_replay (RemoteStore —
            # its RemoteWatch frames every (re)connect's replay itself).
            return self._store.watch(kinds=[self.kind])

    def _loop(self) -> None:
        from tf_operator_tpu.runtime.remote_store import UnauthorizedError

        try:
            while True:
                self._consume(self._watch)
                # The iterator ended: deliberate stop, or the store closed
                # an overflowed local watch. Only the latter re-subscribes
                # (RemoteWatch reconnects internally and only ever ends on
                # stop()).
                if self._stopped or not getattr(self._watch, "overflowed", False):
                    return
                log.warning(
                    "informer %s: watch overflowed (consumer lagged); "
                    "re-listing", self.kind,
                )
                self._watch = self._subscribe()
        except UnauthorizedError as exc:
            # Permanent credential rejection: record it and unblock sync
            # waiters LOUDLY (has_synced raises) rather than letting the
            # thread die silently behind a green /healthz.
            self.failed = str(exc)
            log.critical("informer %s: store credentials rejected (%s)",
                         self.kind, exc)
            self._synced.set()

    def _consume(self, watch) -> None:
        """Drain one watch subscription: replay-aware cache maintenance +
        handler dispatch. Replays (bracketed by REPLAY_START/SYNCED) are
        reconciled against the cache: an ADD for a cached key is a
        MODIFIED (the DeltaFIFO re-list rule — replay ADDs would otherwise
        re-fire creation_observed on the expectations cache and let a
        concurrent sync trust a stale view), and anything cached but
        absent from the replay gets a synthetic DELETED on SYNCED
        (deletions during a disconnect are never replayed)."""
        replay_seen: Optional[set] = None
        for ev in watch:
            if ev.type is WatchEventType.REPLAY_START:
                replay_seen = set()
                continue
            if ev.type is WatchEventType.SYNCED:
                if replay_seen is not None:
                    with self._lock:
                        stale = [
                            (k, obj) for k, obj in self._cache.items()
                            if k not in replay_seen
                        ]
                    for _, obj in stale:
                        self._dispatch(WatchEvent(WatchEventType.DELETED, obj))
                replay_seen = None
                self._synced.set()
                continue
            if replay_seen is not None:
                meta = ev.obj.metadata
                key = (meta.namespace, meta.name)
                replay_seen.add(key)
                if ev.type is WatchEventType.ADDED and key in self._cache:
                    ev = WatchEvent(WatchEventType.MODIFIED, ev.obj)
            self._dispatch(ev)
        self._synced.set()

    def _dispatch(self, ev) -> None:
        meta = ev.obj.metadata
        key = (meta.namespace, meta.name)
        with self._lock:
            old = self._cache.get(key)
            if ev.type is WatchEventType.DELETED:
                self._cache_pop(key)
            else:
                self._cache_put(key, ev.obj)
        try:
            if ev.type is WatchEventType.ADDED:
                for h in self._on_add:
                    h(ev.obj)
            elif ev.type is WatchEventType.MODIFIED:
                for h in self._on_update:
                    h(old, ev.obj)
            else:
                for h in self._on_delete:
                    h(ev.obj)
        except Exception:  # a handler bug must not kill the watch thread
            log.exception("informer handler failed for %s %s", self.kind, key)

    def stop(self) -> None:
        self._stopped = True
        if self._watch is not None:
            self._watch.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
