"""Leader election: single-active-controller HA.

Reference parity: the operator's EndpointsLock leader election with
lease 15s / renew 5s / retry 3s (cmd/tf-operator/app/server.go:109-132).

Two interchangeable lock objects, one elector:

- ``FileLease`` — a lease file updated atomically (write-to-temp + rename),
  serialized by a kernel flock. One machine only: RunOrDie for operators
  sharing a filesystem.
- ``StoreLease`` — a ``Lease`` object in the Store, mutated only through
  versioned compare-and-swap updates (the apiserver-resourceVersion CAS
  that EndpointsLock itself rides on). Works identically over the
  in-process Store and ``RemoteStore`` (HTTP), so two operators on
  *different machines* pointing at one store get real cluster-wide
  RunOrDie. Expiry is judged on each candidate's local monotonic clock
  (the record's version must stand still for a full lease_duration before
  takeover — client-go's rule), so machine clock skew cannot cause a
  false takeover.

The holder renews on a background thread and calls ``on_stopped_leading``
if the lease is lost, at which point the daemon must exit.
"""

from __future__ import annotations

import json
import os
import socket
import tempfile
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Callable, Optional

LEASE_DURATION = 15.0
RENEW_PERIOD = 5.0
RETRY_PERIOD = 3.0


@dataclass
class LeaseRecord:
    holder: str
    acquired: float
    renewed: float
    lease_duration: float

    def expired(self, now: float) -> bool:
        return now - self.renewed > self.lease_duration


class FileLease:
    def __init__(
        self,
        path: str,
        identity: Optional[str] = None,
        lease_duration: float = LEASE_DURATION,
        renew_period: float = RENEW_PERIOD,
        retry_period: float = RETRY_PERIOD,
    ) -> None:
        self.path = path
        self.identity = identity or f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:6]}"
        self.lease_duration = lease_duration
        self.renew_period = renew_period
        self.retry_period = retry_period

    # -- record IO (atomic) ----------------------------------------------

    def _read(self) -> Optional[LeaseRecord]:
        try:
            with open(self.path) as f:
                data = json.load(f)
            return LeaseRecord(**data)
        except (OSError, ValueError, TypeError):
            return None

    def _write(self, rec: LeaseRecord) -> None:
        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".lease-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(rec.__dict__, f)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- acquire / renew --------------------------------------------------

    def _mutex(self):
        """Serialize the read-check-write critical section with a kernel
        flock — without it two candidates can both observe an expired
        lease, both write, and both believe they won (split brain). flock
        is released by the kernel when the holder dies, so there is no
        staleness heuristic to race on (an unlink-based stale-break had a
        TOCTOU where two candidates could both break the same stale lock)."""
        return _LockFile(self.path + ".lock")

    def try_acquire(self) -> bool:
        mutex = self._mutex()
        if not mutex.acquire():
            return False  # someone else is mid-acquire; retry later
        try:
            now = time.time()
            cur = self._read()
            if cur is not None and cur.holder != self.identity and not cur.expired(now):
                return False
            acquired = cur.acquired if (cur and cur.holder == self.identity) else now
            self._write(LeaseRecord(self.identity, acquired, now, self.lease_duration))
            return True
        finally:
            mutex.release()

    def renew(self, stop: Optional[threading.Event] = None) -> bool:
        """Renew the held lease. Mutex contention (a standby candidate
        holding the .lock file for its few-ms expiry check) is NOT lease
        loss — while the record still names us and the renew budget lasts,
        keep retrying; only a record naming someone else (or gone) means
        the lease was genuinely taken. The retry budget is the lease's own
        expiry (not renew_period): until the record we hold actually
        expires there is no reason to abdicate. A mutex held by a DEAD
        candidate is released by the kernel (flock); one held by a hung
        but alive thread is never broken — we simply time out at lease
        expiry and abdicate. ``stop`` aborts the retry loop early so
        daemon shutdown never waits out the full lease window."""
        while True:
            cur = self._read()
            if cur is None or cur.holder != self.identity:
                return False
            if self.try_acquire():
                return True
            if time.time() >= cur.renewed + cur.lease_duration:
                return False
            if stop is not None and stop.wait(0.05):
                return False
            if stop is None:
                time.sleep(0.05)

    def release(self) -> None:
        """Release the lease, re-checking ownership UNDER the mutex — a
        release racing a successor's acquire must not unlink the
        successor's valid lease."""
        mutex = self._mutex()
        if not mutex.acquire():
            return  # contended; our lease (if any) will simply expire
        try:
            cur = self._read()
            if cur is not None and cur.holder == self.identity:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass
        finally:
            mutex.release()


class _LockFile:
    """Advisory mutex via kernel flock on a persistent file. Crash-safe:
    the kernel drops the lock when the holding process dies, so no
    staleness-breaking (and none of its TOCTOU races) is needed."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._fd: Optional[int] = None

    def acquire(self) -> bool:
        import fcntl

        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        self._fd = fd
        return True

    def release(self) -> None:
        import fcntl

        if self._fd is not None:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
            finally:
                os.close(self._fd)
                self._fd = None


class StoreLease:
    """Store-backed lease with the same duck-type surface as FileLease
    (try_acquire / renew / release / identity / periods), so LeaderElector
    takes either.

    Mutual exclusion comes from the store's optimistic concurrency: every
    write is ``update(check_version=True)`` against the version this
    candidate last observed, so two candidates racing a takeover produce
    one winner and one ConflictError — no flock, no read-check-write
    window. Over RemoteStore the same CAS rides the HTTP PUT's
    resource_version check, giving cross-machine exclusion.
    """

    def __init__(
        self,
        store,
        name: str = "operator-leader",
        namespace: str = "system",
        identity: Optional[str] = None,
        lease_duration: float = LEASE_DURATION,
        renew_period: float = RENEW_PERIOD,
        retry_period: float = RETRY_PERIOD,
    ) -> None:
        self.store = store
        self.name = name
        self.namespace = namespace
        self.identity = identity or f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:6]}"
        self.lease_duration = lease_duration
        self.renew_period = renew_period
        self.retry_period = retry_period
        # Local observation clock (client-go semantics): a foreign record is
        # expired only once its resource_version has stood still for
        # lease_duration of OUR monotonic time. Wall stamps in the record
        # are observability only.
        self._observed_rv: Optional[int] = None
        self._observed_at: float = 0.0
        self._observed_duration: float = lease_duration

    def _observe(self, rec) -> None:
        rv = rec.metadata.resource_version
        self._observed_duration = rec.lease_duration or self.lease_duration
        if rv != self._observed_rv:
            self._observed_rv = rv
            self._observed_at = time.monotonic()

    def _record_expired(self) -> bool:
        # The RECORD's advertised duration (client-go rule): the holder
        # declares how long its hold is good for, observers time it locally.
        return time.monotonic() - self._observed_at >= self._observed_duration

    def try_acquire(self) -> bool:
        from tf_operator_tpu.api.types import KIND_LEASE, ObjectMeta
        from tf_operator_tpu.runtime.objects import Lease
        from tf_operator_tpu.runtime.store import (
            AlreadyExistsError,
            ConflictError,
            NotFoundError,
            TransientStoreError,
        )

        now = time.time()
        try:
            cur = self.store.get(KIND_LEASE, self.namespace, self.name)
        except NotFoundError:
            rec = Lease(
                metadata=ObjectMeta(name=self.name, namespace=self.namespace),
                holder=self.identity,
                acquired=now,
                renewed=now,
                lease_duration=self.lease_duration,
            )
            try:
                out = self.store.create(rec)
            except (AlreadyExistsError, TransientStoreError):
                return False  # lost the create race; retry later
            self._observe(out)
            return True
        except TransientStoreError:
            return False
        self._observe(cur)
        held_by_me = cur.holder == self.identity
        free = cur.holder == ""  # explicit release
        if not (held_by_me or free or self._record_expired()):
            return False
        cur.acquired = cur.acquired if held_by_me else now
        cur.holder = self.identity
        cur.renewed = now
        # Advertise OUR duration: rivals time expiry against the record's
        # declared duration, so a takeover must not leave a previous
        # holder's (possibly shorter) value in place — mixed-duration
        # candidates would otherwise disagree about when the hold lapses.
        cur.lease_duration = self.lease_duration
        try:
            out = self.store.update(cur, check_version=True)
        except (ConflictError, NotFoundError, TransientStoreError):
            return False  # a rival CAS'd first (or store blinked); retry later
        self._observe(out)
        return True

    def renew(self, stop: Optional[threading.Event] = None) -> bool:
        """Renew the held lease. Transient store unreachability is NOT lease
        loss — keep retrying until the hold we last confirmed would itself
        have expired in a rival's eyes (observed_at + lease_duration); only
        a record naming someone else means the lease was genuinely taken.
        ``stop`` aborts early so shutdown never waits out the window."""
        from tf_operator_tpu.api.types import KIND_LEASE
        from tf_operator_tpu.runtime.store import (
            ConflictError,
            NotFoundError,
            TransientStoreError,
        )

        deadline = self._observed_at + self.lease_duration
        while True:
            try:
                cur = self.store.get(KIND_LEASE, self.namespace, self.name)
            except NotFoundError:
                return False  # deleted out from under us: abdicate
            except TransientStoreError:
                cur = None
            if cur is not None:
                self._observe(cur)
                if cur.holder != self.identity:
                    return False
                cur.renewed = time.time()
                cur.lease_duration = self.lease_duration
                try:
                    out = self.store.update(cur, check_version=True)
                    self._observe(out)
                    return True
                except ConflictError:
                    continue  # re-read and re-judge ownership
                except NotFoundError:
                    return False
                except TransientStoreError:
                    pass
            if time.monotonic() >= deadline:
                return False
            if stop is not None:
                if stop.wait(0.2):
                    return False
            else:
                time.sleep(0.2)

    def release(self) -> None:
        """Hand off by CAS-clearing the holder (rivals treat "" as free, so
        a successor takes over without waiting out the lease). Conflict
        means a successor already took it — nothing to do."""
        from tf_operator_tpu.api.types import KIND_LEASE
        from tf_operator_tpu.runtime.store import (
            ConflictError,
            NotFoundError,
            TransientStoreError,
        )

        try:
            cur = self.store.get(KIND_LEASE, self.namespace, self.name)
            if cur.holder != self.identity:
                return
            cur.holder = ""
            cur.renewed = time.time()
            self.store.update(cur, check_version=True)
        except (ConflictError, NotFoundError, TransientStoreError):
            pass


class LeaderElector:
    """Blocks in run() until elected; renews in the background; invokes
    on_stopped_leading if the lease is lost (reference: RunOrDie)."""

    def __init__(
        self,
        lease: FileLease,
        on_started_leading: Callable[[], None],
        on_stopped_leading: Callable[[], None],
        stop_event: Optional[threading.Event] = None,
    ) -> None:
        self.lease = lease
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.stop_event = stop_event or threading.Event()
        self.is_leader = threading.Event()

    def run(self) -> None:
        # acquisition loop
        while not self.stop_event.is_set():
            if self.lease.try_acquire():
                break
            if self.stop_event.wait(self.lease.retry_period):
                return
        if self.stop_event.is_set():
            return
        self.is_leader.set()
        self.on_started_leading()
        # renewal loop
        while not self.stop_event.wait(self.lease.renew_period):
            if not self.lease.renew(stop=self.stop_event):
                if self.stop_event.is_set():
                    break  # shutdown requested mid-renew; release below
                self.is_leader.clear()
                self.on_stopped_leading()
                return
        self.lease.release()

    def run_in_background(self) -> threading.Thread:
        t = threading.Thread(target=self.run, name="leader-elector", daemon=True)
        t.start()
        return t
