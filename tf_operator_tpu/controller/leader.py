"""Leader election: single-active-controller HA via a file lease.

Reference parity: the operator's EndpointsLock leader election with
lease 15s / renew 5s / retry 3s (cmd/tf-operator/app/server.go:109-132).
On a bare host the lock object is a lease file updated atomically
(write-to-temp + rename); the holder renews on a background thread and
calls ``on_stopped_leading`` if the lease is lost, at which point the
daemon must exit (the reference's RunOrDie semantics).
"""

from __future__ import annotations

import json
import os
import socket
import tempfile
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Callable, Optional

LEASE_DURATION = 15.0
RENEW_PERIOD = 5.0
RETRY_PERIOD = 3.0


@dataclass
class LeaseRecord:
    holder: str
    acquired: float
    renewed: float
    lease_duration: float

    def expired(self, now: float) -> bool:
        return now - self.renewed > self.lease_duration


class FileLease:
    def __init__(
        self,
        path: str,
        identity: Optional[str] = None,
        lease_duration: float = LEASE_DURATION,
        renew_period: float = RENEW_PERIOD,
        retry_period: float = RETRY_PERIOD,
    ) -> None:
        self.path = path
        self.identity = identity or f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:6]}"
        self.lease_duration = lease_duration
        self.renew_period = renew_period
        self.retry_period = retry_period

    # -- record IO (atomic) ----------------------------------------------

    def _read(self) -> Optional[LeaseRecord]:
        try:
            with open(self.path) as f:
                data = json.load(f)
            return LeaseRecord(**data)
        except (OSError, ValueError, TypeError):
            return None

    def _write(self, rec: LeaseRecord) -> None:
        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".lease-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(rec.__dict__, f)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- acquire / renew --------------------------------------------------

    def _mutex(self):
        """Serialize the read-check-write critical section with a kernel
        flock — without it two candidates can both observe an expired
        lease, both write, and both believe they won (split brain). flock
        is released by the kernel when the holder dies, so there is no
        staleness heuristic to race on (an unlink-based stale-break had a
        TOCTOU where two candidates could both break the same stale lock)."""
        return _LockFile(self.path + ".lock")

    def try_acquire(self) -> bool:
        mutex = self._mutex()
        if not mutex.acquire():
            return False  # someone else is mid-acquire; retry later
        try:
            now = time.time()
            cur = self._read()
            if cur is not None and cur.holder != self.identity and not cur.expired(now):
                return False
            acquired = cur.acquired if (cur and cur.holder == self.identity) else now
            self._write(LeaseRecord(self.identity, acquired, now, self.lease_duration))
            return True
        finally:
            mutex.release()

    def renew(self, stop: Optional[threading.Event] = None) -> bool:
        """Renew the held lease. Mutex contention (a standby candidate
        holding the .lock file for its few-ms expiry check) is NOT lease
        loss — while the record still names us and the renew budget lasts,
        keep retrying; only a record naming someone else (or gone) means
        the lease was genuinely taken. The retry budget is the lease's own
        expiry (not renew_period): until the record we hold actually
        expires there is no reason to abdicate. A mutex held by a DEAD
        candidate is released by the kernel (flock); one held by a hung
        but alive thread is never broken — we simply time out at lease
        expiry and abdicate. ``stop`` aborts the retry loop early so
        daemon shutdown never waits out the full lease window."""
        while True:
            cur = self._read()
            if cur is None or cur.holder != self.identity:
                return False
            if self.try_acquire():
                return True
            if time.time() >= cur.renewed + cur.lease_duration:
                return False
            if stop is not None and stop.wait(0.05):
                return False
            if stop is None:
                time.sleep(0.05)

    def release(self) -> None:
        """Release the lease, re-checking ownership UNDER the mutex — a
        release racing a successor's acquire must not unlink the
        successor's valid lease."""
        mutex = self._mutex()
        if not mutex.acquire():
            return  # contended; our lease (if any) will simply expire
        try:
            cur = self._read()
            if cur is not None and cur.holder == self.identity:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass
        finally:
            mutex.release()


class _LockFile:
    """Advisory mutex via kernel flock on a persistent file. Crash-safe:
    the kernel drops the lock when the holding process dies, so no
    staleness-breaking (and none of its TOCTOU races) is needed."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._fd: Optional[int] = None

    def acquire(self) -> bool:
        import fcntl

        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        self._fd = fd
        return True

    def release(self) -> None:
        import fcntl

        if self._fd is not None:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
            finally:
                os.close(self._fd)
                self._fd = None


class LeaderElector:
    """Blocks in run() until elected; renews in the background; invokes
    on_stopped_leading if the lease is lost (reference: RunOrDie)."""

    def __init__(
        self,
        lease: FileLease,
        on_started_leading: Callable[[], None],
        on_stopped_leading: Callable[[], None],
        stop_event: Optional[threading.Event] = None,
    ) -> None:
        self.lease = lease
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.stop_event = stop_event or threading.Event()
        self.is_leader = threading.Event()

    def run(self) -> None:
        # acquisition loop
        while not self.stop_event.is_set():
            if self.lease.try_acquire():
                break
            if self.stop_event.wait(self.lease.retry_period):
                return
        if self.stop_event.is_set():
            return
        self.is_leader.set()
        self.on_started_leading()
        # renewal loop
        while not self.stop_event.wait(self.lease.renew_period):
            if not self.lease.renew(stop=self.stop_event):
                if self.stop_event.is_set():
                    break  # shutdown requested mid-renew; release below
                self.is_leader.clear()
                self.on_stopped_leading()
                return
        self.lease.release()

    def run_in_background(self) -> threading.Thread:
        t = threading.Thread(target=self.run, name="leader-elector", daemon=True)
        t.start()
        return t
