"""ControllerExpectations: bridging informer-cache staleness.

Reference parity: k8s.io/kubernetes/pkg/controller expectations as used by
the operator (controller.v2/controller.go:125-141; SURVEY.md calls this the
subtlest logic in the reference). After issuing N creates/deletes for a
(job, replica-type, object-kind) the controller records "I expect to observe
N creations/deletions"; informer callbacks decrement the counters; a sync
only trusts its (possibly stale) cache once expectations are satisfied,
which prevents duplicate creations while watch events are in flight.

Expectations expire after a TTL so a lost watch event cannot wedge a job
forever (k8s uses 5 minutes).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


EXPECTATION_TTL_SECONDS = 300.0


@dataclass
class _Expectation:
    adds: int = 0
    dels: int = 0
    timestamp: float = field(default_factory=time.monotonic)

    def fulfilled(self) -> bool:
        return self.adds <= 0 and self.dels <= 0

    def expired(self, ttl: float) -> bool:
        return time.monotonic() - self.timestamp > ttl


class ControllerExpectations:
    def __init__(self, ttl: float = EXPECTATION_TTL_SECONDS) -> None:
        self._lock = threading.Lock()
        self._store: dict = {}  # key -> _Expectation
        self._ttl = ttl

    def expect_creations(self, key: str, count: int) -> None:
        self._raise(key, adds=count, dels=0)

    def expect_deletions(self, key: str, count: int) -> None:
        self._raise(key, adds=0, dels=count)

    def _raise(self, key: str, adds: int, dels: int) -> None:
        """Accumulate into the live record: one sync may both create missing
        members and delete failed ones, and the two sets of expectations must
        coexist (replacing would let the cache be trusted while watch events
        for the other half are still in flight)."""
        with self._lock:
            exp = self._store.get(key)
            if exp is None or exp.expired(self._ttl):
                self._store[key] = _Expectation(adds=adds, dels=dels)
                return
            exp.adds = max(exp.adds, 0) + adds
            exp.dels = max(exp.dels, 0) + dels
            exp.timestamp = time.monotonic()

    def creation_observed(self, key: str) -> None:
        self._lower(key, adds=1)

    def deletion_observed(self, key: str) -> None:
        self._lower(key, dels=1)

    def _lower(self, key: str, adds: int = 0, dels: int = 0) -> None:
        with self._lock:
            exp = self._store.get(key)
            if exp is None:
                return
            exp.adds -= adds
            exp.dels -= dels

    def satisfied(self, key: str) -> bool:
        """True if the cache can be trusted for this key: expectations are
        fulfilled, expired (assume the watch event was lost), or were never
        set (fresh job — first sync sets them)."""
        with self._lock:
            exp = self._store.get(key)
            if exp is None:
                return True
            if exp.fulfilled() or exp.expired(self._ttl):
                return True
            return False

    def delete_expectations(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)

    # CreationObserved on a creation failure: the reference decrements
    # expectations when a create call fails so the controller retries
    # (pod creation bookkeeping in createNewPod, controller_pod.go:123-183).
    def creation_failed(self, key: str) -> None:
        self.creation_observed(key)

    def deletion_failed(self, key: str) -> None:
        self.deletion_observed(key)
