"""TPUJobController: the idempotent reconciler.

Reference parity: pkg/controller.v2 (SURVEY.md §3.3). Object events enqueue
job keys; workers pop keys and run ``sync_job``, which: trusts the informer
cache only once expectations are satisfied (controller.go:417-436), claims
child processes by label + owner uid (ClaimPods analogue,
controller_pod.go:222-258), creates missing gang members with rendezvous env
(createNewPod + TF_CONFIG analogue, controller_pod.go:123-206), applies
restart policy to failures — ExitCode consults the taxonomy and deletes
retryable-failed children so reconcile recreates them
(controller_pod.go:77-92) — and drives conditions-based status
(controller_status.go:39-120).

TPU-first deltas:

- **Gang restart.** One process dying severs the slice-wide SPMD program, so
  with ``run_policy.gang_restart`` (default) a retryable failure restarts the
  whole gang — every gang process is deleted and recreated with a fresh
  rendezvous — rather than the reference's per-pod restart (SURVEY.md §7
  hard part b). ``status.restart_count`` counts gang restarts against
  ``backoff_limit``.
- **Chief semantics.** The job succeeds when the coordinator process (or
  worker 0 when no coordinator replica exists) succeeds — the reference's
  chief-present vs worker-0 rule (controller_status.go:39-120).
- **Rendezvous, not cluster spec.** Each gang member gets coordinator
  address + process count + rank + mesh axes env instead of a host:port map
  (SURVEY.md §5 "communication backend").
"""

from __future__ import annotations

import json
import logging
import socket
import statistics
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from tf_operator_tpu.api import set_defaults, validate_job
from tf_operator_tpu.api.types import (
    KIND_ENDPOINT,
    KIND_HOST,
    KIND_PROCESS,
    KIND_SPAN,
    KIND_TELEMETRY,
    KIND_TPUJOB,
    LABEL_GROUP,
    LABEL_JOB_NAME,
    LABEL_REPLICA_INDEX,
    LABEL_REPLICA_TYPE,
    API_GROUP,
    CleanupPolicy,
    ConditionType,
    ObjectMeta,
    ReplicaType,
    RestartPolicy,
    TPUJob,
)
from tf_operator_tpu.api.helpers import accelerator_env, as_owner
from tf_operator_tpu.api.validation import ValidationError
from tf_operator_tpu.autopilot.controller import (
    DECISION_CADENCE,
    DECISION_DEPRIORITIZE,
    DECISION_MIGRATE,
    DECISION_WARMPOOL,
    AutopilotConfig,
    Decision,
    JobAutopilot,
    TickInputs,
)
from tf_operator_tpu.controller import events as ev
from tf_operator_tpu.controller.events import EventRecorder
from tf_operator_tpu.controller.expectations import ControllerExpectations
from tf_operator_tpu.controller.informer import Informer
from tf_operator_tpu.controller.metrics import ControllerMetrics
from tf_operator_tpu.controller.status import (
    clear_condition,
    has_condition,
    initialize_replica_statuses,
    is_finished,
    new_condition,
    set_condition,
    update_replica_status,
)
from tf_operator_tpu.controller.workqueue import RateLimitingQueue, ShardedQueueView
from tf_operator_tpu.obs.spans import (
    COMPONENT_SCHEDULER,
    SpanRecorder,
    first_step_span_name,
    job_trace,
    trace8,
)
from tf_operator_tpu.obs.blackbox import Blackbox, delete_forensics
from tf_operator_tpu.obs.telemetry import (
    CAUSE_CKPT_STALL,
    CAUSE_COMPILE_INIT,
    CAUSE_DATA_WAIT,
    CAUSE_HANG as GOODPUT_HANG,
    CAUSE_RESIZE as GOODPUT_RESIZE,
    CAUSE_RESTART as GOODPUT_RESTART,
    HostRisk,
    StragglerTracker,
    goodput_decomposition,
    job_telemetry,
    latest_window,
)
from tf_operator_tpu.obs.watchdog import GangWatchdog, HangVerdict
from tf_operator_tpu.rendezvous.env import (
    ENV_API_SERVER,
    ENV_CHECKPOINT_DIR,
    ENV_COMPILE_CACHE,
    ENV_COORDINATOR_ADDRESS,
    ENV_DCN_MESH_AXES,
    ENV_MESH_AXES,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
    ENV_RESIZE_EPOCH,
    ENV_RESTORE_PEERS,
    ENV_RESUME_STEP,
    ENV_TRACE_ID,
    ENV_WORKLOAD,
)
from tf_operator_tpu.runtime.objects import (
    Endpoint,
    EndpointAddress,
    HostPhase,
    Process,
    ProcessPhase,
    ProcessSpec,
    declare_lost,
)
from tf_operator_tpu.runtime.process_backend import ProcessControl
from tf_operator_tpu.runtime.scheduler import GangScheduler, SchedulingError
from tf_operator_tpu.runtime.store import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    Store,
)
from tf_operator_tpu.sched import fleet as fleetsched
from tf_operator_tpu.train.checkpoint import latest_checkpoint_step
from tf_operator_tpu.utils.exit_codes import ExitClass, classify_exit_code, is_retryable

log = logging.getLogger(__name__)

# Annotation where the controller persists the job's allocated rendezvous
# port (so reconciles are stable across controller restarts).
ANNOTATION_PORT = "tpujob.dev/rendezvous-port"
# Fleet-scheduler preemption request: stamped on a victim job (value = the
# preemptor's key); the victim's own sync drains it through the graceful
# preemption lifecycle (cause ``preemption``, warm-resumed, backoff-exempt)
# and clears the annotation store-side.
ANNOTATION_PREEMPT = "tpujob.dev/preempt"
# Grow-beyond-spec reclaim request (r19): stamped on an elastic job that
# holds over-spec chips when quota pressure needs them back (value = the
# requester's key). The victim's own sync shrinks it back to spec through
# the ordinary resize protocol — no drain, no restart, no backoff charge
# — and the loaned chips return to the queue once the over-spec members
# are observably gone.
ANNOTATION_RECLAIM = "tpujob.dev/reclaim-overspec"
# Straggler flag: stamped on a gang member Process whose host the detector
# flagged (value = the host name); cleared when the host's step times
# return under the bar for the hysteresis window.
ANNOTATION_SLOW_HOST = "tpujob.dev/slow-host"

# Gang-restart causes (status.last_restart_cause + the by-cause metric).
# Preemption restarts are graceful — checkpoint-resumed and NOT counted
# against backoff_limit; the other two consume restart_count.
CAUSE_PREEMPTION = "preemption"
CAUSE_FAILURE = "retryable-failure"
CAUSE_NODE_LOST = "node-lost"
# OOM kills restart only under ALWAYS/ON_FAILURE policies (the taxonomy
# classifies OOM permanent for EXIT_CODE: retrying on identical hardware
# just OOMs again) — but when they do restart, the cause must say so:
# an OOM loop and a preemption storm need different operator responses.
CAUSE_OOM = "oom"
# Elastic resizes (r12, run_policy.elastic): NOT restarts. A resize kills
# no survivor, bumps neither restart_count nor preemption_count, and is
# never charged to backoff_limit — the values exist so last_restart_cause
# answers "what happened to this gang last" uniformly.
CAUSE_RESIZE_SHRINK = "resize_shrink"
CAUSE_RESIZE_GROW = "resize_grow"
# Gang-progress hang (r15, obs/watchdog.py): no rank advanced a step for
# hang_timeout_seconds while heartbeats stayed live. Retryable under
# ALWAYS/ON_FAILURE/EXIT_CODE and charged to restart_count/backoff_limit
# like a crash — but its downtime is the HANG span's width (backdated to
# when progress stopped), so _restart_gang opens NO restart span for it:
# one window, one cause, never double-counted (docs/design.md §6.3).
CAUSE_HANG = "hang"
# Pre-emptive autopilot migrate (r16, autopilot/): the autopilot shrank
# the gang away from a risk-flagged host BEFORE anything died. Same
# mechanics and accounting as any other shrink (resize_count, resize
# span, never charged to backoff) — the cause string in resize_history
# records that the straggler signal, not a failure, triggered it.
CAUSE_AUTOPILOT_MIGRATE = "autopilot-straggler"
# Grow-beyond-spec reclaim (r19): the resize_history cause for the shrink
# that returns loaned over-spec chips under quota pressure. Same
# accounting as any other resize (resize span, never backoff).
CAUSE_OVERSPEC_RECLAIM = "overspec-reclaim"
# Bound on status.resize_history (r19 satellite): older entries fold into
# status.resize_history_folded so a long elastic soak cannot grow the job
# status without limit. Display total = folded + len(history).
RESIZE_HISTORY_KEEP = 32
# Host annotation the autopilot's warm-pool actuator writes (value = the
# slot target as a decimal string); each HostAgent's heartbeat loop
# polls its own Host object and resizes its local pool to match.
ANNOTATION_WARMPOOL_TARGET = "tpujob.dev/warmpool-target"
# How long one autopilot deprioritization verdict keeps a host soft-
# avoided in place_gang (sched/fleet.py deprioritize_host). TTL-bounded:
# after a migrate the host runs no ranks for this job, so no telemetry
# exists to clear it the way the straggler tracker clears slow hosts.
AUTOPILOT_DEPRIORITIZE_TTL_S = 600.0
# How long the reconciler holds a declared-HUNG gang alive waiting for
# every rank's stack dump to be acked before shooting it anyway — the
# forensics window must never stall recovery indefinitely (a wedged
# harness cannot run its own signal handler's file flush forever).
FORENSICS_GRACE_SECONDS = 5.0
# Mesh axes an elastic resize may re-carve. dp/fsdp shard DATA and
# replicated/re-shardable optimizer+param state; tp/pp/ep shard the model
# PROGRAM itself — losing a member there removes layers/experts/operand
# slices no survivor holds, so those meshes always take the full-restart
# path regardless of run_policy.elastic (docs/design.md §4.11).
ELASTIC_AXES = ("dp", "fsdp")


def _elastic_mesh_ok(job: TPUJob) -> bool:
    """True when the job's mesh is elastically re-carvable: every ICI axis
    with extent > 1 is dp/fsdp, and every DCN axis with extent > 1 is dp
    (a cross-slice fsdp axis would strip param shards with a lost slice)."""
    for ax, size in (job.spec.topology.mesh_axes or {}).items():
        if ax not in ELASTIC_AXES and int(size or 1) > 1:
            return False
    for ax, size in (job.spec.topology.dcn_mesh_axes or {}).items():
        if ax != "dp" and int(size or 1) > 1:
            return False
    return True


def _default_host_resolver(process: Process) -> str:
    del process
    return "127.0.0.1"


def _default_port_allocator() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TPUJobController:
    """The reconciling controller (reference: TFJobController,
    controller.v2/controller.go:82-153)."""

    def __init__(
        self,
        store: Store,
        process_control: ProcessControl,
        recorder: Optional[EventRecorder] = None,
        resync_period: float = 15.0,
        host_resolver: Callable[[Process], str] = _default_host_resolver,
        port_allocator: Callable[[], int] = _default_port_allocator,
        controller_config=None,
        api_url: Optional[str] = None,
    ) -> None:
        self.store = store
        self.process_control = process_control
        self.recorder = recorder or EventRecorder(store)
        self.resync_period = resync_period
        self.host_resolver = host_resolver
        self.port_allocator = port_allocator
        # Operator API base URL injected into child env (ENV_API_SERVER) so
        # workloads can report results (eval scores) back through the API.
        # Mutable: the daemon sets it after the dashboard binds its port.
        self.api_url = api_url
        # Admin accelerator/runtime injection (ControllerConfig,
        # api/helpers.py; reference server.go:138-156 + helpers.go:50-104).
        self.controller_config = controller_config
        # Fleet compile-cache service (cachesvc/, r11): when the daemon
        # hosts one it sets the URL here; every created gang member gets
        # it stamped as ENV_COMPILE_CACHE, turning compile_cache.enable()
        # into a two-tier read-through. ``aot`` (cachesvc/aot.py) is the
        # admission-time compiler the sync path kicks on admit/park so
        # compilation overlaps the scheduling wait. Both optional — unset
        # reproduces the r10 local-only behavior exactly.
        self.compile_cache_url: Optional[str] = None
        self.aot = None

        self.queue = RateLimitingQueue()
        self.expectations = ControllerExpectations()
        self.metrics = ControllerMetrics(store=store, queue=self.queue)
        # Gang-atomic placement onto registered Hosts (runtime/scheduler.py);
        # with no Hosts the scheduler reports unmanaged and the controller
        # launches through process_control exactly as before. The lock
        # serializes place+create across workers so two jobs cannot be
        # promised the same free chips.
        self.scheduler = GangScheduler(store)
        self._sched_lock = threading.Lock()
        # Fleet scheduler (sched/): multi-tenant quota admission, priority
        # preemption, and head-of-line reservations in FRONT of gang
        # placement. It has no lock of its own — every call happens under
        # _sched_lock, the same hold that serializes placement+create, so
        # "usage never exceeds quota" is an invariant, not a race window.
        self.fleet = fleetsched.FleetScheduler(store, self.scheduler)
        # Lifecycle tracing (obs/): the reconciler records the controller-
        # and scheduler-side spans of every job's timeline and derives the
        # TTFS / time-to-scheduled / restart-downtime histograms from the
        # same boundaries. All best-effort — a failed span write never
        # fails a sync. Keyed by trace id (job uid); the workqueue's
        # single-flight-per-key guarantee means no two workers touch the
        # same job's entries concurrently.
        self.tracer = SpanRecorder(store)
        self._sched_observed: set = set()  # uids with a scheduled span
        self._ttfs_observed: set = set()  # uids whose TTFS hit the histogram
        self._ckpt_observed: set = set()  # uids whose ckpt spans hit histograms
        self._serve_observed: set = set()  # uids whose request spans were folded
        self._open_restart: Dict[str, Dict[str, Any]] = {}  # uid -> span info
        self._open_schedwait: Dict[str, Dict[str, Any]] = {}
        self._open_queued: Dict[str, Dict[str, Any]] = {}  # uid -> span info
        self._open_resize: Dict[str, Dict[str, Any]] = {}  # uid -> span info
        # Preempt/reclaim requests deferred because they landed mid-resize
        # (r19): uids whose deferral was already evented, so the wait
        # doesn't spam one warning per sync. Cleared when the drain runs.
        self._deferred_preempts: set = set()
        self._goodput_observed: set = set()  # uids whose goodput was folded
        # Straggler detection (obs/telemetry.py): per-job flap-damped
        # trackers over the live telemetry stream, plus the fleet-wide
        # slow-host set place_gang deprioritizes for NEW gangs. Same
        # race-freedom argument as the span maps: single-flight-per-key.
        self._stragglers: Dict[str, StragglerTracker] = {}  # uid -> tracker
        self._straggler_seen_seq: Dict[str, int] = {}  # uid -> last window seq
        self._slow_hosts: Dict[str, float] = {}  # host -> flagged-at time
        # Hang plane (r15): per-job gang-progress watchdogs over the same
        # telemetry stream, the bounded flight recorders frozen into
        # postmortem bundles, and the open hang span per uid (closed when
        # the recovered gang is RUNNING again — the hang-downtime source).
        self._watchdogs: Dict[str, GangWatchdog] = {}  # uid -> watchdog
        self._blackboxes: Dict[str, Blackbox] = {}  # uid -> flight recorder
        self._open_hang: Dict[str, Dict[str, Any]] = {}  # uid -> span info
        # Goodput autopilot (r16, autopilot/): per-job decision engines
        # driven from the gang-running sync path, reading the SAME
        # surfaces the dashboards read (telemetry windows, save-stall
        # spans, the cause ledger, StragglerTracker.host_risk()) and
        # acting through actuators that already exist. Keyed by uid like
        # the trackers — decision state dies with the incarnation.
        self._autopilots: Dict[str, JobAutopilot] = {}  # uid -> engine
        # Fleet ledger (r18, obs/ledger.py): the durable cross-job record
        # of outcomes. Attached by the daemon via attach_ledger(); every
        # terminal job folds one compact JobRecord, and the ledger's
        # per-cohort MTBF history feeds fresh jobs' first cadence
        # decisions (use_fleet_priors) plus per-host reputation into the
        # scheduler's deprioritized set. None = no cross-job memory.
        self.ledger = None
        # uid -> (prior_mtbf_s, prior_failures, prior_jobs): the prior is
        # computed ONCE at the job's first autopilot tick and pinned, so
        # records folded mid-run never shift a live job's estimate.
        self._prior_cache: Dict[str, Tuple[float, int, int]] = {}
        self._host_risk: Dict[str, Dict[str, HostRisk]] = {}  # uid -> host -> risk
        self._last_step_time: Dict[str, float] = {}  # uid -> last window median
        self._ap_ttfs_seen: set = set()  # uids whose TTFS fed the cold/warm split
        self._ttfs_cold = 0  # fleet-level cold first-step marks (warmpool input)
        self._ttfs_warm = 0
        self._warmpool_target = 1  # last fleet warm-pool target annotated
        # Workqueue shards (run(shards=N) expands): keys hash by NAMESPACE,
        # so one tenant's burst cannot head-of-line-block another tenant's
        # keys behind a single queue mutex, while all of one job's events
        # stay on one shard (the single-flight-per-key guarantee holds).
        self._shards: List[RateLimitingQueue] = [self.queue]

        self.job_informer = Informer(store, KIND_TPUJOB)
        self.process_informer = Informer(store, KIND_PROCESS)

        self.job_informer.add_event_handler(
            on_add=self._on_job_add,
            on_update=self._on_job_update,
            on_delete=self._on_job_delete,
        )
        self.process_informer.add_event_handler(
            on_add=self._on_process_add,
            on_update=self._on_process_update,
            on_delete=self._on_process_delete,
        )

        self._stop = threading.Event()
        self._workers: List[threading.Thread] = []
        self._resync_thread: Optional[threading.Thread] = None

    # ---- informer callbacks (controller_pod.go:285-412) -----------------

    def _on_job_add(self, job) -> None:
        self._enqueue(job.key())

    def _on_job_update(self, old, new) -> None:
        del old
        self._enqueue(new.key())

    def _on_job_delete(self, job) -> None:
        self._enqueue(job.key())

    def _job_key_for_process(self, process: Process) -> Optional[str]:
        name = process.spec.job_name or process.metadata.labels.get(LABEL_JOB_NAME)
        if not name:
            return None
        return f"{process.metadata.namespace}/{name}"

    def _on_process_add(self, process: Process) -> None:
        key = self._job_key_for_process(process)
        if key:
            self.expectations.creation_observed(self._exp_key(key))
            self._enqueue(key)

    def _on_process_update(self, old, new) -> None:
        del old
        key = self._job_key_for_process(new)
        if key:
            self._enqueue(key)

    def _on_process_delete(self, process: Process) -> None:
        key = self._job_key_for_process(process)
        if key:
            self.expectations.deletion_observed(self._exp_key(key))
            self._enqueue(key)

    @staticmethod
    def _exp_key(job_key: str) -> str:
        return f"{job_key}/processes"

    # ---- workqueue sharding ---------------------------------------------

    def _route(self, key: str) -> RateLimitingQueue:
        """Shard for a job key: hash by namespace (crc32, not the salted
        builtin hash) so a tenant's keys always land together."""
        shards = self._shards
        if len(shards) == 1:
            return shards[0]
        ns = key.split("/", 1)[0]
        return shards[zlib.crc32(ns.encode("utf-8")) % len(shards)]

    def _enqueue(self, key: str) -> None:
        self._route(key).add(key)

    # ---- lifecycle ------------------------------------------------------

    def run(
        self,
        workers: int = 1,
        wait_synced_timeout: float = 10.0,
        shards: int = 1,
    ) -> None:
        """Start informers and worker threads (controller.go:245-277).

        ``shards`` > 1 partitions the workqueue by namespace hash: each
        worker serves shard ``i % shards``, so multi-tenant bursts stop
        contending on one queue mutex. Shard 0 stays ``self.queue`` —
        single-shard (the default) is byte-for-byte the old behavior."""
        shards = max(1, min(shards, max(1, workers)))
        if shards > 1:
            self._shards = [self.queue] + [
                RateLimitingQueue() for _ in range(shards - 1)
            ]
            # The workqueue-depth gauge keeps meaning "keys waiting
            # anywhere" after the split.
            self.metrics.queue = ShardedQueueView(self._shards)
        self.job_informer.run()
        self.process_informer.run()
        deadline = time.time() + wait_synced_timeout
        while not (self.job_informer.has_synced() and self.process_informer.has_synced()):
            if time.time() > deadline:
                raise TimeoutError("informer caches failed to sync")
            time.sleep(0.01)
        for i in range(workers):
            t = threading.Thread(
                target=self._worker_loop, args=(i,),
                name=f"sync-worker-{i}", daemon=True,
            )
            t.start()
            self._workers.append(t)
        self._resync_thread = threading.Thread(
            target=self._resync_loop, name="resync", daemon=True
        )
        self._resync_thread.start()

    def stop(self) -> None:
        self._stop.set()
        for q in self._shards:
            q.shutdown()
        self.job_informer.stop()
        self.process_informer.stop()
        for t in self._workers:
            t.join(timeout=5)
        self._workers.clear()

    def record_recovery(self, info) -> int:
        """Post-restart re-adoption pass (call once, after :meth:`run`,
        when the store was recovered from durable state — cli/operator.py
        does when ``--data-dir`` found existing WAL/snapshot data).

        The in-memory expectations died with the previous incarnation, so
        the first syncs will trust the informer cache — which, because the
        informers replayed the RECOVERED store, already holds every child
        that survived the crash. This pass makes the re-adoption explicit
        and observable: for every live (non-terminal) job it claims the
        recovered Process children (stamping owner_uid on any orphan whose
        job-name label matches — the ClaimPods half the reference leans on
        after a controller restart), records a ``controller-restart`` span
        into the job's trace plus a ControllerRestarted event, bumps
        ``tpujob_controller_restarts_total``, and enqueues the job so the
        next sync reconciles recovered state against the data plane
        (agents re-register and resync orphans on their own). Returns the
        number of live jobs recovered. ``info`` is a persist.RecoveryInfo;
        its resource_version uniquely names this restart's spans."""
        self.metrics.inc("tpujob_controller_restarts_total")
        t0 = time.time()
        n = 0
        for job in self.store.list(KIND_TPUJOB):
            if is_finished(job.status):
                continue
            n += 1
            try:
                claimed = self._claim_processes(job)
                adopted = len(claimed)
            except Exception:
                log.exception("recovery claim failed for %s", job.key())
                claimed, adopted = [], -1
            # Controller-supervised (unbound) children: the dead
            # incarnation's OS children are orphans THIS backend does not
            # supervise — no monitor thread will ever report their exit,
            # so the job would sit Running forever. Declare them lost
            # (the exact mirror of the agent-restart rule,
            # runtime/agent.py) and let the fenced gang restart recover
            # warm. Host-bound children are untouched: their agents kept
            # supervising right through the operator outage.
            tracks = getattr(self.process_control, "tracks", None)
            if tracks is not None:
                for p in claimed:
                    if p.spec.node_name or p.is_finished():
                        continue
                    if tracks(p.metadata.namespace, p.metadata.name):
                        continue
                    if declare_lost(
                        self.store, p,
                        "operator restarted; controller-supervised "
                        "process no longer tracked",
                    ) is not None:
                        self.metrics.inc("tpujob_node_lost_total")
                        log.warning(
                            "recovery: declared %s lost (untracked after "
                            "operator restart)", p.key(),
                        )
            self._rearm_open_spans(job)
            self.tracer.record(
                job.metadata.namespace, job.metadata.name, job.metadata.uid,
                "controller-restart", t0, time.time(),
                attrs={
                    "recovered_rv": str(info.resource_version),
                    "adopted": str(adopted),
                    "track": "controller",
                },
                name=f"{job.metadata.name}-{trace8(job.metadata.uid)}"
                     f"-ctl-restart-{info.resource_version}",
            )
            self.recorder.normal(
                job, ev.REASON_CONTROLLER_RESTARTED,
                f"controller restarted; recovered store at rv "
                f"{info.resource_version}, re-adopted {adopted} children",
            )
            self._enqueue(job.key())
        return n

    def _rearm_open_spans(self, job: TPUJob) -> None:
        """Re-register the job's still-open restart / scheduling-wait
        spans (read back from the durable trace) in the recovered
        controller's in-memory maps, so the span a DEAD incarnation
        opened is closed by THIS one when the gang returns to RUNNING —
        keeping MTTR trace-accurate across operator restarts instead of
        leaving the span dangling until job completion."""
        uid = job.metadata.uid
        try:
            spans = job_trace(
                self.store, job.metadata.namespace, job.metadata.name
            )
        except Exception:  # noqa: BLE001 — telemetry read is best-effort
            return
        for s in spans:
            if s.end_time or s.trace_id != uid:
                continue
            if s.op == "restart" and uid not in self._open_restart:
                self._open_restart[uid] = {
                    "ns": s.metadata.namespace, "name": s.metadata.name,
                    "start": s.start_time,
                    "cause": s.attrs.get("cause", CAUSE_FAILURE),
                }
            elif s.op == "resize" and uid not in self._open_resize:
                self._open_resize[uid] = {
                    "ns": s.metadata.namespace, "name": s.metadata.name,
                    "start": s.start_time,
                    "direction": s.attrs.get("direction", "shrink"),
                    "epoch": int(s.attrs.get("epoch", "0") or 0),
                }
            elif s.op == "hang" and uid not in self._open_hang:
                self._open_hang[uid] = {
                    "ns": s.metadata.namespace, "name": s.metadata.name,
                    "start": s.start_time,
                }
            elif s.op == "scheduling-wait" and uid not in self._open_schedwait:
                self._open_schedwait[uid] = {
                    "ns": s.metadata.namespace, "name": s.metadata.name,
                }
            elif s.op == "queued" and uid not in self._open_queued:
                self._open_queued[uid] = {
                    "ns": s.metadata.namespace, "name": s.metadata.name,
                    "start": s.start_time,
                    "queue": s.attrs.get("queue", "default"),
                    "priority": s.attrs.get("priority", "none"),
                }

    def _resync_loop(self) -> None:
        """Periodic resync (ReconcilerSyncLoopPeriod, controller.go:63-78).

        Enqueues only jobs that still have work: non-terminal ones, plus
        finished ones whose replica counters haven't drained to zero yet
        (their children are still exiting/being GC'd and the CleanUp →
        Done/Failed phase transition depends on observing that). A done,
        drained job is pure noise to re-sync — at 500 live jobs the old
        enqueue-everything pass made every resync O(population) syncs,
        each a no-op costing child lists and status diffs."""
        while not self._stop.wait(self.resync_period):
            self.resync_once()

    def resync_once(self) -> int:
        """One resync pass; returns the number of jobs enqueued."""
        n = 0
        for job in self.job_informer.list():
            if is_finished(job.status) and not any(
                rs.active for rs in job.status.replica_statuses.values()
            ):
                continue
            self._enqueue(job.key())
            n += 1
        return n

    def _worker_loop(self, i: int = 0) -> None:
        queue = self._shards[i % len(self._shards)]
        while self.process_next_item(queue):
            pass

    def process_next_item(self, queue: Optional[RateLimitingQueue] = None) -> bool:
        """One workqueue pop + sync (controller.go:289-321)."""
        queue = self.queue if queue is None else queue
        key = queue.get()
        if key is None:
            return False
        t0 = time.perf_counter()
        error = False
        try:
            self.sync_job(key)
        except Exception:
            error = True
            log.exception("sync failed for %s; requeueing", key)
            queue.add_rate_limited(key)
        else:
            queue.forget(key)
        finally:
            queue.done(key)
            self.metrics.observe_sync(time.perf_counter() - t0, error)
        return True

    # ---- the sync -------------------------------------------------------

    def sync_job(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        cached = self.job_informer.get(namespace, name)
        if cached is None:
            # Job deleted: cascade-delete children (the reference leans on
            # k8s GC via owner refs; our store has no GC, so the controller
            # is the GC). The job's trace goes with it — spans survive job
            # COMPLETION (they are the timeline) but not deletion.
            self._delete_children(namespace, name, cleanup=CleanupPolicy.ALL)
            self._delete_spans(namespace, name)
            self._delete_telemetry(namespace, name)
            # Forensics (postmortem bundle + stack dumps) are GC'd with the
            # job exactly like spans/telemetry; `tpujob debug` on a GC'd
            # job then 404s loudly instead of returning an empty tar.
            delete_forensics(self.store, namespace, name)
            # Cardinality: the per-job goodput series is folded into the
            # ledger's histogram by now — drop it so 100 submit->GC
            # cycles leave /metrics bounded. (The ledger record itself
            # SURVIVES this GC; that is its whole point.)
            self.metrics.clear_gauge(
                "tpujob_goodput_ratio",
                labels={"namespace": namespace, "job": name},
            )
            self.expectations.delete_expectations(self._exp_key(key))
            self._release_job(key)
            return

        job = cached.deepcopy()
        set_defaults(job)
        try:
            validate_job(job)
        except ValidationError as exc:
            self._fail_job(job, reason="TPUJobValidationFailed", message=str(exc))
            self._write_status(job)
            return

        if is_finished(job.status):
            # Safety-net fold (r18): normally _finish folded the record;
            # this covers a previous incarnation that wrote the terminal
            # status and died before folding. Dedupe is durable (uid in
            # the ledger), so the common case is one cheap has() check.
            self._ledger_fold(
                job, job.status.completion_time or time.time()
            )
            self._delete_children(namespace, name, job.spec.run_policy.cleanup_policy)
            # Keep the replica counters live through the CleanUp window:
            # with them frozen at the terminal transition, active>0 would
            # report phase CleanUp forever even after every child exited or
            # was GC'd (the v1alpha1 phase surface depends on the counters
            # draining to reach Done/Failed).
            self._refresh_terminal_counters(job)
            return

        if not self.expectations.satisfied(self._exp_key(key)):
            return  # watch events still in flight; they will re-enqueue us

        processes = self._claim_processes(job)
        processes = self._mark_node_lost(job, processes)
        self._reconcile(job, processes)

    # ---- child accounting ----------------------------------------------

    def _labels_for(self, job: TPUJob) -> Dict[str, str]:
        return {LABEL_GROUP: API_GROUP, LABEL_JOB_NAME: job.metadata.name}

    def _claim_processes(self, job: TPUJob) -> List[Process]:
        """List + adopt children (ClaimPods analogue, controller_pod.go:222-258):
        orphans matching our labels are adopted by stamping owner_uid; children
        owned by a DEAD incarnation are garbage-collected here. The reference
        leans on the k8s GC (ownerReferences to a deleted uid ⇒ collected);
        our store has no GC, and without this a delete → same-name recreate
        race wedges the new job: the old job's deletion sync can find the NEW
        job already in the informer and skip cascade-GC, leaving an
        old-incarnation child squatting on a deterministic process name so
        every recreate hits AlreadyExists forever."""
        claimed = []
        for p in self.process_informer.list(
            namespace=job.metadata.namespace, label_selector=self._labels_for(job)
        ):
            if p.metadata.owner_uid is None:
                try:
                    fresh = self.store.get(KIND_PROCESS, p.metadata.namespace, p.metadata.name)
                    if fresh.metadata.owner_uid is None:
                        fresh.metadata.owner_uid = job.metadata.uid
                        fresh.metadata.owner_kind = KIND_TPUJOB
                        fresh.metadata.owner_name = job.metadata.name
                        p = self.store.update(fresh, check_version=True)
                    else:
                        p = fresh
                except (NotFoundError, ConflictError):
                    continue
            if p.metadata.owner_uid == job.metadata.uid:
                claimed.append(p)
            elif (
                p.metadata.owner_kind == KIND_TPUJOB
                and p.metadata.owner_name == job.metadata.name
            ):
                # Same job name, different owner uid: names are unique per
                # namespace, so the owning incarnation is gone. Collect it.
                try:
                    self._delete_child(p)
                except NotFoundError:
                    pass
        return claimed

    def _job_heartbeat_ttl(self, job: TPUJob) -> float:
        """Node-lost window for this job: run_policy override, else the
        controller-wide scheduler default."""
        ttl = job.spec.run_policy.heartbeat_ttl_seconds
        return self.scheduler.heartbeat_ttl if ttl is None else ttl

    def _mark_node_lost(self, job: TPUJob, processes: List[Process]) -> List[Process]:
        """Failure detection for dead hosts: a process bound to a host whose
        agent stopped heartbeating is marked Failed (exit 137, NodeLost) so
        the normal retry machinery — gang restart for retryable exits —
        takes over. The kubelet-gone analogue of the reference's
        pod-status-driven detection (SURVEY.md §5 failure detection). A
        binding to a host whose Host OBJECT is gone entirely (admin
        drain/delete) counts as lost too, after the same TTL grace —
        otherwise such processes would sit Pending/Running forever with no
        agent to drive them and no heartbeat to miss."""
        ttl = self._job_heartbeat_ttl(job)
        lost = {h.metadata.name for h in self.scheduler.lost_hosts(ttl=ttl)}
        known = {h.metadata.name for h in self.store.list(KIND_HOST)}
        now = time.time()
        out: List[Process] = []
        for p in processes:
            node = p.spec.node_name
            node_lost = node in lost or (
                node
                and node not in known
                and now - p.metadata.creation_timestamp > ttl
            )
            if node_lost and not p.is_finished():
                updated = declare_lost(
                    self.store, p, f"host {p.spec.node_name} lost"
                )
                if updated is not None:
                    p = updated
                    self.metrics.inc("tpujob_node_lost_total")
                    self.recorder.warning(
                        job, ev.REASON_NODE_LOST,
                        f"{p.metadata.name}: host {p.spec.node_name} "
                        "stopped heartbeating",
                    )
            out.append(p)
        return out

    def _delete_children(self, namespace: str, job_name: str, cleanup: CleanupPolicy) -> None:
        if cleanup is CleanupPolicy.NONE:
            return
        selector = {LABEL_JOB_NAME: job_name}
        for p in self.store.list(KIND_PROCESS, namespace=namespace, label_selector=selector):
            if cleanup is CleanupPolicy.RUNNING and p.is_finished():
                continue  # keep finished processes for debugging
            self._delete_child(p)
        for e in self.store.list(KIND_ENDPOINT, namespace=namespace, label_selector=selector):
            try:
                self.store.delete(KIND_ENDPOINT, namespace, e.metadata.name)
            except NotFoundError:
                pass

    def _delete_spans(self, namespace: str, job_name: str) -> None:
        """GC a deleted job's trace spans (indexed list by job label)."""
        try:
            spans = self.store.list(
                KIND_SPAN, namespace=namespace,
                label_selector={LABEL_JOB_NAME: job_name},
            )
        except Exception:  # noqa: BLE001 — GC of telemetry is best-effort
            return
        for s in spans:
            try:
                self.store.delete(KIND_SPAN, namespace, s.metadata.name)
            except NotFoundError:
                pass

    def _delete_telemetry(self, namespace: str, job_name: str) -> None:
        """GC a deleted job's telemetry ring alongside its spans — the
        stream is live-observability, not an archive; it goes with the
        job (same rule as spans: survives completion, not deletion)."""
        try:
            batches = self.store.list(
                KIND_TELEMETRY, namespace=namespace,
                label_selector={LABEL_JOB_NAME: job_name},
            )
        except Exception:  # noqa: BLE001 — GC of telemetry is best-effort
            return
        for b in batches:
            try:
                self.store.delete(KIND_TELEMETRY, namespace, b.metadata.name)
            except NotFoundError:
                pass

    def _refresh_terminal_counters(self, job: TPUJob) -> None:
        """Recompute replica counters for a FINISHED job from the children
        still in the store (no adoption — a terminal job claims nothing),
        so the active counts drain as children exit or are GC'd and the
        derived phase resolves CleanUp → Done/Failed. Writes only on
        change to keep the resync loop from churning resource versions."""
        before = {
            rt: (rs.active, rs.succeeded, rs.failed)
            for rt, rs in job.status.replica_statuses.items()
        }
        procs = self.store.list(
            KIND_PROCESS,
            namespace=job.metadata.namespace,
            label_selector=self._labels_for(job),
        )
        initialize_replica_statuses(job.status, job.spec.replica_specs.keys())
        for p in procs:
            if p.metadata.owner_uid != job.metadata.uid:
                continue
            try:
                rtype = ReplicaType(p.spec.replica_type)
            except ValueError:
                continue
            update_replica_status(job.status, rtype, p)
        after = {
            rt: (rs.active, rs.succeeded, rs.failed)
            for rt, rs in job.status.replica_statuses.items()
        }
        if after != before:
            self._write_status(job)

    # ---- gang layout ----------------------------------------------------

    @staticmethod
    def _gang_roles(job: TPUJob) -> List[Tuple[ReplicaType, int]]:
        """Orderered gang membership: coordinator first, then workers.
        Evaluators are not gang members — like the evaluator's exclusion
        from the reference's cluster spec (controller_tensorflow.go:91-95).
        Grow-beyond-spec (r19): status.overspec_workers extra worker
        indices append to the tail, so the expanded gang is the real
        membership everywhere (placement, world size, hang/straggler
        checks) until a quota reclaim shrinks it back."""
        gang: List[Tuple[ReplicaType, int]] = []
        if ReplicaType.COORDINATOR in job.spec.replica_specs:
            gang.append((ReplicaType.COORDINATOR, 0))
        workers = job.spec.replica_specs.get(ReplicaType.WORKER)
        if workers is not None:
            count = (workers.replicas or 1) + max(job.status.overspec_workers, 0)
            gang.extend((ReplicaType.WORKER, i) for i in range(count))
        return gang

    @staticmethod
    def _chief_role(job: TPUJob) -> Tuple[ReplicaType, int]:
        """Chief-present vs worker-0 semantics (controller_status.go:39-120)."""
        if ReplicaType.COORDINATOR in job.spec.replica_specs:
            return (ReplicaType.COORDINATOR, 0)
        return (ReplicaType.WORKER, 0)

    @staticmethod
    def _process_name(job: TPUJob, rtype: ReplicaType, index: int) -> str:
        # Deterministic v1alpha2-style naming (genGeneralName,
        # controller_helper.go:60-67) — determinism is what makes create
        # idempotent under AlreadyExists.
        return f"{job.metadata.name}-{rtype.value.lower()}-{index}"

    def _rendezvous_port(self, job: TPUJob) -> int:
        """Stable per-job port, allocated once and persisted as an annotation.

        The STORE copy is authoritative: after a gang restart fences the
        old port (_clear_rendezvous), a sync still running from a stale
        informer snapshot must not resurrect the cleared annotation and
        hand the new gang the zombie incarnation's port."""
        try:
            stored = self.store.get(
                KIND_TPUJOB, job.metadata.namespace, job.metadata.name
            )
            existing = stored.metadata.annotations.get(ANNOTATION_PORT)
        except NotFoundError:
            existing = job.metadata.annotations.get(ANNOTATION_PORT)
        if existing:
            job.metadata.annotations[ANNOTATION_PORT] = existing
            return int(existing)
        job.metadata.annotations.pop(ANNOTATION_PORT, None)
        port = self.port_allocator()
        job.metadata.annotations[ANNOTATION_PORT] = str(port)

        # Persist on the stored object so the allocation survives restarts.
        def stamp(fresh):
            fresh.metadata.annotations[ANNOTATION_PORT] = str(port)

        self.store.update_with_retry(
            KIND_TPUJOB, job.metadata.namespace, job.metadata.name, stamp
        )
        return port

    # ---- the reconcile core ---------------------------------------------

    def _reconcile(self, job: TPUJob, processes: List[Process]) -> None:
        key = job.key()
        exp_key = self._exp_key(key)
        observed: Dict[Tuple[str, int], Process] = {
            (p.spec.replica_type, p.spec.replica_index): p for p in processes
        }
        gang = self._gang_roles(job)
        evaluators = [
            (ReplicaType.EVALUATOR, i)
            for i in range(
                (job.spec.replica_specs.get(ReplicaType.EVALUATOR).replicas or 1)
                if ReplicaType.EVALUATOR in job.spec.replica_specs
                else 0
            )
        ]
        # Elastic (r12): the live membership. Equal to ``gang`` except
        # while a shrink directive is in force, when the lost members are
        # deliberately absent — they must be neither recreated (the
        # symmetric re-grow handles that) nor counted as missing/failed.
        active = self._active_members(job, gang)

        if not has_condition(job.status, ConditionType.CREATED):
            set_condition(
                job.status,
                new_condition(
                    ConditionType.CREATED, ev.REASON_JOB_CREATED, f"TPUJob {key} created"
                ),
            )
            self.recorder.normal(job, ev.REASON_JOB_CREATED, f"TPUJob {key} created")
            # Trace: admission = submit (store creation) -> first sync.
            self.tracer.record(
                job.metadata.namespace, job.metadata.name, job.metadata.uid,
                "admission", job.metadata.creation_timestamp, time.time(),
                name=self._span_name(job, "admission"),
            )

        # -- active deadline (RunPolicy) ---------------------------------
        rp = job.spec.run_policy
        if (
            rp.active_deadline_seconds is not None
            and job.status.start_time is not None
            and time.time() - job.status.start_time > rp.active_deadline_seconds
        ):
            self._fail_job(
                job, ev.REASON_JOB_DEADLINE,
                f"active deadline {rp.active_deadline_seconds}s exceeded",
            )
            self._finish(job)
            return

        # -- chief success ⇒ job success (checked BEFORE failure handling:
        # once the chief has exited cleanly the training result exists, and
        # a co-worker crashing during shutdown must not re-run the job —
        # chief state drives job state, controller_status.go:39-120) -------
        chief = self._chief_role(job)
        chief_proc = observed.get((chief[0].value, chief[1]))
        if chief_proc is not None and chief_proc.status.phase is ProcessPhase.SUCCEEDED:
            set_condition(
                job.status,
                new_condition(
                    ConditionType.SUCCEEDED, ev.REASON_JOB_SUCCEEDED,
                    f"chief {chief_proc.metadata.name} succeeded",
                ),
            )
            self.recorder.normal(job, ev.REASON_JOB_SUCCEEDED, "TPUJob succeeded")
            job.status.completion_time = time.time()
            self._finish(job)
            return

        # -- fleet preemption request (preempt-by-priority) ---------------
        # A higher-priority job's admission stamped the preempt annotation
        # on this one: drain the gang through the SAME graceful lifecycle
        # as a host preemption notice (checkpoint warm-resume, cause
        # ``preemption``, exempt from backoff), release its quota to the
        # preemptor, and let the next create re-admit it — it will queue
        # behind the job that evicted it. Gated on the STORE-side clear
        # succeeding, so a sync from a stale informer snapshot can never
        # drain the gang twice for one request.
        if job.metadata.annotations.get(ANNOTATION_PREEMPT):
            # Resize×preemption commutation (r19): a preempt landing
            # MID-RESIZE defers until the resize epoch completes. Draining
            # now would kill survivors the chief's ack barrier is waiting
            # on (shrink) or members mid-(re)creation (grow) — the drain
            # is strictly ordered AFTER the resize, never interleaved.
            # Mid-resize = the resize span is still open, or the live
            # directive has no chief-published barrier yet (the span can
            # close between syncs while the workload still re-deals).
            d = job.status.resize_directive or {}
            if job.metadata.uid in self._open_resize or (
                d and "boundary_remaining" not in d
            ):
                if job.metadata.uid not in self._deferred_preempts:
                    self._deferred_preempts.add(job.metadata.uid)
                    self.recorder.normal(
                        job, ev.REASON_JOB_PREEMPTING,
                        f"preemption deferred: resize epoch "
                        f"{job.status.resize_epoch} still completing; gang "
                        "drains at the post-resize epoch",
                    )
                # Leave the annotation STORE-side (this sync only drops
                # its local copy) and fall through, so this sync keeps
                # driving the resize to completion; the completion sync
                # re-enters here with the barrier published and drains.
                job.metadata.annotations.pop(ANNOTATION_PREEMPT, None)
                self._enqueue(key)
            else:
                self._deferred_preempts.discard(job.metadata.uid)
                preemptor = job.metadata.annotations.pop(ANNOTATION_PREEMPT)

                def _drop_preempt(fresh):
                    if ANNOTATION_PREEMPT not in fresh.metadata.annotations:
                        return False
                    fresh.metadata.annotations.pop(ANNOTATION_PREEMPT, None)

                cleared = self.store.update_with_retry(
                    KIND_TPUJOB, job.metadata.namespace, job.metadata.name,
                    _drop_preempt,
                )
                if cleared is not None:
                    # Two-phase handoff: the victim KEEPS its quota while
                    # the gang drains (the chips are still physically
                    # occupied); _create_processes releases it once the
                    # gang is observed gone, so victim and preemptor never
                    # hold the same headroom at once — not even for one
                    # store snapshot.
                    with self._sched_lock:
                        self.fleet.begin_preempt(key)
                    live = [
                        p
                        for r in gang
                        if (p := observed.get((r[0].value, r[1]))) is not None
                        and not p.is_finished()
                    ]
                    if live:
                        self.recorder.warning(
                            job, ev.REASON_JOB_PREEMPTED,
                            f"preempted by higher-priority job {preemptor}; "
                            "gang restarting (checkpoint-resumed, not "
                            "counted against backoff)",
                        )
                        self._restart_gang(
                            job, gang, observed, exp_key,
                            cause=CAUSE_PREEMPTION,
                        )
                        return

        # -- grow-beyond-spec reclaim request (r19) -----------------------
        # Quota pressure wants this job's loaned over-spec chips back:
        # shrink to spec through the resize protocol (no drain, no
        # restart). Deferred mid-resize exactly like a preemption.
        if job.metadata.annotations.get(ANNOTATION_RECLAIM):
            if self._handle_overspec_reclaim(job, gang, active, observed, exp_key):
                return
        # A published reclaim completes once the over-spec members are
        # observably gone: only THEN does the loan return to the queue
        # (two-phase, like begin_preempt→release).
        if self._finish_overspec_reclaim(job, gang, observed):
            gang = self._gang_roles(job)
            active = self._active_members(job, gang)

        # -- failure handling --------------------------------------------
        # Hosts under a preemption notice: live members there take the
        # graceful drain path below; already-failed members classify by
        # exit code (130/143 ⇒ preemption — graceful, backoff-exempt).
        draining = {
            h.metadata.name
            for h in self.scheduler.draining_hosts(
                ttl=self._job_heartbeat_ttl(job)
            )
        }
        gang_failed = [
            observed[(r[0].value, r[1])]
            for r in active
            if _failed(observed.get((r[0].value, r[1])))
        ]
        permanent_msgs: List[str] = []
        retry_needed = False
        for p in gang_failed:
            policy = self._policy_for(job, p)
            cls = classify_exit_code(p.status.exit_code or 0, p.status.oom_killed)
            if policy is RestartPolicy.NEVER:
                permanent_msgs.append(
                    f"{p.metadata.name} exited {p.status.exit_code} (policy Never)"
                )
            elif policy is RestartPolicy.EXIT_CODE and cls in (
                ExitClass.PERMANENT, ExitClass.OOM
            ):
                permanent_msgs.append(
                    f"{p.metadata.name} exited {p.status.exit_code} "
                    f"({'oom-killed' if cls is ExitClass.OOM else 'permanent'})"
                )
            else:  # ALWAYS, ON_FAILURE, or retryable/preempted EXIT_CODE
                retry_needed = True

        if permanent_msgs:
            self._fail_job(job, ev.REASON_JOB_FAILED, "; ".join(permanent_msgs))
            self._finish(job)
            return

        if retry_needed:
            cause = _restart_cause(gang_failed)
            # Elastic shrink (r12): offer the survivors a smaller world
            # instead of tearing every one of them down. Falls through to
            # the full gang restart whenever the resize would be unsound
            # (non-dp/fsdp mesh, chief among the dead, OOM, no survivor).
            if self._try_resize_shrink(
                job, active, observed, gang_failed, exp_key, cause
            ):
                return
            if cause is not CAUSE_PREEMPTION:
                # Freshen restart_count from the store BEFORE the limit
                # check: the informer cache may not have absorbed a previous
                # restart's own status write, and comparing the stale count
                # would allow a crash-looping job one restart past its
                # backoff_limit. Preemption restarts skip the check entirely
                # — eviction never consumes the job's failure budget, and an
                # at-limit job must still be movable off a dying host.
                try:
                    stored = self.store.get(
                        KIND_TPUJOB, job.metadata.namespace, job.metadata.name
                    )
                    job.status.restart_count = max(
                        job.status.restart_count, stored.status.restart_count
                    )
                except NotFoundError:
                    pass
                if (
                    rp.backoff_limit is not None
                    and job.status.restart_count >= rp.backoff_limit
                ):
                    self._fail_job(
                        job, ev.REASON_JOB_FAILED,
                        f"backoff limit {rp.backoff_limit} exceeded "
                        f"({job.status.restart_count} restarts)",
                    )
                    self._finish(job)
                    return
            self._restart_gang(job, gang, observed, exp_key, cause=cause)
            return

        # -- preemption drain: graceful gang restart -----------------------
        # No member has failed yet, but some live member sits on a host
        # under a preemption notice. Restart the WHOLE gang now, while the
        # checkpoint on disk is fresh and the draining host can still
        # SIGTERM cleanly — waiting for the host to die would turn a
        # graceful drain into a NodeLost fence. Deletions reach the
        # draining host's agent as SIGTERM (exit 143, preemption-retryable);
        # recreation lands on non-draining hosts with warm-restart env.
        if draining:
            drain_live = [
                p
                for r in gang
                if (p := observed.get((r[0].value, r[1]))) is not None
                and not p.is_finished()
                and p.spec.node_name in draining
            ]
            if drain_live:
                self.recorder.warning(
                    job, ev.REASON_JOB_PREEMPTED,
                    f"host(s) {sorted({p.spec.node_name for p in drain_live})} "
                    "draining under preemption notice; gang restarting "
                    "(checkpoint-resumed, not counted against backoff)",
                )
                self._restart_gang(
                    job, gang, observed, exp_key, cause=CAUSE_PREEMPTION
                )
                return

        # ALWAYS policy also restarts gang members that *succeeded*? No —
        # Always applies to failures and external deletions; a cleanly
        # succeeded member stays finished (job completion handles it).

        # -- create missing gang members ---------------------------------
        # Missing = expected-but-absent ACTIVE members (+ evaluators): the
        # members a shrink declared inactive are not missing — the
        # symmetric re-grow below recreates them when capacity returns.
        missing = [r for r in active + evaluators if (r[0].value, r[1]) not in observed]
        if missing:
            self._create_processes(job, missing, exp_key, observed)
        elif active != gang:
            if self._try_regrow(job, gang, active, observed, exp_key):
                return
        elif self._try_grow_beyond_spec(job, gang, active, observed, exp_key):
            # Grow-beyond-spec (r19): a full-strength elastic gang with
            # elastic_max_world headroom took idle in-quota chips. End
            # the sync for the same reason _try_regrow does.
            return

        # -- running condition -------------------------------------------
        gang_running = active and all(
            (r[0].value, r[1]) in observed
            and observed[(r[0].value, r[1])].status.phase is ProcessPhase.RUNNING
            for r in active
        )
        if gang_running:
            now_running = time.time()
            # Close the open resize span (if any): shrink closes when the
            # survivors are confirmed running; grow when the recreated
            # members report RUNNING. Its width is the control-plane
            # resize downtime, by direction.
            self._close_resize_span(job, now_running)
            # Restart-span close, condition-independent: the RUNNING
            # edge below is the primary close point, but a lost
            # RESTARTING status write skips the edge entirely and the
            # span would drift open until terminal — charging the whole
            # healthy tail to cause restart. All members RUNNING with at
            # least one created after the outage began is the recovery
            # receipt regardless of condition history; a stale informer
            # snapshot (members all predating the span) is refused.
            open_restart = self._open_restart.get(job.metadata.uid)
            if open_restart is not None and any(
                observed[(r[0].value, r[1])].metadata.creation_timestamp
                > open_restart["start"]
                for r in active
            ):
                self._close_restart_span(job, now_running)
            if job.status.start_time is None:
                job.status.start_time = time.time()
            if not has_condition(job.status, ConditionType.RUNNING):
                set_condition(
                    job.status,
                    new_condition(
                        ConditionType.RUNNING, ev.REASON_JOB_RUNNING, "all gang members running"
                    ),
                )
                self.recorder.normal(job, ev.REASON_JOB_RUNNING, "TPUJob running")
                now = time.time()
                # Trace: the gang is (back) up — close any open restart
                # span; its width IS the recovery downtime (MTTR).
                self._close_restart_span(job, now)
                # ... and the hang span: progress stopped -> RUNNING again
                # is the whole wedge window (detection wait included).
                self._close_hang_span(job, now)
                self.tracer.record(
                    job.metadata.namespace, job.metadata.name,
                    job.metadata.uid, "running", now, now,
                    attrs={"track": "running"},
                    name=self._span_name(job, "running"),
                )
            # Hang watchdog first (r15): a whole-gang step-progress stall
            # is HIS, not the straggler tracker's (whose median-ratio rule
            # is silent by design when every rank stops together). When
            # the hang path shot the gang (or failed the job at the
            # backoff limit) this sync is done.
            if self._check_hang(job, gang, active, observed, exp_key):
                return
            # Live telemetry consumer: evaluate any new cross-rank
            # step-time windows for stragglers (resync ticks drive this
            # between watch events).
            self._check_stragglers(job, processes)
            # Goodput autopilot (r16): turn the numbers the two checks
            # above maintain into policy. A pre-emptive migrate shrinks
            # the gang — end the sync exactly like the failure-path
            # shrink does (the directive is published; survivors
            # re-shard; the next sync sees the new world).
            if self._autopilot_tick(job, gang, active, observed, exp_key):
                return

        # -- evaluator restarts (per-replica, not gang) -------------------
        for r in evaluators:
            p = observed.get((r[0].value, r[1]))
            if _failed(p):
                policy = self._policy_for(job, p)
                if policy in (RestartPolicy.ALWAYS, RestartPolicy.ON_FAILURE) or (
                    policy is RestartPolicy.EXIT_CODE
                    # retryable OR preemption-retryable (exit 130/143)
                    and is_retryable(p.status.exit_code or 0, p.status.oom_killed)
                ):
                    self.expectations.expect_deletions(exp_key, 1)
                    try:
                        self._delete_child(p)
                    except Exception:
                        self.expectations.deletion_failed(exp_key)
                        raise
                    self.recorder.normal(
                        job, ev.REASON_SUCCESSFUL_DELETE,
                        f"restarting evaluator {p.metadata.name}",
                    )

        # -- status counters ----------------------------------------------
        initialize_replica_statuses(job.status, job.spec.replica_specs.keys())
        for p in processes:
            try:
                rtype = ReplicaType(p.spec.replica_type)
            except ValueError:
                continue
            update_replica_status(job.status, rtype, p)

        job.status.last_reconcile_time = time.time()
        self._write_status(job)

    # ---- tracing helpers (obs/) -----------------------------------------

    @staticmethod
    def _span_name(job: TPUJob, op: str) -> str:
        """Deterministic per-(job-incarnation, op) span name: recording is
        create-once — a re-sync or controller restart can never duplicate
        a lifecycle span, because the store dedupes on the name."""
        return f"{job.metadata.name}-{trace8(job.metadata.uid)}-{op}"

    def _mark_scheduled(self, job: TPUJob, now: float) -> None:
        """First successful placement decision for this job: record the
        submit->scheduled span and observe tpujob_time_to_scheduled_seconds
        — exactly once per job (store-name dedupe backs the in-memory
        set across controller restarts)."""
        uid = job.metadata.uid
        wait = self._open_schedwait.pop(uid, None)
        if wait is not None:
            self.tracer.close(wait["ns"], wait["name"], now)
        if uid in self._sched_observed:
            return
        self._sched_observed.add(uid)
        span = self.tracer.record(
            job.metadata.namespace, job.metadata.name, uid,
            "scheduled", job.metadata.creation_timestamp, now,
            name=self._span_name(job, "scheduled"),
        )
        if span is not None:
            self.metrics.observe_hist(
                "tpujob_time_to_scheduled_seconds",
                max(0.0, now - job.metadata.creation_timestamp),
            )

    def _close_restart_span(self, job: TPUJob, now: float) -> None:
        """Close the open restart span (opened by _restart_gang) and
        observe its width as recovery downtime, labeled by cause."""
        info = self._open_restart.pop(job.metadata.uid, None)
        if info is None:
            return
        self.tracer.close(info["ns"], info["name"], now)
        downtime = max(0.0, now - info["start"])
        self.metrics.observe_hist(
            "tpujob_restart_downtime_seconds",
            downtime,
            labels={"cause": info["cause"]},
        )
        # Goodput: the SAME width feeds lost-seconds — one close point,
        # so the histogram and the goodput surface can never
        # double-count each other. A preemption drain gets its own
        # cause (r19): its remedy is quota/priority policy, not
        # crash-loop debugging, and folding it into "restart" would
        # make the cause ledger claim downtime the backoff budget never
        # charged.
        self.metrics.inc(
            "tpujob_lost_seconds_total", downtime,
            labels={
                "cause": (
                    CAUSE_PREEMPTION
                    if info["cause"] == CAUSE_PREEMPTION
                    else GOODPUT_RESTART
                )
            },
        )

    # ---- hang plane (r15, obs/watchdog.py + obs/blackbox.py) -------------

    def _check_hang(
        self,
        job: TPUJob,
        gang: List[Tuple[ReplicaType, int]],
        active: List[Tuple[ReplicaType, int]],
        observed: Dict[Tuple[str, int], Process],
        exp_key: str,
    ) -> bool:
        """Drive the job's gang-progress watchdog from the telemetry ring;
        declare HUNG, run the forensics sweep, and recover. Returns True
        when the hang path consumed this sync (gang restarted or job
        failed terminally) — the caller stops reconciling.

        Only reached from the all-members-RUNNING block, so heartbeats
        are live by construction: a heartbeat-dead host fails its members
        (node-lost) before this point and routes to the LOUD retry path,
        never here."""
        rp = job.spec.run_policy
        if rp.hang_timeout_seconds is None:
            return False
        uid = job.metadata.uid
        wd = self._watchdogs.get(uid)
        if wd is None:
            wd = self._watchdogs[uid] = GangWatchdog(rp.hang_timeout_seconds)
        now = time.time()
        try:
            window = latest_window(
                job_telemetry(
                    self.store, job.metadata.namespace, job.metadata.name
                )
            )
        except Exception:  # noqa: BLE001 — telemetry read is best-effort
            return False
        first_step_time: Optional[float] = None
        try:
            span = self.store.get(
                KIND_SPAN, job.metadata.namespace,
                first_step_span_name(job.metadata.name, uid),
            )
            first_step_time = span.start_time
        except Exception:  # noqa: BLE001 — pre-first-step grace applies
            pass
        verdict = wd.observe(
            window, now,
            resize_epoch=job.status.resize_epoch,
            first_step_time=first_step_time,
        )
        if verdict is not None:
            self._declare_hang(job, verdict, now)
        if wd.hung and job.status.hang_state:
            return self._maybe_recover_hang(job, gang, active, observed, exp_key, now)
        return False

    def _declare_hang(self, job: TPUJob, verdict: HangVerdict, now: float) -> None:
        """Latch one declared hang: count it, stamp hang_state (what
        ``tpujob top`` renders), publish the stack-sweep directive (a
        monotonic epoch the HostAgents act on exactly once — the
        profile_directive protocol), and open the hang span BACKDATED to
        when progress stopped, so its eventual width is the full wedge
        window under cause ``hang`` and nothing leaks into restart."""
        uid = job.metadata.uid
        job.status.hang_count += 1
        epoch = int((job.status.stackdump_directive or {}).get("epoch", 0)) + 1
        job.status.hang_state = {
            "stuck_step": verdict.stuck_step,
            "since": verdict.since,
            "last_moving_ranks": list(verdict.last_moving_ranks),
            "time": now,
        }
        job.status.stackdump_directive = {"epoch": epoch, "time": now, "acks": {}}
        self.metrics.inc("tpujob_hangs_total")
        self.metrics.inc("tpujob_stackdump_sweeps_total")
        self.recorder.warning(
            job, ev.REASON_JOB_HUNG,
            f"gang hung at step {verdict.stuck_step}: no rank advanced for "
            f"{verdict.stalled_for:.0f}s (hang_timeout_seconds="
            f"{job.spec.run_policy.hang_timeout_seconds}); last-moving "
            f"ranks {verdict.last_moving_ranks}; sweeping stacks "
            f"(epoch {epoch}) before recovery",
        )
        span_name = self._span_name(job, f"hang-{job.status.hang_count}")
        if uid not in self._open_hang and self.tracer.record(
            job.metadata.namespace, job.metadata.name, uid,
            "hang", verdict.since, 0.0,
            attrs={"stuck_step": str(verdict.stuck_step),
                   "sweep_epoch": str(epoch), "track": "hang"},
            name=span_name,
        ) is not None:
            self._open_hang[uid] = {
                "ns": job.metadata.namespace, "name": span_name,
                "start": verdict.since,
            }
        self._write_status(job)

    def _maybe_recover_hang(
        self,
        job: TPUJob,
        gang: List[Tuple[ReplicaType, int]],
        active: List[Tuple[ReplicaType, int]],
        observed: Dict[Tuple[str, int], Process],
        exp_key: str,
        now: float,
    ) -> bool:
        """After declaration: hold the wedged gang alive until every
        active rank's stack dump is acked (or the forensics grace runs
        out), freeze the postmortem bundle, then recover — a hang-caused
        gang restart charged to restart_count, or a terminal failure at
        the backoff limit. Returns True once recovery was issued."""
        directive = job.status.stackdump_directive or {}
        acks = directive.get("acks") or {}
        declared_at = float((job.status.hang_state or {}).get("time") or now)
        if (
            len(acks) < len(active)
            and now - declared_at < FORENSICS_GRACE_SECONDS
        ):
            # Sweep still in flight: each agent ack re-enqueues us via the
            # job MODIFIED event; the rate-limited requeue is the backstop
            # that ends the wait when an agent never acks.
            self._route(job.key()).add_rate_limited(job.key())
            return False
        bb = self._blackboxes.setdefault(job.metadata.uid, Blackbox())
        bb.observe_status(job)
        art = bb.freeze(
            self.store, job, reason="hang",
            detail=dict(job.status.hang_state or {}),
        )
        if art is not None:
            self.recorder.normal(
                job, ev.REASON_POSTMORTEM_FROZEN,
                f"postmortem bundle frozen ({len(acks)}/{len(active)} rank "
                f"stack dumps shipped): tpujob debug {job.metadata.name}",
            )
        rp = job.spec.run_policy
        # Hangs consume the failure budget exactly like crashes: freshen
        # restart_count from the store first (same staleness rule as the
        # retry path), then fail at the limit.
        try:
            stored = self.store.get(
                KIND_TPUJOB, job.metadata.namespace, job.metadata.name
            )
            job.status.restart_count = max(
                job.status.restart_count, stored.status.restart_count
            )
        except NotFoundError:
            pass
        if (
            rp.backoff_limit is not None
            and job.status.restart_count >= rp.backoff_limit
        ):
            self._fail_job(
                job, ev.REASON_JOB_FAILED,
                f"hung at step {(job.status.hang_state or {}).get('stuck_step')} "
                f"and backoff limit {rp.backoff_limit} exceeded "
                f"({job.status.restart_count} restarts)",
            )
            self._finish(job)
            return True
        self._restart_gang(job, gang, observed, exp_key, cause=CAUSE_HANG)
        wd = self._watchdogs.get(job.metadata.uid)
        if wd is not None:
            wd.reset(now)
        return True

    def _close_hang_span(
        self, job: TPUJob, now: float, terminal: bool = False
    ) -> None:
        """Close the open hang span (opened backdated at declaration) and
        observe its width — last observed progress -> recovered gang
        RUNNING — as hang downtime; the SAME width feeds lost-seconds
        under cause ``hang`` (single source, like restart/resize). On
        recovery the declared state clears; at terminal it stays — the
        job never recovered, and hang_state is the forensic residue."""
        info = self._open_hang.pop(job.metadata.uid, None)
        if info is None:
            return
        self.tracer.close(info["ns"], info["name"], now)
        downtime = max(0.0, now - info["start"])
        self.metrics.observe_hist("tpujob_hang_downtime_seconds", downtime)
        self.metrics.inc(
            "tpujob_lost_seconds_total", downtime,
            labels={"cause": GOODPUT_HANG},
        )
        if not terminal:
            job.status.hang_state = {}

    # ---- elastic gangs (r12) --------------------------------------------

    def _active_members(
        self, job: TPUJob, gang: List[Tuple[ReplicaType, int]]
    ) -> List[Tuple[ReplicaType, int]]:
        """The gang roles the job's LIVE resize directive declares active:
        the shrink directive's member list while one is in force, the
        full gang otherwise (never resized, or re-grown)."""
        directive = job.status.resize_directive or {}
        if directive.get("direction") != "shrink":
            return gang
        names = set(directive.get("members") or [])
        chosen = [
            r for r in gang if self._process_name(job, r[0], r[1]) in names
        ]
        return chosen or gang

    def _try_resize_shrink(
        self,
        job: TPUJob,
        active: List[Tuple[ReplicaType, int]],
        observed: Dict[Tuple[str, int], Process],
        gang_failed: List[Process],
        exp_key: str,
        cause: str,
    ) -> bool:
        """Elastic shrink decision: on member loss, keep the survivors
        running and stamp a new resize epoch into the job status instead
        of restarting the whole gang. Returns True when the shrink was
        taken (the caller's full-restart path is skipped).

        Refused — falling back to the full restart — when:
        - ``run_policy.elastic`` is off, or the mesh has a >1 axis outside
          dp/fsdp (the model program itself is sharded there);
        - the loss is a preemption drain (the WHOLE gang must move off the
          draining host — a shrink would leave survivors on it);
        - the loss is an OOM (fewer hosts hold MORE state per host: a
          shrink converts one OOM into a cascade);
        - the chief/coordinator died (every member's rendezvous points at
          it — only a full restart can re-anchor);
        - no survivor would remain, or nothing actually shrank.
        """
        if not job.spec.run_policy.elastic or not _elastic_mesh_ok(job):
            return False
        if cause is CAUSE_PREEMPTION or cause is CAUSE_OOM:
            return False
        # Resize×preemption commutation (r19): a shrink landing MID-DRAIN
        # is refused until the victim's quota releases — the gang is
        # winding down whole; publishing a resize epoch now would leave
        # survivors running on chips the preemptor was promised. Same for
        # a preempt request that just landed (annotation still pending):
        # the drain, deferred or not, owns the gang's next transition.
        with self._sched_lock:
            if self.fleet.draining(job.key()):
                return False
        if job.metadata.annotations.get(ANNOTATION_PREEMPT):
            return False
        failed_keys = {
            (p.spec.replica_type, p.spec.replica_index) for p in gang_failed
        }
        chief = self._chief_role(job)
        if (chief[0].value, chief[1]) in failed_keys:
            return False
        survivors = [
            r for r in active if (r[0].value, r[1]) not in failed_keys
        ]
        if not survivors or len(survivors) == len(active):
            return False

        now = time.time()
        epoch = job.status.resize_epoch + 1
        members = [self._process_name(job, r[0], r[1]) for r in survivors]
        job.status.resize_epoch = epoch
        job.status.resize_count += 1
        job.status.world_size = len(survivors)
        job.status.last_restart_cause = CAUSE_RESIZE_SHRINK
        job.status.resize_directive = {
            "epoch": epoch,
            "direction": "shrink",
            "world_size": len(survivors),
            "members": members,
            "time": now,
        }
        self._append_resize_history(job, {
            "epoch": epoch, "direction": "shrink",
            "world_size": len(survivors), "cause": cause, "time": now,
        })
        self.metrics.inc("tpujob_gang_resizes_total")
        self.metrics.inc(
            "tpujob_gang_resizes_by_direction_total",
            labels={"direction": "shrink"},
        )
        self._open_resize_span(job, "shrink", epoch, now)
        self.recorder.warning(
            job, ev.REASON_JOB_RESTARTING,
            f"elastic shrink #{job.status.resize_count} (epoch {epoch}, "
            f"{cause}): {len(active)} -> {len(survivors)} members; "
            "survivors re-shard at the next step boundary (not counted "
            "against backoff)",
        )
        # Hold the lost members' per-host capacity for the symmetric
        # re-grow: the job's quota is already held (no release happens on
        # a resize); without the host-level hold a backfiller could squat
        # on the freed chips and make the re-grow unplaceable forever.
        lost_hosts: Dict[str, int] = {}
        targets = [
            observed[(r[0].value, r[1])]
            for r in active
            if (r[0].value, r[1]) in failed_keys
            and (r[0].value, r[1]) in observed
        ]
        for p in targets:
            if p.spec.node_name:
                lost_hosts[p.spec.node_name] = (
                    lost_hosts.get(p.spec.node_name, 0) + max(p.spec.chips, 0)
                )
        with self._sched_lock:
            self.fleet.hold_for_regrow(job.key(), lost_hosts)
        # Delete only the DEAD members' records. Survivors are untouched —
        # that is the whole point.
        if targets:
            self.expectations.expect_deletions(exp_key, len(targets))
            deleted = 0
            try:
                for p in targets:
                    self._delete_child(p)
                    deleted += 1
            except Exception:
                for _ in range(len(targets) - deleted):
                    self.expectations.deletion_failed(exp_key)
                raise
        self._write_status(job)
        return True

    def _try_regrow(
        self,
        job: TPUJob,
        gang: List[Tuple[ReplicaType, int]],
        active: List[Tuple[ReplicaType, int]],
        observed: Dict[Tuple[str, int], Process],
        exp_key: str,
    ) -> bool:
        """Symmetric re-grow: a shrunk job whose survivors are all RUNNING
        tries to recreate its lost members every sync. Success publishes a
        ``grow`` directive at the next epoch — survivors re-carve to the
        full world at their next step boundary, and the created members
        (stamped ENV_RESIZE_EPOCH = the new epoch) wait for the directive
        to reach their epoch before joining. Placement failure leaves the
        job running shrunk — never parked in QUEUED, never failed.

        Returns True when a grow was published — the caller must END the
        sync: its ``active`` still reflects the shrink directive, and
        falling through would close the just-opened grow span against the
        survivors alone."""
        lost = [r for r in gang if r not in active]
        if not lost:
            return False
        # A reclaim shrink in flight (r19) deliberately removed the
        # over-spec tail: recreating it here would undo the reclaim. Once
        # the loan returns (overspec_workers back to 0) the gang equals
        # spec and ordinary re-grow of failure-lost members resumes.
        if job.status.overspec_workers > 0 and (
            (job.status.resize_directive or {}).get("reclaim")
        ):
            return False
        # Mid-drain the gang is winding down whole — no resize commutes
        # with that (same refusal as _try_resize_shrink).
        with self._sched_lock:
            if self.fleet.draining(job.key()):
                return False
        for r in active:
            p = observed.get((r[0].value, r[1]))
            if p is None or p.status.phase is not ProcessPhase.RUNNING:
                return False  # survivors still settling; re-grow would stack
        epoch = job.status.resize_epoch + 1
        if not self._create_processes(
            job, lost, exp_key, observed, resize_epoch=epoch
        ):
            return False
        now = time.time()
        job.status.resize_epoch = epoch
        job.status.resize_count += 1
        job.status.world_size = len(gang)
        job.status.last_restart_cause = CAUSE_RESIZE_GROW
        job.status.resize_directive = {
            "epoch": epoch,
            "direction": "grow",
            "world_size": len(gang),
            "members": [self._process_name(job, r[0], r[1]) for r in gang],
            "time": now,
        }
        self._append_resize_history(job, {
            "epoch": epoch, "direction": "grow",
            "world_size": len(gang), "cause": "member-returned", "time": now,
        })
        self.metrics.inc("tpujob_gang_resizes_total")
        self.metrics.inc(
            "tpujob_gang_resizes_by_direction_total",
            labels={"direction": "grow"},
        )
        self._open_resize_span(job, "grow", epoch, now)
        self.recorder.normal(
            job, ev.REASON_JOB_RUNNING,
            f"elastic re-grow #{job.status.resize_count} (epoch {epoch}): "
            f"{len(active)} -> {len(gang)} members; recreated "
            f"{len(lost)} member(s)",
        )
        with self._sched_lock:
            self.fleet.clear_regrow_hold(job.key())
        self._write_status(job)
        return True

    @staticmethod
    def _append_resize_history(job: TPUJob, entry: Dict[str, Any]) -> None:
        """Bounded history append (r19 satellite): keep the newest
        RESIZE_HISTORY_KEEP entries, fold everything older into the
        resize_history_folded count. Display total = folded + len."""
        job.status.resize_history.append(entry)
        overflow = len(job.status.resize_history) - RESIZE_HISTORY_KEEP
        if overflow > 0:
            del job.status.resize_history[:overflow]
            job.status.resize_history_folded += overflow

    def _try_grow_beyond_spec(
        self,
        job: TPUJob,
        gang: List[Tuple[ReplicaType, int]],
        active: List[Tuple[ReplicaType, int]],
        observed: Dict[Tuple[str, int], Process],
        exp_key: str,
    ) -> bool:
        """Grow-beyond-spec (r19): a fully-RUNNING elastic job with
        ``scheduling.elastic_max_world`` above its current world asks the
        fleet for idle in-quota chips and, when granted, drives the grow
        path past spec size — extra worker indices append to the gang
        tail and the usual grow directive re-carves the mesh. The fleet
        refuses whenever ANY queued admission exists in the job's queue
        (backfill never starves the admission queue); the loaned chips
        are the first thing reclaimed under quota pressure.

        Returns True when a grow was published — the caller must END the
        sync, exactly like _try_regrow."""
        target = int(
            getattr(job.spec.scheduling, "elastic_max_world", 0) or 0
        )
        if target <= len(gang):
            return False
        if not job.spec.run_policy.elastic or not _elastic_mesh_ok(job):
            return False
        if job.metadata.uid in self._open_resize:
            return False
        d = job.status.resize_directive or {}
        if d and "boundary_remaining" not in d:
            return False  # prior resize still at the re-deal barrier
        if job.metadata.annotations.get(
            ANNOTATION_PREEMPT
        ) or job.metadata.annotations.get(ANNOTATION_RECLAIM):
            return False
        for r in active:
            p = observed.get((r[0].value, r[1]))
            if p is None or p.status.phase is not ProcessPhase.RUNNING:
                return False
        workers = job.spec.replica_specs.get(ReplicaType.WORKER)
        if workers is None:
            return False
        chips_each = max(
            workers.template.chips_per_process
            or job.spec.topology.chips_per_host
            or 1,
            1,
        )
        # Largest affordable step first: the fleet's grant is
        # all-or-nothing per offer, so probe k, k-1, ... 1 members.
        granted_members = 0
        for k in range(target - len(gang), 0, -1):
            with self._sched_lock:
                if self.fleet.offer_grow(job, k * chips_each):
                    granted_members = k
                    break
        if not granted_members:
            return False
        prev_over = job.status.overspec_workers
        job.status.overspec_workers = prev_over + granted_members
        new_gang = self._gang_roles(job)
        new_members = [r for r in new_gang if r not in gang]
        epoch = job.status.resize_epoch + 1
        if not self._create_processes(
            job, new_members, exp_key, observed, resize_epoch=epoch
        ):
            # Placement refused the offer: hand the loan straight back
            # (only the chips just borrowed — an earlier grant stays).
            job.status.overspec_workers = prev_over
            with self._sched_lock:
                self.fleet.reclaim_overspec(
                    job.key(), chips=granted_members * chips_each
                )
            return False
        now = time.time()
        job.status.resize_epoch = epoch
        job.status.resize_count += 1
        job.status.world_size = len(new_gang)
        job.status.last_restart_cause = CAUSE_RESIZE_GROW
        job.status.resize_directive = {
            "epoch": epoch,
            "direction": "grow",
            "world_size": len(new_gang),
            "members": [
                self._process_name(job, r[0], r[1]) for r in new_gang
            ],
            "time": now,
        }
        self._append_resize_history(job, {
            "epoch": epoch, "direction": "grow",
            "world_size": len(new_gang), "cause": "grow-beyond-spec",
            "time": now,
        })
        self.metrics.inc("tpujob_gang_resizes_total")
        self.metrics.inc(
            "tpujob_gang_resizes_by_direction_total",
            labels={"direction": "grow"},
        )
        self.metrics.inc(
            "tpujob_overspec_grants_total", granted_members * chips_each
        )
        self._open_resize_span(job, "grow", epoch, now)
        self.recorder.normal(
            job, ev.REASON_JOB_RUNNING,
            f"grow-beyond-spec #{job.status.resize_count} (epoch {epoch}): "
            f"{len(gang)} -> {len(new_gang)} members on "
            f"{granted_members * chips_each} idle in-quota chip(s); "
            "first-reclaimed under quota pressure",
        )
        self._write_status(job)
        return True

    def _clear_reclaim_annotation(self, job: TPUJob):
        """Drop the reclaim request locally AND store-side; returns the
        store's update result (None ⇒ another sync already took it)."""
        job.metadata.annotations.pop(ANNOTATION_RECLAIM, None)

        def _drop(fresh):
            if ANNOTATION_RECLAIM not in fresh.metadata.annotations:
                return False
            fresh.metadata.annotations.pop(ANNOTATION_RECLAIM, None)

        return self.store.update_with_retry(
            KIND_TPUJOB, job.metadata.namespace, job.metadata.name, _drop
        )

    def _handle_overspec_reclaim(
        self,
        job: TPUJob,
        gang: List[Tuple[ReplicaType, int]],
        active: List[Tuple[ReplicaType, int]],
        observed: Dict[Tuple[str, int], Process],
        exp_key: str,
    ) -> bool:
        """Victim side of a grow-beyond-spec reclaim (r19): publish a
        reclaim-flagged shrink back to spec and SIGTERM the over-spec
        tail. No drain, no restart, no backoff charge — the job keeps
        running on its spec world. Deferred mid-resize exactly like a
        preemption. Returns True when the shrink was published (the
        caller ends the sync)."""
        key = job.key()
        k = max(job.status.overspec_workers, 0)
        d = job.status.resize_directive or {}
        if not k or d.get("reclaim"):
            # Stale request (nothing loaned) or a reclaim already in
            # flight: clear the annotation; completion handles the rest.
            self._clear_reclaim_annotation(job)
            return False
        if job.metadata.uid in self._open_resize or (
            d and "boundary_remaining" not in d
        ):
            if job.metadata.uid not in self._deferred_preempts:
                self._deferred_preempts.add(job.metadata.uid)
                self.recorder.normal(
                    job, ev.REASON_JOB_PREEMPTING,
                    f"over-spec reclaim deferred: resize epoch "
                    f"{job.status.resize_epoch} still completing",
                )
            job.metadata.annotations.pop(ANNOTATION_RECLAIM, None)
            self._enqueue(key)
            return False
        self._deferred_preempts.discard(job.metadata.uid)
        requester = job.metadata.annotations.get(ANNOTATION_RECLAIM, "")
        if self._clear_reclaim_annotation(job) is None:
            return False  # raced: another sync already handled it
        spec_gang = gang[: len(gang) - k]
        # Survivors = the spec members still active (a concurrent failure
        # shrink may have lost one; it stays lost and re-grows later).
        keep = [r for r in spec_gang if r in active]
        targets = [
            observed[(r[0].value, r[1])]
            for r in gang[len(gang) - k:]
            if (r[0].value, r[1]) in observed
        ]
        now = time.time()
        epoch = job.status.resize_epoch + 1
        members = [self._process_name(job, r[0], r[1]) for r in keep]
        job.status.resize_epoch = epoch
        job.status.resize_count += 1
        job.status.world_size = len(keep)
        job.status.last_restart_cause = CAUSE_RESIZE_SHRINK
        job.status.resize_directive = {
            "epoch": epoch,
            "direction": "shrink",
            "world_size": len(keep),
            "members": members,
            "time": now,
            # The workload's completion gate honors this flag: a reclaim
            # shrink is terminal-eligible (no symmetric re-grow of the
            # over-spec tail is coming), unlike a failure shrink whose
            # done gate holds for the re-grow.
            "reclaim": True,
        }
        self._append_resize_history(job, {
            "epoch": epoch, "direction": "shrink",
            "world_size": len(keep), "cause": CAUSE_OVERSPEC_RECLAIM,
            "time": now,
        })
        self.metrics.inc("tpujob_gang_resizes_total")
        self.metrics.inc(
            "tpujob_gang_resizes_by_direction_total",
            labels={"direction": "shrink"},
        )
        self._open_resize_span(job, "shrink", epoch, now)
        self.recorder.normal(
            job, ev.REASON_JOB_RESTARTING,
            f"over-spec reclaim (epoch {epoch}"
            + (f", requested by {requester}" if requester else "")
            + f"): {len(gang)} -> {len(keep)} members; loaned chips "
            "return to the queue once the over-spec members exit (not "
            "counted against backoff)",
        )
        # SIGTERM the over-spec tail by deleting its records — the same
        # mechanism every drain uses. Survivors are untouched.
        if targets:
            self.expectations.expect_deletions(exp_key, len(targets))
            deleted = 0
            try:
                for p in targets:
                    self._delete_child(p)
                    deleted += 1
            except Exception:
                for _ in range(len(targets) - deleted):
                    self.expectations.deletion_failed(exp_key)
                raise
        self._write_status(job)
        return True

    def _finish_overspec_reclaim(
        self,
        job: TPUJob,
        gang: List[Tuple[ReplicaType, int]],
        observed: Dict[Tuple[str, int], Process],
    ) -> bool:
        """Completion side of a reclaim: once every over-spec member is
        observably gone (absent or finished), zero overspec_workers and
        return the loan to the queue — strictly two-phase, so the waiting
        admitter and the over-spec members never hold the same headroom
        at once. Returns True when the loan was just returned (callers
        recompute gang/active from the now-spec-sized membership)."""
        k = max(job.status.overspec_workers, 0)
        d = job.status.resize_directive or {}
        if not k or not d.get("reclaim") or d.get("direction") != "shrink":
            return False
        tail = gang[len(gang) - k:]
        leftovers: List[Process] = []
        for r in tail:
            p = observed.get((r[0].value, r[1]))
            if p is not None and not p.is_finished():
                return False  # still winding down: the loan stays charged
            if p is not None:
                leftovers.append(p)
        # A member that exited on its own (directive SystemExit beat the
        # delete) leaves a finished record — clear it with the rest.
        if leftovers:
            exp_key = self._exp_key(job.key())
            self.expectations.expect_deletions(exp_key, len(leftovers))
            deleted = 0
            try:
                for p in leftovers:
                    self._delete_child(p)
                    deleted += 1
            except Exception:
                for _ in range(len(leftovers) - deleted):
                    self.expectations.deletion_failed(exp_key)
                raise
        job.status.overspec_workers = 0
        with self._sched_lock:
            freed = self.fleet.reclaim_overspec(job.key())
            keys = self.fleet.next_queued() if freed else []
        for qk in keys:
            self._enqueue(qk)
        if freed:
            self.metrics.inc("tpujob_overspec_reclaimed_chips_total", freed)
        self.recorder.normal(
            job, ev.REASON_JOB_RUNNING,
            f"over-spec reclaim complete: {k} member(s) gone, "
            f"{freed} chip(s) returned to the queue",
        )
        self._write_status(job)
        return True

    def _open_resize_span(
        self, job: TPUJob, direction: str, epoch: int, now: float
    ) -> None:
        """Open the resize span (closed when the resized gang is RUNNING;
        width = control-plane resize downtime, by direction). A resize
        landing while another's span is still open closes the old window
        first — consecutive resizes are separate downtime windows."""
        uid = job.metadata.uid
        if uid in self._open_resize:
            self._close_resize_span(job, now, force=True)
        span_name = self._span_name(job, f"resize-{job.status.resize_count}")
        if self.tracer.record(
            job.metadata.namespace, job.metadata.name, uid,
            "resize", now, 0.0,
            attrs={"direction": direction, "epoch": str(epoch),
                   "track": "resize"},
            name=span_name,
        ) is not None:
            self._open_resize[uid] = {
                "ns": job.metadata.namespace, "name": span_name,
                "start": now, "direction": direction, "epoch": epoch,
            }

    def _close_resize_span(
        self, job: TPUJob, now: float, force: bool = False
    ) -> None:
        """Close the open resize span and observe its width into
        ``tpujob_resize_downtime_seconds{direction}``.

        A sync running from a STALE informer snapshot (status epoch behind
        the span's) computes ``active`` against the superseded directive —
        its all-RUNNING verdict says nothing about the resized gang, so
        the close is refused until the caller's job reflects the span's
        epoch. ``force`` (the terminal path) closes unconditionally."""
        info = self._open_resize.get(job.metadata.uid)
        if info is None:
            return
        if not force and job.status.resize_epoch < info.get("epoch", 0):
            return
        self._open_resize.pop(job.metadata.uid, None)
        self.tracer.close(info["ns"], info["name"], now)
        downtime = max(0.0, now - info["start"])
        self.metrics.observe_hist(
            "tpujob_resize_downtime_seconds",
            downtime,
            labels={"direction": info["direction"]},
        )
        # Goodput: resize downtime lost-seconds from the same close (see
        # _close_restart_span — one source per cause, never double-counted).
        self.metrics.inc(
            "tpujob_lost_seconds_total", downtime,
            labels={"cause": GOODPUT_RESIZE},
        )

    def _observe_first_step(self, job: TPUJob) -> None:
        """Fold the workload-reported first-step span into the TTFS
        histogram (once per job, at the terminal transition — the span
        arrives through the API seam while the job runs)."""
        uid = job.metadata.uid
        if uid in self._ttfs_observed:
            return
        try:
            span = self.store.get(
                KIND_SPAN, job.metadata.namespace,
                first_step_span_name(job.metadata.name, uid),
            )
        except NotFoundError:
            return
        except Exception:  # noqa: BLE001 — telemetry read is best-effort
            return
        self._ttfs_observed.add(uid)
        ttfs = max(0.0, span.start_time - job.metadata.creation_timestamp)
        self.metrics.observe_hist("tpujob_time_to_first_step_seconds", ttfs)
        # r11 split: the workload stamps warm="1" on the first-step span
        # when it ran from a warm slot or hit a compile cache tier. Two
        # separate families (not labels) so existing scrapers of the
        # aggregate family keep working unchanged.
        warm = (getattr(span, "attrs", None) or {}).get("warm") == "1"
        self.metrics.observe_hist(
            "tpujob_time_to_first_step_warm_seconds" if warm
            else "tpujob_time_to_first_step_cold_seconds",
            ttfs,
        )

    def _observe_ckpt_spans(self, job: TPUJob) -> None:
        """Fold workload-reported checkpoint spans into histograms (once
        per job, at terminal): ``checkpoint-save-stall`` spans — the step
        loop's staging stall per accepted async save — become
        ``tpujob_checkpoint_save_stall_seconds``; ``restore`` spans become
        ``tpujob_restore_seconds{source=peer|disk}``."""
        uid = job.metadata.uid
        if uid in self._ckpt_observed:
            return
        self._ckpt_observed.add(uid)
        try:
            spans = job_trace(self.store, job.metadata.namespace, job.metadata.name)
        except Exception:  # noqa: BLE001 — telemetry read is best-effort
            return
        for span in spans:
            dur = span.duration()
            if dur is None:  # still open — not a measurement
                continue
            if span.op == "checkpoint-save-stall":
                self.metrics.observe_hist(
                    "tpujob_checkpoint_save_stall_seconds", dur
                )
            elif span.op == "restore":
                source = span.attrs.get("source", "disk")
                self.metrics.observe_hist(
                    "tpujob_restore_seconds", dur,
                    labels={"source": "peer" if source == "peer" else "disk"},
                )

    def _observe_serve_spans(self, job: TPUJob) -> None:
        """Fold serve-job request spans (workloads/serve.py) into metrics
        once per job, at terminal: each ``first-token`` span's width
        (arrival -> first generated token) lands in
        ``tpujob_request_ttft_seconds``, and each ``finished`` span's
        ``tokens`` attr accumulates into ``tpujob_request_tokens_total``
        — the serving analogue of the checkpoint-span folding above."""
        uid = job.metadata.uid
        if uid in self._serve_observed:
            return
        self._serve_observed.add(uid)
        try:
            spans = job_trace(self.store, job.metadata.namespace, job.metadata.name)
        except Exception:  # noqa: BLE001 — telemetry read is best-effort
            return
        for span in spans:
            dur = span.duration()
            if dur is None:
                continue
            if span.op == "first-token":
                self.metrics.observe_hist(
                    "tpujob_request_ttft_seconds", max(0.0, dur)
                )
            elif span.op == "finished":
                try:
                    tokens = float(span.attrs.get("tokens", "0"))
                except ValueError:
                    tokens = 0.0
                if tokens > 0:
                    self.metrics.inc("tpujob_request_tokens_total", tokens)

    def _observe_goodput(self, job: TPUJob, end: float) -> None:
        """Fold the job's goodput decomposition into metrics, once per
        job at terminal: the telemetry/first-step-derived causes
        (compile-init, data-wait, ckpt-stall) increment
        ``tpujob_lost_seconds_total`` here — restart and resize already
        did at their span closes, the single source both the downtime
        histograms and the counter share — and the per-job ratio lands
        in the ``tpujob_goodput_ratio`` gauge."""
        uid = job.metadata.uid
        if uid in self._goodput_observed:
            return
        self._goodput_observed.add(uid)
        try:
            spans = job_trace(self.store, job.metadata.namespace, job.metadata.name)
            batches = job_telemetry(
                self.store, job.metadata.namespace, job.metadata.name
            )
        except Exception:  # noqa: BLE001 — telemetry read is best-effort
            return
        g = goodput_decomposition(
            spans, batches, job.metadata.creation_timestamp, end
        )
        for cause in (CAUSE_COMPILE_INIT, CAUSE_DATA_WAIT, CAUSE_CKPT_STALL):
            v = g["lost_s"].get(cause, 0.0)
            if v > 0:
                self.metrics.inc(
                    "tpujob_lost_seconds_total", v, labels={"cause": cause}
                )
        self.metrics.set_gauge(
            "tpujob_goodput_ratio", g["goodput_ratio"],
            labels={
                "namespace": job.metadata.namespace,
                "job": job.metadata.name,
            },
        )

    # ---- fleet ledger (r18) ---------------------------------------------

    def attach_ledger(self, ledger) -> None:
        """Attach the FleetLedger and sweep: fold any job the PREVIOUS
        incarnation drove terminal but died before folding (SIGKILL
        between the terminal status write and the fold). The ledger's
        durable uid dedupe makes the sweep idempotent — a job folded
        before the crash is skipped, so nothing double-counts. Runs at
        every operator start, then seeds the scheduler's deprioritized
        set from ledger host reputation so a host that ate jobs last
        hour starts flagged before any new job touches it."""
        self.ledger = ledger
        if ledger is None:
            return
        now = time.time()
        try:
            jobs = self.store.list(KIND_TPUJOB)
        except Exception:  # noqa: BLE001 — best-effort, like all obs
            jobs = []
        for job in jobs:
            if is_finished(job.status) and not ledger.has(job.metadata.uid):
                self._ledger_fold(job, job.status.completion_time or now)
        self._apply_host_reputation(now)

    def _ledger_fold(self, job: TPUJob, end: float) -> None:
        """Fold one terminal job into the fleet ledger, exactly once
        (the dedupe is the ledger's durable uid set, not process
        memory). Best-effort: a fold failure never fails a sync."""
        if self.ledger is None:
            return
        uid = job.metadata.uid
        if not uid or self.ledger.has(uid):
            return
        try:
            if self.ledger.fold(self._job_record(job, end)):
                self._apply_host_reputation(time.time())
        except Exception:  # noqa: BLE001
            log.exception("ledger fold failed for %s", job.key())

    def _job_record(self, job: TPUJob, end: float):
        """Build the compact JobRecord from surfaces that already exist
        (status counters, the trace, telemetry, live children). Runs
        BEFORE _delete_children so hosts-touched is still observable."""
        from tf_operator_tpu.obs.ledger import JobRecord

        uid = job.metadata.uid
        ns = job.metadata.namespace
        name = job.metadata.name
        phase = (
            "Succeeded"
            if has_condition(job.status, ConditionType.SUCCEEDED)
            else "Failed"
        )
        submit = job.metadata.creation_timestamp or job.status.start_time or end
        try:
            spans = job_trace(self.store, ns, name)
        except Exception:  # noqa: BLE001
            spans = []
        try:
            batches = job_telemetry(self.store, ns, name)
        except Exception:  # noqa: BLE001
            batches = []
        g = goodput_decomposition(spans, batches, submit, end)
        stalls = [
            s.duration() for s in spans
            if s.op == "checkpoint-save-stall" and s.duration() is not None
        ]
        ttfs_s, ttfs_kind = 0.0, ""
        try:
            fs = self.store.get(KIND_SPAN, ns, first_step_span_name(name, uid))
        except Exception:  # noqa: BLE001
            fs = None
        if fs is not None and fs.duration() is not None:
            ttfs_s = fs.duration()
            ttfs_kind = (
                "warm"
                if (getattr(fs, "attrs", None) or {}).get("warm") == "1"
                else "cold"
            )
        decisions = [
            dict(s.attrs or {}) for s in spans if s.op == "autopilot-decision"
        ][-16:]  # bounded: the record stays compact however long the run
        hosts = set()
        try:
            for p in self.store.list(
                KIND_PROCESS, namespace=ns,
                label_selector={LABEL_JOB_NAME: name},
            ):
                if p.spec.node_name:
                    hosts.add(p.spec.node_name)
        except Exception:  # noqa: BLE001
            pass
        for s in spans:  # restart/resize spans also name hosts they hit
            host = (getattr(s, "attrs", None) or {}).get("host", "")
            if host:
                hosts.add(host)
        return JobRecord(
            uid=uid,
            namespace=ns,
            name=name,
            queue=job.spec.scheduling.queue,
            priority_class=job.spec.scheduling.priority_class,
            job_class=job.spec.scheduling.job_class,
            phase=phase,
            submit_ts=submit,
            end_ts=end,
            wall_s=max(0.0, end - submit),
            restarts=job.status.restart_count,
            preemptions=job.status.preemption_count,
            hangs=job.status.hang_count,
            resizes=job.status.resize_count,
            last_restart_cause=job.status.last_restart_cause,
            lost_s={k: v for k, v in g["lost_s"].items() if v > 0},
            goodput_ratio=g["goodput_ratio"],
            ttfs_s=ttfs_s,
            ttfs_kind=ttfs_kind,
            save_stall_s=sum(stalls) / len(stalls) if stalls else 0.0,
            saves=len(stalls),
            step_time_s=self._last_step_time.get(uid, 0.0),
            autopilot_decisions=int(
                (job.status.autopilot or {}).get("decisions_total", 0)
            ),
            decisions=decisions,
            hosts=sorted(hosts),
        )

    def _apply_host_reputation(self, now: float) -> None:
        """Seed place_gang's soft-avoid set from ledger host reputation:
        a host that ate REPUTATION_THRESHOLD incident jobs inside the
        window starts deprioritized for the NEXT job — the same actuator
        the autopilot's deprioritize decision uses, fed by fleet memory
        instead of live telemetry."""
        if self.ledger is None:
            return
        try:
            flagged = self.ledger.host_reputation(now)
        except Exception:  # noqa: BLE001
            return
        if not flagged:
            return
        with self._sched_lock:
            for host in flagged:
                self.fleet.deprioritize_host(
                    host, now + AUTOPILOT_DEPRIORITIZE_TTL_S
                )

    def _check_stragglers(self, job: TPUJob, processes: List[Process]) -> None:
        """Evaluate new cross-rank telemetry windows for stragglers.

        A window is one batch seq with a report from EVERY reporting
        rank; each unevaluated complete window feeds the job's
        flap-damped tracker (median-ratio rule, obs/telemetry.py). A
        flag annotates the member's Process with ANNOTATION_SLOW_HOST,
        emits a SlowHost event (message carries window count and ratio —
        the bench's oracle), raises the by-host gauge, and enters the
        host into the fleet-wide deprioritized set place_gang consults
        for NEW gangs. Clean windows clear all four. Best-effort end to
        end — a telemetry read failure never fails a sync."""
        uid = job.metadata.uid
        # Disambiguation (r15): while the gang-progress watchdog has a
        # stall pending or declared, EVERY rank has stopped — that is a
        # hang, not a straggler; feeding the frozen windows to the
        # median-ratio tracker would burn its flap hysteresis on
        # non-movement and could flag arbitrary ranks on resume.
        wd = self._watchdogs.get(uid)
        if wd is not None and wd.stalled:
            return
        try:
            batches = job_telemetry(
                self.store, job.metadata.namespace, job.metadata.name
            )
        except Exception:  # noqa: BLE001
            return
        if not batches:
            return
        by_seq: Dict[int, Dict[int, float]] = {}
        ranks: set = set()
        rank_host: Dict[int, str] = {}
        for b in batches:
            ranks.add(b.rank)
            if b.host:
                rank_host[b.rank] = b.host
            if b.step_time_s > 0:
                by_seq.setdefault(b.seq, {})[b.rank] = b.step_time_s
        # Host binding from the scheduler beats the worker-reported
        # hostname (single-machine test rigs share one HOSTNAME).
        gang = self._gang_roles(job)
        by_role = {
            (p.spec.replica_type, p.spec.replica_index): p for p in processes
        }
        for i, r in enumerate(gang):
            p = by_role.get((r[0].value, r[1]))
            if p is not None and p.spec.node_name:
                rank_host[i] = p.spec.node_name
        last = self._straggler_seen_seq.get(uid, -1)
        # A window only counts once EVERY gang member has reported it —
        # gating on ranks-seen would evaluate (and burn tracker windows
        # on) early partial windows while slower ranks are still flushing.
        need = len(gang) if gang else len(ranks)
        complete = sorted(
            s for s, w in by_seq.items() if s > last and len(w) >= need
        )
        if not complete:
            return
        tracker = self._stragglers.setdefault(uid, StragglerTracker())
        for seq in complete:
            window = by_seq[seq]
            flagged, cleared = tracker.observe(window)
            # One shared struct (r16): the flag surface below and the
            # autopilot both read the tracker's typed host_risk()
            # snapshot instead of re-deriving ratios from the window.
            risk = tracker.host_risk()
            for rank in flagged:
                host = rank_host.get(rank, "")
                self._flag_slow_host(
                    job, rank, host, by_role, gang,
                    windows=tracker.windows_seen,
                    ratio=risk[rank].slow_ratio if rank in risk else 0.0,
                )
            for rank in cleared:
                self._clear_slow_host(job, rank, rank_host.get(rank, ""), by_role, gang)
        self._straggler_seen_seq[uid] = complete[-1]
        # Autopilot inputs (r16): the latest window's cross-rank median
        # step time, and the rank risk snapshot keyed by HOST (the unit
        # placement and migration act on). When several ranks share a
        # host, the riskiest rank speaks for it.
        self._last_step_time[uid] = statistics.median(
            by_seq[complete[-1]].values()
        )
        by_host: Dict[str, HostRisk] = {}
        for rank, r in tracker.host_risk().items():
            r.host = rank_host.get(rank, "") or f"rank-{rank}"
            prev = by_host.get(r.host)
            if prev is None or (r.flagged, r.slow_ratio) > (
                prev.flagged, prev.slow_ratio
            ):
                by_host[r.host] = r
        self._host_risk[uid] = by_host

    def _flag_slow_host(
        self,
        job: TPUJob,
        rank: int,
        host: str,
        by_role: Dict[Tuple[str, int], Process],
        gang: List[Tuple[ReplicaType, int]],
        windows: int,
        ratio: float,
    ) -> None:
        label = host or f"rank-{rank}"
        self.recorder.warning(
            job, ev.REASON_SLOW_HOST,
            f"rank {rank} on host {label} flagged as straggler after "
            f"{windows} windows (step time {ratio:.2f}x gang median); "
            f"deprioritizing host for new gangs",
        )
        self.metrics.set_gauge(
            "tpujob_straggler_host", 1.0, labels={"host": label}
        )
        if host:
            self._slow_hosts[host] = time.time()
        if rank < len(gang):
            r = gang[rank]
            p = by_role.get((r[0].value, r[1]))
            if p is not None:
                self._annotate_process(p, ANNOTATION_SLOW_HOST, label)

    def _clear_slow_host(
        self,
        job: TPUJob,
        rank: int,
        host: str,
        by_role: Dict[Tuple[str, int], Process],
        gang: List[Tuple[ReplicaType, int]],
    ) -> None:
        label = host or f"rank-{rank}"
        self.recorder.normal(
            job, ev.REASON_SLOW_HOST_CLEARED,
            f"rank {rank} on host {label} back under the straggler bar; "
            f"host eligible for new gangs again",
        )
        self.metrics.clear_gauge(
            "tpujob_straggler_host", labels={"host": label}
        )
        if host:
            self._slow_hosts.pop(host, None)
        if rank < len(gang):
            r = gang[rank]
            p = by_role.get((r[0].value, r[1]))
            if p is not None:
                self._annotate_process(p, ANNOTATION_SLOW_HOST, None)

    def _annotate_process(
        self, process: Process, key: str, value: Optional[str]
    ) -> None:
        """Set (value) or remove (None) one annotation on a child process,
        best-effort."""

        def mutate(cur):
            if value is None:
                if key not in cur.metadata.annotations:
                    return False
                cur.metadata.annotations.pop(key, None)
            else:
                if cur.metadata.annotations.get(key) == value:
                    return False
                cur.metadata.annotations[key] = value

        try:
            self.store.update_with_retry(
                KIND_PROCESS, process.metadata.namespace,
                process.metadata.name, mutate,
            )
        except Exception:  # noqa: BLE001 — the flag is advisory
            pass

    # ---- goodput autopilot (autopilot/, r16) ----------------------------

    def _autopilot_tick(
        self,
        job: TPUJob,
        gang: List[Tuple[ReplicaType, int]],
        active: List[Tuple[ReplicaType, int]],
        observed: Dict[Tuple[str, int], Process],
        exp_key: str,
    ) -> bool:
        """One decision step for a RUNNING gang: gather measured inputs,
        let the job's JobAutopilot decide, execute each decision through
        an existing actuator, and receipt it (autopilot-decision span +
        per-kind counter + status mirror). Returns True when a decision
        shrank the gang — the caller must end the sync like the
        failure-path shrink does. Best-effort end to end: a gather or
        actuator failure never fails a sync."""
        cfg = AutopilotConfig.from_run_policy(job.spec.run_policy.autopilot)
        if cfg is None:
            return False
        uid = job.metadata.uid
        ap = self._autopilots.get(uid)
        if ap is None:
            ap = self._autopilots[uid] = JobAutopilot(cfg)
        now = time.time()
        try:
            inputs = self._autopilot_inputs(job, active, cfg, now)
            decisions = ap.tick(inputs)
        except Exception:  # noqa: BLE001 — advisory loop, never sync-fatal
            log.exception("autopilot tick failed for %s", job.key())
            return False
        resized = False
        # One directive in flight at a time: a new cadence epoch is only
        # authored once the chief acked the previous one (applied_epoch
        # catches up), so epochs can't outrun the apply loop and the
        # final directive of a run is at most one epoch ahead of its ack.
        cc = job.status.checkpoint_cadence_directive or {}
        cadence_pending = int(cc.get("epoch", 0)) > int(cc.get("applied_epoch", 0))
        for d in decisions:
            if d.kind == DECISION_MIGRATE and resized:
                continue  # one resize per sync; the rest re-propose later
            if d.kind == DECISION_CADENCE and cadence_pending:
                continue  # previous epoch not applied yet; re-propose later
            try:
                acted = self._autopilot_execute(
                    job, d, active, observed, exp_key, now
                )
            except Exception:  # noqa: BLE001
                log.exception(
                    "autopilot %s failed for %s", d.kind, job.key()
                )
                continue
            if acted and d.kind == DECISION_MIGRATE:
                resized = True
        return resized

    def _autopilot_inputs(
        self,
        job: TPUJob,
        active: List[Tuple[ReplicaType, int]],
        cfg: AutopilotConfig,
        now: float,
    ) -> TickInputs:
        """Measured inputs for one decision step — every number comes
        from a surface that already exists (spans, telemetry windows,
        the status counters, the tracker snapshot)."""
        uid = job.metadata.uid
        save_stall_s, saves, restart_down = 0.0, 0, 0.0
        try:
            spans = job_trace(
                self.store, job.metadata.namespace, job.metadata.name
            )
        except Exception:  # noqa: BLE001 — telemetry read is best-effort
            spans = []
        stalls = [
            s.duration() for s in spans
            if s.op == "checkpoint-save-stall" and s.duration() is not None
        ]
        if stalls:
            saves = len(stalls)
            save_stall_s = sum(stalls) / saves
        restart_down = sum(
            s.duration() or 0.0 for s in spans
            if s.op in ("restart", "hang") and s.duration() is not None
        )
        # Fleet-level TTFS cold/warm split (warm-pool sizing input): fold
        # each job's first-step span exactly once, as soon as it exists.
        if uid not in self._ap_ttfs_seen:
            try:
                span = self.store.get(
                    KIND_SPAN, job.metadata.namespace,
                    first_step_span_name(job.metadata.name, uid),
                )
            except Exception:  # noqa: BLE001 — not marked yet
                span = None
            if span is not None:
                self._ap_ttfs_seen.add(uid)
                if (getattr(span, "attrs", None) or {}).get("warm") == "1":
                    self._ttfs_warm += 1
                else:
                    self._ttfs_cold += 1
        directive = job.status.checkpoint_cadence_directive or {}
        epoch = int(directive.get("epoch", 0))
        if epoch:
            current_every = int(directive.get("checkpoint_every", 0))
        else:
            current_every = int(
                (job.spec.workload or {}).get("checkpoint_every", 0)
            )
        wd = self._watchdogs.get(uid)
        failures = (
            job.status.restart_count
            + job.status.preemption_count
            + job.status.hang_count
        )
        # Fleet prior (r18): the ledger cohort's MTBF, computed once per
        # job and pinned in _prior_cache so mid-run folds never shift a
        # live job's estimate. (0.0, 0, 0) = no usable history: the tick
        # falls through to the plain own-data path.
        prior_mtbf_s, prior_failures, prior_jobs = 0.0, 0, 0
        if cfg.use_fleet_priors and self.ledger is not None:
            cached_prior = self._prior_cache.get(uid)
            if cached_prior is None:
                from tf_operator_tpu.obs.priors import cadence_prior

                try:
                    p = cadence_prior(
                        self.ledger,
                        queue=job.spec.scheduling.queue,
                        workload_class=job.spec.scheduling.job_class,
                    )
                except Exception:  # noqa: BLE001 — advisory
                    p = None
                cached_prior = (
                    (p.mtbf_s, p.failures, p.jobs)
                    if p is not None
                    else (0.0, 0, 0)
                )
                self._prior_cache[uid] = cached_prior
            prior_mtbf_s, prior_failures, prior_jobs = cached_prior
        submit = job.metadata.creation_timestamp or job.status.start_time or now
        return TickInputs(
            now=now,
            step_time_s=self._last_step_time.get(uid, 0.0),
            save_stall_s=save_stall_s,
            saves_observed=saves,
            failures=failures,
            run_elapsed_s=max(0.0, now - submit),
            restart_downtime_s=restart_down,
            current_every=current_every,
            directive_epoch=epoch,
            directive_acked=int(directive.get("applied_epoch", 0)) >= epoch,
            prior_mtbf_s=prior_mtbf_s,
            prior_failures=prior_failures,
            prior_jobs=prior_jobs,
            host_risk=dict(self._host_risk.get(uid, {})),
            watchdog_stalled=wd is not None and wd.stalled,
            elastic_ok=(
                job.spec.run_policy.elastic and _elastic_mesh_ok(job)
            ),
            world_size=len(active),
            min_world_size=2,
            cold_starts=self._ttfs_cold,
            warm_starts=self._ttfs_warm,
            warmpool_current=self._warmpool_target,
        )

    def _autopilot_execute(
        self,
        job: TPUJob,
        d: Decision,
        active: List[Tuple[ReplicaType, int]],
        observed: Dict[Tuple[str, int], Process],
        exp_key: str,
        now: float,
    ) -> bool:
        """Run one decision through its EXISTING actuator (the no-new-
        actuators rule, docs/design.md §4.12) and receipt it."""
        acted = False
        if d.kind == DECISION_CADENCE:
            # Actuator: the checkpoint-cadence status directive — same
            # monotonic-epoch protocol as profiling; the chief applies it
            # at the next step boundary and acks back.
            cur = job.status.checkpoint_cadence_directive or {}
            epoch = int(cur.get("epoch", 0)) + 1
            directive = {
                "epoch": epoch,
                "checkpoint_every": d.checkpoint_every,
                "time": now,
            }
            # Carry the chief's last ack forward: applied_epoch means
            # "last epoch the chief applied", which legitimately trails
            # the live epoch by one while this directive is in flight.
            # Without this the new-epoch wholesale write would erase the
            # ack history the round-trip invariant reads.
            if "applied_epoch" in cur:
                directive["applied_epoch"] = int(cur["applied_epoch"])
                if "applied_step" in cur:
                    directive["applied_step"] = int(cur["applied_step"])
            job.status.checkpoint_cadence_directive = directive
            d.attrs["epoch"] = str(epoch)
            acted = True
        elif d.kind == DECISION_DEPRIORITIZE:
            # Actuator: the fleet scheduler's deprioritized-host registry,
            # unioned into place_gang's soft-avoid set for NEW gangs.
            if d.host:
                with self._sched_lock:
                    self.fleet.deprioritize_host(
                        d.host, now + AUTOPILOT_DEPRIORITIZE_TTL_S
                    )
                acted = True
        elif d.kind == DECISION_MIGRATE:
            # Actuator: the r12 elastic shrink, aimed at the risky host's
            # LIVE members before the watchdog (or the host) kills them.
            # All of _try_resize_shrink's refusals apply unchanged —
            # chief on the host, no survivor, non-elastic mesh — so a
            # refused migrate simply falls back to deprioritize-only.
            victims = [
                observed[(r[0].value, r[1])]
                for r in active
                if (r[0].value, r[1]) in observed
                and observed[(r[0].value, r[1])].spec.node_name == d.host
            ]
            if victims:
                acted = self._try_resize_shrink(
                    job, active, observed, victims, exp_key,
                    CAUSE_AUTOPILOT_MIGRATE,
                )
        elif d.kind == DECISION_WARMPOOL:
            # Actuator: the warm-pool target annotation on Host objects;
            # each HostAgent's heartbeat loop applies it locally.
            acted = self._annotate_warmpool_targets(d.warmpool_target)
            if acted:
                self._warmpool_target = d.warmpool_target
        if not acted:
            return False
        # The receipt: span (authoritative, carries the justifying
        # numbers), per-kind counter, status mirror, human event.
        self.metrics.inc(
            "tpujob_autopilot_decisions_total", labels={"kind": d.kind}
        )
        seq = int((job.status.autopilot or {}).get("decisions_total", 0)) + 1
        self.tracer.record(
            job.metadata.namespace, job.metadata.name, job.metadata.uid,
            "autopilot-decision", now, now,
            attrs={
                "kind": d.kind, "action": d.action, "track": "autopilot",
                **d.attrs,
            },
            name=f"{self._span_name(job, 'autopilot')}-{d.kind}-{seq}",
        )
        job.status.autopilot = {
            "last_decision": {
                "kind": d.kind, "action": d.action, "time": now, **d.attrs,
            },
            "decisions_total": seq,
            "active_checkpoint_every": (
                d.checkpoint_every if d.kind == DECISION_CADENCE else int(
                    (job.status.checkpoint_cadence_directive or {}).get(
                        "checkpoint_every", 0
                    )
                    or (job.spec.workload or {}).get("checkpoint_every", 0)
                    or 0
                )
            ),
        }
        self.recorder.normal(
            job, ev.REASON_AUTOPILOT, f"autopilot: {d.action}"
        )
        # The migrate path already wrote status inside _try_resize_shrink,
        # but the receipt fields above landed after that write.
        self._write_status(job)
        return True

    def _annotate_warmpool_targets(self, target: int) -> bool:
        """Stamp the warm-pool slot target on every registered Host; the
        agents' heartbeat loops pick it up. Returns True when at least
        one host was annotated."""
        try:
            hosts = self.store.list(KIND_HOST)
        except Exception:  # noqa: BLE001 — advisory
            return False
        wrote = False
        for h in hosts:
            def mutate(cur, value=str(int(target))):
                if cur.metadata.annotations.get(
                    ANNOTATION_WARMPOOL_TARGET
                ) == value:
                    return False
                cur.metadata.annotations[ANNOTATION_WARMPOOL_TARGET] = value
            try:
                self.store.update_with_retry(
                    KIND_HOST, h.metadata.namespace, h.metadata.name, mutate
                )
                wrote = True
            except Exception:  # noqa: BLE001 — advisory
                continue
        return wrote

    def _depot_peers(self) -> List[str]:
        """Depot URLs of hosts that can serve peer warm restores: every
        Ready or Draining host announcing ``spec.depot_url``. Draining
        hosts are deliberately included — a preempted gang's replacement
        pulls from exactly those hosts while they drain."""
        try:
            hosts = self.store.list(KIND_HOST)
        except Exception:  # noqa: BLE001 — advisory hint; never block create
            return []
        urls = {
            h.spec.depot_url
            for h in hosts
            if h.spec.depot_url and h.status.phase != HostPhase.NOT_READY
        }
        return sorted(urls)

    # ---- actions --------------------------------------------------------

    def _delete_child(self, process: Process) -> None:
        """Delete one child process, honoring the controller/kubelet split:
        a host-bound process is deleted from the store only — its agent
        observes DELETED and kills the local child; an unbound one goes
        through the local backend, which kills and deletes."""
        if process.spec.node_name:
            try:
                self.store.delete(
                    KIND_PROCESS, process.metadata.namespace, process.metadata.name
                )
            except NotFoundError:
                return  # already gone — nothing was deleted; don't count it
            self.metrics.inc("tpujob_processes_deleted_total")
        else:
            self.process_control.delete_process(
                process.metadata.namespace, process.metadata.name
            )
            self.metrics.inc("tpujob_processes_deleted_total")

    def _policy_for(self, job: TPUJob, process: Process) -> RestartPolicy:
        try:
            rs = job.spec.replica_specs.get(ReplicaType(process.spec.replica_type))
        except ValueError:
            rs = None
        return rs.restart_policy if rs and rs.restart_policy else RestartPolicy.EXIT_CODE

    def _create_processes(
        self,
        job: TPUJob,
        roles: List[Tuple[ReplicaType, int]],
        exp_key: str,
        observed: Optional[Dict[Tuple[str, int], Process]] = None,
        resize_epoch: int = 0,
    ) -> bool:
        """Create the given members. Returns True when the batch proceeded
        to creation, False when admission/placement blocked it.

        ``resize_epoch`` (r12) marks this batch as an elastic re-grow at
        that epoch: the created members get ENV_RESIZE_EPOCH stamped to it,
        and a placement failure returns False WITHOUT parking the job in
        QUEUED — a running shrunk gang must never be demoted because its
        re-grow attempt found no capacity yet."""
        gang = self._gang_roles(job)
        num_processes = len(gang)
        port = self._rendezvous_port(job)
        chief_type, chief_idx = self._chief_role(job)
        chief_name = self._process_name(job, chief_type, chief_idx)
        # Warm-restart discovery, once per create batch: the latest step
        # already checkpointed under the job's checkpoint_dir (0 if none /
        # no checkpointing). A cheap filesystem scan — no orbax import.
        ckpt_dir = job.spec.workload.get("checkpoint_dir")
        resume_step = latest_checkpoint_step(str(ckpt_dir)) if ckpt_dir else 0
        restore_peers = self._depot_peers() if ckpt_dir else []

        # Build every Process object first so the chief's host can be
        # resolved once and injected into ALL members' coordinator address —
        # resolving per-member would point each process at its own host.
        procs: List[Process] = []
        for rtype, index in roles:
            rs = job.spec.replica_specs[rtype]
            name = self._process_name(job, rtype, index)
            labels = {
                **self._labels_for(job),
                LABEL_REPLICA_TYPE: rtype.value,
                LABEL_REPLICA_INDEX: str(index),
            }
            is_gang = (rtype, index) in gang
            rank = gang.index((rtype, index)) if is_gang else 0
            # Admin accelerator env first (defaults), user template env on
            # top, rendezvous identity last (helpers.go:50-104 analogue).
            # LD_LIBRARY_PATH path-merges instead of clobbering: admin
            # library dirs (libtpu/driver) are prepended to the template's
            # own value (or the ambient one) by accelerator_env — the
            # reference appends admin volumes unconditionally.
            admin_env = accelerator_env(
                self.controller_config,
                job.spec.topology.slice_type,
                base_ld_library_path=rs.template.env.get("LD_LIBRARY_PATH", ""),
            )
            env = dict(admin_env)
            tmpl_env = dict(rs.template.env)
            if "LD_LIBRARY_PATH" in admin_env:
                tmpl_env.pop("LD_LIBRARY_PATH", None)  # already merged in
            env.update(tmpl_env)
            mesh = job.spec.topology.mesh_axes
            env.update(
                {
                    ENV_NUM_PROCESSES: str(num_processes if is_gang else 1),
                    ENV_PROCESS_ID: str(rank),
                    ENV_MESH_AXES: json.dumps(mesh),
                    ENV_WORKLOAD: json.dumps(job.spec.workload),
                }
            )
            if job.spec.topology.dcn_mesh_axes:
                env[ENV_DCN_MESH_AXES] = json.dumps(job.spec.topology.dcn_mesh_axes)
            # Trace context: the job uid is the trace id, stable across
            # gang restarts — agent/backend and workload spans join the
            # same timeline the controller writes into (obs/).
            env[ENV_TRACE_ID] = job.metadata.uid
            if resize_epoch or job.status.resize_epoch:
                # Elastic contract (rendezvous/env.py): the epoch at
                # creation. The env of SURVIVING members is frozen — the
                # live truth stays the status directive; this tells a
                # created member it joins mid-resize.
                env[ENV_RESIZE_EPOCH] = str(
                    resize_epoch or job.status.resize_epoch
                )
            if ckpt_dir:
                # Warm-restart contract (rendezvous/env.py): a recreated
                # gang is told the directory and the step it will resume
                # from; 0 marks the cold first incarnation. The trainer's
                # authoritative resume stays latest_step() on disk.
                env[ENV_CHECKPOINT_DIR] = str(ckpt_dir)
                env[ENV_RESUME_STEP] = str(resume_step)
                if restore_peers:
                    # Peer warm-restore hint: depot URLs of live hosts a
                    # recreated gang may pull committed shards from before
                    # touching disk (rendezvous/statechannel.py). Advisory —
                    # the workload's decision order still falls back to
                    # disk when no peer holds a step >= the disk step.
                    env[ENV_RESTORE_PEERS] = json.dumps(restore_peers)
            chips = rs.template.chips_per_process or job.spec.topology.chips_per_host
            procs.append(
                Process(
                    metadata=ObjectMeta(
                        name=name,
                        namespace=job.metadata.namespace,
                        labels=labels,
                        **as_owner(job),
                    ),
                    spec=ProcessSpec(
                        job_name=job.metadata.name,
                        replica_type=rtype.value,
                        replica_index=index,
                        entrypoint=rs.template.entrypoint,
                        args=list(rs.template.args),
                        env=env,
                        chips=chips if is_gang else rs.template.chips_per_process,
                        port=port if (rtype, index) == (chief_type, chief_idx) else 0,
                        workdir=rs.template.workdir,
                    ),
                )
            )

        # Gang-atomic host placement (multi-host mode): bind every process
        # to a Ready host BEFORE any create — a partially-placed gang must
        # never exist (SURVEY.md §7 hard part b). The scheduler lock spans
        # admission through creation so concurrent workers cannot promise
        # the same free chips — or the same quota headroom — to two jobs
        # (uncontended-lock cost in single-host mode is negligible).
        # Preemption handoff, second half: the victim's quota releases
        # only once its drained gang is observably gone from the store —
        # the release kicks the preemptor's admission, so the preemptor's
        # gang is created strictly after the victim's chips freed.
        if self.fleet.draining(job.key()):
            still_live = any(
                (p := (observed or {}).get((r[0].value, r[1]))) is not None
                and not p.is_finished()
                for r in gang
            )
            if not still_live:
                self._release_job(job.key())

        placement: Dict[str, Any] = {}
        blocked: Optional[fleetsched.Decision] = None
        sched_reason = ""
        with self._sched_lock:
            managed = self.scheduler.managed()
            t_place = time.time()
            decision = self.fleet.admit(job)
            if decision.action != fleetsched.ADMIT:
                blocked = decision
            elif managed:
                # Rank-keyed placement: a member's host slot is its gang
                # rank mod num_hosts, and slots already holding LIVE bound
                # members stay pinned to those hosts — a partial recreate
                # keeps every member's topology position.
                # Over-spec elastic members (r19) ride on loaned idle
                # chips OUTSIDE the slice shape: no gang rank (the spec
                # slots are exactly full), no slot pin — place_gang's
                # overflow path parks them on any host with room.
                spec_workers = (
                    job.spec.replica_specs.get(ReplicaType.WORKER)
                )
                spec_replicas = (
                    (spec_workers.replicas or 1) if spec_workers else 0
                )
                overspec_names = {
                    self._process_name(job, r[0], r[1])
                    for r in gang
                    if (job.status.overspec_workers or 0) > 0
                    and r[0] is ReplicaType.WORKER
                    and r[1] >= spec_replicas
                }
                ranks = {
                    self._process_name(job, r[0], r[1]): i
                    for i, r in enumerate(gang)
                    if self._process_name(job, r[0], r[1])
                    not in overspec_names
                }
                bound_slots: Dict[int, str] = {}
                want_hosts = max(1, job.spec.topology.num_hosts)
                for i, r in enumerate(gang):
                    if self._process_name(job, r[0], r[1]) in overspec_names:
                        continue
                    live = (observed or {}).get((r[0].value, r[1]))
                    if live is not None and not live.is_finished() and live.spec.node_name:
                        bound_slots[i % want_hosts] = live.spec.node_name
                try:
                    placement = self.scheduler.place_gang(
                        job, procs, ranks=ranks, bound_slots=bound_slots,
                        ttl=self._job_heartbeat_ttl(job),
                        reserved=self.fleet.reserved_for_others(job),
                        overflow=overspec_names or None,
                        # Straggler-flagged hosts plus the autopilot's
                        # TTL-bounded deprioritizations (r16) — both soft:
                        # the scheduler prefers other hosts but still
                        # places here when nothing else fits.
                        deprioritized=set(self._slow_hosts)
                        | self.fleet.deprioritized_hosts(time.time()),
                    )
                except SchedulingError as exc:
                    self.recorder.warning(
                        job, ev.REASON_FAILED_SCHEDULING, str(exc)
                    )
                    if resize_epoch:
                        # Elastic re-grow probe found no capacity: the
                        # job keeps running shrunk; the resync loop
                        # retries. on_unplaceable would park it in the
                        # admission queue — wrong for a RUNNING gang.
                        blocked = fleetsched.Decision(
                            fleetsched.WAIT, reason=str(exc)
                        )
                    else:
                        # No atomic placement: park in the admission queue
                        # (QUEUED condition) instead of raising into the
                        # workqueue rate limiter — the old hot loop of
                        # SchedulingError retries. The fleet scheduler may
                        # answer with victims to drain (preempt-by-priority)
                        # or a host reservation that keeps backfillers from
                        # starving this gang; either way a release or the
                        # periodic resync retries the placement.
                        blocked = self.fleet.on_unplaceable(job)
                    sched_reason = str(exc)
                else:
                    for p in procs:
                        p.spec.node_name = placement[p.metadata.name].metadata.name
                    # Trace: the placement decision itself (scheduler span).
                    self.tracer.record(
                        job.metadata.namespace, job.metadata.name,
                        job.metadata.uid, "placement", t_place, time.time(),
                        attrs={
                            "hosts": ",".join(sorted(
                                {h.metadata.name for h in placement.values()}
                            )),
                            "processes": str(len(procs)),
                            "track": "placement",
                        },
                        component=COMPONENT_SCHEDULER,
                    )
            if blocked is None:
                # Quota commits only AFTER placement succeeded, so a
                # placement failure never leaks quota.
                self.fleet.commit(job)
                now = time.time()
                self._kick_aot(job)  # overlap compile with placement+spawn
                self._mark_admitted(job, now)
                self._mark_scheduled(job, now)
                self._bind_and_create(
                    job, procs, placement, managed, port, chief_name,
                    exp_key, resume_step,
                )
        if blocked is not None:
            if resize_epoch:
                # Elastic re-grow attempt blocked: never fail, preempt for,
                # or queue a gang that is running shrunk.
                return False
            # Handled OUTSIDE the lock: _finish and _queue_job re-enter
            # paths (_release_job) that take the same non-reentrant lock.
            if blocked.action == fleetsched.FAIL:
                self._fail_job(job, "TPUJobQuotaUnsatisfiable", blocked.reason)
                self._finish(job)
                return False
            if blocked.victims:
                if blocked.action == fleetsched.RECLAIM:
                    # Quota pressure reclaims over-spec loans FIRST —
                    # the victims shrink back to spec (no drain) and the
                    # freed chips re-kick this job's admission.
                    self._request_overspec_reclaims(job, blocked.victims)
                else:
                    self._request_preemptions(job, blocked.victims)
            self._queue_job(job, sched_reason or blocked.reason)
            return False
        return True

    def _bind_and_create(
        self,
        job: TPUJob,
        procs: List[Process],
        placement: Dict[str, Any],
        managed: bool,
        port: int,
        chief_name: str,
        exp_key: str,
        resume_step: int,
    ) -> None:
        """Resolve the chief address, stamp rendezvous env, and create the
        gang. Called with _sched_lock held — creation must complete before
        another worker reads chip usage, or two gangs get the same chips."""
        # Chief host: prefer the existing rendezvous Endpoint (the chief
        # may already be running and we are only recreating lost
        # members); then the chief's bound host; then the resolver. An
        # endpoint owned by a DEAD incarnation (delete → same-name
        # recreate race) is garbage, not truth: collect it instead.
        chief_host: Optional[str] = None
        try:
            ep = self.store.get(
                KIND_ENDPOINT, job.metadata.namespace,
                f"{job.metadata.name}-rendezvous",
            )
            if ep.metadata.owner_uid not in (None, job.metadata.uid):
                try:
                    self.store.delete(
                        KIND_ENDPOINT, ep.metadata.namespace, ep.metadata.name
                    )
                except NotFoundError:
                    pass
                raise NotFoundError(ep.metadata.key())
            chief_host = ep.address.host
        except NotFoundError:
            if chief_name in placement:
                chief_host = placement[chief_name].spec.address
            else:
                for p in procs:
                    if p.metadata.name == chief_name:
                        chief_host = self.host_resolver(p)
                        break
        if chief_host is None and managed:
            # Partial recreate with no Endpoint and a chief that already
            # exists elsewhere: resolve through the chief's node binding
            # — defaulting to loopback here would point the recreated
            # members' coordinator address at themselves.
            try:
                cp = self.store.get(
                    KIND_PROCESS, job.metadata.namespace, chief_name
                )
                if cp.spec.node_name:
                    chief_host = self.store.get(
                        KIND_HOST, "default", cp.spec.node_name
                    ).spec.address
            except NotFoundError:
                pass
        if chief_host is None:
            chief_host = "127.0.0.1"
        for p in procs:
            p.spec.env[ENV_COORDINATOR_ADDRESS] = f"{chief_host}:{port}"
            if self.api_url:
                p.spec.env.setdefault(ENV_API_SERVER, self.api_url)
            if self.compile_cache_url:
                # Fleet compile-cache tier (cachesvc/): enable() turns this
                # into a read-through/write-back remote cache.
                p.spec.env.setdefault(ENV_COMPILE_CACHE, self.compile_cache_url)

        self.expectations.expect_creations(exp_key, len(procs))
        created = 0
        t_create = time.time()
        try:
            for proc in procs:
                try:
                    if proc.spec.node_name:
                        # Bound: create the object only — the host's
                        # agent launches it (controller/kubelet split).
                        self.store.create(proc)
                    else:
                        self.process_control.create_process(proc)
                except AlreadyExistsError:
                    self.expectations.creation_failed(exp_key)
                else:
                    created += 1
                    self.metrics.inc("tpujob_processes_created_total")
                    self.recorder.normal(
                        job, ev.REASON_SUCCESSFUL_CREATE,
                        f"created process {proc.metadata.name}"
                        + (f" on {proc.spec.node_name}" if proc.spec.node_name else ""),
                    )
                if proc.metadata.name == chief_name:
                    self._ensure_endpoint(job, chief_name, chief_host, port)
        except Exception as exc:
            # Roll back unobserved expectations so the job isn't stuck
            # waiting for creations that will never happen.
            for _ in range(len(procs) - created):
                self.expectations.creation_failed(exp_key)
            self.recorder.warning(job, ev.REASON_FAILED_CREATE, str(exc))
            raise
        if created:
            # Trace: one gang-create span per create batch (restarts
            # produce one each; the warm-restart step is an attr).
            self.tracer.record(
                job.metadata.namespace, job.metadata.name,
                job.metadata.uid, "gang-create", t_create, time.time(),
                attrs={
                    "processes": str(created),
                    "resume_step": str(resume_step),
                    "track": "gang-create",
                },
            )

    # ---- fleet-scheduler actions ----------------------------------------

    def _kick_aot(self, job: TPUJob) -> None:
        """AOT-at-admission (cachesvc/aot.py): the moment the fleet
        scheduler decides — admit or park — start compiling the workload's
        declared step function so the compile overlaps the scheduling/
        placement/spawn wait and the gang finds a warm cache at
        ``compile_cache.enable()``. O(enqueue) on the sync path; no-op
        without a hosted cachesvc or a workload AOT declaration."""
        if self.aot is None:
            return
        try:
            if self.aot.kick(job.metadata.namespace, job.metadata.name,
                             job.metadata.uid, job.spec.workload):
                self.metrics.inc("tpujob_aot_compiles_kicked_total")
        except Exception:  # noqa: BLE001 — a broken AOT pool never fails a sync
            log.exception("aot kick for %s failed", job.key())

    def _aot_span(self, namespace: str, job_name: str, trace_id: str,
                  key: str, mode: str, start: float, end: float,
                  ok: bool) -> None:
        """on_done callback for the AOT pool: land the aot-compile span in
        the job timeline (width = the compile cost that was overlapped
        with scheduling) and count the publish outcome."""
        self.metrics.inc(
            "tpujob_aot_compiles_published_total" if ok
            else "tpujob_aot_compiles_failed_total"
        )
        self.tracer.record(
            namespace, job_name, trace_id, "aot-compile", start, end,
            attrs={
                "key": key[:16], "mode": mode,
                "published": str(ok).lower(), "track": "aot-compile",
            },
            component=COMPONENT_SCHEDULER,
        )

    def _queue_job(self, job: TPUJob, reason: str) -> None:
        """Park the job in the QUEUED condition and open the ``queued``
        trace span (admission-queue entry → admitted). Repeats update the
        condition message in place — no event/span churn while waiting."""
        first = not has_condition(job.status, ConditionType.QUEUED)
        # A parked job is the best AOT candidate: the whole queue wait is
        # compile-overlap budget (idempotent — kick() dedupes per job/key).
        self._kick_aot(job)
        message = reason or "waiting in fleet-scheduler admission queue"
        set_condition(
            job.status,
            new_condition(ConditionType.QUEUED, ev.REASON_JOB_QUEUED, message),
        )
        if first:
            self.recorder.normal(job, ev.REASON_JOB_QUEUED, message)
            uid = job.metadata.uid
            if uid not in self._open_queued:
                sched = job.spec.scheduling
                queue = sched.queue or "default"
                priority = sched.priority_class or "none"
                # One span per queue visit: a preempted job that re-queues
                # gets a fresh span (the counters moved), not a dedupe hit.
                n = job.status.restart_count + job.status.preemption_count
                name = self._span_name(job, f"queued-{n}")
                start = time.time()
                if self.tracer.record(
                    job.metadata.namespace, job.metadata.name, uid,
                    "queued", start, 0.0,
                    attrs={
                        "reason": message[:200], "queue": queue,
                        "priority": priority, "track": "queued",
                    },
                    name=name, component=COMPONENT_SCHEDULER,
                ) is not None:
                    self._open_queued[uid] = {
                        "ns": job.metadata.namespace, "name": name,
                        "start": start, "queue": queue, "priority": priority,
                    }
        self._write_status(job)

    def _mark_admitted(self, job: TPUJob, now: float) -> None:
        """The fleet scheduler admitted the job: close the open ``queued``
        span — its width is the admission-queue wait, observed into the
        per-queue/per-priority histogram — and drop the QUEUED condition."""
        uid = job.metadata.uid
        info = self._open_queued.pop(uid, None)
        if info is not None:
            self.tracer.close(info["ns"], info["name"], now)
            self.metrics.observe_hist(
                "tpujob_queue_wait_seconds",
                max(0.0, now - info["start"]),
                labels={"queue": info["queue"], "priority": info["priority"]},
            )
        clear_condition(job.status, ConditionType.QUEUED)

    def _request_preemptions(self, job: TPUJob, victims: List[str]) -> None:
        """Stamp the preempt annotation on each victim; the victim's own
        sync drains its gang gracefully (cause ``preemption``) and releases
        its quota. Idempotent: a victim already under a notice — or already
        finished — is skipped."""
        stamped = []
        for vkey in victims:
            ns, _, name = vkey.partition("/")

            def _stamp(fresh):
                if is_finished(fresh.status):
                    return False
                if fresh.metadata.annotations.get(ANNOTATION_PREEMPT):
                    return False  # already being drained
                fresh.metadata.annotations[ANNOTATION_PREEMPT] = job.key()

            if self.store.update_with_retry(KIND_TPUJOB, ns, name, _stamp) is not None:
                stamped.append(vkey)
                self.metrics.inc("tpujob_preemptions_requested_total")
                self._enqueue(vkey)
        if stamped:
            self.recorder.normal(
                job, ev.REASON_JOB_PREEMPTING,
                f"requested preemption of {len(stamped)} lower-priority "
                f"job(s): {', '.join(sorted(stamped))}",
            )

    def _request_overspec_reclaims(
        self, job: TPUJob, victims: List[str]
    ) -> None:
        """Stamp the reclaim annotation on each over-spec holder; the
        holder's own sync shrinks it back to spec through the resize
        protocol and the loan returns once its over-spec members exit.
        Idempotent like _request_preemptions."""
        stamped = []
        for vkey in victims:
            ns, _, name = vkey.partition("/")

            def _stamp(fresh):
                if is_finished(fresh.status):
                    return False
                if fresh.metadata.annotations.get(ANNOTATION_RECLAIM):
                    return False  # already being reclaimed
                fresh.metadata.annotations[ANNOTATION_RECLAIM] = job.key()

            if self.store.update_with_retry(KIND_TPUJOB, ns, name, _stamp) is not None:
                stamped.append(vkey)
                self.metrics.inc("tpujob_overspec_reclaims_requested_total")
                self._enqueue(vkey)
        if stamped:
            self.recorder.normal(
                job, ev.REASON_JOB_PREEMPTING,
                f"requested over-spec reclaim from {len(stamped)} elastic "
                f"job(s): {', '.join(sorted(stamped))}",
            )

    def _release_job(self, key: str) -> None:
        """Release a finished/deleted/preempted job's quota and re-kick the
        admission-queue heads. ONE lock hold for both steps — _sched_lock
        is non-reentrant, so release() and next_queued() must not be split
        across nested acquisitions."""
        with self._sched_lock:
            released = self.fleet.release(key)
            keys = self.fleet.next_queued() if released else []
        for k in keys:
            self._enqueue(k)

    def _ensure_endpoint(self, job: TPUJob, target: str, host: str, port: int) -> None:
        name = f"{job.metadata.name}-rendezvous"
        try:
            self.store.create(
                Endpoint(
                    metadata=ObjectMeta(
                        name=name,
                        namespace=job.metadata.namespace,
                        labels=self._labels_for(job),
                        **as_owner(job),
                    ),
                    address=EndpointAddress(host=host, port=port),
                    target_process=target,
                )
            )
        except AlreadyExistsError:
            pass

    def _restart_gang(
        self,
        job: TPUJob,
        gang: List[Tuple[ReplicaType, int]],
        observed: Dict[Tuple[str, int], Process],
        exp_key: str,
        cause: str = CAUSE_FAILURE,
    ) -> None:
        """Whole-gang restart: delete every existing gang process; the next
        sync (after deletions are observed) recreates them.

        ``cause`` distinguishes graceful preemption restarts (host drain:
        counted in status.preemption_count, exempt from backoff_limit) from
        failure/node-lost restarts (counted in restart_count, which feeds
        backoff_limit)."""
        targets = [observed[(r[0].value, r[1])] for r in gang if (r[0].value, r[1]) in observed]
        # Escalate to a FULL gang restart even with gang_restart=False when
        # (a) the chief died — every member's coordinator address points at
        # it, so recreating only the chief (possibly on a new host) would
        # leave survivors rendezvousing with a dead address forever — or
        # (b) any failure is a declared loss (NodeLost / agent restart):
        # the "failed" process may still be ALIVE as a zombie, and a
        # partial restart would hand its replacement the same rendezvous
        # port and rank, letting both join the live chief's gang — or
        # (c) a preemption drain: the gang moves off the draining host
        # atomically, so every member relocates together.
        chief = self._chief_role(job)
        full = (
            job.spec.run_policy.gang_restart
            or cause is CAUSE_PREEMPTION
            # A hang wedges every rank in the same dead collective — no
            # member has FAILED, so a partial restart would select zero
            # targets; the whole (still-alive) gang goes down together.
            or cause is CAUSE_HANG
            or _failed(observed.get((chief[0].value, chief[1])))
            or any(_failed(p) and p.status.node_lost for p in targets)
        )
        if not full:
            targets = [p for p in targets if _failed(p)]
        job.status.last_restart_cause = cause
        if cause is CAUSE_PREEMPTION:
            job.status.preemption_count += 1
            n = job.status.preemption_count
            message = (
                f"gang preemption restart #{n} (checkpoint-resumed, "
                "not counted against backoff)"
            )
            reason = ev.REASON_JOB_PREEMPTED
        else:
            # restart_count was freshened against the store by _reconcile
            # just before the backoff_limit check; only the increment
            # happens here.
            job.status.restart_count += 1
            message = f"gang restart #{job.status.restart_count} ({cause})"
            reason = ev.REASON_JOB_RESTARTING
        self.metrics.inc("tpujob_gang_restarts_total")
        self.metrics.inc(
            "tpujob_gang_restarts_by_cause_total", labels={"cause": cause}
        )
        # Trace: open the restart span NOW — the gang is going down; it
        # closes when the recreated gang reports RUNNING again, so its
        # width is the job's actual recovery downtime (MTTR), by cause.
        # EXCEPT cause hang: the hang span (opened at declaration,
        # backdated to when progress stopped) is already the open window;
        # opening a restart span too would double-count the same outage
        # across two lost-seconds causes (docs/design.md §6.3).
        now = time.time()
        if cause is CAUSE_HANG:
            set_condition(
                job.status,
                new_condition(ConditionType.RESTARTING, reason, message),
            )
            self.recorder.normal(
                job, reason, f"{message} ({len(targets)} processes)"
            )
            self._delete_gang_targets(job, targets, exp_key, full)
            return
        open_info = self._open_restart.get(job.metadata.uid)
        if open_info is not None and open_info["cause"] != cause:
            # A differently-caused restart supersedes the open window: a
            # preemption landing mid crash-recovery (or vs.) must appear
            # as its own window in the trace, not be silently folded into
            # the first cause's downtime. Close the old window here —
            # its recovery never completed on its own terms — and let the
            # new cause open a fresh span below. Same-cause repeats (a
            # crash loop) stay one window: the outage never ended.
            self._close_restart_span(job, now)
        n = job.status.restart_count + job.status.preemption_count
        span_name = self._span_name(job, f"restart-{n}")
        if job.metadata.uid not in self._open_restart:
            if self.tracer.record(
                job.metadata.namespace, job.metadata.name, job.metadata.uid,
                "restart", now, 0.0,
                attrs={"cause": cause, "full": str(full).lower(),
                       "track": "restart"},
                name=span_name,
            ) is not None:
                self._open_restart[job.metadata.uid] = {
                    "ns": job.metadata.namespace, "name": span_name,
                    "start": now, "cause": cause,
                }
        set_condition(
            job.status,
            new_condition(ConditionType.RESTARTING, reason, message),
        )
        self.recorder.normal(
            job, reason, f"{message} ({len(targets)} processes)"
        )
        self._delete_gang_targets(job, targets, exp_key, full)

    def _delete_gang_targets(
        self, job: TPUJob, targets: List[Process], exp_key: str, full: bool
    ) -> None:
        """The teardown half of a gang restart (shared by every cause,
        hang included): delete the targets under deletion expectations,
        fence the rendezvous on a full restart, persist status."""
        if targets:
            self.expectations.expect_deletions(exp_key, len(targets))
            deleted = 0
            try:
                for p in targets:
                    self._delete_child(p)
                    deleted += 1
            except Exception:
                # Roll back every unobserved deletion expectation (not just
                # the failed one) so a transient delete error can't wedge
                # the job until the expectation TTL.
                for _ in range(len(targets) - deleted):
                    self.expectations.deletion_failed(exp_key)
                raise
        if full:
            # Fence the old incarnation: drop the rendezvous port + endpoint
            # so the next gang gets a FRESH port. A zombie member whose host
            # went silent (NodeLost) may still be alive; it must rendezvous
            # with a dead address, never with the new gang.
            self._clear_rendezvous(job)
        self._write_status(job)

    def _clear_rendezvous(self, job: TPUJob) -> None:
        job.metadata.annotations.pop(ANNOTATION_PORT, None)

        def drop(fresh):
            if ANNOTATION_PORT not in fresh.metadata.annotations:
                return False
            fresh.metadata.annotations.pop(ANNOTATION_PORT, None)

        self.store.update_with_retry(
            KIND_TPUJOB, job.metadata.namespace, job.metadata.name, drop
        )
        try:
            self.store.delete(
                KIND_ENDPOINT, job.metadata.namespace,
                f"{job.metadata.name}-rendezvous",
            )
        except NotFoundError:
            pass

    def _fail_job(self, job: TPUJob, reason: str, message: str) -> None:
        set_condition(job.status, new_condition(ConditionType.FAILED, reason, message))
        if job.status.completion_time is None:
            job.status.completion_time = time.time()
        self.recorder.warning(job, reason, message)

    def _finish(self, job: TPUJob) -> None:
        """Terminal transition: persist status, then clean up children."""
        # A terminal job holds no over-spec loan: _release_job below
        # returns the chips regardless of where the reclaim two-phase
        # stood, so the status must agree — a job can finish between
        # publishing a reclaim shrink and observing its tail gone, and
        # reconcile never runs _finish_overspec_reclaim for a terminal
        # job.
        if job.status.overspec_workers:
            job.status.overspec_workers = 0
        # Forensics first (r15): freeze the flight recorder into the
        # postmortem bundle for ANY terminal failure — the children are
        # about to be GC'd and the scene with them. Idempotent (the
        # first freeze of the incarnation wins; a hang already froze).
        if has_condition(job.status, ConditionType.FAILED):
            bb = self._blackboxes.setdefault(job.metadata.uid, Blackbox())
            bb.observe_status(job)
            if bb.freeze(
                self.store, job,
                reason="hang" if job.status.hang_state else "failed",
            ) is not None:
                self.recorder.normal(
                    job, ev.REASON_POSTMORTEM_FROZEN,
                    f"postmortem bundle frozen: "
                    f"tpujob debug {job.metadata.name}",
                )
        self._write_status(job)
        # Trace: seal the timeline. The root span (span_id = trace id —
        # what every other span parents to) covers submit -> completion;
        # its create-once name makes the whole block idempotent, so the
        # derived TTFS observation happens exactly once per job.
        now = time.time()
        end = job.status.completion_time or now
        phase = (
            "Succeeded"
            if has_condition(job.status, ConditionType.SUCCEEDED)
            else "Failed"
        )
        uid = job.metadata.uid
        root = self.tracer.record(
            job.metadata.namespace, job.metadata.name, uid,
            "job", job.metadata.creation_timestamp, end,
            attrs={
                "phase": phase,
                "restarts": str(job.status.restart_count),
                "preemptions": str(job.status.preemption_count),
                "track": "job",
            },
            name=self._span_name(job, "job"),
            span_id=uid, parent_id="",
        )
        if root is not None:
            # A restart still open at terminal (the gang never came back)
            # closes at completion time — bounded, not dangling.
            self._close_restart_span(job, end)
            self._close_resize_span(job, end, force=True)
            self._close_hang_span(job, end, terminal=True)
            wait = self._open_schedwait.pop(uid, None)
            if wait is not None:
                self.tracer.close(wait["ns"], wait["name"], end)
            queued = self._open_queued.pop(uid, None)
            if queued is not None:
                self.tracer.close(queued["ns"], queued["name"], end)
            self._observe_first_step(job)
            self._observe_ckpt_spans(job)
            self._observe_serve_spans(job)
            self._observe_goodput(job, end)
            # Fleet ledger fold (r18): BEFORE _delete_children below, so
            # hosts-touched and the decision receipts are still live.
            self._ledger_fold(job, end)
            self._sched_observed.discard(uid)
            self._ttfs_observed.discard(uid)
            self._ckpt_observed.discard(uid)
            self._serve_observed.discard(uid)
            self._goodput_observed.discard(uid)
        # Straggler bookkeeping dies with the job; a host the job flagged
        # stays flagged (the signal is about the HOST) until a later
        # running job's clean windows clear it.
        self._stragglers.pop(uid, None)
        self._straggler_seen_seq.pop(uid, None)
        self._watchdogs.pop(uid, None)
        self._blackboxes.pop(uid, None)
        self._open_hang.pop(uid, None)
        # Autopilot state (r16): hysteresis streaks and cached inputs are
        # per-incarnation; the fleet-level TTFS counters stay (they feed
        # warm-pool sizing across jobs).
        self._autopilots.pop(uid, None)
        self._prior_cache.pop(uid, None)
        self._host_risk.pop(uid, None)
        self._last_step_time.pop(uid, None)
        self._ap_ttfs_seen.discard(uid)
        self._delete_children(
            job.metadata.namespace, job.metadata.name, job.spec.run_policy.cleanup_policy
        )
        # Quota back to the pool; queued heads get re-kicked.
        self._release_job(job.key())

    def _write_status(self, job: TPUJob) -> None:
        """Persist job.status (status-subresource analogue,
        controller_status.go:123-126) with optimistic retry. The
        last_reconcile_time heartbeat is excluded from the change check —
        stamping it every sync would otherwise make every write produce a
        MODIFIED event that re-enqueues the job: a hot loop.

        Coalescing fast path: when the informer's cached copy already
        matches the computed status (ignoring the heartbeat), skip the
        store round-trip entirely — the mutate-returns-False path below
        avoids the PUT but still pays a GET per sync (a network RTT in
        --store-server mode, a lock acquisition locally), which at
        hundreds of no-op resyncs per pass was pure overhead. Staleness
        is safe: if the cache lags a store-side difference, the pending
        MODIFIED event re-enqueues the job and the next sync writes."""
        cached = self.job_informer.get(job.metadata.namespace, job.metadata.name)
        if (
            cached is not None
            and _status_equal_ignoring_heartbeat(cached.status, job.status)
            and _annotations_except_port(cached.metadata.annotations)
            == _annotations_except_port(job.metadata.annotations)
        ):
            return

        def mutate(fresh):
            if (
                _status_equal_ignoring_heartbeat(fresh.status, job.status)
                and _annotations_except_port(fresh.metadata.annotations)
                == _annotations_except_port(job.metadata.annotations)
            ):
                return False  # no change — avoid a MODIFIED->enqueue->sync loop
            # restart_count/preemption_count are monotonic: a sync that
            # started from a stale informer snapshot must never roll back
            # restarts recorded by a sync that raced ahead of the cache.
            # The CAUSE travels with the counters: whichever side recorded
            # more restarts named the latest one — a stale snapshot (or a
            # freshly-recovered controller's first syncs) must not blank
            # or regress last_restart_cause while the max() keeps its
            # count. eval_metrics belongs to the evaluator's API writes —
            # always keep the store's copy.
            count = max(fresh.status.restart_count, job.status.restart_count)
            pcount = max(fresh.status.preemption_count, job.status.preemption_count)
            if (
                fresh.status.restart_count + fresh.status.preemption_count
                > job.status.restart_count + job.status.preemption_count
            ):
                cause = fresh.status.last_restart_cause
            else:
                cause = job.status.last_restart_cause or fresh.status.last_restart_cause
            # Elastic resize state (r12) merges like the restart counters:
            # epoch/count are monotonic; the directive, history, and world
            # size travel with the side that saw the NEWER epoch. At equal
            # epochs the store-side directive fields win the merge — the
            # chief publishes barrier fields into the stored directive
            # mid-epoch (publish_resize_barrier), and a reconciler sync
            # holding a stale snapshot must not blank them.
            rz_epoch = max(fresh.status.resize_epoch, job.status.resize_epoch)
            rz_count = max(fresh.status.resize_count, job.status.resize_count)
            if fresh.status.resize_epoch > job.status.resize_epoch:
                directive = fresh.status.resize_directive
                world = fresh.status.world_size
                overspec = fresh.status.overspec_workers
            else:
                directive = dict(job.status.resize_directive or {})
                if fresh.status.resize_epoch == job.status.resize_epoch:
                    directive.update(fresh.status.resize_directive or {})
                world = job.status.world_size or fresh.status.world_size
                # overspec_workers travels with the resize-epoch winner;
                # at EQUAL epochs the reclaim-completion write zeroes it
                # without bumping the epoch, so the smaller value is the
                # newer one (grants always come with an epoch bump).
                overspec = (
                    min(fresh.status.overspec_workers, job.status.overspec_workers)
                    if fresh.status.resize_epoch == job.status.resize_epoch
                    else job.status.overspec_workers
                )
            # The bounded history and its folded count move together —
            # whichever side recorded more TOTAL resizes has the newer
            # pair (folding only ever raises the total).
            if (
                fresh.status.resize_history_folded
                + len(fresh.status.resize_history)
                > job.status.resize_history_folded
                + len(job.status.resize_history)
            ):
                history = fresh.status.resize_history
                rz_folded = fresh.status.resize_history_folded
            else:
                history = job.status.resize_history
                rz_folded = job.status.resize_history_folded
            eval_metrics = fresh.status.eval_metrics
            # profile_directive is API-authored end to end (the CLI/server
            # publishes requests, the chief acks captures) — always keep
            # the store's copy, exactly like eval_metrics.
            profile_directive = fresh.status.profile_directive
            # Hang plane (r15): hang_count is monotonic like the restart
            # counters. The stackdump directive merges by epoch — the
            # reconciler authors epoch bumps, the HostAgents write acks
            # store-side; a higher epoch wins wholesale, and at equal
            # epochs the ack maps UNION (neither a stale reconciler
            # snapshot nor a racing agent write may drop a shipped rank).
            hang_count = max(fresh.status.hang_count, job.status.hang_count)
            sd_fresh = fresh.status.stackdump_directive or {}
            sd_job = job.status.stackdump_directive or {}
            if sd_fresh.get("epoch", 0) > sd_job.get("epoch", 0):
                stackdump = sd_fresh
            else:
                stackdump = dict(sd_job)
                if sd_fresh.get("epoch", 0) == sd_job.get("epoch", 0):
                    acks = dict(sd_job.get("acks") or {})
                    acks.update(sd_fresh.get("acks") or {})
                    if acks:
                        stackdump["acks"] = acks
            # Autopilot cadence directive (r16) merges by epoch exactly
            # like the stackdump directive: the reconciler authors epoch
            # bumps, the chief acks store-side; a higher epoch wins
            # wholesale, at equal epochs the store-side ack fields win
            # (a stale reconciler snapshot must not blank an ack the
            # chief just wrote — the autopilot would re-send forever).
            cc_fresh = fresh.status.checkpoint_cadence_directive or {}
            cc_job = job.status.checkpoint_cadence_directive or {}
            if cc_fresh.get("epoch", 0) > cc_job.get("epoch", 0):
                cadence = cc_fresh
            else:
                cadence = dict(cc_job)
                if cc_fresh.get("epoch", 0) == cc_job.get("epoch", 0):
                    cadence.update(cc_fresh)
            fresh.status = job.status
            fresh.status.restart_count = count
            fresh.status.preemption_count = pcount
            fresh.status.last_restart_cause = cause
            fresh.status.resize_epoch = rz_epoch
            fresh.status.resize_count = rz_count
            fresh.status.resize_directive = directive
            fresh.status.resize_history = history
            fresh.status.resize_history_folded = rz_folded
            fresh.status.world_size = world
            fresh.status.overspec_workers = overspec
            fresh.status.eval_metrics = eval_metrics
            fresh.status.profile_directive = profile_directive
            fresh.status.hang_count = hang_count
            fresh.status.stackdump_directive = stackdump
            fresh.status.checkpoint_cadence_directive = cadence
            # The rendezvous-port annotation is managed store-side
            # (_rendezvous_port persists it, _clear_rendezvous removes it);
            # merging it from a stale cached copy here would resurrect a
            # fenced port, so it is excluded from the merge.
            fresh.metadata.annotations.update(
                _annotations_except_port(job.metadata.annotations)
            )

        self.store.update_with_retry(
            KIND_TPUJOB, job.metadata.namespace, job.metadata.name, mutate
        )


def _failed(p: Optional[Process]) -> bool:
    return p is not None and p.status.phase is ProcessPhase.FAILED


def _restart_cause(gang_failed: List[Process]) -> str:
    """Classify a retryable gang failure into a restart cause.

    Priority: a declared loss anywhere means the fenced node-lost path
    (zombies may live); an OOM kill anywhere means the restart — which
    only happens under ALWAYS/ON_FAILURE policies — is an oom restart,
    never mistakable for a preemption (both can present as SIGKILL);
    otherwise the restart is a preemption only when EVERY failure is
    eviction-shaped (exit 130/143, the graceful-kill signals) — a genuine
    crash racing a drain still consumes backoff; everything else is a
    plain retryable failure."""
    if any(p.status.node_lost for p in gang_failed):
        return CAUSE_NODE_LOST
    if any(
        classify_exit_code(p.status.exit_code or 0, p.status.oom_killed)
        is ExitClass.OOM
        for p in gang_failed
    ):
        return CAUSE_OOM
    if gang_failed and all(
        classify_exit_code(p.status.exit_code or 0, p.status.oom_killed)
        is ExitClass.PREEMPTED
        for p in gang_failed
    ):
        return CAUSE_PREEMPTION
    return CAUSE_FAILURE


def _annotations_except_port(annotations: Dict[str, str]) -> Dict[str, str]:
    # The preempt annotation is managed store-side exactly like the port
    # (_request_preemptions stamps it, the victim's drain clears it);
    # merging it back from a stale snapshot would re-preempt the victim
    # on every status write.
    # ANNOTATION_RECLAIM (r19) is store-managed the same way: stamped by
    # the admitter, cleared by the holder's own sync.
    return {
        k: v
        for k, v in annotations.items()
        if k not in (ANNOTATION_PORT, ANNOTATION_PREEMPT, ANNOTATION_RECLAIM)
    }


def _status_equal_ignoring_heartbeat(a, b) -> bool:
    """eval_metrics is excluded alongside the heartbeat: the reconciler
    never authors it (evaluator processes write it through the API), so a
    difference there must neither trigger a write nor be overwritten.
    resize_directive is excluded for the same reason with a twist: the
    reconciler authors it ONLY together with a resize_epoch bump (which
    already breaks equality), while the chief publishes barrier fields
    into it mid-epoch through the API — a chief-side difference must not
    make every subsequent sync rewrite the status (write → MODIFIED →
    enqueue → write: a hot loop)."""
    import dataclasses

    # stackdump_directive follows the resize_directive rule: the
    # reconciler authors it only together with a hang declaration (which
    # breaks equality through hang_count/hang_state anyway), while the
    # HostAgents write acks into it through the API mid-sweep.
    # checkpoint_cadence_directive is the same shape again: the autopilot
    # authors epoch bumps only together with a status.autopilot update
    # (which breaks equality), while the chief acks applied_epoch
    # store-side — acks alone must not hot-loop the status writer.
    return dataclasses.replace(
        a, last_reconcile_time=None, eval_metrics={}, resize_directive={},
        profile_directive={}, stackdump_directive={},
        checkpoint_cadence_directive={},
    ) == dataclasses.replace(
        b, last_reconcile_time=None, eval_metrics={}, resize_directive={},
        profile_directive={}, stackdump_directive={},
        checkpoint_cadence_directive={},
    )
