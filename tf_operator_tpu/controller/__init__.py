"""Control plane: the reconciling job controller.

Reference parity: pkg/controller.v2 — the informer/expectations architecture
(SURVEY.md §3.3): object events enqueue job keys into a rate-limited
workqueue; workers pop keys and run an idempotent sync that compares desired
gang membership against observed processes, creates/deletes children through
the ProcessControl seam, and drives conditions-based status. The
expectations cache bridges informer staleness (the subtlest part of the
reference, controller.v2/controller.go:125-141,417-436).
"""

from tf_operator_tpu.controller.workqueue import RateLimitingQueue  # noqa: F401
from tf_operator_tpu.controller.expectations import ControllerExpectations  # noqa: F401
from tf_operator_tpu.controller.events import EventRecorder  # noqa: F401
from tf_operator_tpu.controller.informer import Informer  # noqa: F401
from tf_operator_tpu.controller.reconciler import TPUJobController  # noqa: F401
