"""Event recorder: durable occurrences for observability and test oracles.

Reference parity: the k8s EventBroadcaster the operator wires at startup
(pkg/controller/controller.go:107-120) and the per-action events emitted by
pod/service control (pod_control.go:37-51, replicas.go:470-474). Events
double as the e2e test oracle — the reference asserts creation-event counts
equal replica counts (py/test_runner.py:307-338) — so reasons are stable
API, and repeats aggregate into a count like k8s event compaction.
"""

from __future__ import annotations

import time

from tf_operator_tpu.api.types import ObjectMeta
from tf_operator_tpu.runtime.objects import Event, EventType
from tf_operator_tpu.runtime.store import AlreadyExistsError, Store

# Stable event reasons (reference: SuccessfulCreate/FailedCreate etc.).
REASON_SUCCESSFUL_CREATE = "SuccessfulCreateProcess"
REASON_FAILED_CREATE = "FailedCreateProcess"
REASON_SUCCESSFUL_DELETE = "SuccessfulDeleteProcess"
REASON_FAILED_DELETE = "FailedDeleteProcess"
REASON_JOB_RESTARTING = "TPUJobRestarting"
REASON_JOB_SUCCEEDED = "TPUJobSucceeded"
REASON_JOB_FAILED = "TPUJobFailed"
REASON_JOB_RUNNING = "TPUJobRunning"
REASON_JOB_CREATED = "TPUJobCreated"
REASON_JOB_DEADLINE = "TPUJobDeadlineExceeded"
REASON_FAILED_SCHEDULING = "FailedScheduling"
REASON_NODE_LOST = "NodeLost"
# Preemption drain: a host under a preemption notice forced a graceful
# (checkpoint-resumed, backoff-exempt) gang restart.
REASON_JOB_PREEMPTED = "TPUJobPreempted"
# Fleet scheduler: the job is parked in the admission queue (over quota,
# behind a higher-precedence job, or waiting for fleet capacity).
REASON_JOB_QUEUED = "TPUJobQueued"
# Fleet scheduler: this job requested preemption of lower-priority victims.
REASON_JOB_PREEMPTING = "TPUJobPreempting"
# Control-plane crash-recovery: a restarted operator recovered this job
# from the durable store and re-adopted its children (record_recovery).
REASON_CONTROLLER_RESTARTED = "ControllerRestarted"
# Straggler detection (obs/telemetry.py): a gang member's step time sat
# above the cross-rank median-ratio bar for enough consecutive windows;
# its host is flagged (SlowHost annotation + by-host gauge) and
# deprioritized for new gang placements until it clears.
REASON_SLOW_HOST = "SlowHost"
REASON_SLOW_HOST_CLEARED = "SlowHostCleared"
# Goodput autopilot (autopilot/, r16): one event per executed decision —
# cadence retune, pre-emptive migrate, host deprioritization, warm-pool
# retarget. The authoritative receipt is the autopilot-decision span;
# the event is the human-readable echo.
REASON_AUTOPILOT = "AutopilotDecision"
# Hang plane (obs/watchdog.py, r15): the gang-progress watchdog declared
# the job HUNG (no rank advanced a step for hang_timeout_seconds with
# heartbeats live); a stack sweep + postmortem freeze precede recovery.
REASON_JOB_HUNG = "TPUJobHung"
# A frozen postmortem bundle is available for this job
# (GET /api/tpujob/<ns>/<name>/postmortem, `tpujob debug`).
REASON_POSTMORTEM_FROZEN = "PostmortemFrozen"


class EventRecorder:
    def __init__(self, store: Store, component: str = "tpujob-controller") -> None:
        self._store = store
        self._component = component

    def event(
        self,
        involved,  # object with .kind and .metadata
        etype: EventType,
        reason: str,
        message: str,
    ) -> None:
        """Record one occurrence; repeats aggregate into count++ on the
        same (object, reason) Event.

        Lock-free by design: the old recorder held ONE process-wide lock
        across the whole get/update/create round-trip, serializing every
        event emission from every sync worker behind store latency (a
        network RTT each in --store-server mode). The store's own
        optimistic concurrency is sufficient: repeats go through
        update_with_retry (conflicts re-apply), and the create/create
        race on a brand-new event resolves through AlreadyExists into
        the update path."""
        meta = involved.metadata
        name = f"{meta.name}.{reason.lower()}"

        def bump(cur):
            cur.count += 1
            cur.message = message
            cur.timestamp = time.time()
            if not cur.first_timestamp:
                # events recorded before first_timestamp existed
                cur.first_timestamp = cur.timestamp

        if self._store.update_with_retry("Event", meta.namespace, name, bump):
            return
        now = time.time()
        ev = Event(
            metadata=ObjectMeta(name=name, namespace=meta.namespace),
            type=etype,
            reason=reason,
            message=message,
            involved_kind=involved.kind,
            involved_name=meta.name,
            involved_namespace=meta.namespace,
            timestamp=now,
            first_timestamp=now,
        )
        try:
            self._store.create(ev)
        except AlreadyExistsError:
            # Lost the first-occurrence race: fold into the winner.
            self._store.update_with_retry("Event", meta.namespace, name, bump)

    def normal(self, involved, reason: str, message: str) -> None:
        self.event(involved, EventType.NORMAL, reason, message)

    def warning(self, involved, reason: str, message: str) -> None:
        self.event(involved, EventType.WARNING, reason, message)
