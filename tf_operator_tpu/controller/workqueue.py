"""Rate-limited deduplicating workqueue.

Reference parity: the k8s client-go workqueue the operator builds on
(pkg/controller/controller.go:122-126): dedup semantics (a key queued while
being processed is deferred, never processed concurrently), per-item
exponential backoff 5 ms → 1000 s, and an overall 10 qps / burst 100 token
bucket; the combined limiter takes the max of the two delays.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Hashable, List, Optional


class ItemExponentialBackoff:
    """Per-item exponential failure backoff (5ms base, 1000s cap)."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0) -> None:
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: Dict[Hashable, int] = {}
        self._lock = threading.Lock()

    def when(self, item: Hashable) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        return min(self.base_delay * (2 ** n), self.max_delay)

    def forget(self, item: Hashable) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: Hashable) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class TokenBucket:
    """Overall-rate limiter (10 qps / burst 100 by default)."""

    def __init__(self, qps: float = 10.0, burst: int = 100) -> None:
        self.qps = qps
        self.burst = burst
        self._tokens = float(burst)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def when(self, item: Hashable = None) -> float:
        del item
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
            self._last = now
            self._tokens -= 1.0
            if self._tokens >= 0:
                return 0.0
            return -self._tokens / self.qps


class ShardedQueueView:
    """Read-only depth aggregation over partitioned worker queues (the
    reconciler's namespace shards). Metrics only ever call ``depth()``,
    so the workqueue-depth gauge keeps meaning "keys waiting anywhere"
    after the queue splits into shards."""

    def __init__(self, shards) -> None:
        self._shards = list(shards)

    def depth(self) -> int:
        return sum(q.depth() for q in self._shards)

    def __len__(self) -> int:
        return self.depth()


class RateLimitingQueue:
    """Deduplicating queue with delayed adds and combined rate limiting.

    Contract (client-go): ``add`` enqueues unless the key is already queued;
    a key added while in-flight is re-queued when ``done`` is called;
    ``add_rate_limited`` delays by max(per-item backoff, bucket);
    ``forget`` resets the per-item failure history after a successful sync.
    """

    def __init__(
        self,
        base_delay: float = 0.005,
        max_delay: float = 1000.0,
        qps: float = 10.0,
        burst: int = 100,
    ) -> None:
        self._cond = threading.Condition()
        self._queue: List[Hashable] = []
        self._dirty: set = set()
        self._processing: set = set()
        self._shutdown = False
        self._backoff = ItemExponentialBackoff(base_delay, max_delay)
        self._bucket = TokenBucket(qps, burst)
        self._timers: set = set()

    # -- core dedup queue -------------------------------------------------

    def depth(self) -> int:
        """Keys waiting to be popped (telemetry gauge)."""
        with self._cond:
            return len(self._queue)

    def add(self, item: Hashable) -> None:
        with self._cond:
            if self._shutdown or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return  # deferred: re-queued on done()
            self._queue.append(item)
            self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[Hashable]:
        """Block for the next item; None on shutdown or timeout."""
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._queue and not self._shutdown:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining)
            if self._shutdown and not self._queue:
                return None
            item = self._queue.pop(0)
            self._dirty.discard(item)
            self._processing.add(item)
            return item

    def done(self, item: Hashable) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    # -- delays / rate limiting ------------------------------------------

    def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        timer = threading.Timer(delay, self._timer_fire, args=(item,))
        timer.daemon = True
        with self._cond:
            if self._shutdown:
                return
            self._timers.add(timer)
        timer.start()

    def _timer_fire(self, item: Hashable) -> None:
        with self._cond:
            self._timers = {t for t in self._timers if t.is_alive()}
        self.add(item)

    def add_rate_limited(self, item: Hashable) -> None:
        self.add_after(item, max(self._backoff.when(item), self._bucket.when(item)))

    def forget(self, item: Hashable) -> None:
        self._backoff.forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return self._backoff.num_requeues(item)

    # -- lifecycle --------------------------------------------------------

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            for t in self._timers:
                t.cancel()
            self._timers.clear()
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)
