"""Flash attention as a Pallas TPU kernel (forward + custom-VJP backward).

Why a kernel at all: dense attention materializes the [t, t] score matrix
in HBM — O(t²) bytes of traffic on the op XLA cannot fuse away. The
flash/online-softmax formulation streams K/V blocks through VMEM and keeps
only [block_q, d] / [block_k, d] tiles plus per-row (m, l) accumulators
resident, so HBM traffic is O(t·d) and the MXU stays fed. The backward
pass recomputes P from the saved logsumexp instead of storing it (the
standard flash recipe), trading FLOPs for HBM exactly as TPUs want.

Kernel structure: the contraction dimension is a GRID dimension, not a
VMEM-resident loop — grid (b, h_kv, nq, nk) for forward/dq and
(b, h_kv, nk, nq) for dk/dv, with the running (m, l, acc) state in VMEM
scratch that persists across the innermost grid dimension (TPU grids
iterate the last dimension sequentially, which is what makes carried
scratch sound). VMEM holds only one block of each operand at a time, so
sequence length is bounded by HBM, not by the ~16 MB VMEM budget. Causal
grids skip above-diagonal blocks with `pl.when` (zero compute, still one
grid step).

GQA is folded into the q tile: the grid's head dimension iterates K/V
heads, and each step's q tile is [g·block_q, d] — the g query heads of
the group stacked on the sublane dim (g = h // h_kv, 1 for classic MHA).
One K/V block load therefore serves every query head of its group, so
in-kernel K/V HBM reads scale with h_kv, not h — the whole point of GQA
(llama2-70b's 64q/8kv shape reads 8x less K/V than a repeat would), and
the s = q·kᵀ contraction sees a g·block_q-row tile, which feeds the MXU
better than g separate block_q-row tiles.

Layout: q/k/v are [b, t, h, d] (the model layout), transposed to
[b, h, t, d] so seq is the sublane dim and head_dim the lane dim. The
kernel path engages on TPU when t divides into 8-aligned blocks and
either d % 128 == 0 (any length) or d % 64 == 0 with t >= 2048 — the
measured END-TO-END crossover for hd=64 models (gpt-small/bert-base):
in-model the kernel wins 1.49x at t=2048 but loses to dense at t=512
under full remat, even though the isolated attention probe favors it at
every length (`tools/roofline --mode attn --d 64`; BASELINE.md). Off-TPU
the entry falls back to a jnp reference (same math, same f32 softmax) so
one model config runs everywhere; ``interpret=True`` forces the Pallas
interpreter — the CPU test path for the kernel logic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-negative instead of -inf: exp() of a whole masked
                 # row must give 0 without generating inf-inf = nan
LSE_LANES = 128  # lse/delta carry a full lane dim to satisfy TPU tiling


def _use_kernel(t: int, d: int, block_q: int, block_k: int, interpret: bool) -> bool:
    if t % block_q or t % block_k:
        return False  # kernels assume exact tiling; odd lengths fall back
    if block_q % 8 or block_k % 8:
        return False  # clamped blocks (short t) must stay sublane-aligned
    if interpret:
        return True
    if jax.default_backend() != "tpu":
        return False
    if d % 128 == 0:
        return True
    # hd=64 (gpt-small, bert-base): the kernel serves long context —
    # measured END-TO-END in the model it wins from t=2048 (train MFU
    # 28.9% vs 19.4% dense, 1.49x; isolated attention 1.60x @ 2048 up to
    # 27x @ 8192 where dense spills) but loses at t=512 under full remat
    # (36.4% vs 38.0% — the in-model remat interaction the r1 fwd-only
    # probe couldn't see). Gate on the measured crossover.
    return d % 64 == 0 and t >= 2048


def reference_attention(q, k, v, causal: bool = False):
    """Dense attention, f32 softmax — the correctness oracle and the
    off-TPU fallback (same contract as the kernel path). GQA-native: k/v
    may carry fewer heads than q (h % h_kv == 0); the grouped einsum
    keeps the group dim in the contraction instead of materializing
    repeated K/V heads. One implementation: softmax(s) == exp(s − lse),
    so this is the lse variant with the lse dropped."""
    return reference_attention_lse(q, k, v, causal=causal)[0]


def _causal_mask(s, qi, kb, block_q, block_k):
    """Causal mask for an s tile whose rows may stack g group members:
    row r is sequence position qi*block_q + (r % block_q) — members share
    the same q sequence block, so position repeats per member (for g=1,
    r % block_q == r and this is the classic tile mask)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) % block_q
    qpos = qi * block_q + rows
    kpos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(kpos <= qpos, s, NEG_INF)


def _gqa_specs(g, block_q, block_k, d, q_grid_dim):
    """BlockSpec factories shared by all three folded-GQA grids.

    Query-side tiles are (1, g, block_q, last) — the g query heads of kv
    head ``hk`` (contiguous in the h dim) stacked over one sequence
    block. ``q_grid_dim`` says which innermost grid dim walks q blocks:
    2 for the (b, h_kv, nq, nk) fwd/dq grids, 3 for the (b, h_kv, nk, nq)
    dk/dv grid; the other innermost dim walks K/V blocks. Returns
    (q_spec_factory, kv_spec)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if q_grid_dim == 2:
        q_idx = lambda bi, hk, qi, kb: (bi, hk, qi, 0)
        kv_idx = lambda bi, hk, qi, kb: (bi, hk, kb, 0)
    else:
        q_idx = lambda bi, hk, ki, qb: (bi, hk, qb, 0)
        kv_idx = lambda bi, hk, ki, qb: (bi, hk, ki, 0)

    def q_spec(shape_last):
        return pl.BlockSpec(
            (1, g, block_q, shape_last), q_idx, memory_space=pltpu.VMEM
        )

    kv_spec = pl.BlockSpec(
        (1, 1, block_k, d), kv_idx, memory_space=pltpu.VMEM
    )
    return q_spec, kv_spec


# ---------------------------------------------------------------------------
# forward kernel — grid (b, h_kv, nq, nk), carry (m, l, acc) in scratch
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, causal, block_q, block_k, scale, g):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    kb = pl.program_id(3)
    nkb = pl.num_programs(3)
    d = q_ref.shape[-1]
    rows = g * block_q

    @pl.when(kb == 0)
    def _init():
        m_scr[:, :] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:, :] = jnp.zeros_like(l_scr)
        acc_scr[:, :] = jnp.zeros_like(acc_scr)

    # Above-diagonal blocks contribute nothing under causal masking (every
    # group member in the tile shares the same q sequence block).
    live = (kb * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0].reshape(rows, d).astype(jnp.float32) * scale  # [g·bq, d]
        k = k_ref[0, 0, :, :].astype(jnp.float32)                  # [bk, d]
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [g·bq, bk]
        if causal:
            s = _causal_mask(s, qi, kb, block_q, block_k)
        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:, :] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[:, :] = l_scr[:, :] * alpha[:, None] + jnp.sum(p, axis=1)[:, None]
        acc_scr[:, :] = acc_scr[:, :] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(kb == nkb - 1)
    def _finish():
        l = l_scr[:, 0]
        o_ref[0] = (acc_scr[:, :] / l[:, None]).reshape(g, block_q, d).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(
            (m_scr[:, 0] + jnp.log(l))[:, None], (rows, LSE_LANES)
        ).reshape(g, block_q, LSE_LANES)


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, t, h, d = q.shape
    h_kv = k.shape[2]
    g = h // h_kv  # GQA group: g query heads fold into one q tile
    scale = d**-0.5
    # [b, t, h, d] -> [b, h, t, d]: sequence in the sublane dim, head_dim in
    # lanes — the MXU-native layout for the q·kᵀ and p·v contractions.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    q_by_qi, kv_by_kb = _gqa_specs(g, block_q, block_k, d, q_grid_dim=2)

    kernel = functools.partial(
        _fwd_kernel, causal=causal, block_q=block_q, block_k=block_k,
        scale=scale, g=g,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(b, h_kv, t // block_q, t // block_k),
        in_specs=[q_by_qi(d), kv_by_kb, kv_by_kb],
        out_specs=[q_by_qi(d), q_by_qi(LSE_LANES)],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, t, LSE_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((g * block_q, LSE_LANES), jnp.float32),  # running max m
            pltpu.VMEM((g * block_q, LSE_LANES), jnp.float32),  # running sum l
            pltpu.VMEM((g * block_q, d), jnp.float32),          # output accumulator
        ],
        interpret=interpret,
    )(qt, kt, vt)
    # Residuals carry the COMPACT [b, h, t] lse (the kernel's LSE_LANES
    # lane-broadcast is rebuilt in _bwd): saved residuals under a
    # selective-remat policy would otherwise store 128x the lse bytes.
    return o.transpose(0, 2, 1, 3), (qt, kt, vt, o, lse[..., 0])


# ---------------------------------------------------------------------------
# backward kernels — same streaming-grid structure
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
                   *, causal, block_q, block_k, scale, g):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    kb = pl.program_id(3)
    nkb = pl.num_programs(3)
    d = q_ref.shape[-1]
    rows = g * block_q

    @pl.when(kb == 0)
    def _init():
        dq_scr[:, :] = jnp.zeros_like(dq_scr)

    live = (kb * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0].reshape(rows, d).astype(jnp.float32) * scale
        do = do_ref[0].reshape(rows, d).astype(jnp.float32)
        lse = lse_ref[0].reshape(rows, LSE_LANES)[:, :1]      # value replicated on lanes
        delta = delta_ref[0].reshape(rows, LSE_LANES)[:, :1]
        k = k_ref[0, 0, :, :].astype(jnp.float32)
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            s = _causal_mask(s, qi, kb, block_q, block_k)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dq_scr[:, :] = dq_scr[:, :] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(kb == nkb - 1)
    def _finish():
        dq_ref[0] = (dq_scr[:, :] * scale).reshape(g, block_q, d).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                    dk_scr, dv_scr, *, causal, block_q, block_k, scale, g):
    """dk/dv for one k/v head. The q tile stacks the g query heads of the
    group ([g·block_q, d]), so the row contraction in p·ᵀdo and ds·ᵀq sums
    over every group member in one matmul — the [block_k, d] scratch
    accumulates across the innermost q-block grid dim and writes once at
    the end (the output block (bi, hk, ki) is revisited only on
    consecutive grid steps, which is what makes carried scratch and one
    final write sound on TPU)."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    qb = pl.program_id(3)
    nqb = pl.num_programs(3)
    d = q_ref.shape[-1]
    rows = g * block_q

    @pl.when(qb == 0)
    def _init():
        dk_scr[:, :] = jnp.zeros_like(dk_scr)
        dv_scr[:, :] = jnp.zeros_like(dv_scr)

    # Causal: q-blocks strictly before this k-block see none of it.
    live = (qb * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(live)
    def _step():
        k = k_ref[0, 0, :, :].astype(jnp.float32)
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        q = q_ref[0].reshape(rows, d).astype(jnp.float32) * scale
        do = do_ref[0].reshape(rows, d).astype(jnp.float32)
        lse = lse_ref[0].reshape(rows, LSE_LANES)[:, :1]
        delta = delta_ref[0].reshape(rows, LSE_LANES)[:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [g·bq, bk]
        if causal:
            s = _causal_mask(s, qb, ki, block_q, block_k)
        p = jnp.exp(s - lse)  # [g·bq, bk]
        dv_scr[:, :] = dv_scr[:, :] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bk, d] — row contraction sums the whole group
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [g·bq, bk]
        ds = p * (dp - delta)
        dk_scr[:, :] = dk_scr[:, :] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bk, d]

    @pl.when(qb == nqb - 1)
    def _finish():
        dk_ref[0, 0, :, :] = dk_scr[:, :].astype(dk_ref.dtype)  # q pre-scaled
        dv_ref[0, 0, :, :] = dv_scr[:, :].astype(dv_ref.dtype)


def _bwd(causal, block_q, block_k, interpret, residuals, g, dlse=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    qt, kt, vt, o, lse_c = residuals
    b, h, t, d = qt.shape
    h_kv = kt.shape[1]
    grp = h // h_kv  # GQA group size (1 = classic MHA)
    scale = d**-0.5
    # Rebuild the kernels' lane-broadcast lse layout from the compact
    # [b, h, t] residual (transient — lives only through the bwd kernels).
    lse = jnp.broadcast_to(lse_c[..., None], (b, h, t, LSE_LANES))
    do = g.transpose(0, 2, 1, 3)
    # delta_i = rowsum(do_i * o_i) — the softmax-jacobian correction term —
    # lane-broadcast to the same [b,h,t,LSE_LANES] layout as lse.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        # lse cotangent (the flash_attention_lse entry): ∂lse_i/∂s_ij = p_ij,
        # so the s gradient gains p_ij·g_i — algebraically ds = p·(dp −
        # (delta − g)), i.e. the whole lse-gradient path folds into the
        # delta term and the kernels run UNCHANGED. dlse arrives [b, t, h].
        delta = delta - dlse.astype(jnp.float32).transpose(0, 2, 1)
    delta = jnp.broadcast_to(delta[..., None], (b, h, t, LSE_LANES))

    # ---- dq: grid (b, h_kv, nq, nk); q tiles fold the group ------------
    q_by_qi, kv_by_kb = _gqa_specs(grp, block_q, block_k, d, q_grid_dim=2)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, causal=causal, block_q=block_q, block_k=block_k,
        scale=scale, g=grp,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h_kv, t // block_q, t // block_k),
        in_specs=[q_by_qi(d), kv_by_kb, kv_by_kb, q_by_qi(d),
                  q_by_qi(LSE_LANES), q_by_qi(LSE_LANES)],
        out_specs=q_by_qi(d),
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), qt.dtype),
        scratch_shapes=[pltpu.VMEM((grp * block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, do, lse, delta)

    # ---- dk/dv: grid (b, h_kv, nk, nq) ---------------------------------
    # Query-side tiles fold the group ([grp·block_q, d] rows), so one K/V
    # block load serves all grp query heads and the scratch accumulates
    # the whole group per grid step (see _bwd_dkv_kernel).
    q_by_qb, kv_by_ki = _gqa_specs(grp, block_q, block_k, d, q_grid_dim=3)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, causal=causal, block_q=block_q, block_k=block_k,
        scale=scale, g=grp,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h_kv, t // block_k, t // block_q),
        in_specs=[q_by_qb(d), kv_by_ki, kv_by_ki, q_by_qb(d),
                  q_by_qb(LSE_LANES), q_by_qb(LSE_LANES)],
        out_specs=[kv_by_ki, kv_by_ki],
        out_shape=[
            jax.ShapeDtypeStruct((b, h_kv, t, d), kt.dtype),
            jax.ShapeDtypeStruct((b, h_kv, t, d), vt.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, do, lse, delta)

    to_model = lambda x: x.transpose(0, 2, 1, 3)
    return to_model(dq), to_model(dk), to_model(dv)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------


# Selective rematerialization contract (r5): the custom-VJP boundary is
# opaque to jax.checkpoint policies — checkpoint_name tags INSIDE the fwd
# rule are invisible to save_only_these_names (measured:
# print_saved_residuals shows only the arguments, and compiled FLOPs are
# identical with and without internal tags, optimize_remat or not). So
# the residuals are restructured to be exactly the MODEL-LAYOUT inputs
# and public outputs, and the q/k/v INPUTS are tagged in the public
# entries, outside the call, where the policy can see them. A policy
# saving flash_q/k/v then retires the qkv projection recompute (the
# residual q/k/v are literally the saved tagged values); the flash
# forward itself still re-runs once in the backward to rebuild (o, lse)
# — the structural floor of this boundary, ~2 of the 31 per-layer fwd
# matmul units at gqa-2048 shapes. The bwd pays three cheap re-transposes
# to kernel layout (<1% of step time; the fwd no longer stores its
# transposed copies, which is a small memory WIN in the no-remat case).
FLASH_SAVE_NAMES = ("flash_q", "flash_k", "flash_v")


def _tag_inputs(q, k, v):
    from jax.ad_checkpoint import checkpoint_name

    return (
        checkpoint_name(q, "flash_q"),
        checkpoint_name(k, "flash_k"),
        checkpoint_name(v, "flash_v"),
    )


def _to_kernel_res(q, k, v, o, lse_pub):
    """Model-layout residuals → the kernel-layout tuple _bwd consumes."""
    tr = lambda a: a.transpose(0, 2, 1, 3)
    return tr(q), tr(k), tr(v), tr(o), lse_pub.transpose(0, 2, 1)


def _lse_public(lse_c):
    """Compact kernel residual [b, h, t] → the public [b, t, h] f32
    row-logsumexp."""
    return lse_c.transpose(0, 2, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse(q, k, v, causal, block_q, block_k, interpret):
    out, res = _fwd(q, k, v, causal, block_q, block_k, interpret)
    return out, _lse_public(res[4])


def _flash_lse_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, res = _fwd(q, k, v, causal, block_q, block_k, interpret)
    lse_pub = _lse_public(res[4])
    # model-layout residuals: q/k/v are the (possibly checkpoint_name-
    # tagged) INPUTS — under a names policy they are saved values, so the
    # backward reconstruction does not replay the qkv projections.
    return (out, lse_pub), (q, k, v, out, lse_pub)


def _flash_lse_bwd(causal, block_q, block_k, interpret, residuals, cts):
    do, dlse = cts
    res = _to_kernel_res(*residuals)
    return _bwd(causal, block_q, block_k, interpret, res, do, dlse=dlse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd, optimize_remat=True)


def reference_attention_lse(q, k, v, causal: bool = False):
    """Dense (o, lse) — the fallback for flash_attention_lse. lse is the
    row logsumexp of the scaled (masked) scores, [b, t, h] f32; rows with
    every key masked get lse = NEG_INF — the finite -1e30 sentinel, NOT
    -inf (their o is the uniform-softmax artifact over NEG_INF scores).
    Downstream merges must treat lse <= NEG_INF/2 as masked/weight-0 the
    way ring_attention's _merge_partials does; an isinf check will NOT
    catch it."""
    b, tq, hq, d = q.shape
    h_kv = k.shape[2]
    scale = d**-0.5
    if hq != h_kv:
        g = hq // h_kv
        q5 = q.reshape(b, tq, h_kv, g, d)
        s = jnp.einsum(
            "bqhgd,bkhd->bhgqk", q5, k, preferred_element_type=jnp.float32
        ) * scale
    else:
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        ) * scale
    if causal:
        tk = k.shape[1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask.reshape((1,) * (s.ndim - 2) + mask.shape), s, NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)  # [b,h,q] or [b,h_kv,g,q]
    p = jnp.exp(s - lse[..., None]).astype(q.dtype)
    if hq != h_kv:
        out = jnp.einsum("bhgqk,bkhd->bqhgd", p, v).reshape(b, tq, hq, d)
        lse = lse.reshape(b, h_kv * (hq // h_kv), tq)  # head hi = hk·g + gi
    else:
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return out, lse.transpose(0, 2, 1)


def flash_attention_lse(
    q,
    k,
    v,
    causal: bool = False,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    force_kernel: Optional[bool] = None,
):
    """flash_attention returning ``(o, lse)`` with lse [b, t, h] f32 —
    the row logsumexp of scaled scores. This is the composition surface
    for blockwise/distributed attention (ring attention's per-hop local
    compute): normalized partial outputs merge exactly across key blocks
    via their lse. Gradients are exact THROUGH lse — the lse cotangent
    folds into the backward kernels' delta term (see _bwd), so callers
    may use lse in differentiable math. Same dispatch gate and fallback
    as flash_attention — including the explicit-block clamp/rounding
    documented there. Fully-masked rows report the finite NEG_INF
    sentinel, not -inf (see reference_attention_lse)."""
    use, block_q, block_k = _dispatch(q, k, v, block_q, block_k, interpret,
                                      force_kernel)
    q, k, v = _tag_inputs(q, k, v)
    if not use:
        return reference_attention_lse(q, k, v, causal=causal)
    return _flash_lse(q, k, v, causal, block_q, block_k, bool(interpret))


def _pick_block(t: int, target: int) -> int:
    """Largest 8-aligned divisor of t not exceeding target (grid overhead
    falls with block size: 512/1024 blocks measured 2.2x faster than
    128/128 at t=2048 on v5e). A misaligned target is first rounded down
    to a multiple of 8 — the candidate scan steps by 8, so an unaligned
    start would only ever visit unaligned candidates and the gate would
    silently reject the kernel (the g=3/5/12 GQA default targets hit
    exactly this). Returns target when none divides — the _use_kernel
    gate then routes to the dense fallback."""
    target = max(8, target - target % 8)
    if t <= target:
        return t
    for cand in range(target, 7, -8):
        if t % cand == 0:
            return cand
    return target


def _dispatch(q, k, v, block_q, block_k, interpret, force_kernel):
    """Shared entry logic: validate head shapes, pick group-bounded
    blocks, and decide kernel-vs-fallback. Returns (use, block_q,
    block_k)."""
    t, d = q.shape[1], q.shape[3]
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"q heads {q.shape[2]} not a multiple of kv heads {k.shape[2]}"
        )
    if k.shape[2] != v.shape[2]:
        raise ValueError(f"k/v head mismatch: {k.shape[2]} vs {v.shape[2]}")
    grp = q.shape[2] // k.shape[2]
    # Folded tiles and scratch scale as grp*block_q rows, so the q-block
    # target is bounded by the group: default lands on the measured
    # 512-row sweet spot, and an EXPLICIT block_q is clamped to 1024 rows
    # — without the clamp a block size that compiled fine pre-fold (per-
    # query-head tiles) would blow VMEM at large g instead of running.
    block_q = _pick_block(
        t, max(8, min(block_q or (512 // grp), 1024 // grp))
    )
    block_k = _pick_block(t, block_k or 1024)
    use = _use_kernel(t, d, block_q, block_k, bool(interpret))
    if force_kernel is not None:
        # HARD constraints still bind (exact tiling; a compiled Pallas TPU
        # kernel cannot run on CPU — off-TPU only the interpreter engages).
        # The d % 128 lane HEURISTIC is deliberately overridden: the kernel
        # is correct at any d (Mosaic pads the lane dim) — d % 128 is a
        # performance gate, and measuring shapes on the other side of it
        # is exactly what this hook is for (tools/roofline --mode attn).
        use = force_kernel and not (
            t % block_q or t % block_k or block_q % 8 or block_k % 8
        ) and (bool(interpret) or jax.default_backend() == "tpu")
    return use, block_q, block_k


def flash_attention(
    q,
    k,
    v,
    causal: bool = False,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    force_kernel: Optional[bool] = None,
):
    """Self-attention over [b, t, h, d] with softmax(q·kᵀ/√d)·v semantics.

    GQA-native (r3): k/v may carry h_kv < h heads (h % h_kv == 0, the
    llama2-70b 64q/8kv shape). Neither path materializes repeated K/V —
    the kernel folds the g = h/h_kv group members into its q tile
    ([g·block_q, d] rows per K/V block load, grid over K/V heads), so
    both the repeated-K/V TENSOR and the in-kernel K/V HBM re-reads per
    query head are gone: K/V traffic scales with h_kv. The dense
    fallback contracts through a grouped einsum. The default q-block
    target shrinks by g so the folded tile stays within the measured
    512-row sweet spot (and VMEM).

    Dispatches to the Pallas kernel on TPU when shapes tile cleanly
    (t divisible by both block sizes, blocks 8-aligned, d a lane-friendly
    multiple — see _use_kernel); otherwise the jnp reference (identical
    math). Blocks default to the largest divisors of t up to 512/g (q) /
    1024 (k) — measured optimum on v5e. ``interpret=True`` forces the
    kernel through the Pallas interpreter — the CPU test path for kernel
    logic. ``force_kernel`` overrides the dispatch heuristic both ways
    (tiling constraints still apply) — the measurement hook behind the
    tools/roofline --mode attn crossover table.

    An EXPLICIT ``block_q``/``block_k`` is a TARGET, not a verbatim
    config: block_q is clamped to 1024//g rows (VMEM bound for folded
    GQA tiles), both are rounded down to a multiple of 8 and then to a
    divisor of t when one exists (_pick_block) — the resolved blocks may
    differ from what was passed. Callers probing an exact configuration
    should treat a changed block as "that config cannot run", not as a
    measurement of it."""
    use, block_q, block_k = _dispatch(q, k, v, block_q, block_k, interpret,
                                      force_kernel)
    q, k, v = _tag_inputs(q, k, v)
    if not use:
        return reference_attention(q, k, v, causal=causal)
    # One custom-vjp entry serves both public surfaces (the lse output is
    # a residual either way, so dropping it here costs nothing).
    return _flash_lse(q, k, v, causal, block_q, block_k, bool(interpret))[0]


# ---------------------------------------------------------------------------
# paged decode path (serving, r10)
# ---------------------------------------------------------------------------
#
# Single-query-per-sequence attention over a PAGED K/V cache
# (serve/kvcache.py): K/V live in fixed-size pages of a preallocated pool
# and each sequence owns an ordered page table. The decode step never
# materializes a contiguous [t, d] K/V tensor on TPU — the kernel walks
# the page table as its innermost grid dimension and DMAs one page per
# step, with page ids resolved through scalar-prefetch (the page table is
# in SMEM before the grid runs, so the K/V BlockSpec index_map can
# compute each step's HBM source block from it). The online-softmax
# carry (m, l, acc) is the forward kernel's, shrunk to the g rows of one
# GQA group — a decode step has exactly one query position per sequence.


def paged_decode_reference(q, k_pages, v_pages, page_table, seq_lens):
    """Pure-JAX paged decode attention — the correctness oracle and the
    off-TPU fallback (same contract as the decode kernel).

    q [s, h, d] (one query token per sequence), k_pages/v_pages
    [n_pages, page_size, h_kv, d], page_table [s, p] int32 (page ids in
    sequence order; rows padded with any valid id past the live prefix),
    seq_lens [s] int32 = valid K/V tokens per sequence INCLUDING the
    current position. Gathers pages to [s, p·page_size, h_kv, d], masks
    positions >= seq_len with the NEG_INF sentinel, f32 softmax. Rows
    with seq_len == 0 produce the uniform-softmax artifact (see
    reference_attention_lse) — callers mask inactive slots out."""
    s_n, h, d = q.shape
    n_pages, page_size, h_kv, _ = k_pages.shape
    p = page_table.shape[1]
    g = h // h_kv
    scale = d**-0.5
    k = k_pages[page_table].reshape(s_n, p * page_size, h_kv, d)
    v = v_pages[page_table].reshape(s_n, p * page_size, h_kv, d)
    q5 = q.reshape(s_n, h_kv, g, d).astype(jnp.float32) * scale
    s = jnp.einsum(
        "shgd,sthd->shgt", q5, k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )  # [s, h_kv, g, t]
    kpos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
    s = jnp.where(kpos < seq_lens[:, None, None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    pr = jnp.exp(s - m)
    l = jnp.sum(pr, axis=-1, keepdims=True)
    out = jnp.einsum(
        "shgt,sthd->shgd", pr / l, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(s_n, h, d).astype(q.dtype)


def _decode_kernel(pt_ref, sl_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, page_size, g, scale):
    """One (sequence, kv-head) pair streams its pages through VMEM. The
    innermost grid dim walks page-table SLOTS; pages past the sequence's
    live prefix are skipped with pl.when (the DMA still lands — a valid
    pool page, contents ignored). In-page positions past seq_len mask to
    NEG_INF, so a sequence ending mid-page is exact (the page-boundary-
    crossing case tests/test_flash_decode.py pins)."""
    from jax.experimental import pallas as pl

    si = pl.program_id(0)
    pi = pl.program_id(2)
    npi = pl.num_programs(2)
    d = q_ref.shape[-1]

    @pl.when(pi == 0)
    def _init():
        m_scr[:, :] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:, :] = jnp.zeros_like(l_scr)
        acc_scr[:, :] = jnp.zeros_like(acc_scr)

    length = sl_ref[si]
    live = pi * page_size < length

    @pl.when(live)
    def _step():
        q = q_ref[0, 0].reshape(g, d).astype(jnp.float32) * scale
        k = k_ref[0, :, 0, :].astype(jnp.float32)  # [page_size, d]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [g, page_size]
        kpos = pi * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos < length, s, NEG_INF)
        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:, :] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[:, :] = l_scr[:, :] * alpha[:, None] + jnp.sum(p, axis=1)[:, None]
        acc_scr[:, :] = acc_scr[:, :] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(pi == npi - 1)
    def _finish():
        # seq_len == 0 leaves l at 0 (no live page ever ran) — guard the
        # divide so inactive slots emit zeros, not nan.
        l = l_scr[:, 0]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:, :] / l[:, None]).reshape(g, d).astype(o_ref.dtype)


def _decode_call(q, k_pages, v_pages, page_table, seq_lens, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s_n, h, d = q.shape
    _, page_size, h_kv, _ = k_pages.shape
    p = page_table.shape[1]
    g = h // h_kv
    q4 = q.reshape(s_n, h_kv, g, d)

    # Scalar-prefetch args (page_table, seq_lens) reach the index_maps as
    # TRAILING refs after the grid indices — the K/V source block for
    # grid step (si, hk, pi) is whatever page the table names, which is
    # the whole paging trick.
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_n, h_kv, p),
        in_specs=[
            pl.BlockSpec((1, 1, g, d), lambda si, hk, pi, pt, sl: (si, hk, 0, 0)),
            pl.BlockSpec(
                (1, page_size, 1, d),
                lambda si, hk, pi, pt, sl: (pt[si, pi], 0, hk, 0),
            ),
            pl.BlockSpec(
                (1, page_size, 1, d),
                lambda si, hk, pi, pt, sl: (pt[si, pi], 0, hk, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, g, d), lambda si, hk, pi, pt, sl: (si, hk, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((g, LSE_LANES), jnp.float32),  # running max m
            pltpu.VMEM((g, LSE_LANES), jnp.float32),  # running sum l
            pltpu.VMEM((g, d), jnp.float32),          # output accumulator
        ],
    )
    kernel = functools.partial(
        _decode_kernel, page_size=page_size, g=g, scale=d**-0.5
    )
    o = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_n, h_kv, g, d), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), seq_lens.astype(jnp.int32),
      q4, k_pages, v_pages)
    return o.reshape(s_n, h, d)


def flash_attention_decode(
    q,
    k_pages,
    v_pages,
    page_table,
    seq_lens,
    interpret: Optional[bool] = None,
    force_kernel: Optional[bool] = None,
):
    """Paged decode attention: one query token per sequence against a
    paged K/V cache.

    q [s, h, d]; k_pages/v_pages [n_pages, page_size, h_kv, d] (the
    serve/kvcache.py pool layout); page_table [s, max_pages] int32;
    seq_lens [s] int32 (valid K/V length per sequence, INCLUDING the
    just-written current position — decode attends to itself). Returns
    [s, h, d] in q's dtype. GQA-native: h % h_kv folds into the q tile
    exactly as in the full kernel.

    Dispatch mirrors flash_attention: the Pallas kernel engages on TPU
    (or under ``interpret=True`` — the CPU test path) when the page size
    is sublane-aligned; otherwise the pure-JAX gather reference (same
    math, same f32 softmax, same NEG_INF masking) — the documented
    off-TPU path, so the serve engine runs everywhere. ``force_kernel``
    overrides the heuristic both ways (alignment still binds). Rows with
    seq_lens == 0 are inactive slots: both paths return garbage-but-
    finite output there (zeros from the kernel, the uniform artifact
    from the reference) — callers mask, never read."""
    if q.ndim != 3 or k_pages.ndim != 4:
        raise ValueError(
            f"decode shapes: q [s,h,d] (got {q.shape}), pages "
            f"[n,page,h_kv,d] (got {k_pages.shape})"
        )
    if k_pages.shape != v_pages.shape:
        raise ValueError(f"k/v pool mismatch: {k_pages.shape} vs {v_pages.shape}")
    h, h_kv = q.shape[1], k_pages.shape[2]
    if h % h_kv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {h_kv}")
    page_size = k_pages.shape[1]
    aligned = page_size % 8 == 0
    use = aligned and (bool(interpret) or jax.default_backend() == "tpu")
    if force_kernel is not None:
        use = force_kernel and aligned and (
            bool(interpret) or jax.default_backend() == "tpu"
        )
    if not use:
        return paged_decode_reference(q, k_pages, v_pages, page_table, seq_lens)
    return _decode_call(
        q, k_pages, v_pages, page_table, seq_lens, bool(interpret)
    )
