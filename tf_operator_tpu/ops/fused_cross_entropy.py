"""Fused (blockwise) softmax cross-entropy over a tied vocab projection.

The naive LM loss materializes f32 logits ``[batch*seq, vocab]`` in HBM
(BERT-base at b=32/s=512: 2.0 GB), then log_softmax re-reads and re-writes
them, the gather reads them again, and autodiff stores log-probs as a
residual for the backward — on a bandwidth-bound chip those passes cost
more than the head matmul itself. This op never materializes the logits:
the hidden states are processed in row (token) blocks, each block computes
its ``[rows, vocab]`` logits tile on the MXU with f32 accumulation,
reduces it to a log-sum-exp and the target logit immediately, and the
backward pass recomputes the tile (flash-attention-style) to form
``softmax - onehot`` on the fly. Residuals are just the per-token LSE —
O(batch*seq) instead of O(batch*seq*vocab).

The reference operator has no numerics at all (SURVEY.md §2 — it
configures TensorFlow's runtime); this is part of the TPU data-plane layer
that replaces what TF shipped pre-compiled. Same-math unfused path =
``models.transformer.lm_loss`` with ``fused_xent=False``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def fused_cross_entropy(
    x: jax.Array,
    embed: jax.Array,
    targets: jax.Array,
    weights: Optional[jax.Array] = None,
    *,
    row_block: int = 1024,
) -> jax.Array:
    """Mean softmax cross-entropy of ``x @ embed.T`` against ``targets``.

    Args:
      x: [n, d] hidden states (bf16 or f32). Differentiated.
      embed: [vocab, d] tied projection table (f32 params). Differentiated.
      targets: [n] int32 class ids. Not differentiated.
      weights: optional [n] per-token weights (e.g. an MLM mask); the loss
        is ``sum(w * xent) / max(sum(w), 1)`` — with weights omitted this
        is the plain mean, matching the unfused path exactly.
      row_block: tokens per block; each block's logit tile is
        ``[row_block, vocab]`` f32 and lives only inside the block.

    Returns: scalar f32 loss.
    """
    n, d = x.shape
    if n == 0:
        raise ValueError(
            "fused_cross_entropy needs at least one row (n=0; causal lm_loss "
            "with seq_len=1 produces an empty target set)"
        )
    if weights is None:
        weights = jnp.ones((n,), jnp.float32)
    weights = weights.astype(jnp.float32)

    r = min(row_block, _round_up(n, 8))
    n_pad = _round_up(n, r)
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
        targets = jnp.pad(targets, (0, n_pad - n))
        weights = jnp.pad(weights, (0, n_pad - n))  # pad rows weigh zero
    nb = n_pad // r

    # targets/weights ride the closure: non-differentiated, trace-constant
    # structure. Only (x, embed) are custom_vjp primals.
    @jax.custom_vjp
    def weighted_xent_sum(x, embed):
        return _fwd(x, embed)[0]

    def _fwd(x, embed):
        et = embed.astype(x.dtype)  # one cast, reused by every block
        cols = jnp.arange(embed.shape[0], dtype=targets.dtype)

        def block(loss_sum, inp):
            x_c, t_c, w_c = inp
            logits = jnp.dot(x_c, et.T, preferred_element_type=jnp.float32)
            m = jnp.max(logits, axis=-1)
            lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
            # target logit via a fused compare+select reduction over the tile
            # (a take_along_axis gather here costs a real gather op per block)
            onehot = t_c[:, None] == cols[None, :]
            tgt = jnp.sum(jnp.where(onehot, logits, 0.0), axis=-1)
            return loss_sum + jnp.sum(w_c * (lse - tgt)), lse

        xs = (x.reshape(nb, r, d), targets.reshape(nb, r), weights.reshape(nb, r))
        loss_sum, lse = jax.lax.scan(block, jnp.float32(0.0), xs)
        return loss_sum, (x, embed, lse)

    def _bwd(res, g):
        x, embed, lse = res
        et = embed.astype(x.dtype)
        coef = (g * weights).reshape(nb, r)

        cols = jnp.arange(embed.shape[0], dtype=targets.dtype)

        def block(d_embed, inp):
            x_c, t_c, c_c, lse_c = inp
            logits = jnp.dot(x_c, et.T, preferred_element_type=jnp.float32)
            p = jnp.exp(logits - lse_c[:, None])  # softmax, recomputed
            # minus onehot(target), as fused select (not a scatter)
            p = jnp.where(t_c[:, None] == cols[None, :], p - 1.0, p)
            pc = (p * c_c[:, None]).astype(x.dtype)
            dx_c = jnp.dot(pc, et, preferred_element_type=jnp.float32)
            d_embed = d_embed + jnp.dot(pc.T, x_c, preferred_element_type=jnp.float32)
            return d_embed, dx_c

        xs = (x.reshape(nb, r, d), targets.reshape(nb, r), coef, lse)
        d_embed, dx = jax.lax.scan(block, jnp.zeros(embed.shape, jnp.float32), xs)
        # dx matches the (padded) primal x; autodiff of the outer jnp.pad
        # slices the pad rows back off for the caller.
        dx = dx.reshape(n_pad, d).astype(x.dtype)
        return dx, d_embed.astype(embed.dtype)

    weighted_xent_sum.defvjp(_fwd, _bwd)

    denom = jnp.maximum(jnp.sum(weights), 1.0)
    return weighted_xent_sum(x, embed) / denom
