"""Pallas TPU kernels for the hot ops.

The reference contains no numerics code at all (SURVEY.md §2: the operator
configures TensorFlow, it never touches tensors); this package is the
TPU-native data-plane layer the workload library builds on — attention is
where long-context FLOPs and HBM traffic concentrate, so it gets a
hand-written kernel while everything else rides XLA fusion.
"""

from tf_operator_tpu.ops.flash_attention import flash_attention  # noqa: F401
from tf_operator_tpu.ops.fused_cross_entropy import fused_cross_entropy  # noqa: F401
