"""Fused matmul + batch-norm-statistics epilogue (Pallas TPU kernel).

The ResNet train step's biggest non-conv cost is the BN batch-stats
barrier: every conv output is written to HBM, re-read to reduce E[x] and
E[x²], and read again to normalize — measured 10.8 ms of a 51.4 ms
ResNet-50 train step on v5e (tools/roofline decomposition). XLA cannot
fuse a cross-row reduction into a convolution's output epilogue, so that
traffic is irreducible *in XLA*. But a 1x1 convolution IS a matmul
([b·h·w, cin] x [cin, cout]) — and ~83% of ResNet-50's BN'd activations
come out of 1x1 convs (bottleneck conv1/conv3/proj). This kernel computes
the matmul AND accumulates per-channel sum / sum-of-squares while the
output block is still in VMEM: the statistics cost zero extra HBM
traffic. The input side optionally applies the PREVIOUS layer's
normalize+ReLU while loading (prologue), so that elementwise pass fuses
away too.

Grid design: (N-blocks, M-blocks, K-blocks) with K innermost (sequential
on TPU) carrying the f32 accumulator in VMEM scratch — the standard
pallas matmul shape. M iterates inside N so the per-channel stats block
(indexed by N only) stays resident across all M-blocks and accumulates;
TPU grids execute sequentially, which is what makes cross-step output
accumulation sound (same reasoning as flash_attention.py's carried
scratch).

Backward is NOT a kernel: dsum/dssq cotangents fold into an effective
dy (dy + dsum + 2·y·dssq), after which dx/dw are plain matmuls XLA
already does at peak — see _fused_bwd. Only the forward needed custom
fusion.

Stats semantics: sum/ssq are accumulated in f32 from the UNROUNDED f32
matmul accumulator — slightly better conditioned than the XLA path
(which reduces the bf16-rounded activations). Means agree to bf16
tolerance; tests pin the parity.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

# Stats are carried as [_STAT_ROWS, N] with each row holding partial/8 —
# a 1-sublane block would fight TPU (8, 128) tiling; callers sum axis 0.
_STAT_ROWS = 8


def _pick(dim: int, target: int) -> int:
    """Largest divisor of dim not exceeding target, 8-aligned if possible.

    Sibling of flash_attention._pick_block with a different fallback
    contract, deliberately: there, a non-dividing block routes dispatch to
    the dense fallback (returning `target` is the rejection signal); here
    the kernel MUST run for whatever shape it was handed, so the fallback
    walks down to any true divisor (worst case dim itself) — never an
    invalid tiling."""
    if dim <= target:
        return dim
    for cand in range(target, 7, -8):
        if dim % cand == 0:
            return cand
    for cand in range(target, 0, -1):
        if dim % cand == 0:
            return cand
    return dim


def _kernel(x_ref, w_ref, a_ref, b_ref, y_ref, sum_ref, ssq_ref, acc,
            *, nk_steps, relu_in, out_dtype):
    from jax.experimental import pallas as pl

    nm = pl.program_id(1)
    nk = pl.program_id(2)

    @pl.when(nk == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)

    x = x_ref[...].astype(jnp.float32)  # [bm, bk]
    if relu_in:
        # previous layer's folded BN affine + ReLU applied while loading:
        # the normalize pass never exists as HBM traffic
        x = jax.nn.relu(x * a_ref[...] + b_ref[...])
    w = w_ref[...].astype(jnp.float32)  # [bk, bn]
    acc[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(nk == nk_steps - 1)
    def _epilogue():
        y = acc[...]  # [bm, bn] f32 — still in VMEM
        y_ref[...] = y.astype(out_dtype)

        @pl.when(nm == 0)
        def _zero():
            sum_ref[...] = jnp.zeros_like(sum_ref)
            ssq_ref[...] = jnp.zeros_like(ssq_ref)

        # per-channel partials, spread over _STAT_ROWS sublanes (each row
        # carries partial/_STAT_ROWS; the host-side wrapper sums rows)
        s = jnp.sum(y, axis=0) / _STAT_ROWS  # [bn]
        q = jnp.sum(y * y, axis=0) / _STAT_ROWS
        sum_ref[...] += jnp.broadcast_to(s[None, :], sum_ref.shape)
        ssq_ref[...] += jnp.broadcast_to(q[None, :], ssq_ref.shape)


def _fwd_impl(x, w, a, b, relu_in: bool, interpret: bool,
              block_m: int, block_n: int, block_k: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = x.shape
    _, n = w.shape
    bm = _pick(m, block_m)
    bn = _pick(n, block_n)
    bk = _pick(k, block_k)
    grid = (n // bn, m // bm, k // bk)

    a2 = a.reshape(1, k).astype(jnp.float32)
    b2 = b.reshape(1, k).astype(jnp.float32)

    kernel = functools.partial(
        _kernel, nk_steps=grid[2], relu_in=relu_in, out_dtype=x.dtype
    )
    y, s, q = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda nn, nm, nk: (nm, nk),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, bn), lambda nn, nm, nk: (nk, nn),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk), lambda nn, nm, nk: (0, nk),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk), lambda nn, nm, nk: (0, nk),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda nn, nm, nk: (nm, nn),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_STAT_ROWS, bn), lambda nn, nm, nk: (0, nn),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_STAT_ROWS, bn), lambda nn, nm, nk: (0, nn),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), x.dtype),
            jax.ShapeDtypeStruct((_STAT_ROWS, n), jnp.float32),
            jax.ShapeDtypeStruct((_STAT_ROWS, n), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        # N-blocks are independent (parallel); M must stay sequential — the
        # stats block accumulates across M-steps; K carries the accumulator.
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(x, w, a2, b2)
    return y, jnp.sum(s, axis=0), jnp.sum(q, axis=0)


def _reference(x, w, a, b, relu_in: bool):
    """Same math, plain jnp — the off-TPU fallback and correctness oracle."""
    xin = x.astype(jnp.float32)
    if relu_in:
        xin = jax.nn.relu(xin * a.astype(jnp.float32) + b.astype(jnp.float32))
    y32 = xin @ w.astype(jnp.float32)
    y = y32.astype(x.dtype)
    return y, jnp.sum(y32, axis=0), jnp.sum(y32 * y32, axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _fused(x, w, a, b, relu_in, interpret, bm, bn, bk):
    out, _ = _fused_fwd(x, w, a, b, relu_in, interpret, bm, bn, bk)
    return out


def _fused_fwd(x, w, a, b, relu_in, interpret, bm, bn, bk):
    use_kernel = interpret or jax.default_backend() == "tpu"
    if use_kernel:
        y, s, q = _fwd_impl(x, w, a, b, relu_in, interpret, bm, bn, bk)
    else:
        y, s, q = _reference(x, w, a, b, relu_in)
    return (y, s, q), (x, w, a, b, y)


def _fused_bwd(relu_in, interpret, bm, bn, bk, residuals, cts):
    del interpret, bm, bn, bk
    x, w, a, b, y = residuals
    dy, dsum, dssq = cts
    # Cotangents of the stats fold into an effective dy: sum and ssq are
    # row-reductions of y, so d/dy sum = 1 and d/dy ssq = 2y.
    dy_eff = (
        dy.astype(jnp.float32)
        + dsum[None, :]
        + 2.0 * y.astype(jnp.float32) * dssq[None, :]
    )
    xin = x.astype(jnp.float32)
    if relu_in:
        pre = xin * a.astype(jnp.float32) + b.astype(jnp.float32)
        xin = jax.nn.relu(pre)
    dw = (xin.T @ dy_eff).astype(w.dtype)
    dxin = dy_eff @ w.astype(jnp.float32).T
    if relu_in:
        mask = (pre > 0).astype(jnp.float32)
        dpre = dxin * mask
        dx = (dpre * a.astype(jnp.float32)).astype(x.dtype)
        da = jnp.sum(dpre * x.astype(jnp.float32), axis=0).astype(a.dtype)
        db = jnp.sum(dpre, axis=0).astype(b.dtype)
    else:
        dx = dxin.astype(x.dtype)
        da = jnp.zeros_like(a)
        db = jnp.zeros_like(b)
    return dx, dw, da, db


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_linear_stats(
    x,
    w,
    prologue: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """y = (relu(x·a + b) if prologue else x) @ w, plus per-column
    (sum, sum-of-squares) of y computed in the matmul epilogue.

    x: [M, K]; w: [K, N]; prologue: optional (a [K], b [K]) — the previous
    layer's folded BN affine, applied with ReLU while loading x.
    Returns (y [M, N] in x.dtype, sum [N] f32, ssq [N] f32).

    On TPU this is one Pallas kernel (stats cost no HBM traffic); off-TPU
    an identical-math jnp fallback keeps CPU tests running. Fully
    differentiable (custom VJP: stats cotangents fold into dy, then plain
    matmuls).
    """
    if prologue is None:
        k = x.shape[1]
        a = jnp.ones((k,), jnp.float32)
        b = jnp.zeros((k,), jnp.float32)
        relu_in = False
    else:
        a, b = prologue
        relu_in = True
    return _fused(x, w, a, b, relu_in, bool(interpret), 512, 512, 512)
