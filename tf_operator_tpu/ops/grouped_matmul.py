"""Grouped (block-diagonal) matmul as a Pallas TPU kernel — MoE experts
without capacity padding.

The capacity-queue formulation pads every expert's token queue to
cf·k·T/E rows, so expert FLOPs scale with cf (2x the active FLOPs at the
quality-safe cf=2 — the top structural term in the r4 MoE decomposition,
BASELINE.md). ``jax.lax.ragged_dot`` removes the padding in principle but
its XLA lowering measured ~19 TFLOP/s at moe-small bench shapes vs the
~50 TFLOP/s the same chip sustains on the equivalent dense matmul (r5
probe) — the lowering runs full-height masked matmuls per group. This
kernel is the Megablocks-style alternative the VERDICT asked for:

- Tokens arrive SORTED by expert and padded only to the row-block
  granularity B (total rows R = T·k rounded up per expert: overhead
  E·B/(T·k) worst case — 12.5% at B=256 on the bench shapes, vs 100%
  for cf=2).
- The grid walks (row-block i, col-tile j); a scalar-prefetched
  ``block_expert[i]`` array steers the WEIGHT BlockSpec index map, so
  each step loads exactly its expert's [k, bn] weight tile into VMEM —
  no [NB, k, n] gathered-weight materialization (the XLA block-diagonal
  einsum formulation measured slower than the padded vmap for exactly
  that traffic).
- r6, ep sharding: ``block_expert`` entries may be ``-1`` — SENTINEL
  blocks. The static grid still visits them (XLA needs static shapes;
  the ep all_to_all hands each shard a worst-case-sized buffer whose
  occupancy is data-dependent) but the kernel skips the dot and writes
  zeros, so sentinel blocks cost a VMEM zero-fill instead of MXU FLOPs
  — compute scales with OCCUPIED blocks, not the static bound.
- r6, fused combine epilogue: ``row_scale`` (one f32 per row) multiplies
  the output rows INSIDE the kernel. The MoE combine is
  out[t] = Σ_k w[t,k]·expert(x)[slot[t,k]]; scaling the down-projection's
  output rows by their gate weight in the epilogue turns the combine
  into a pure gather+sum and retires the separate f32 [T,k,d]
  weighted-reduction pass the einsum combine paid per layer.
- r6, dw grid: (expert, col-tile, block-walk) with scalar-prefetched
  per-expert block LISTS, so the output tile's index map depends only on
  grid indices — the f32 [k, bn] accumulator stays resident in VMEM
  across an expert's whole block walk. The previous grid steered the
  output window by ``block_expert[i]`` per step, which is data-dependent:
  the pipeline must conservatively round-trip the accumulator tile
  HBM↔VMEM at every step (k=768, bn=3072 ⇒ ~9 MB x2 per 256-row block —
  the dw walk the r5 roofline named as the kernel's remaining headroom).
  Walk steps beyond an expert's real block count are skipped
  (``l < nblocks[e]``) and their input index maps repeat the last valid
  block so the window doesn't change (no re-DMA); every (expert,
  col-tile) tile is ZEROED at walk step 0, so an expert with zero blocks
  gets an exact-zero gradient rather than uninitialized output memory.

Everything is differentiable through a custom_vjp: dx is the same kernel
with transposed weights (sentinel blocks write zero cotangents, which
keeps the upstream gather/scatter transposes clean), dw the accumulation
kernel, and row_scale's cotangent reuses the dx kernel's unscaled
product (ds[r] = x[r]·(dy[r]@Wᵀ) = dy[r]·(x[r]@W) — no extra matmul).
The sort/pad bookkeeping lives in parallel.moe (_moe_single_gmm /
_moe_local_gmm).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _pick_cols(n: int, target: int) -> int:
    """Largest 128-aligned divisor of n not exceeding target (falls back
    to n itself for small/odd widths — one tile)."""
    if n <= target:
        return n
    for cand in range(target - target % 128, 127, -128):
        if n % cand == 0:
            return cand
    return n


def _auto_cols(n: int, k: int, elem_bytes: int) -> int:
    """Column tile bounded by a ~4 MB VMEM budget for the [k, bn] weight
    tile (fwd) or the f32 [k, bn] accumulator (dw). Wider is faster:
    full-width tiles measured 60.6 TFLOP/s vs 52.0 at bn=512 on the
    moe-small shapes (98% of XLA's same-FLOPs dense rate) — the
    per-grid-step dot is what feeds the MXU."""
    return _pick_cols(n, max(128, (4 * 2**20) // (elem_bytes * k)))


def gmm(x, w, block_expert, *, row_scale=None, block_rows: int = 256,
        block_cols: int | None = None, interpret: bool = False):
    """y[r] = x[r] @ w[block_expert[r // block_rows]]  (· row_scale[r]).

    x: [R, k] with R % block_rows == 0, rows grouped so every row-block
    maps to ONE expert; w: [E, k, n]; block_expert: [R // block_rows]
    int32 — entries may be ``-1`` (sentinel: the block's output rows are
    written as zeros and no FLOPs are spent; used by the ep-sharded
    dispatch whose statically-sized all-to-all buffers are partially
    occupied). ``row_scale``: optional [R] f32 applied to the output
    rows inside the kernel (the fused MoE combine epilogue).
    Returns [R, n] in x.dtype (f32 MXU accumulation inside).
    Differentiable in x, w and row_scale (not in block_expert — routing
    indices). ``block_cols`` None = VMEM-budgeted auto (the
    measured-fastest full-width tiles where they fit). ``interpret``
    runs the Pallas interpreter (CPU test path)."""
    if row_scale is None:
        return _gmm(x, w, block_expert, block_rows, block_cols,
                    bool(interpret))
    return _gmm_scaled(x, w, row_scale, block_expert, block_rows,
                       block_cols, bool(interpret))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _gmm(x, w, block_expert, block_rows, block_cols, interpret):
    return _gmm_call(x, w, None, block_expert, block_rows, block_cols,
                     interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _gmm_scaled(x, w, row_scale, block_expert, block_rows, block_cols,
                interpret):
    return _gmm_call(x, w, row_scale, block_expert, block_rows, block_cols,
                     interpret)


def _gmm_fwd_kernel(be_ref, x_ref, w_ref, o_ref):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    e = be_ref[i]

    @pl.when(e >= 0)
    def _compute():
        o_ref[...] = jax.lax.dot_general(
            x_ref[...], w_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(o_ref.dtype)

    @pl.when(e < 0)
    def _sentinel():
        # sentinel blocks still own output rows (static shapes): write
        # zeros so downstream gathers/transposes never see uninitialized
        # memory, but spend no MXU work
        o_ref[...] = jnp.zeros_like(o_ref)


def _gmm_fwd_scaled_kernel(be_ref, x_ref, w_ref, s_ref, o_ref):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    e = be_ref[i]

    @pl.when(e >= 0)
    def _compute():
        acc = jax.lax.dot_general(
            x_ref[...], w_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # the combine epilogue: gate-weight each output row while the
        # tile is still in VMEM — the [T,k,d] weighted-reduction pass
        # this replaces is pure HBM traffic
        o_ref[...] = (acc * s_ref[...]).astype(o_ref.dtype)

    @pl.when(e < 0)
    def _sentinel():
        o_ref[...] = jnp.zeros_like(o_ref)


def _gmm_call(x, w, row_scale, block_expert, block_rows, block_cols,
              interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, k = x.shape
    E, k2, n = w.shape
    if k2 != k:
        raise ValueError(f"contraction mismatch: x k={k} vs w k={k2}")
    if R % block_rows:
        raise ValueError(f"rows {R} not divisible by block_rows {block_rows}")
    # Budget on the INPUT's element size (not a hardcoded bf16 2): an f32
    # x/w would otherwise get a [k, bn] weight tile 2x the 4 MB budget
    # and fail VMEM-exceeded at compile (the dw path already budgets on
    # its f32 accumulator's 4 bytes).
    bn = (
        _auto_cols(n, k, x.dtype.itemsize)
        if block_cols is None
        else _pick_cols(n, block_cols)
    )
    nb = R // block_rows

    in_specs = [
        pl.BlockSpec((block_rows, k), lambda i, j, be: (i, 0),
                     memory_space=pltpu.VMEM),
        # sentinel blocks (-1) clamp to expert 0's tile — a dead DMA the
        # skipped dot never reads
        pl.BlockSpec((1, k, bn),
                     lambda i, j, be: (jnp.maximum(be[i], 0), 0, j),
                     memory_space=pltpu.VMEM),
    ]
    operands = [block_expert, x, w]
    kernel = _gmm_fwd_kernel
    if row_scale is not None:
        in_specs.append(
            pl.BlockSpec((block_rows, 1), lambda i, j, be: (i, 0),
                         memory_space=pltpu.VMEM)
        )
        operands.append(row_scale.astype(jnp.float32).reshape(R, 1))
        kernel = _gmm_fwd_scaled_kernel
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb, n // bn),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_rows, bn), lambda i, j, be: (i, j),
                               memory_space=pltpu.VMEM),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, n), x.dtype),
        interpret=interpret,
    )(*operands)


def _dw_kernel(nb_ref, bl_ref, x_ref, dy_ref, dw_ref):
    from jax.experimental import pallas as pl

    e = pl.program_id(0)
    l = pl.program_id(2)  # block-walk step — INNERMOST (accumulation dim)

    @pl.when(l == 0)
    def _zero():
        # every (expert, col-tile) zeroes at walk start — an expert with
        # ZERO blocks gets an exact-zero dw tile, never uninitialized
        # kernel output memory
        dw_ref[...] = jnp.zeros_like(dw_ref)

    @pl.when(l < nb_ref[e])
    def _accum():
        dw_ref[...] += jax.lax.dot_general(
            x_ref[...], dy_ref[...],
            (((0,), (0,)), ((), ())),  # [bR,k]ᵀ·[bR,bn] -> [k,bn]
            preferred_element_type=jnp.float32,
        )[None]


def _dw_scaled_kernel(nb_ref, bl_ref, x_ref, dy_ref, s_ref, dw_ref):
    from jax.experimental import pallas as pl

    e = pl.program_id(0)
    l = pl.program_id(2)

    @pl.when(l == 0)
    def _zero():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    @pl.when(l < nb_ref[e])
    def _accum():
        # dw_e = Σ (s⊙x)ᵀ·dy — the scale rides the x rows so the scaled
        # forward's weight cotangent needs no [R,d] pre-scaled copy of x
        dw_ref[...] += jax.lax.dot_general(
            x_ref[...] * s_ref[...], dy_ref[...],
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )[None]


def _expert_block_lists(block_expert, n_experts: int, nb: int):
    """Per-expert block lists from a block→expert map: blist[e, l] = the
    l-th row-block of expert e (walk entries past an expert's count
    repeat its LAST valid block so the input window never changes on
    skipped steps — no re-DMA), nblocks[e] = its real count. Sentinel
    (-1) blocks belong to no expert."""
    be = block_expert.astype(jnp.int32)
    bucket = jnp.where(be >= 0, be, n_experts)  # sentinels into a spare bucket
    order = jnp.argsort(bucket, stable=True).astype(jnp.int32)
    cnt = jnp.bincount(bucket, length=n_experts + 1)[:n_experts].astype(jnp.int32)
    starts = jnp.cumsum(cnt) - cnt  # [E]
    walk = jnp.minimum(jnp.arange(nb, dtype=jnp.int32)[None, :],
                       jnp.maximum(cnt[:, None] - 1, 0))
    idx = jnp.clip(starts[:, None] + walk, 0, nb - 1)
    return cnt, order[idx].reshape(-1)  # nblocks [E], blist [E*nb]


def _gmm_dw(x, dy, w_shape, block_expert, block_rows, block_cols, interpret,
            row_scale=None):
    """dw[e] = Σ_{blocks of e} x_blᵀ @ dy_bl — grid (expert, col-tile,
    block-walk) over scalar-prefetched per-expert block lists. The
    output tile's index map is (e, 0, j): grid-only, so the f32
    accumulator stays in VMEM for the whole inner walk instead of
    round-tripping per step behind a data-dependent window."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, k = x.shape
    E, k2, n = w_shape
    # dw accumulates in an f32 [k, bn] output tile held across the inner
    # block walk — budget on 4 bytes, not the bf16 fwd tile
    bn = _auto_cols(n, k, 4) if block_cols is None else _pick_cols(n, block_cols)
    nb = R // block_rows
    nblocks, blist = _expert_block_lists(block_expert, E, nb)

    def x_map(e, j, l, nbr, blr):
        return (blr[e * nb + l], 0)

    def dy_map(e, j, l, nbr, blr):
        return (blr[e * nb + l], j)

    in_specs = [
        pl.BlockSpec((block_rows, k), x_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((block_rows, bn), dy_map, memory_space=pltpu.VMEM),
    ]
    operands = [nblocks, blist, x, dy]
    kernel = _dw_kernel
    if row_scale is not None:
        in_specs.append(
            pl.BlockSpec((block_rows, 1), x_map, memory_space=pltpu.VMEM)
        )
        operands.append(row_scale.astype(jnp.float32).reshape(R, 1))
        kernel = _dw_scaled_kernel
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(E, n // bn, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, k, bn), lambda e, j, l, nbr, blr: (e, 0, j),
                               memory_space=pltpu.VMEM),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((E, k, n), jnp.float32),
        interpret=interpret,
    )(*operands)


def _gmm_fwd_rule(x, w, block_expert, block_rows, block_cols, interpret):
    y = _gmm_call(x, w, None, block_expert, block_rows, block_cols, interpret)
    return y, (x, w, block_expert)


def _gmm_bwd_rule(block_rows, block_cols, interpret, res, dy):
    x, w, block_expert = res
    # dx: the same grouped matmul against transposed weight tiles. The
    # [E, n, k] transpose materializes once per call (~2 copies of w in
    # HBM traffic — ~0.3 ms at moe-small shapes, negligible next to the
    # padded-FLOP term this kernel retires).
    dx = _gmm_call(
        dy, jnp.swapaxes(w, 1, 2), None, block_expert, block_rows,
        block_cols, interpret,
    )
    dw = _gmm_dw(
        x, dy, w.shape, block_expert, block_rows, block_cols, interpret
    ).astype(w.dtype)
    return dx.astype(x.dtype), dw, None


_gmm.defvjp(_gmm_fwd_rule, _gmm_bwd_rule)


def _gmm_scaled_fwd_rule(x, w, row_scale, block_expert, block_rows,
                         block_cols, interpret):
    y = _gmm_call(x, w, row_scale, block_expert, block_rows, block_cols,
                  interpret)
    return y, (x, w, row_scale, block_expert)


def _gmm_scaled_bwd_rule(block_rows, block_cols, interpret, res, dy):
    x, w, row_scale, block_expert = res
    # One UNSCALED transposed product serves two cotangents:
    #   t = dy @ w_eᵀ  ⇒  dx = s ⊙ t   and   ds[r] = x[r]·t[r]
    # (x·(dy@wᵀ) = (x@w)·dy — the scale's cotangent without recomputing
    # the forward or saving an unscaled copy of y).
    t = _gmm_call(
        dy, jnp.swapaxes(w, 1, 2), None, block_expert, block_rows,
        block_cols, interpret,
    ).astype(jnp.float32)
    dx = row_scale.astype(jnp.float32)[:, None] * t
    ds = jnp.sum(x.astype(jnp.float32) * t, axis=-1)
    dw = _gmm_dw(
        x, dy, w.shape, block_expert, block_rows, block_cols, interpret,
        row_scale=row_scale,
    ).astype(w.dtype)
    return dx.astype(x.dtype), dw, ds.astype(row_scale.dtype), None


_gmm_scaled.defvjp(_gmm_scaled_fwd_rule, _gmm_scaled_bwd_rule)
