"""Grouped (block-diagonal) matmul as a Pallas TPU kernel — MoE experts
without capacity padding.

The capacity-queue formulation pads every expert's token queue to
cf·k·T/E rows, so expert FLOPs scale with cf (2x the active FLOPs at the
quality-safe cf=2 — the top structural term in the r4 MoE decomposition,
BASELINE.md). ``jax.lax.ragged_dot`` removes the padding in principle but
its XLA lowering measured ~19 TFLOP/s at moe-small bench shapes vs the
~50 TFLOP/s the same chip sustains on the equivalent dense matmul (r5
probe) — the lowering runs full-height masked matmuls per group. This
kernel is the Megablocks-style alternative the VERDICT asked for:

- Tokens arrive SORTED by expert and padded only to the row-block
  granularity B (total rows R = T·k rounded up per expert: overhead
  E·B/(T·k) worst case — 12.5% at B=256 on the bench shapes, vs 100%
  for cf=2).
- The grid walks (row-block i, col-tile j); a scalar-prefetched
  ``block_expert[i]`` array steers the WEIGHT BlockSpec index map, so
  each step loads exactly its expert's [k, bn] weight tile into VMEM —
  no [NB, k, n] gathered-weight materialization (the XLA block-diagonal
  einsum formulation measured slower than the padded vmap for exactly
  that traffic).
- dw runs as a second kernel with the row-blocks INNERMOST: consecutive
  grid steps that share an expert revisit the same output tile, which is
  the TPU-legal accumulation pattern (same rule the flash kernels use
  for their carried scratch); the first block of each expert zeroes the
  tile.

Everything is differentiable through a custom_vjp: dx is the same kernel
with transposed weights, dw the accumulation kernel. The sort/pad
bookkeeping lives in parallel.moe (_moe_single_gmm).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _gmm_fwd_kernel(be_ref, x_ref, w_ref, o_ref):
    o_ref[...] = jax.lax.dot_general(
        x_ref[...], w_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(o_ref.dtype)


def _pick_cols(n: int, target: int) -> int:
    """Largest 128-aligned divisor of n not exceeding target (falls back
    to n itself for small/odd widths — one tile)."""
    if n <= target:
        return n
    for cand in range(target - target % 128, 127, -128):
        if n % cand == 0:
            return cand
    return n


def _auto_cols(n: int, k: int, elem_bytes: int) -> int:
    """Column tile bounded by a ~4 MB VMEM budget for the [k, bn] weight
    tile (fwd) or the f32 [k, bn] accumulator (dw). Wider is faster:
    full-width tiles measured 60.6 TFLOP/s vs 52.0 at bn=512 on the
    moe-small shapes (98% of XLA's same-FLOPs dense rate) — the
    per-grid-step dot is what feeds the MXU."""
    return _pick_cols(n, max(128, (4 * 2**20) // (elem_bytes * k)))


def gmm(x, w, block_expert, *, block_rows: int = 256,
        block_cols: int | None = None, interpret: bool = False):
    """y[r] = x[r] @ w[block_expert[r // block_rows]].

    x: [R, k] with R % block_rows == 0, rows grouped so every row-block
    maps to ONE expert; w: [E, k, n]; block_expert: [R // block_rows]
    int32. Returns [R, n] in x.dtype (f32 MXU accumulation inside).
    Differentiable in x and w (not in block_expert — routing indices).
    ``block_cols`` None = VMEM-budgeted auto (the measured-fastest
    full-width tiles where they fit). ``interpret`` runs the Pallas
    interpreter (CPU test path)."""
    return _gmm(x, w, block_expert, block_rows, block_cols, bool(interpret))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _gmm(x, w, block_expert, block_rows, block_cols, interpret):
    return _gmm_call(x, w, block_expert, block_rows, block_cols, interpret)


def _gmm_call(x, w, block_expert, block_rows, block_cols, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, k = x.shape
    E, k2, n = w.shape
    if k2 != k:
        raise ValueError(f"contraction mismatch: x k={k} vs w k={k2}")
    if R % block_rows:
        raise ValueError(f"rows {R} not divisible by block_rows {block_rows}")
    # Budget on the INPUT's element size (not a hardcoded bf16 2): an f32
    # x/w would otherwise get a [k, bn] weight tile 2x the 4 MB budget
    # and fail VMEM-exceeded at compile (the dw path already budgets on
    # its f32 accumulator's 4 bytes).
    bn = (
        _auto_cols(n, k, x.dtype.itemsize)
        if block_cols is None
        else _pick_cols(n, block_cols)
    )
    nb = R // block_rows

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb, n // bn),
        in_specs=[
            pl.BlockSpec((block_rows, k), lambda i, j, be: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k, bn), lambda i, j, be: (be[i], 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, bn), lambda i, j, be: (i, j),
                               memory_space=pltpu.VMEM),
    )
    return pl.pallas_call(
        _gmm_fwd_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, n), x.dtype),
        interpret=interpret,
    )(block_expert, x, w)


def _dw_kernel(be_ref, x_ref, dy_ref, dw_ref):
    from jax.experimental import pallas as pl

    i = pl.program_id(1)  # row-block index — INNERMOST (accumulation dim)
    e = be_ref[i]
    prev = be_ref[jnp.maximum(i - 1, 0)]
    first = jnp.logical_or(i == 0, e != prev)

    @pl.when(first)
    def _zero():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    dw_ref[...] += jax.lax.dot_general(
        x_ref[...], dy_ref[...],
        (((0,), (0,)), ((), ())),  # [bR,k]ᵀ·[bR,bn] -> [k,bn]
        preferred_element_type=jnp.float32,
    )[None]


def _gmm_dw(x, dy, w_shape, block_expert, block_rows, block_cols, interpret):
    """dw[e] = Σ_{blocks i of e} x_i^T @ dy_i — grid (col-tile, row-block)
    with row-blocks innermost so same-expert revisits are consecutive."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, k = x.shape
    E, k2, n = w_shape
    # dw accumulates in an f32 [k, bn] output tile held across the inner
    # row-block walk — budget on 4 bytes, not the bf16 fwd tile
    bn = _auto_cols(n, k, 4) if block_cols is None else _pick_cols(n, block_cols)
    nb = R // block_rows

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // bn, nb),
        in_specs=[
            pl.BlockSpec((block_rows, k), lambda j, i, be: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, bn), lambda j, i, be: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, k, bn), lambda j, i, be: (be[i], 0, j),
                               memory_space=pltpu.VMEM),
    )
    return pl.pallas_call(
        _dw_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((E, k, n), jnp.float32),
        interpret=interpret,
    )(block_expert, x, dy)


def _gmm_fwd_rule(x, w, block_expert, block_rows, block_cols, interpret):
    y = _gmm_call(x, w, block_expert, block_rows, block_cols, interpret)
    return y, (x, w, block_expert)


def _gmm_bwd_rule(block_rows, block_cols, interpret, res, dy):
    x, w, block_expert = res
    # dx: the same grouped matmul against transposed weight tiles. The
    # [E, n, k] transpose materializes once per call (~2 copies of w in
    # HBM traffic — ~0.3 ms at moe-small shapes, negligible next to the
    # padded-FLOP term this kernel retires).
    dx = _gmm_call(
        dy, jnp.swapaxes(w, 1, 2), block_expert, block_rows, block_cols,
        interpret,
    )
    dw = _gmm_dw(
        x, dy, w.shape, block_expert, block_rows, block_cols, interpret
    ).astype(w.dtype)
    return dx.astype(x.dtype), dw, None


_gmm.defvjp(_gmm_fwd_rule, _gmm_bwd_rule)
