"""Device-state re-shard for elastic resizes (r19).

When a resize directive lands, every surviving member must rebuild its
device-resident params + optimizer state for the NEW world. Two sources
feed the rebuild, and the distinction is the whole design:

- **Re-laid-out** rows: state this member's device copy is already
  authoritative for (rows it consumed itself, or refreshed at the last
  barrier). These move device-to-device through a pjit re-layout — no
  host round-trip, no disk.
- **Re-fetched** rows: state some OTHER member advanced since our last
  refresh. The authoritative copy lives in the shared row store (one
  atomically-written ``.npy`` per row); a re-grown member with no device
  state at all first restores the chief's last committed checkpoint
  through the world-size-tagged shard depot (peer depot -> local disk,
  ``rendezvous.statechannel.choose_restore_source`` order) and then
  overlays the row store on top.

The soak's model is deliberately tiny — params are a ``(total, D)``
float32 matrix and the optimizer state one momentum scalar per row, each
row touched by exactly one consume — so "bit-identical final params vs
the uninterrupted run" is a meaningful hard gate across any composition
of shrinks, re-grows, preemptions, and grow-beyond-spec epochs: every
update is row-local and deterministic, so any lost, duplicated, or
mis-sourced row changes the digest.

``jax`` arrays are built with ``jax.make_array_from_callback`` against a
local 1-device ``dp`` mesh (the CI data plane — one process, one CPU
device), and the re-layout goes through ``jax.jit`` with
``out_shardings``; ``parallel/collectives.shard_map_compat`` papers over
the shard_map API gap for the row-update body so the same code shape
lifts to a real multi-device mesh.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

# Default row width of the soak model's params matrix.
PARAM_DIM = 8
# The row update: row' = decay * row + lr * w, momentum' = lr * w. Chosen
# so the final value depends on the init row AND the consumed window —
# a row sourced from the wrong place cannot collide with the right one.
ROW_DECAY = 0.5
ROW_LR = 1e-3


# ---- local device mesh -------------------------------------------------


def local_mesh():
    """1-device ``dp`` mesh over the first local device. The soak's data
    plane is one process per member (CI cannot run multi-process SPMD),
    so each member's "shard" is a full replica on its own device; the
    sharding machinery below is exactly what a >1-device member would
    run with a non-trivial PartitionSpec."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:1]), ("dp",))


def replicated_sharding(mesh):
    import jax

    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())


def rows_to_device(host: np.ndarray, sharding):
    """Host rows -> device array via ``jax.make_array_from_callback`` —
    each addressable device pulls exactly its index slice, which is what
    keeps this path host-memory-flat on a real sharded mesh."""
    import jax

    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx]
    )


def relayout(arr, sharding):
    """pjit re-layout onto ``sharding`` (device-to-device when possible):
    the "re-laid-out" half of a re-shard."""
    import jax

    return jax.jit(lambda x: x, out_shardings=sharding)(arr)


def device_to_host(arr) -> np.ndarray:
    return np.asarray(arr)


# ---- deterministic row model -------------------------------------------


def init_row(seed: int, p: int, dim: int = PARAM_DIM) -> np.ndarray:
    """Deterministic init for row ``p``: every member of every
    incarnation derives the identical value (SeedSequence over the
    (seed, position) pair)."""
    rng = np.random.default_rng([int(seed), int(p)])
    return rng.standard_normal(dim).astype(np.float32)


def make_row_update() -> Callable:
    """The jitted one-touch row update. Runs the body through
    shard_map_compat over the local mesh so the identical code shape
    lifts to a real dp mesh; on the 1-device mesh the spec is fully
    replicated and the compat wrapper is an identity layout."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tf_operator_tpu.parallel.collectives import shard_map_compat

    mesh = local_mesh()

    def body(row, mom, w):
        new_row = ROW_DECAY * row + ROW_LR * w
        new_mom = ROW_LR * w * jnp.ones_like(mom)
        return new_row, new_mom

    shard = shard_map_compat(
        body, mesh=mesh,
        in_specs=(P(), P(), P()),
        out_specs=(P(), P()),
    )
    return jax.jit(shard)


# ---- shared row store --------------------------------------------------


def state_dir(workdir: str) -> str:
    return os.path.join(workdir, "state")


def row_path(sdir: str, p: int) -> str:
    return os.path.join(sdir, f"row-{int(p):06d}.npy")


def write_row(sdir: str, p: int, row: np.ndarray, mom: float) -> None:
    """Durably publish row ``p``: momentum scalar appended to the row,
    written tmp-then-rename so a member killed mid-write leaves either
    the old row or nothing — never a torn one. Written BEFORE the
    consumption record, so a durable record implies a durable row."""
    os.makedirs(sdir, exist_ok=True)
    buf = np.concatenate(
        [np.asarray(row, dtype=np.float32).ravel(),
         np.asarray([mom], dtype=np.float32)]
    )
    tmp = row_path(sdir, p) + f".tmp-{os.getpid()}"
    with open(tmp, "wb") as f:
        np.save(f, buf)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, row_path(sdir, p))


def read_row(
    sdir: str, p: int, dim: int = PARAM_DIM
) -> Optional[Tuple[np.ndarray, float]]:
    try:
        buf = np.load(row_path(sdir, p))
    except (OSError, ValueError):
        return None
    if buf.shape != (dim + 1,):
        return None
    return buf[:dim].astype(np.float32), float(buf[dim])


# ---- the re-shard itself -----------------------------------------------


@dataclass
class ReshardPlan:
    """What a rebuild did, row by row — the soak's receipt that the
    re-shard actually re-laid-out device state rather than round-tripping
    everything through the filesystem."""
    relaid: int = 0      # rows taken from this member's own device copy
    refetched: int = 0   # rows read back from the shared row store
    inited: int = 0      # rows nobody has consumed yet (deterministic init)
    epochs: List[int] = field(default_factory=list)
    # Rows whose rebuilt device value is FINAL (relaid or refetched): the
    # one-touch update means a consumed row never changes again, so these
    # stay authoritative across every later rebuild. Init rows are NOT
    # authoritative — another member may consume them after this barrier.
    authoritative: Set[int] = field(default_factory=set)

    def merge(self, other: "ReshardPlan") -> None:
        self.relaid += other.relaid
        self.refetched += other.refetched
        self.inited += other.inited
        self.epochs.extend(other.epochs)


def plan_rows(
    total: int, fresh: Set[int]
) -> Tuple[List[int], List[int]]:
    """Split [0, total) into (kept, stale): kept rows re-layout from the
    member's device copy, stale rows re-fetch from the row store."""
    kept = [p for p in range(total) if p in fresh]
    stale = [p for p in range(total) if p not in fresh]
    return kept, stale


def rebuild_state(
    total: int,
    dim: int,
    seed: int,
    sdir: str,
    device_params,
    device_mom,
    fresh: Set[int],
    sharding,
    epoch: int = 0,
) -> Tuple[object, object, ReshardPlan]:
    """Rebuild the full (total, dim) params + (total,) momentum device
    arrays for a new epoch.

    Source order per row: this member's own device copy when the row is
    still fresh (re-laid-out), else the shared row store (re-fetched),
    else the deterministic init (never consumed). Returns the new device
    arrays and the plan receipt."""
    plan = ReshardPlan(epochs=[epoch])
    kept, stale = plan_rows(total, fresh)
    host_params = np.empty((total, dim), dtype=np.float32)
    host_mom = np.zeros((total,), dtype=np.float32)
    if kept:
        # One device->host pull for every kept row, then the re-layout
        # below pushes the assembled matrix back through pjit — on a
        # >1-device mesh the callback form keeps this per-shard.
        cur_p = device_to_host(device_params) if device_params is not None else None
        cur_m = device_to_host(device_mom) if device_mom is not None else None
        for p in kept:
            host_params[p] = cur_p[p]
            host_mom[p] = cur_m[p]
            plan.relaid += 1
            plan.authoritative.add(p)
    for p in stale:
        got = read_row(sdir, p, dim)
        if got is not None:
            host_params[p], host_mom[p] = got
            plan.refetched += 1
            plan.authoritative.add(p)
        else:
            host_params[p] = init_row(seed, p, dim)
            plan.inited += 1
    new_params = relayout(rows_to_device(host_params, sharding), sharding)
    new_mom = relayout(rows_to_device(host_mom, sharding), sharding)
    return new_params, new_mom, plan


def assemble_final(
    total: int, dim: int, seed: int, sdir: str
) -> np.ndarray:
    """The chief's final assembly: every row from the row store (all
    consumed by the time the coverage gate passes), init where a row is
    genuinely absent. Pure host-side — the digest input."""
    out = np.empty((total, dim), dtype=np.float32)
    for p in range(total):
        got = read_row(sdir, p, dim)
        out[p] = got[0] if got is not None else init_row(seed, p, dim)
    return out


def expected_params(
    total: int, dim: int, seed: int, order: Sequence[int]
) -> np.ndarray:
    """The uninterrupted run's final params: the SAME jitted row update
    applied once per position (each row is touched exactly once and the
    update is row-local, so consumption order cannot matter). Routed
    through the identical compiled function as the live members — a
    host-side re-derivation could differ in the last bit if XLA fuses
    the multiply-add, and "bit-identical" means bit-identical."""
    import jax.numpy as jnp

    update = make_row_update()
    out = np.empty((total, dim), dtype=np.float32)
    zero_mom = jnp.zeros((), jnp.float32)
    for p in range(total):
        row, _ = update(
            jnp.asarray(init_row(seed, p, dim)),
            zero_mom,
            jnp.asarray(float(int(order[p])), jnp.float32),
        )
        out[p] = np.asarray(row)
    return out


def params_digest(params: np.ndarray) -> str:
    """Sha256 over the row-major float32 bytes — bit-identical or bust."""
    import hashlib

    return hashlib.sha256(
        np.ascontiguousarray(params, dtype=np.float32).tobytes()
    ).hexdigest()
