"""Profiler capture: one switch around the hot loop.

The reference has no profiling story at all (SURVEY.md §5: per-sync
latency logs only); here any workload or bench run can capture an XLA
trace by pointing a directory at it — ``profile_dir`` in the workload
dict, or ``BENCH_PROFILE=/dir`` for bench.py. The output is a TensorBoard
-loadable xplane (host + device timelines, op breakdown), written per
process under ``<dir>/<process_index>`` so multi-host gangs don't
clobber each other.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def profile_ctx(trace_dir: Optional[str]) -> Iterator[None]:
    """jax.profiler.trace around the body when ``trace_dir`` is set; a
    no-op otherwise (so call sites need no branching)."""
    if not trace_dir:
        yield
        return
    import os

    import jax

    path = os.path.join(str(trace_dir), str(jax.process_index()))
    with jax.profiler.trace(path):
        yield
