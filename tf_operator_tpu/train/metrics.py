"""Step-time and MFU telemetry.

The reference's only timing is per-sync controller latency logging
(SURVEY.md §5); training telemetry is the TPU framework's north-star
metric surface (BASELINE.md: ≥50% MFU ResNet-50, images/sec/chip,
submit→first-step latency).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# Peak dense bf16 FLOP/s per chip by device generation.
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v5": 459e12,
    "v6 lite": 918e12,  # Trillium
    "v6e": 918e12,
}


def peak_flops_per_chip(device=None) -> float:
    """Best-effort peak bf16 FLOP/s for the attached chip; tiny fallback for
    CPU so MFU stays finite (and obviously non-comparable) in tests."""
    import jax

    dev = device or jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    for marker, flops in _PEAK_FLOPS.items():
        if marker in kind:
            return flops
    if dev.platform == "tpu":
        return 197e12  # unknown TPU: assume v5e-class
    return 1e12  # CPU/debug


def mfu(model_flops_per_step: float, step_seconds: float, n_chips: int, device=None) -> float:
    """Model FLOPs utilization: achieved / peak."""
    peak = peak_flops_per_chip(device) * n_chips
    return model_flops_per_step / (step_seconds * peak)


def host_fetch(x) -> None:
    """Force device→host synchronization on one array (or the first leaf of
    a pytree). IMPORTANT: jax.block_until_ready does NOT synchronize through
    a remote/tunneled TPU backend — only an actual host fetch does; all
    timing in this framework must sync via this helper."""
    import jax
    import numpy as np

    leaves = jax.tree_util.tree_leaves(x)
    if leaves:
        np.asarray(leaves[0])


@dataclass
class StepTimer:
    """Wall-clock step timing with warmup exclusion (first steps compile).

    ``stop(result)`` host-fetches ``result`` before reading the clock —
    without it, async dispatch makes the measurement meaningless (and on a
    tunneled TPU even block_until_ready lies; see host_fetch)."""

    warmup: int = 2
    _t0: Optional[float] = None
    durations: List[float] = field(default_factory=list)
    _seen: int = 0

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, result=None) -> None:
        if self._t0 is None:
            return
        if result is not None:
            host_fetch(result)
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self._seen += 1
        if self._seen > self.warmup:
            self.durations.append(dt)

    def mean(self) -> float:
        if not self.durations:
            return float("nan")
        return sum(self.durations) / len(self.durations)

    def summary(self, flops_per_step: float = 0.0, n_chips: int = 1) -> Dict[str, float]:
        m = self.mean()
        out = {"step_time_s": m, "steps_timed": float(len(self.durations))}
        if flops_per_step and m == m:  # not nan
            out["mfu"] = mfu(flops_per_step, m, n_chips)
            out["tflops_per_chip"] = flops_per_step / m / n_chips / 1e12
        return out


def transformer_train_flops(n_params: int, tokens_per_step: int) -> float:
    """6ND rule: fwd 2ND + bwd 4ND.

    This deliberately EXCLUDES the attention score/value matmuls (they scale
    with sequence length, not parameter count) — at t=8192 on gpt-small the
    attention term is the same order as 6ND, so a 6ND-only MFU under-reports
    long-context utilization by ~2x. Use transformer_train_flops_exact for
    honest long-context accounting; report both (bench.py does)."""
    return 6.0 * n_params * tokens_per_step


def attention_train_flops(
    n_layers: int, d_model: int, seq_len: int, tokens_per_step: int
) -> float:
    """Attention matmul FLOPs (PaLM appendix-B accounting): per token the
    QK^T and AV einsums each cost 2·t·d fwd per layer, so fwd = 4·L·t·d and
    train (fwd+bwd = 3x fwd) = 12·L·t·d per token. Counted over the full
    t^2 score matrix, per the PaLM convention, even for causal models —
    a causal kernel that skips masked blocks shows up as MFU > its dense
    counterpart, which is the honest reading (it did less wall-clock work
    for the same model math)."""
    return 12.0 * n_layers * seq_len * d_model * tokens_per_step


def transformer_train_flops_exact(
    n_params: int,
    tokens_per_step: int,
    n_layers: int,
    d_model: int,
    seq_len: int,
) -> float:
    """6ND plus the attention term — the exact model-FLOPs accounting for
    long-context MFU (6ND alone halves the reported utilization at
    t≈8k on gpt-small-class models)."""
    return transformer_train_flops(n_params, tokens_per_step) + attention_train_flops(
        n_layers, d_model, seq_len, tokens_per_step
    )


def resnet_train_flops(fwd_flops_per_image: float, images_per_step: int) -> float:
    """Training ≈ 3× forward."""
    return 3.0 * fwd_flops_per_image * images_per_step
