"""Training harness: sharded train loops, telemetry, checkpointing.

The layer the reference left entirely to user containers (its operator only
ever saw exit codes); here it is library code so that a TPUJob's workload
is a config, not a program. Exceeds the reference's observability bar
(SURVEY.md §5: "TPU build should add first-class step-time/MFU telemetry").
"""

from tf_operator_tpu.train.trainer import TrainState, Trainer, TrainerConfig  # noqa: F401
from tf_operator_tpu.train.checkpoint import (  # noqa: F401
    CheckpointManager,
    WorkloadCheckpointer,
)
from tf_operator_tpu.train.metrics import (  # noqa: F401
    StepTimer,
    host_fetch,
    mfu,
    peak_flops_per_chip,
)
from tf_operator_tpu.train.data import (  # noqa: F401
    ArrayDataset,
    DeviceLoader,
    SyntheticImages,
    SyntheticTokens,
)
from tf_operator_tpu.train.profile import profile_ctx  # noqa: F401
