"""Sharded trainer: init/step compiled once over the job's mesh.

Usage shape:

    trainer = Trainer(mesh, loss_fn=..., init_fn=..., logical_axes=...,
                      config=TrainerConfig(...))
    state = trainer.init(jax.random.PRNGKey(0))
    state, metrics = trainer.step(state, batch)   # jitted, donated

Sharding: param placement comes from the model's logical axes through
parallel.sharding.ShardingRules (DP/FSDP/TP by table edit); optimizer state
inherits the param shardings; batches shard over ("dp","fsdp").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax

from tf_operator_tpu.parallel.sharding import DEFAULT_RULES, ShardingRules, replicated


@dataclass
class TrainerConfig:
    optimizer: str = "adamw"  # "adamw" | "sgd"
    learning_rate: float = 3e-4
    weight_decay: float = 0.0
    momentum: float = 0.9
    beta1: float = 0.9
    beta2: float = 0.95
    grad_clip: Optional[float] = 1.0
    warmup_steps: int = 0
    lr_schedule: str = "constant"  # "constant" | "cosine"
    total_steps: int = 10000
    # Microbatch gradient accumulation: >1 splits each step's batch into
    # grad_accum equal microbatches, runs fwd+bwd per microbatch in a
    # lax.scan, and applies ONE optimizer update with the mean gradient —
    # the lever for configs whose global batch exceeds per-chip activation
    # memory (trade steps-in-flight for batch; peak activation memory drops
    # ~grad_accum-fold while the optimizer sees the same global batch).
    # Mean-of-microbatch-means == full-batch mean for equal-size
    # microbatches, so the loss trajectory is identical up to float
    # reassociation (oracle-pinned in tests/test_trainer_accum.py).
    grad_accum: int = 1
    # Re-seed init()'s key onto the 'rbg' PRNG (r4 submit-latency lever):
    # threefry RNG subgraphs dominate the init EXECUTABLE — the unrolled
    # ResNet-50 init measured 2.5 s of executable transfer + 11.6 s cold
    # compile through the tunnel vs 0.4 s / 5.4 s with rbg. Same
    # distributions, different stream — and rbg streams vary with
    # BACKEND, COMPILER VERSION, and MESH/PARTITION LAYOUT (XLA
    # RngBitGenerator documents no stability across any of these), so
    # same-seed init is no longer bit-identical across dp=4 vs dp=8
    # meshes the way threefry was. Default False (r5, ADVICE r4):
    # library callers keep deterministic threefry init for seed-matched
    # ablations; the submit-latency paths (bench.py, the lm/resnet
    # workloads) opt in explicitly. Restores/resumes never re-init, so
    # recovery semantics are unchanged either way.
    fast_init_rng: bool = False


@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: Any  # int32 scalar array
    extra: Any = None  # model state (e.g. BN stats), optional


def _make_tx(cfg: TrainerConfig) -> optax.GradientTransformation:
    if cfg.lr_schedule == "cosine":
        sched = optax.warmup_cosine_decay_schedule(
            0.0, cfg.learning_rate, max(cfg.warmup_steps, 1), cfg.total_steps
        )
    elif cfg.warmup_steps:
        sched = optax.linear_schedule(0.0, cfg.learning_rate, cfg.warmup_steps)
    else:
        sched = cfg.learning_rate
    if cfg.optimizer == "adamw":
        tx = optax.adamw(sched, b1=cfg.beta1, b2=cfg.beta2, weight_decay=cfg.weight_decay)
    elif cfg.optimizer == "sgd":
        tx = optax.sgd(sched, momentum=cfg.momentum)
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    if cfg.grad_clip:
        tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip), tx)
    return tx


class Trainer:
    """Builds sharded, jitted init and train-step functions.

    loss_fn(params, batch, extra) -> loss  OR  (loss, new_extra).
    init_fn(key) -> params  OR  (params, extra).
    logical_axes: pytree matching params with logical axis tuples (or None
    to replicate everything).
    """

    def __init__(
        self,
        mesh,
        loss_fn: Callable,
        init_fn: Callable,
        logical_axes: Any = None,
        rules: ShardingRules = DEFAULT_RULES,
        config: Optional[TrainerConfig] = None,
    ) -> None:
        self.mesh = mesh
        self.config = config if config is not None else TrainerConfig()
        self.tx = _make_tx(self.config)
        self.loss_fn = loss_fn
        self.init_fn = init_fn
        self.rules = rules
        self.logical_axes = logical_axes
        self._repl = replicated(mesh)

        # Resolve param shardings by tracing init_fn's output structure
        # (traced once; _opt_shardings reuses it).
        shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        self._has_extra = isinstance(shapes, tuple)
        self._params_shape = shapes[0] if self._has_extra else shapes
        self._extra_shape = shapes[1] if self._has_extra else None
        self._opt_shape_cache = None
        self._opt_shardings_cache = None
        if logical_axes is None:
            self.param_shardings = jax.tree_util.tree_map(
                lambda _: self._repl, self._params_shape
            )
        else:
            self.param_shardings = jax.tree_util.tree_map(
                lambda axes: self.rules.sharding(mesh, list(axes)),
                logical_axes,
                is_leaf=lambda x: isinstance(x, tuple),
            )
        self.batch_sharding = self.rules.sharding(mesh, ["batch"])

        self._init_jit = None
        self._step_jit = None
        self._step_compiled = None
        self._precompile_error = None
        self._compiled_hits = 0
        self._compiled_rejections = 0
        self._multi_jit: Dict[Any, Any] = {}

    # ---- init -----------------------------------------------------------

    @staticmethod
    def _fast_init_key(key):
        """Derive an 'rbg'-impl key from the caller's key (threefry or
        typed): distinct seeds stay distinct, and the init executable
        sheds its threefry subgraphs (see TrainerConfig.fast_init_rng)."""
        import numpy as np

        try:
            data = jax.random.key_data(key)
        except Exception:  # already a raw uint32 key array
            data = key
        arr = np.asarray(data).ravel().astype(np.uint64)
        seed = 0
        for word in arr:
            seed = (seed * 1000003 + int(word)) % (1 << 63)
        return jax.random.key(seed, impl="rbg")

    def init(self, key) -> TrainState:
        if self.config.fast_init_rng:
            key = self._fast_init_key(key)
        if self._init_jit is None:
            opt_shardings = self._opt_shardings()
            extra_out = self._repl if self._has_extra else None

            def go(key):
                out = self.init_fn(key)
                params, extra = out if self._has_extra else (out, None)
                return params, self.tx.init(params), jnp.zeros((), jnp.int32), extra

            self._init_jit = jax.jit(
                go,
                out_shardings=(
                    self.param_shardings,
                    opt_shardings,
                    self._repl,
                    extra_out,
                ),
            )
        params, opt_state, step, extra = self._init_jit(key)
        return TrainState(params, opt_state, step, extra)

    def _opt_shape(self):
        if self._opt_shape_cache is None:
            self._opt_shape_cache = jax.eval_shape(self.tx.init, self._params_shape)
        return self._opt_shape_cache

    def _opt_shardings(self):
        """Optimizer slots inherit their param's sharding, matched by tree
        PATH (optimizer moment trees embed the param tree, e.g.
        mu.layers.wq mirrors params.layers.wq). Shape-based matching would
        collide for same-shape params with transposed shardings (wq vs wo
        when n_heads*head_dim == d_model). Scalars and unmatched leaves
        replicate."""
        if self._opt_shardings_cache is not None:
            return self._opt_shardings_cache
        opt_shape = self._opt_shape()
        param_leaves = jax.tree_util.tree_flatten_with_path(self._params_shape)[0]
        sharding_leaves = jax.tree_util.tree_flatten(self.param_shardings)[0]
        path_map = {}
        for (path, leaf), sharding in zip(param_leaves, sharding_leaves):
            path_map[tuple(str(p) for p in path)] = (leaf.shape, sharding)

        def pick(opt_path, leaf):
            key = tuple(str(p) for p in opt_path)
            # Longest path suffix that names a param with the same shape.
            for k in range(len(key), 0, -1):
                hit = path_map.get(key[-k:])
                if hit is not None:
                    shape, sharding = hit
                    if shape == leaf.shape:
                        return sharding
                    break
            return self._repl

        self._opt_shardings_cache = jax.tree_util.tree_map_with_path(pick, opt_shape)
        return self._opt_shardings_cache

    def state_template(self) -> "TrainState":
        """Abstract TrainState (ShapeDtypeStructs carrying shardings) —
        the restore target for CheckpointManager.restore without paying
        an init compile."""
        opt_shape = self._opt_shape()
        opt_shardings = self._opt_shardings()

        def tag(shape_tree, sharding_tree):
            return jax.tree_util.tree_map(
                lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
                shape_tree,
                sharding_tree,
            )

        extra = (
            jax.tree_util.tree_map(
                lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=self._repl),
                self._extra_shape,
            )
            if self._extra_shape is not None
            else None
        )
        return TrainState(
            params=tag(self._params_shape, self.param_shardings),
            opt_state=tag(opt_shape, opt_shardings),
            step=jax.ShapeDtypeStruct((), jnp.int32, sharding=self._repl),
            extra=extra,
        )

    def reshard_state(self, state: "TrainState") -> "TrainState":
        """Re-lay an existing TrainState onto THIS trainer's shardings —
        the elastic resize seam (r12). After a gang shrink/re-grow the
        surviving members build a Trainer over the NEW mesh and pass the
        old state through here at the next step boundary; every leaf is
        device_put onto the new state_template's sharding (params by rule,
        optimizer slots by param path, step/extra replicated). The same
        sharding machinery that lays out a restore lays out the resize —
        there is no separate elastic layout path to drift."""
        tmpl = self.state_template()

        def relay(leaf, spec):
            return jax.device_put(leaf, spec.sharding)

        return jax.tree_util.tree_map(relay, state, tmpl)

    def restore_or_init(self, key, ckpt=None) -> "TrainState":
        """Resume from ``ckpt``'s latest checkpoint if one exists, else
        fresh init — the restart-based recovery contract (SURVEY.md §5):
        the controller's gang restart relaunches the workload, which lands
        here and picks up at the saved step."""
        if ckpt is not None and ckpt.latest_step() is not None:
            return ckpt.restore(self.state_template())
        return self.init(key)

    def init_and_step(self, key, batch) -> tuple:
        """Init + FIRST train step as ONE program — the submit-latency fast
        path. On a tunneled/remote TPU the dominant cost of submit→first-
        step is executable upload (a persistent-cache HIT on the init
        program alone measured 4.2 s of transfer); fusing init into the
        first step ships one executable instead of two, delivering the
        first loss seconds sooner. Identical math to init() followed by
        step(); subsequent steps use the normal step program. Returns
        (TrainState, {"loss": ...}) like step()."""
        if self.config.fast_init_rng:
            key = self._fast_init_key(key)
        opt_shardings = self._opt_shardings()
        extra_out = self._repl if self._has_extra else None

        def go(key, batch):
            out = self.init_fn(key)
            params, extra = out if self._has_extra else (out, None)
            return self._step_body(
                params, self.tx.init(params), jnp.zeros((), jnp.int32), extra, batch
            )

        fused = jax.jit(
            go,
            out_shardings=(
                self.param_shardings,
                opt_shardings,
                self._repl,
                extra_out,
                self._repl,
            ),
        )
        params, opt_state, step, extra, loss = fused(key, batch)
        return TrainState(params, opt_state, step, extra), {"loss": loss}

    # ---- step -----------------------------------------------------------

    def precompile_step_async(self, batch):
        """Start compiling the train-step program on a BACKGROUND thread —
        the submit-latency overlap (VERDICT r3 #4): after trace time the
        step program's compile + executable upload is independent of the
        init program's execution, but the lazy jit path serializes them
        (r3 submit_breakdown: init_dispatch 5.0 s THEN first_step 9.9 s).
        Call this before ``init()`` with a batch (concrete arrays or
        ShapeDtypeStructs; host arrays assume ``batch_sharding``), then
        ``join()`` the returned thread — the next ``step()`` call runs
        the AOT-compiled executable instead of paying a cold jit. The
        Python trace briefly contends for the GIL; the XLA compile and
        upload (the dominant term, remote through the tunnel) genuinely
        overlap. Any failure is swallowed: step() falls back to the lazy
        jit path, losing only the overlap."""
        import threading

        from jax.sharding import NamedSharding

        tmpl = self.state_template()

        def spec(a):
            # honor a leaf's sharding only when it's a mesh sharding (a
            # staged batch or an explicit ShapeDtypeStruct); a host array
            # or an unstaged jnp array carries a single-device sharding
            # that would contradict the state template's mesh
            sh = getattr(a, "sharding", None)
            if not isinstance(sh, NamedSharding) or sh.mesh != self.mesh:
                sh = self.batch_sharding
            return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)

        batch_spec = jax.tree_util.tree_map(spec, batch)
        if self._step_jit is None:
            self._step_jit = self._build_step()
        fn = self._step_jit

        def go():
            try:
                lowered = fn.lower(
                    tmpl.params, tmpl.opt_state, tmpl.step, tmpl.extra,
                    batch_spec,
                )
                self._step_compiled = lowered.compile()
                self._precompile_error = None
            except Exception as exc:  # noqa: BLE001 — overlap is best-effort
                self._step_compiled = None
                self._precompile_error = exc  # inspectable; jit path covers
                import logging

                # WARNING, not debug: a silent failure here makes the
                # submit overlap quietly disappear — the first step then
                # pays the full cold compile with no signal why.
                logging.getLogger(__name__).warning(
                    "step precompile failed; first step falls back to the "
                    "lazy jit path (losing the submit overlap): %s", exc,
                )

        t = threading.Thread(target=go, name="step-precompile", daemon=True)
        t.start()
        return t

    def step(self, state: TrainState, batch) -> tuple:
        if self._step_compiled is not None:
            try:
                params, opt_state, step, extra, loss = self._step_compiled(
                    state.params, state.opt_state, state.step, state.extra,
                    batch,
                )
                self._compiled_hits += 1
                self._compiled_rejections = 0
                return (TrainState(params, opt_state, step, extra),
                        {"loss": loss})
            except (TypeError, ValueError) as exc:
                # Argument/aval mismatch — raised by pre-execution
                # checking, so no buffer was donated. Route only THIS
                # call to the jit path and KEEP the executable: one
                # odd-shaped batch (e.g. a final partial batch) must not
                # force a cold recompile of the common shape. But an
                # executable that NEVER matched (the precompile guessed
                # the wrong batch spec) is dropped after 3 straight
                # rejections — otherwise every step of a long run pays
                # the failed call + a warning. Runtime errors propagate —
                # retrying after a mid-execution failure could touch
                # already-donated buffers.
                import logging

                self._compiled_rejections += 1
                if self._compiled_rejections == 1:
                    logging.getLogger(__name__).warning(
                        "precompiled step rejected args (%s); jit path "
                        "for this call", exc,
                    )
                if self._compiled_hits == 0 and self._compiled_rejections >= 3:
                    logging.getLogger(__name__).warning(
                        "precompiled step never matched a real batch; "
                        "dropping it (submit overlap not realized)",
                    )
                    self._step_compiled = None
        if self._step_jit is None:
            self._step_jit = self._build_step()
        params, opt_state, step, extra, loss = self._step_jit(
            state.params, state.opt_state, state.step, state.extra, batch
        )
        return TrainState(params, opt_state, step, extra), {"loss": loss}

    def _step_body(self, params, opt_state, step, extra, batch):
        if self.config.grad_accum > 1:
            loss, new_extra, grads = self._accum_grads(params, extra, batch)
        else:
            def wrapped(p):
                out = self.loss_fn(p, batch, extra)
                if isinstance(out, tuple):
                    return out
                return out, extra

            (loss, new_extra), grads = jax.value_and_grad(wrapped, has_aux=True)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, step + 1, new_extra, loss

    def _accum_grads(self, params, extra, batch):
        """Microbatched fwd+bwd: split the batch's leading dim into
        ``grad_accum`` equal microbatches and scan, summing grads in f32
        param-shaped accumulators; one mean at the end. Model ``extra``
        (e.g. BN stats) threads sequentially through the microbatches —
        the same semantics as training the microbatches as small steps.

        The [b,...] -> [accum, b/accum, ...] reshape keeps the microbatch
        dim under the batch sharding (constraint below) so each device
        keeps an equal slice of every microbatch — XLA lowers it to a
        layout change (worst case one input-sized reshard, amortized over
        grad_accum fwd+bwd passes)."""
        accum = self.config.grad_accum
        micro_shard = self.rules.sharding(self.mesh, [None, "batch"])

        def split(x):
            b = x.shape[0]
            if b % accum:
                raise ValueError(
                    f"batch dim {b} not divisible by grad_accum={accum}"
                )
            mb = x.reshape((accum, b // accum) + x.shape[1:])
            return jax.lax.with_sharding_constraint(mb, micro_shard)

        micro = jax.tree_util.tree_map(split, batch)

        def wrapped(p, mb, ex):
            out = self.loss_fn(p, mb, ex)
            if isinstance(out, tuple):
                return out
            return out, ex

        grad_fn = jax.value_and_grad(wrapped, has_aux=True)

        def body(carry, mb):
            gsum, loss_sum, ex = carry
            (loss, ex), g = grad_fn(params, mb, ex)
            # accumulate in f32 regardless of param dtype: with bf16 params
            # and accum>=8, summing in bf16 (~8 mantissa bits) absorbs
            # small microbatch contributions and breaks the oracle
            gsum = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(jnp.float32), gsum, g
            )
            return (gsum, loss_sum + loss, ex), None

        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        (gsum, loss_sum, new_extra), _ = jax.lax.scan(
            body, (zeros, jnp.zeros((), jnp.float32), extra), micro
        )
        inv = 1.0 / accum
        grads = jax.tree_util.tree_map(
            lambda g, p: (g * inv).astype(p.dtype), gsum, params
        )
        return loss_sum * inv, new_extra, grads

    def _build_step(self):
        # Donation contract for checkpointing: params/opt_state/extra are
        # donated, so the moment the next step dispatches, buffers any
        # in-flight save captured may be reused by XLA. The async save
        # pipeline (checkpoint._stage_tree) therefore snapshots the state
        # with a blocking device-side copy BEFORE returning control to the
        # step loop — that copy is the save stall; everything after it
        # (device->host fetch, chunked writes, commit) overlaps training.
        return jax.jit(self._step_body, donate_argnums=(0, 1, 3))

    # ---- multi-step (device loop) ---------------------------------------

    def multi_step(
        self, state: TrainState, batch, n_steps: int, stacked: bool = False
    ) -> tuple:
        """Run ``n_steps`` train steps inside ONE compiled call — a
        ``lax.scan`` over the step body, so per-step host dispatch (and on
        a remote/tunneled TPU, per-execution round trips) disappears from
        the step time. ``batch`` is one batch trained repeatedly
        (``stacked=False``, the benchmarking shape) or, with
        ``stacked=True``, a pytree with a leading [n_steps] dim — one
        slice per step, e.g. ``n_steps`` loader batches stacked.
        Returns ``(state, {"loss": last, "losses": [n_steps]})``.
        Compiles once per (n_steps, stacked) pair."""
        if stacked:
            for a in jax.tree_util.tree_leaves(batch):
                if a.shape[0] != n_steps:
                    raise ValueError(
                        f"stacked batch leading dim {a.shape[0]} != n_steps {n_steps}"
                    )
        key = (int(n_steps), bool(stacked))
        if self._multi_jit.get(key) is None:
            self._multi_jit[key] = self._build_multi_step(n_steps, stacked)
        params, opt_state, step, extra, losses = self._multi_jit[key](
            state.params, state.opt_state, state.step, state.extra, batch
        )
        return (
            TrainState(params, opt_state, step, extra),
            {"loss": losses[-1], "losses": losses},
        )

    def _build_multi_step(self, n_steps: int, stacked: bool):
        def go(params, opt_state, step, extra, batch):
            def body(carry, xs):
                params, opt_state, step, extra = carry
                b = xs if stacked else batch
                params, opt_state, step, extra, loss = self._step_body(
                    params, opt_state, step, extra, b
                )
                return (params, opt_state, step, extra), loss

            (params, opt_state, step, extra), losses = jax.lax.scan(
                body,
                (params, opt_state, step, extra),
                batch if stacked else None,
                length=None if stacked else n_steps,
            )
            return params, opt_state, step, extra, losses

        return jax.jit(go, donate_argnums=(0, 1, 3))
