"""Checkpoint/resume harness: sharded train-state save/restore.

Reference parity: the reference operator has NO checkpoint subsystem — user
workloads checkpoint to volumes/GCS through the PodTemplate and the
operator's own resume story is idempotent reconcile over CRD status
(/root/reference/tf_job_design_doc.md:73; SURVEY.md §5 "Checkpoint/resume").
The TPU build keeps that split but supplies the workload half as library
code: a checkpoint manager the training harness calls, so a gang restart
(controller deletes + recreates every process after a retryable failure)
resumes from the last saved step instead of step 0.

Two backends behind one API:

- **orbax** (preferred): ``orbax.checkpoint.CheckpointManager`` with
  ``StandardSave/StandardRestore`` — handles sharded arrays, multi-host
  coordination, and atomic finalization natively. Restoring onto a
  *different* mesh/sharding works by passing the target template (abstract
  arrays carrying NamedShardings). Saves are ASYNC by default (r3): the
  step loop only pays the device→host transfer; serialization overlaps
  subsequent steps, with a completion fence before the next save and on
  job end (the wrong default at v5p-128 scale is a synchronous save
  blocking the gang every checkpoint_every steps).
- **npy** (dependency-free fallback): one ``.npy`` per leaf plus a JSON
  tree manifest, written to a temp dir and atomically renamed. Requires
  fully-addressable arrays (single-host); restore ``device_put``s onto the
  template's shardings. With ``async_save`` (r8) the npy backend runs the
  chunked staging pipeline: ``save()`` only pays a device-side staging
  copy of the state (donation-safe — the step loop may immediately reuse
  the donated buffers), then a background drain moves staged leaves
  device→host and to disk in fixed-byte quanta, releasing each staging
  buffer as its leaf lands. The ONLY hard fence is the commit-marker
  write (``manifest.json`` written fsync'd-last into the temp dir, then
  one atomic rename) — the same contract ``latest_checkpoint_step()``
  already requires, so a crash anywhere in the pipeline leaves a
  ``.tmp_step_*`` orphan, never a resumable torn step.

Both are step-indexed directories with keep-N retention and
``latest_step()`` discovery, so "resume" is simply
``trainer.restore_or_init(key, manager)``. ``on_commit`` fires once per
step that actually COMMITTED — the seam the peer shard depot
(rendezvous/statechannel.py) feeds from, so peers only ever serve state a
crash could also have restored from disk.
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, Optional

log = logging.getLogger("tpujob.checkpoint")

_STEP_DIR = re.compile(r"^step_(\d+)$")

# Completeness markers orbax leaves in a FINALIZED step directory:
# `_CHECKPOINT_METADATA` (modern orbax, written at commit) or
# `commit_success.txt` (the multihost/GCS-era marker). A bare numeric dir
# without either is a save torn mid-crash — orbax renames its tmp dir
# into place before the final metadata write, so "directory exists" alone
# is NOT a commit. Resuming from a torn step bricks the warm restart
# (restore raises, or worse, loads garbage), so discovery requires a
# marker and falls back to the newest COMPLETE step.
_ORBAX_COMMIT_MARKERS = ("_CHECKPOINT_METADATA", "commit_success.txt")


def _orbax_step_complete(step_dir: str) -> bool:
    return any(
        os.path.exists(os.path.join(step_dir, m)) for m in _ORBAX_COMMIT_MARKERS
    )


def checkpoint_world_size(directory: str, step: int) -> int:
    """World size recorded in a committed npy step's manifest (its commit
    marker), 0 when untagged (pre-r12 checkpoints, orbax steps) or absent.
    Dependency-free like :func:`latest_checkpoint_step` — the controller
    and the chaos checkers read it without importing jax."""
    try:
        with open(os.path.join(directory, f"step_{int(step)}", "manifest.json")) as f:
            return int(json.load(f).get("world_size", 0) or 0)
    except (OSError, ValueError, TypeError):
        return 0


def latest_checkpoint_step(directory: str) -> int:
    """Latest COMPLETE checkpointed step under ``directory``, 0 when none.

    Dependency-free filesystem scan (no orbax import, no manager
    construction): the control plane calls this on every gang (re)create
    to stamp the warm-restart env (``TPUJOB_RESUME_STEP``), so it must be
    cheap and must not pull jax/orbax into the controller process. Handles
    both on-disk layouts: the npy backend's ``step_N/manifest.json``
    (atomically renamed, so presence of the manifest is the commit) and
    orbax's bare numeric step directories, which count only when their
    commit marker exists (``_ORBAX_COMMIT_MARKERS``) — a save torn by a
    crash mid-write must never become a resume point; the newest complete
    step wins instead."""
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    best = 0
    for name in names:
        m = _STEP_DIR.match(name)
        if m and os.path.exists(os.path.join(directory, name, "manifest.json")):
            best = max(best, int(m.group(1)))
        elif (
            name.isdigit()
            and os.path.isdir(os.path.join(directory, name))
            and _orbax_step_complete(os.path.join(directory, name))
        ):
            best = max(best, int(name))
    return best


def _to_tree(state: Any) -> Any:
    """TrainState -> plain dict pytree (checkpoint wire format)."""
    from tf_operator_tpu.train.trainer import TrainState

    if isinstance(state, TrainState):
        return {
            "params": state.params,
            "opt_state": state.opt_state,
            "step": state.step,
            "extra": state.extra,
        }
    return state


def _from_tree(tree: Any, like: Any) -> Any:
    """Plain dict pytree -> same type as ``like`` (TrainState or dict)."""
    from tf_operator_tpu.train.trainer import TrainState

    if isinstance(like, TrainState) and isinstance(tree, dict):
        return TrainState(
            params=tree.get("params"),
            opt_state=tree.get("opt_state"),
            step=tree.get("step"),
            extra=tree.get("extra"),
        )
    return tree


class CheckpointManager:
    """Step-indexed sharded checkpoints under one directory.

    Args:
        directory: checkpoint root (created if missing).
        keep: retain at most this many checkpoints (oldest pruned).
        backend: "auto" (orbax if importable), "orbax", or "npy".
    """

    def __init__(
        self,
        directory: str,
        keep: int = 3,
        backend: str = "auto",
        readonly: bool = False,
        async_save: bool = True,
        chunk_bytes: int = 64 << 20,
        on_commit: Optional[Callable[[int, str], None]] = None,
        world_size: Optional[int] = None,
        allow_world_resize: bool = False,
    ) -> None:
        """``readonly=True`` is for consumers of someone else's checkpoint
        directory (evaluators): saves are refused and the npy orphan sweep
        is skipped — a live writer may legitimately own a .tmp dir.

        ``async_save``: device→host transfer overlaps subsequent training
        steps instead of stalling the step loop for the full fetch. With
        orbax, ``save()`` pays the device→host transfer (donated step
        buffers stay safe) and the disk write runs in orbax's background
        thread. With npy, ``save()`` pays only a device-side STAGING copy
        (bounded by HBM bandwidth, not PCIe) and a background drain moves
        staged leaves device→host→disk in ``chunk_bytes`` quanta,
        releasing each staging buffer as its leaf lands. In both cases at
        most one write is in flight; ``save(..., wait=True)`` /
        ``wait_until_finished()`` / ``close()`` fence completion — the
        final save of a job must be fenced or the process can exit with a
        torn checkpoint (WorkloadCheckpointer.final does).

        ``on_commit(step, step_dir)`` fires after a step COMMITS on disk
        (npy backend; after the atomic rename) — the peer shard depot's
        feed. Exceptions in the hook are logged, never raised: publishing
        to peers is best-effort, the disk commit already happened.

        ``last_save_stall_s`` after each accepted save is the wall time
        the CALLER was blocked — the step-loop stall the async pipeline
        exists to shrink.

        ``world_size`` (r12): the gang world size stamped into each npy
        manifest at save time (None ⇒ ``jax.process_count()``) and the
        world this manager expects at restore. Elastic trainers update it
        across resizes (``mgr.world_size = n``). A restore whose manifest
        tag disagrees with the declared world REFUSES loudly — a
        mixed-world resume must never materialize silently — unless
        ``allow_world_resize=True`` explicitly declares a resize restore
        (the elastic path, which re-shards onto the new world right
        after)."""
        self.directory = os.path.abspath(str(directory))
        self.keep = int(keep)
        self.readonly = bool(readonly)
        self.async_save = bool(async_save)
        self.chunk_bytes = max(1 << 20, int(chunk_bytes))
        self.on_commit = on_commit
        self.world_size = world_size
        self.allow_world_resize = bool(allow_world_resize)
        self.last_save_stall_s = 0.0
        # npy async pipeline state: at most one drain thread in flight.
        self._drain: Optional[threading.Thread] = None
        self._drain_step: Optional[int] = None
        self._drain_error: Optional[BaseException] = None
        # Test seam: called as _fault_hook(phase, step) with phase in
        # {"leaf", "manifest", "commit"} from inside the drain — lets
        # tests crash the pipeline between any two phases.
        self._fault_hook: Optional[Callable[[str, int], None]] = None
        os.makedirs(self.directory, exist_ok=True)
        if backend == "auto":
            try:
                import orbax.checkpoint  # noqa: F401

                backend = "orbax"
            except Exception:  # pragma: no cover - orbax is baked into CI
                backend = "npy"
        self.backend = backend
        self._ocp_mgr = None
        if backend == "npy" and not self.readonly:
            # Sweep partial-save orphans: a crash mid-_npy_save leaves a
            # .tmp_step_* dir that a restarted process (new PID) would
            # otherwise never clean. The npy backend is single-process
            # (enforced in _npy_save), so nothing live can own these —
            # except when we are a readonly reader of a live writer's dir.
            for name in os.listdir(self.directory):
                if not name.startswith(".tmp_step_"):
                    continue
                # Tmp names end in the writer's pid: skip OUR pid — a
                # second manager in this process may have an async drain
                # live in that dir right now (crashed writers restart
                # with a new pid, so their orphans still sweep; a pid
                # collision merely defers cleanup to that step's next
                # save, which re-creates its tmp from scratch).
                if name.endswith(f"_{os.getpid()}"):
                    continue
                shutil.rmtree(os.path.join(self.directory, name), ignore_errors=True)
        if backend == "orbax":
            import orbax.checkpoint as ocp

            self._ocp = ocp
            self._ocp_mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self.keep,
                    create=True,
                    enable_async_checkpointing=self.async_save,
                ),
            )

    # ---- discovery ------------------------------------------------------

    def all_steps(self) -> List[int]:
        if self._ocp_mgr is not None:
            return sorted(self._ocp_mgr.all_steps())
        steps = set()
        for name in os.listdir(self.directory):
            m = _STEP_DIR.match(name)
            if m and os.path.exists(os.path.join(self.directory, name, "manifest.json")):
                steps.add(int(m.group(1)))
        # Read-your-own-writes, matching the orbax step cache: an ACCEPTED
        # async save counts as existing — it will commit, or its failure
        # surfaces (and the step vanishes from this list) at the next
        # fence. Restore paths fence before reading, so they only ever
        # load committed bytes.
        if self._drain_step is not None and self._drain_error is None:
            steps.add(self._drain_step)
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def reload(self) -> None:
        """Re-scan the directory for checkpoints written by ANOTHER
        process. The orbax manager caches its step list at construction,
        so a polling reader (the evaluator) must reload before
        latest_step() or it never sees new saves; npy scans the
        filesystem every call and needs nothing."""
        if self._ocp_mgr is not None:
            self._ocp_mgr.reload()

    # ---- save -----------------------------------------------------------

    def save(self, step: int, state: Any, wait: bool = False) -> bool:
        """Save ``state`` (TrainState or pytree) at ``step``. Returns True
        if written/accepted (False when this step already exists).

        With the async orbax backend the call returns once device arrays
        are safely on the host; the disk write completes in background.
        A fence on the previous save runs first (at most one write in
        flight), and ``wait=True`` fences this one too — required for the
        last save before process exit. ``wait=True`` fences even when the
        save is rejected as a duplicate: the duplicate may BE the
        in-flight async write (final() re-saving the last periodic step),
        and returning unfenced there would let process exit tear it."""
        if self.readonly:
            raise RuntimeError("CheckpointManager is readonly; refusing to save")
        step = int(step)
        tree = _to_tree(state)
        t0 = time.perf_counter()
        try:
            if self._ocp_mgr is not None:
                # Step check FIRST, against the cached step list: a
                # duplicate-step save (controllers re-drive saves
                # idempotently) must return without paying a completion
                # fence on the PREVIOUS in-flight write. The cache can
                # only miss a step that is itself mid-write — the fence
                # below, required anyway before starting a new write (at
                # most one in flight), makes the re-check authoritative.
                if step in self._ocp_mgr.all_steps():
                    if wait:
                        # The duplicate may be the in-flight write itself
                        # (the step cache counts accepted saves): a waited
                        # call must not return with it still unfenced.
                        self._ocp_mgr.wait_until_finished()
                    return False
                self._ocp_mgr.wait_until_finished()
                if step in self._ocp_mgr.all_steps():
                    return False  # the write just fenced WAS this step
                saved = self._ocp_mgr.save(step, args=self._ocp.args.StandardSave(tree))
                if wait or not self.async_save:
                    self._ocp_mgr.wait_until_finished()
                return bool(saved)
            if self.async_save:
                accepted = self._npy_save_async(step, tree)
                if wait:
                    # Fence even a rejected duplicate (all_steps counts the
                    # accepted in-flight drain): this is the seam final()
                    # relies on — and the fence surfaces any _drain_error.
                    self.wait_until_finished()
                return accepted
            return self._npy_save(step, tree)
        finally:
            self.last_save_stall_s = time.perf_counter() - t0

    def wait_until_finished(self) -> None:
        """Block until any in-flight async save is committed. Re-raises a
        background drain failure ONCE (then clears it): a save that died
        mid-pipeline never committed, and the caller deciding to exit or
        retry must hear about it at the next fence, not from a log line."""
        if self._ocp_mgr is not None:
            self._ocp_mgr.wait_until_finished()
            return
        drain = self._drain
        if drain is not None:
            drain.join()
            self._drain = None
            self._drain_step = None
        err, self._drain_error = self._drain_error, None
        if err is not None:
            raise RuntimeError(
                f"async checkpoint drain failed (step never committed): {err}"
            ) from err

    # -- world-size tagging (r12) -----------------------------------------

    def _writer_world_size(self) -> int:
        """World size stamped into manifests: the declared gang world when
        the caller set one (elastic trainers track the live directive),
        else the jax runtime's process count."""
        if self.world_size:
            return int(self.world_size)
        import jax

        return jax.process_count()

    def _check_restore_world(self, manifest: Dict[str, Any], step: int) -> None:
        """Refuse a silent mixed-world resume: a manifest tagged with a
        writing world size that disagrees with this manager's declared
        world raises unless the caller explicitly declared a resize
        restore (``allow_world_resize`` — the elastic path, which
        re-shards immediately after loading)."""
        saved = int(manifest.get("world_size", 0) or 0)
        expect = int(self.world_size or 0)
        if saved and expect and saved != expect and not self.allow_world_resize:
            raise ValueError(
                f"checkpoint at step {step} was written by a world of "
                f"{saved} but this restore targets a world of {expect}; "
                "a mixed-world resume must be an explicit resize "
                "(allow_world_resize=True), never silent"
            )

    # -- chunked async pipeline (npy backend) -----------------------------

    def _npy_save_async(self, step: int, tree: Any) -> bool:
        """Stage-and-drain save: the caller pays only the device-side
        staging copy; the device→host fetch and disk write overlap the
        caller's subsequent steps. Same step-check-then-fence order as
        the orbax path (a duplicate-step save never fences)."""
        if step in self.all_steps():
            return False
        self.wait_until_finished()  # at most one drain in flight
        if step in self.all_steps():
            return False  # the drain just fenced committed this step
        staged = _stage_tree(tree)  # donation-safe; THIS is the stall
        self._drain_step = step
        self._drain = threading.Thread(
            target=self._npy_drain, args=(step, staged), daemon=True,
            name=f"ckpt-drain-{step}",
        )
        self._drain.start()
        return True

    def _npy_drain(self, step: int, staged: Any) -> None:
        """Background half of the async save. Durability per phase:
        nothing before the final rename is discoverable (tmp dir name is
        dot-prefixed and latest_checkpoint_step requires the manifest), so
        a crash at ANY point here is an orphan sweep, not a torn resume
        point. Each staged device buffer is released as soon as its leaf
        reaches the host — peak staging memory decays during the drain."""
        import jax
        import numpy as np

        tmp = os.path.join(self.directory, f".tmp_step_{step}_{os.getpid()}")
        try:
            final = os.path.join(self.directory, f"step_{step}")
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            leaves_with_path = jax.tree_util.tree_flatten_with_path(staged)[0]
            manifest: Dict[str, Any] = {
                "step": step,
                "world_size": self._writer_world_size(),
                "leaves": [],
            }
            for i, (path, leaf) in enumerate(leaves_with_path):
                if self._fault_hook is not None:
                    self._fault_hook("leaf", step)
                arr = np.asarray(leaf)  # device -> host, one leaf at a time
                _write_npy_chunked(
                    os.path.join(tmp, f"leaf_{i}.npy"), arr, self.chunk_bytes
                )
                if hasattr(leaf, "delete"):
                    try:
                        leaf.delete()  # release the staging copy early
                    except Exception:  # noqa: BLE001 — freeing is advisory
                        pass
                manifest["leaves"].append(
                    {
                        "path": jax.tree_util.keystr(path),
                        "index": i,
                        "shape": list(arr.shape),
                        "dtype": str(arr.dtype),
                    }
                )
            if self._fault_hook is not None:
                self._fault_hook("manifest", step)
            # Commit marker, fsync'd: the manifest is what makes the step
            # discoverable — it must be durable BEFORE the rename
            # publishes the directory.
            with open(os.path.join(tmp, "manifest.json"), "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            if self._fault_hook is not None:
                self._fault_hook("commit", step)
            try:
                os.rename(tmp, final)  # THE commit
            except OSError:
                shutil.rmtree(tmp, ignore_errors=True)
                return  # lost a same-step race; theirs is complete
            self._npy_prune()
            self._fire_on_commit(step, final)
        except BaseException as exc:  # noqa: BLE001 — surfaced at next fence
            # Remove the partial tmp dir NOW: the constructor sweep skips
            # our own pid, and without this each distinct-step drain
            # failure would pin a partially-written dir for the process
            # lifetime — worsening exactly the disk pressure that likely
            # caused the failure. (No-op when the rename already landed.)
            shutil.rmtree(tmp, ignore_errors=True)
            self._drain_error = exc
            log.warning("async checkpoint drain for step %d failed: %s", step, exc)

    def _fire_on_commit(self, step: int, step_dir: str) -> None:
        if self.on_commit is None:
            return
        try:
            self.on_commit(step, step_dir)
        except Exception:  # noqa: BLE001 — peer publish is best-effort
            log.exception("on_commit hook failed for step %d", step)

    def _npy_save(self, step: int, tree: Any) -> bool:
        import jax
        import numpy as np

        if jax.process_count() > 1:
            # np.asarray on non-fully-addressable shards fails anyway, and
            # N processes racing on one tmp dir would corrupt the rename;
            # multi-host saving is what the orbax backend is for.
            raise RuntimeError(
                "npy checkpoint backend is single-process only "
                f"(process_count={jax.process_count()}); use backend='orbax'"
            )
        final = os.path.join(self.directory, f"step_{step}")
        if os.path.exists(final):
            return False
        tmp = os.path.join(self.directory, f".tmp_step_{step}_{os.getpid()}")
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        leaves_with_path = jax.tree_util.tree_flatten_with_path(tree)[0]
        manifest: Dict[str, Any] = {
            "step": step,
            "world_size": self._writer_world_size(),
            "leaves": [],
        }
        for i, (path, leaf) in enumerate(leaves_with_path):
            arr = np.asarray(leaf)
            np.save(os.path.join(tmp, f"leaf_{i}.npy"), arr)
            manifest["leaves"].append(
                {
                    "path": jax.tree_util.keystr(path),
                    "index": i,
                    "shape": list(arr.shape),
                    "dtype": str(arr.dtype),
                }
            )
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        try:
            os.rename(tmp, final)
        except OSError:
            # lost a same-step race to another writer; theirs is complete
            shutil.rmtree(tmp, ignore_errors=True)
            return False
        self._npy_prune()
        self._fire_on_commit(step, final)
        return True

    def _npy_prune(self) -> None:
        steps = self.all_steps()
        for s in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(os.path.join(self.directory, f"step_{s}"), ignore_errors=True)

    # ---- restore --------------------------------------------------------

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        """Restore the checkpoint at ``step`` (default: latest) onto the
        shapes/dtypes/shardings of ``template`` (a TrainState or pytree of
        arrays / ShapeDtypeStructs). Raises FileNotFoundError if none."""
        self.wait_until_finished()  # read-your-own-writes under async save
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        tmpl_tree = _to_tree(template)
        if self._ocp_mgr is not None:
            abstract = _abstractify(tmpl_tree)
            restored = self._ocp_mgr.restore(
                int(step), args=self._ocp.args.StandardRestore(abstract)
            )
            return _from_tree(restored, template)
        return _from_tree(self._npy_restore(int(step), tmpl_tree), template)

    def restore_params(self, template_params: Any, step: Optional[int] = None) -> Any:
        """Restore ONLY the params subtree of a TrainState checkpoint —
        what an evaluator needs. Skips the optimizer moments (2 extra
        param-sized trees under adamw), so restore I/O and device memory
        are ~1/3 of a full-state restore."""
        return self.restore_subtrees(
            {"params": template_params}, step=step
        )["params"]

    def restore_subtrees(
        self, templates: Dict[str, Any], step: Optional[int] = None
    ) -> Dict[str, Any]:
        """Restore a subset of a TrainState checkpoint's top-level items
        by name ({"params": tmpl} — or {"params": ..., "extra": ...},
        what a BatchNorm-model evaluator needs: the BN running stats live
        in ``extra`` and eval-mode inference is wrong without them, r4).
        Skips everything not named (the optimizer moments above all)."""
        self.wait_until_finished()  # the ephemeral manager below reads the
        # directory — an in-flight async write would present a torn item
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        wrapped = dict(templates)
        if self._ocp_mgr is not None:
            abstract = _abstractify(wrapped)
            # Ephemeral manager: an instance that has done a StandardSave
            # pins its handler registry to the Standard handler and then
            # rejects PyTreeRestore args (and vice versa) — a fresh
            # instance resolves the handler from the restore args.
            mgr = self._ocp.CheckpointManager(self.directory)
            # explicit restore_args: without them PyTreeRestore lays
            # arrays out with the sharding recorded at save time, not the
            # template's (evaluator mesh != trainer mesh is the normal
            # case)
            restore_args = self._ocp.checkpoint_utils.construct_restore_args(
                abstract
            )
            try:
                try:
                    restored = mgr.restore(
                        int(step),
                        args=self._ocp.args.PyTreeRestore(
                            item=abstract,
                            restore_args=restore_args,
                            partial_restore=True,
                        ),
                    )
                except TypeError:
                    # orbax < 0.9: PyTreeRestore has no partial_restore
                    # kwarg; the (deprecated-but-kept) transformations API
                    # spells the same contract — item defines the subset,
                    # transforms={} says "no renames, drop the rest"
                    # (r6: previously this raised and evaluators silently
                    # scored nothing on such containers)
                    restored = mgr.restore(
                        int(step),
                        args=self._ocp.args.PyTreeRestore(
                            item=abstract,
                            restore_args=restore_args,
                            transforms={},
                        ),
                    )
            finally:
                mgr.close()
            return {k: restored[k] for k in templates}
        out = self._npy_restore(int(step), wrapped, subtrees=tuple(templates))
        return {k: out[k] for k in templates}

    def _npy_restore(self, step: int, tmpl_tree: Any,
                     subtrees: Optional[tuple] = None) -> Any:
        import jax
        import numpy as np

        d = os.path.join(self.directory, f"step_{step}")
        manifest_path = os.path.join(d, "manifest.json")
        if not os.path.exists(manifest_path):
            raise FileNotFoundError(f"no checkpoint at step {step} under {self.directory}")
        with open(manifest_path) as f:
            manifest = json.load(f)
        self._check_restore_world(manifest, step)
        records = manifest["leaves"]
        if subtrees is not None:
            # Partial restore: only the saved leaves under these top-level
            # keys (their leaf_{index}.npy files carry the full-tree index).
            prefixes = tuple(f"['{k}']" for k in subtrees)
            records = [r for r in records if r["path"].startswith(prefixes)]
        paths, treedef = jax.tree_util.tree_flatten_with_path(tmpl_tree)
        saved_paths = [leaf["path"] for leaf in records]
        tmpl_paths = [jax.tree_util.keystr(p) for p, _ in paths]
        if saved_paths != tmpl_paths:
            # Pairing saved leaf files with template leaves is by
            # flatten order; a structure drift (optimizer/model config
            # changed between save and restore) would silently load
            # weights into the wrong slots.
            missing = set(saved_paths) ^ set(tmpl_paths)
            raise ValueError(
                f"checkpoint tree at step {step} does not match restore "
                f"template (differing leaves: {sorted(missing)[:6] or 'order'})"
            )
        arrays = []
        for (path, tmpl_leaf), rec in zip(paths, records):
            arr = np.load(os.path.join(d, f"leaf_{rec['index']}.npy"))
            if "dtype" in rec and arr.dtype != np.dtype(rec["dtype"]):
                # Extension dtypes (bfloat16, fp8) round-trip through .npy
                # as raw void bytes ('V2'); the manifest carries the real
                # dtype — a same-itemsize view restores it losslessly.
                arr = arr.view(np.dtype(rec["dtype"]))
            if "shape" in rec:
                # Path equality alone misses same-structure config drift
                # (d_model or dtype changed between save and restore) —
                # fail loudly instead of device_put-ing wrong arrays.
                tmpl_shape = tuple(getattr(tmpl_leaf, "shape", np.shape(tmpl_leaf)))
                tmpl_dtype = np.dtype(
                    getattr(tmpl_leaf, "dtype", None) or np.asarray(tmpl_leaf).dtype
                )
                if tuple(rec["shape"]) != tmpl_shape or np.dtype(rec["dtype"]) != tmpl_dtype:
                    raise ValueError(
                        f"checkpoint leaf {rec['path']} at step {step} is "
                        f"{rec['dtype']}{tuple(rec['shape'])} but the restore "
                        f"template expects {tmpl_dtype}{tmpl_shape} — model/"
                        "optimizer config changed between save and restore"
                    )
            sharding = getattr(tmpl_leaf, "sharding", None)
            if sharding is not None:
                arrays.append(jax.device_put(arr, sharding))
            else:
                arrays.append(jax.numpy.asarray(arr))
        return jax.tree_util.tree_unflatten(treedef, arrays)

    def close(self) -> None:
        if self._ocp_mgr is not None:
            self._ocp_mgr.wait_until_finished()
            self._ocp_mgr.close()
            return
        self.wait_until_finished()  # fence the npy drain before exit


class WorkloadCheckpointer:
    """The one checkpoint wiring shared by operator-launchable workloads.

    Config keys (from the TPUJob workload dict): ``checkpoint_dir``,
    ``checkpoint_every`` (steps between saves, 0 = final only),
    ``checkpoint_keep``, ``checkpoint_async`` (default on),
    ``checkpoint_backend`` (auto|npy|orbax). Tracks the
    step count on the HOST (mirroring ``state.step``) so the hot loop
    never forces a device sync on non-saving steps, and saves are keyed
    without fetching the step scalar. Disabled (all methods no-ops) when
    ``checkpoint_dir`` is unset.

    With a :class:`~tf_operator_tpu.rendezvous.context.JobContext` passed
    as ``ctx``, the checkpointer also speaks the peer warm-restore
    protocol (rendezvous/statechannel.py): every committed step is pushed
    to this host's shard depot (``TPUJOB_PEER_DEPOT``), restore consults
    the controller-provided peer depots (``TPUJOB_RESTORE_PEERS``) before
    disk, and save-stall / restore-source spans land in the job trace.
    """

    def __init__(self, workload: Dict[str, Any], ctx=None) -> None:
        self.ctx = ctx
        self.manager: Optional[CheckpointManager] = None
        if workload.get("checkpoint_dir"):
            self.manager = CheckpointManager(
                workload["checkpoint_dir"],
                keep=int(workload.get("checkpoint_keep", 3)),
                backend=str(workload.get("checkpoint_backend", "auto")),
                async_save=bool(workload.get("checkpoint_async", True)),
                on_commit=self._push_to_depot,
                world_size=getattr(ctx, "num_processes", None),
                allow_world_resize=bool(workload.get("elastic")),
            )
        self.every = int(workload.get("checkpoint_every", 0))
        self._step = 0
        self.start_step = 0
        # Checkpoint-cadence directive (r16): last applied epoch + poll
        # throttle. The autopilot retunes `every` live through
        # status.checkpoint_cadence_directive; the chief applies it at a
        # step boundary via poll_cadence_directive().
        self._cadence_epoch = 0
        self._cadence_poll_s = float(workload.get("cadence_poll_s", 2.0))
        self._cadence_last_poll = 0.0
        # Per-accepted-save caller stall (seconds) — the overlap receipt.
        self.save_stalls: List[float] = []
        # "peer" | "disk" after a warm restore; "" cold / not restored.
        self.restore_source = ""

    # -- peer warm-restore protocol (rendezvous/statechannel.py) ----------

    def _push_to_depot(self, step: int, step_dir: str) -> None:
        """on_commit hook: publish a COMMITTED step to this host's shard
        depot so it survives the gang teardown a restart implies. Runs on
        the drain thread; best-effort by contract."""
        if self.ctx is None or not getattr(self.ctx, "peer_depot", ""):
            return
        from tf_operator_tpu.rendezvous.statechannel import DepotClient

        DepotClient().push_step(
            self.ctx.peer_depot, self.ctx.namespace, self.ctx.job_name,
            step, step_dir,
        )

    def prefetch_from_peers(self) -> str:
        """Restore-source decision (docs/design.md §4.9): if a live peer
        depot holds a committed step at least as new as the store's,
        materialize it as a committed step dir under the checkpoint
        directory — the ordinary disk-restore path then loads it
        bit-identically. A tie deliberately goes to the PEER: at flagship
        scale ``checkpoint_dir`` is slow bulk storage, and skipping its
        read even for an already-known step is the protocol's entire
        payoff (when the step is already materialized locally the fetch
        is a no-op). Any peer failure (dead mid-transfer, integrity
        mismatch) excludes that peer and re-runs the source decision over
        the survivors — the NEXT live peer holding an eligible step is
        tried before disk, the fallback order the statechannel module
        promises. Returns the source the subsequent restore will read
        from."""
        if self.manager is None or self.ctx is None:
            return "disk"
        peers = list(getattr(self.ctx, "restore_peers", []) or [])
        if not peers:
            return "disk"
        from tf_operator_tpu.rendezvous.statechannel import (
            DepotClient,
            choose_restore_source,
        )

        disk_step = self.manager.latest_step() or 0
        client = DepotClient()
        remaining = list(peers)
        while remaining:
            source, url, step = choose_restore_source(
                remaining, self.ctx.namespace, self.ctx.job_name, disk_step,
                client=client,
            )
            if source != "peer":
                return "disk"
            fetched = client.fetch_step(
                url, self.ctx.namespace, self.ctx.job_name, step,
                self.manager.directory,
            )
            if fetched is not None:
                log.info("warm restore: pulled step %d from peer %s", step, url)
                return "peer"
            remaining = [u for u in remaining if u != url]
            log.warning(
                "peer restore of step %d from %s failed; %d peer(s) left "
                "before disk fallback (step %d)",
                step, url, len(remaining), disk_step,
            )
        return "disk"

    def restore_or_init(self, trainer, key):
        """Resume from the best warm source (peer depot, then latest disk
        checkpoint) or fresh-init; primes the host-side step mirror and
        records the restore-source span."""
        t0 = time.time()
        self.restore_source = self.prefetch_from_peers()
        state = trainer.restore_or_init(key, self.manager)
        self._step = self.start_step = int(state.step)
        if self.start_step:
            log.info(
                "resumed from checkpoint at step %d (source=%s)",
                self.start_step, self.restore_source,
            )
            if self.ctx is not None:
                self.ctx.record_restore(
                    self.restore_source, self.start_step, t0, time.time()
                )
        return state

    def resume_step(self) -> int:
        """Latest checkpointed step (0 if none) WITHOUT restoring — lets
        stream-data workloads skip already-consumed batches (DeviceLoader
        ``skip``) before entering run_loop."""
        if self.manager is not None:
            return self.manager.latest_step() or 0
        return 0

    def is_complete(self, steps: int) -> bool:
        """True when a previous run already trained past the step budget
        (the +1 accounts for the warmup step, which also trains). Peeks at
        the manifest only — call BEFORE restore_or_init so an
        already-complete job skips the full (possibly many-GB) restore."""
        if self.manager is not None:
            latest = self.manager.latest_step()
            if latest is not None:
                return latest >= steps + 1
        return self.start_step >= steps + 1

    def timed_steps(self, steps: int) -> int:
        """How many timed-loop iterations remain; the telemetry divisor.
        0 means throughput numbers would be meaningless — don't log them."""
        return max(0, steps - self.start_step)

    def advance(self, state, loss=None, n: int = 1) -> None:
        """Call once per trainer.step (or once per ``n``-step device-loop
        chunk); saves when a periodic save is due. Chunked callers must
        align chunks to save boundaries (run_loop does) — a chunk that
        jumps OVER a boundary would silently skip that save.

        Pass the step's loss so a diverged state is never checkpointed —
        saving NaN params would make them the latest checkpoint and poison
        every restart's resume into a permanent crash loop. The finiteness
        check fetches the loss to host, but only on saving steps, so the
        hot loop stays sync-free."""
        import math

        self._step += n
        if self.manager is not None and self.every and self._step % self.every == 0:
            if loss is not None and not math.isfinite(float(loss)):
                raise AssertionError(
                    f"non-finite loss {float(loss)} at step {self._step}; "
                    "refusing to checkpoint a diverged state"
                )
            if self.manager.save(self._step, state):
                self._note_save_stall(self._step)

    def _note_save_stall(self, step: int) -> None:
        """Record how long the step loop was actually blocked by the save
        just accepted — with the async pipeline this is the staging copy,
        not the device→host fetch or the disk write. Span lands in the
        job trace (the overlap-window evidence `tpujob trace` shows)."""
        import time as _time

        stall = self.manager.last_save_stall_s
        self.save_stalls.append(stall)
        if self.ctx is not None:
            now = _time.time()
            self.ctx.record_save_stall(step, now - stall, now)

    def poll_cadence_directive(self, step: Optional[int] = None) -> bool:
        """Apply a pending checkpoint-cadence directive (r16) at a step
        boundary. The autopilot publishes {"epoch", "checkpoint_every"}
        into the job status; the chief calls this between steps, applies
        each epoch exactly once (updating ``self.every`` — run_loop's
        chunk clipping reads it per chunk, so the new interval takes
        effect immediately), and acks ``applied_epoch``/``applied_step``
        back. Throttled to one API read per ``cadence_poll_s`` seconds;
        best-effort by contract (an unreachable API changes nothing).
        Returns True when a new epoch was applied this call."""
        if self.ctx is None:
            return False
        if getattr(self.ctx, "process_id", 0) != 0:
            return False  # the chief owns cadence, as it owns the saves
        poll = getattr(self.ctx, "poll_checkpoint_cadence_directive", None)
        if poll is None:
            return False
        import time as _time

        now = _time.time()
        if now - self._cadence_last_poll < self._cadence_poll_s:
            return False
        self._cadence_last_poll = now
        directive = poll() or {}
        epoch = int(directive.get("epoch", 0))
        if epoch <= self._cadence_epoch:
            return False
        self._cadence_epoch = epoch
        every = int(directive.get("checkpoint_every", 0))
        if every > 0 and every != self.every:
            log.info(
                "checkpoint cadence directive epoch %d: every %d -> %d steps",
                epoch, self.every, every,
            )
            self.every = every
        applied_step = self._step if step is None else int(step)
        self.ctx.ack_checkpoint_cadence(epoch, applied_step)
        return True

    def final(self, state) -> None:
        """Final save — call AFTER any throughput timing is read, so the
        write never pollutes step-time/MFU telemetry. Fenced (wait=True):
        the process may exit right after, and an unfenced async write
        would tear the checkpoint."""
        if self.manager is not None:
            if self.manager.save(self._step, state, wait=True):
                self._note_save_stall(self._step)

    def run_loop(self, trainer, key, batch, steps: int, on_step=None,
                 device_loop: int = 1):
        """The one warmup+timed train loop shared by workloads.

        restore-or-init → warmup step (compile boundary) → ``steps -
        start_step`` timed steps with periodic NaN-gated saves → finiteness
        guard → final save. Returns ``(state, loss, timed, step_s)`` where
        ``timed`` counts only the steps inside the timed region (warmup —
        including the device-loop warmup chunk — trains but is excluded)
        and ``step_s`` is None when no timed steps remained. Callers must check
        :meth:`is_complete` first. ``on_step(global_step)`` fires after
        every advance — the fault-injection / progress-reporting seam.

        ``batch`` is either one fixed batch (re-trained every step: the
        benchmarking shape) or a batch *iterator* — e.g. a
        ``train.data.DeviceLoader`` — pulled once per step. All batches
        must share one shape/dtype structure (jit compiles once). On
        restart-based recovery an iterator starts over unless the caller
        fast-forwards it (``DeviceLoader(skip=resume_step())``) — without
        that, a resumed run re-trains the stream's leading batches.

        ``device_loop=K`` runs up to K steps per compiled call
        (``Trainer.multi_step``), chunks clipped to checkpoint boundaries
        so no periodic save is skipped; iterator batches are stacked K at
        a time through a jitted stacker — multi-host global arrays can't
        be stacked OUTSIDE jit, but inside jit the stack is an ordinary
        SPMD program, so multi-host gangs keep the device loop with
        stream data (r4; the r3 behavior silently fell back to per-step
        dispatch there, costing the ~7% the loop buys at small steps).
        NOTE: ``on_step`` fires once per CHUNK with the post-chunk global
        step, so step-keyed triggers (the lm workload's ``fail_at_step``
        fault injection) can land up to K-1 steps late and after the
        chunk's save — chaos scenarios that need exact-step faults should
        run with device_loop=1 (chunks are deliberately NOT clipped at
        injection points: the loop cannot know which steps a caller's
        callback keys on).
        ``on_step`` then fires once per chunk (with the post-chunk global
        step), so fault-injection / progress hooks see chunk
        granularity."""
        import math
        import time

        from tf_operator_tpu.train.metrics import host_fetch

        is_iter = hasattr(batch, "__next__")
        pull = (lambda: next(batch)) if is_iter else (lambda: batch)
        device_loop = max(1, int(device_loop))
        stackers: dict = {}

        def pull_chunk(k: int):
            if not is_iter:
                return batch, False
            if k == 1:
                return next(batch), False
            import jax
            import jax.numpy as jnp

            slices = [next(batch) for _ in range(k)]
            # Stack INSIDE jit: on multi-host gangs the slices are
            # non-fully-addressable global arrays and jnp.stack on them
            # crashes eagerly, but under jit it is an ordinary SPMD
            # program (output sharded [None, *batch]). One compiled
            # stacker per chunk size (chunks vary only at save
            # boundaries).
            stacker = stackers.get(k)
            if stacker is None:
                stacker = jax.jit(
                    lambda *xs: jax.tree_util.tree_map(
                        lambda *ys: jnp.stack(ys), *xs
                    )
                )
                stackers[k] = stacker
            return stacker(*slices), True

        def chunk_size(remaining: int) -> int:
            k = min(device_loop, remaining)
            if self.manager is not None and self.every:
                # clip to the next save boundary so advance() never jumps
                # one (without a manager there is nothing to save — don't
                # forfeit dispatch amortization for a no-op)
                to_boundary = self.every - (self._step % self.every)
                k = min(k, to_boundary)
            return max(1, k)

        def run_chunk(state, remaining: int):
            k = chunk_size(remaining)
            if k == 1:
                state, m = trainer.step(state, pull())
            else:
                chunk, stacked = pull_chunk(k)
                state, m = trainer.multi_step(state, chunk, k, stacked=stacked)
            self.advance(state, loss=m["loss"], n=k)
            self.poll_cadence_directive()  # cadence retune lands at chunk boundary
            if on_step is not None:
                on_step(self._step)
            return state, m, k

        state = self.restore_or_init(trainer, key)
        remaining = self.timed_steps(steps)
        # warmup (compile boundary): the single-step program, then — when
        # device-looping — one chunk of each distinct upcoming chunk size,
        # so the boundary-clipped AND steady-state programs both compile
        # outside the timed region. Stops before exhausting the budget
        # (at least one chunk stays timed); a novel tail size can still
        # compile in-region, but a tail is by construction small.
        state, m = trainer.step(state, pull())
        self.advance(state, loss=m["loss"])
        if on_step is not None:
            on_step(self._step)
        warmed: set = set()
        while device_loop > 1 and remaining > 0:
            k_next = chunk_size(remaining)
            if k_next <= 1 or k_next in warmed or remaining <= k_next:
                break
            warmed.add(k_next)
            state, m, k = run_chunk(state, remaining)
            remaining -= k
        host_fetch(m["loss"])
        timed = remaining
        t0 = time.perf_counter()
        while remaining > 0:
            state, m, k = run_chunk(state, remaining)
            remaining -= k
        loss = float(m["loss"])
        step_s = (time.perf_counter() - t0) / timed if timed else None
        if not math.isfinite(loss):
            # deliberately NOT checkpointed: saving a diverged state would
            # poison every restart's resume
            raise AssertionError(f"non-finite loss {loss}")
        self.final(state)
        return state, loss, timed, step_s


def _stage_tree(tree: Any) -> Any:
    """Donation-safe staging snapshot of a state pytree.

    The trainer's step is jitted with ``donate_argnums`` over params /
    opt_state / extra — the moment the NEXT step runs, the buffers a save
    captured may be reused. Staging makes a device-side copy of every
    array leaf (an HBM→HBM copy, bounded by device memory bandwidth — the
    deliberate, small stall) and blocks until the copies materialize; the
    background drain then owns the copies outright and the step loop may
    donate the originals immediately."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def one(leaf):
        if isinstance(leaf, jax.Array):
            return jnp.copy(leaf)
        return np.array(leaf, copy=True)

    staged = jax.tree_util.tree_map(one, tree)
    jax.block_until_ready(staged)
    return staged


def _write_npy_chunked(path: str, arr, chunk_bytes: int) -> None:
    """np.save-compatible .npy writer that streams the array body in
    fixed-byte quanta instead of one write syscall — the disk half of the
    chunked pipeline (a multi-GB leaf never pins one giant dirty buffer,
    and the drain yields to the OS between quanta)."""
    import numpy as np

    arr = np.asarray(arr)
    if not arr.flags["C_CONTIGUOUS"]:
        # NOT ascontiguousarray unconditionally: it promotes 0-d arrays
        # to shape (1,), which would corrupt the header (scalars like
        # TrainState.step must round-trip 0-d).
        arr = np.ascontiguousarray(arr)
    with open(path, "wb") as f:
        np.lib.format.write_array_header_1_0(
            f, np.lib.format.header_data_from_array_1_0(arr)
        )
        if arr.ndim == 0:
            f.write(arr.tobytes())  # a scalar is one (tiny) quantum
            return
        try:
            mv = memoryview(arr).cast("B")
        except (TypeError, ValueError):
            # Extension dtypes (bfloat16, fp8) have no buffer protocol;
            # a uint8 view of the contiguous body streams the same bytes.
            mv = memoryview(arr.view(np.uint8)).cast("B")
        for off in range(0, len(mv), chunk_bytes):
            f.write(mv[off : off + chunk_bytes])


def _abstractify(tree: Any) -> Any:
    """Concrete/abstract array pytree -> ShapeDtypeStructs carrying
    shardings (what StandardRestore needs to lay out device arrays)."""
    import jax

    def one(leaf):
        if isinstance(leaf, jax.ShapeDtypeStruct):
            return leaf
        return jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=getattr(leaf, "sharding", None)
        )

    return jax.tree_util.tree_map(one, tree)
