"""Input pipeline: host-side datasets with device prefetch.

The reference delegates data loading entirely to user containers (SURVEY.md
§5: the operator never touches tensors; `tf.data` came with TensorFlow).
A complete TPU framework has to supply the analogue itself: if the host
hands the device one batch at a time synchronously, every step eats a
host→HBM transfer on its critical path. ``DeviceLoader`` pipelines that
away — a background thread stages the next batches onto the device (with
the job's batch sharding) while the current step runs, so steps dequeue
device-resident arrays. This is the jit-era equivalent of TPU infeed /
`tf.data` prefetch-to-device.

Multi-host: each process stages only its addressable shard
(`jax.make_array_from_process_local_data`), so a dp=16 job moves 1/16th
of the global batch per host — the loader contract is "every process
iterates the same dataset structure; each sees its local slice".
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

__all__ = [
    "SyntheticImages",
    "SyntheticTokens",
    "ArrayDataset",
    "DeviceLoader",
    "local_loader",
    "read_idx",
    "write_idx",
    "MnistIdxDataset",
    "TokenMemmapDataset",
    "write_token_corpus",
    "augment_images",
    "AugmentedImages",
    "prepare_classification_images",
    "elastic_global_order",
    "elastic_rank_positions",
    "elastic_coverage",
]


class ArrayDataset:
    """Finite in-memory dataset: yields dict batches sliced from arrays.

    arrays: pytree-of-ndarray with a common leading (example) dim.
    Deterministic order per epoch index (reshuffled by ``seed + epoch``),
    dropping the ragged tail batch (static shapes — XLA recompiles on any
    shape change, SURVEY §6 submit→first-step budget)."""

    def __init__(self, arrays: Any, batch_size: int, *, shuffle: bool = True,
                 seed: int = 0) -> None:
        import jax

        leaves = jax.tree_util.tree_leaves(arrays)
        if not leaves:
            raise ValueError("ArrayDataset needs at least one array")
        n = leaves[0].shape[0]
        for leaf in leaves:
            if leaf.shape[0] != n:
                raise ValueError("all arrays must share the leading dim")
        if batch_size > n:
            raise ValueError(f"batch_size {batch_size} > dataset size {n}")
        self.arrays = arrays
        self.n = n
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed

    def __len__(self) -> int:
        return self.n // self.batch_size

    def epoch(self, epoch: int = 0) -> Iterator[Any]:
        import jax

        order = np.arange(self.n)
        if self.shuffle:
            np.random.default_rng(self.seed + epoch).shuffle(order)
        for i in range(len(self)):
            idx = order[i * self.batch_size : (i + 1) * self.batch_size]
            yield jax.tree_util.tree_map(lambda a: a[idx], self.arrays)

    def __iter__(self) -> Iterator[Any]:
        epoch = 0
        while True:  # repeat forever; the consumer bounds steps
            yield from self.epoch(epoch)
            epoch += 1


class SyntheticImages(ArrayDataset):
    """Deterministic fake image-classification data (ImageNet-shaped by
    default) — the benchmarking stand-in the BASELINE configs train on."""

    def __init__(self, batch_size: int, *, n: int = 1024, image_size: int = 224,
                 channels: int = 3, num_classes: int = 1000, seed: int = 0) -> None:
        rng = np.random.default_rng(seed)
        super().__init__(
            {
                "image": rng.standard_normal(
                    (n, image_size, image_size, channels), dtype=np.float32
                ),
                "label": rng.integers(0, num_classes, (n,), dtype=np.int32),
            },
            batch_size,
            seed=seed,
        )


class SyntheticTokens(ArrayDataset):
    """Deterministic fake LM token data."""

    def __init__(self, batch_size: int, *, n: int = 2048, seq_len: int = 512,
                 vocab: int = 32000, seed: int = 0) -> None:
        rng = np.random.default_rng(seed)
        super().__init__(
            {"tokens": rng.integers(0, vocab, (n, seq_len), dtype=np.int32)},
            batch_size,
            seed=seed,
        )


# ---------------------------------------------------------------------------
# Disk-backed readers: MNIST idx-ubyte + tokenized-corpus memmap
# ---------------------------------------------------------------------------

# once-only latch for the native-dataops-unavailable warning in
# _augment_native (must exist at module scope: the warning path is the
# first reader, on hosts where the C++ build fails)
_dataops_warned = False

_IDX_DTYPES = {
    0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
    0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64,
}
_IDX_CODES = {np.dtype(v): k for k, v in _IDX_DTYPES.items()}


def read_idx(path: str) -> np.ndarray:
    """Read an idx-ubyte file (the MNIST wire format the reference's
    dist_mnist consumes via read_data_sets,
    /root/reference/test/e2e/dist-mnist/dist_mnist.py:214-215): 2 zero
    bytes, dtype code, ndim, big-endian uint32 dims, raw data. ``.gz``
    paths decompress transparently (the distribution format)."""
    import gzip

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        header = f.read(4)
        if len(header) != 4 or header[0] != 0 or header[1] != 0:
            raise ValueError(f"{path}: not an idx file (bad magic {header!r})")
        code, ndim = header[2], header[3]
        if code not in _IDX_DTYPES:
            raise ValueError(f"{path}: unknown idx dtype code 0x{code:02x}")
        dims = np.frombuffer(f.read(4 * ndim), dtype=">u4")
        if dims.size != ndim:
            raise ValueError(f"{path}: truncated idx header")
        data = np.frombuffer(f.read(), dtype=np.dtype(_IDX_DTYPES[code]).newbyteorder(">"))
        n = int(np.prod(dims)) if ndim else 0
        if data.size != n:
            raise ValueError(f"{path}: expected {n} elements, got {data.size}")
        return data.reshape(tuple(int(d) for d in dims)).astype(_IDX_DTYPES[code])


def write_idx(path: str, array: np.ndarray) -> None:
    """Write an idx file (gzip when path ends .gz) — the test/tooling side
    of read_idx, so fixtures carry the real wire format."""
    import gzip

    arr = np.ascontiguousarray(array)
    code = _IDX_CODES.get(arr.dtype)
    if code is None:
        raise ValueError(f"unsupported idx dtype {arr.dtype}")
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wb") as f:
        f.write(bytes([0, 0, code, arr.ndim]))
        f.write(np.asarray(arr.shape, dtype=">u4").tobytes())
        f.write(arr.astype(arr.dtype.newbyteorder(">")).tobytes())


def _find_idx(data_dir: str, names) -> str:
    import os

    for name in names:
        for suffix in ("", ".gz"):
            p = os.path.join(data_dir, name + suffix)
            if os.path.exists(p):
                return p
    raise FileNotFoundError(
        f"none of {list(names)} (or .gz) under {data_dir}"
    )


class MnistIdxDataset(ArrayDataset):
    """Disk-backed image classification from standard idx files.

    Looks for the canonical MNIST names (train-images-idx3-ubyte /
    train-labels-idx1-ubyte, t10k-* for split="test", optionally .gz) —
    drop the real MNIST distribution files in ``data_dir`` and this
    trains actual MNIST, matching the reference's dist_mnist e2e. Images
    normalize to [0, 1] f32; the per-image shape is whatever the file
    carries (28x28 for MNIST; the e2e fixtures write real scanned-digit
    images at 8x8).

    ``process_shard``: in a multi-process gang each process takes a
    disjoint stride of the examples (rank::nprocs), so shards carry
    distinct real data — the reader-side analogue of what local_loader
    does for synthetic seeds."""

    def __init__(self, data_dir: str, batch_size: int, *, split: str = "train",
                 shuffle: bool = True, seed: int = 0,
                 process_shard: bool = True) -> None:
        prefix = {"train": "train", "test": "t10k"}[split]
        images = read_idx(
            _find_idx(data_dir, (f"{prefix}-images-idx3-ubyte", f"{prefix}-images.idx3-ubyte"))
        )
        labels = read_idx(
            _find_idx(data_dir, (f"{prefix}-labels-idx1-ubyte", f"{prefix}-labels.idx1-ubyte"))
        )
        if images.shape[0] != labels.shape[0]:
            raise ValueError(
                f"{data_dir}: {images.shape[0]} images vs {labels.shape[0]} labels"
            )
        # Dtype-derived scale, NOT per-split max: max-based scaling would
        # normalize train and test differently whenever their brightest
        # pixels differ, silently skewing eval accuracy.
        scale = 255.0 if np.issubdtype(images.dtype, np.integer) else 1.0
        x = images.astype(np.float32) / scale
        y = labels.astype(np.int32)
        # Pre-shard (global) example count: every process must derive the
        # SAME steps-per-epoch from it — rank-local shard sizes differ by
        # one when nprocs doesn't divide n, and a step count read off the
        # local shard would deadlock the gang (one rank dispatching an
        # SPMD step the others never join).
        self.global_n = x.shape[0]
        if process_shard:
            import jax

            rank, n = jax.process_index(), jax.process_count()
            if n > 1:
                x, y = x[rank::n], y[rank::n]
        super().__init__({"image": x, "label": y}, batch_size,
                         shuffle=shuffle, seed=seed)


# ---------------------------------------------------------------------------
# Host-side image augmentation (the ResNet/ImageNet-recipe half the
# synthetic paths never needed: random crop + horizontal flip)
# ---------------------------------------------------------------------------


def augment_images(images: np.ndarray, rng: np.random.Generator, *,
                   pad: int = 4, flip: bool = True,
                   native: Optional[bool] = None) -> np.ndarray:
    """Random-crop + horizontal-flip augmentation, host-side.

    The standard small-image recipe (ResNet/CIFAR): zero-pad ``pad``
    pixels on each spatial edge, crop back to the original h×w at a
    per-image random offset, then mirror each image left-right with
    probability 1/2 (``flip=False`` for orientation-sensitive classes —
    digits/text). images: [b, h, w] or [b, h, w, c]; same shape out.

    Runs on the host on purpose: augmentation is per-example branchy work
    the DeviceLoader's prefetch thread hides behind the step, and keeping
    it off the device keeps the train step's compiled program static.

    Dispatch: the RANDOMNESS is always drawn here (numpy Generator, one
    draw order regardless of path — outputs are bit-identical for one
    seed), and the gather work runs through the native dataops library
    (native/dataops.cc: threaded memcpy crop + in-write flip) when
    ``native`` is None/True, falling back to the numpy loop when the
    library is unavailable or the array layout is unsupported
    (``native=False`` forces the fallback; True raises if unusable)."""
    b, h, w = images.shape[:3]
    if not pad and not flip:
        return images  # no-op config: input returned as-is on EVERY path
    dy = dx = do = None
    if pad:
        dy = rng.integers(0, 2 * pad + 1, b)
        dx = rng.integers(0, 2 * pad + 1, b)
    if flip:
        do = rng.random(b) < 0.5
    if native is not False and b > 0:
        out = _augment_native(images, pad, dy, dx, do)
        if out is not None:
            return out
        if native:
            raise RuntimeError("native augmentation unavailable for this input")
    out = images
    if pad:
        widths = [(0, 0), (pad, pad), (pad, pad)] + [(0, 0)] * (images.ndim - 3)
        padded = np.pad(images, widths)
        out = np.empty_like(images)
        for i in range(b):  # host-side; hidden by the loader's prefetch
            out[i] = padded[i, dy[i]:dy[i] + h, dx[i]:dx[i] + w]
    if flip:
        out = np.where(
            do.reshape((b,) + (1,) * (images.ndim - 1)), out[:, :, ::-1], out
        )
    return out


def _augment_native(images: np.ndarray, pad: int, dy, dx, do) -> Optional[np.ndarray]:
    """Run the crop/flip gather through native/dataops.cc. Returns None
    when the native path cannot serve this input (library missing/broken,
    non-C-contiguous array) so the caller falls back — same offsets, same
    output bytes either way."""
    import ctypes

    global _dataops_warned
    # A failed load already warned once — don't re-run the (subprocess,
    # up-to-120s) native build attempt on every batch of a job that is
    # going to fall back to numpy anyway.
    if _dataops_warned:
        return None
    try:
        from tf_operator_tpu.runtime.native import load_dataops

        lib = load_dataops()
    except Exception as exc:
        # Warn ONCE: the numpy fallback is ~6x slower (BASELINE.md) — at
        # ResNet rates it cannot feed the step, and without a diagnostic
        # an input-bound job points at nothing.
        if not _dataops_warned:
            _dataops_warned = True
            import warnings

            warnings.warn(
                f"native dataops unavailable ({exc!r}); augmentation falls "
                "back to the ~6x-slower numpy path", RuntimeWarning)
        return None
    arr = images if images.flags["C_CONTIGUOUS"] else None
    if arr is None:
        return None
    b, h, w = arr.shape[:3]
    # fold trailing dims + element size into bytes-per-pixel (the op is
    # pure byte movement, dtype-agnostic)
    pixel = arr.itemsize
    for dim in arr.shape[3:]:
        pixel *= dim
    out = np.empty_like(arr)
    # staging arrays must stay referenced across the call (ctypes keeps no
    # reference; a GC'd temp would hand C a dangling pointer)
    dy_a = np.ascontiguousarray(dy, dtype=np.int32) if dy is not None else None
    dx_a = np.ascontiguousarray(dx, dtype=np.int32) if dx is not None else None
    do_a = np.ascontiguousarray(do, dtype=np.uint8) if do is not None else None
    rc = lib.tpuj_augment(
        arr.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        b, h, w, pixel, pad,
        dy_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)) if dy_a is not None else None,
        dx_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)) if dx_a is not None else None,
        do_a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)) if do_a is not None else None,
        0,
    )
    if rc != 0:
        return None
    return out


class AugmentedImages:
    """Wraps a dict-batch image iterable with augment_images on the
    ``key`` leaf (fresh randomness per batch, deterministic per seed).
    Sits between a disk reader and the DeviceLoader:

        DeviceLoader(AugmentedImages(MnistIdxDataset(...)), sharding)
    """

    def __init__(self, source: Iterable[Any], *, pad: int = 4,
                 flip: bool = True, seed: int = 0, key: str = "image") -> None:
        self.source = source
        self.pad = pad
        self.flip = flip
        self.key = key
        # ONE rng for the object's lifetime (not per-__iter__): re-seeding
        # each epoch would replay identical "random" crops/flips every
        # epoch, defeating the augmentation.
        self._rng = np.random.default_rng(seed)

    def __iter__(self) -> Iterator[Any]:
        for batch in self.source:
            batch = dict(batch)
            batch[self.key] = augment_images(
                batch[self.key], self._rng, pad=self.pad, flip=self.flip
            )
            yield batch


def prepare_classification_images(images: np.ndarray,
                                  image_size: Optional[int] = None) -> np.ndarray:
    """Adapt reader output to a convnet's [b, h, w, 3] contract:
    grayscale [b, h, w] gets a broadcast channel dim, and ``image_size``
    (must be an integer multiple of the native size) upsamples
    nearest-neighbor — e.g. the 8×8 scanned-digit fixtures to 32×32 so a
    /32-downsampling ResNet keeps a spatial cell at the head."""
    if images.ndim == 3:
        images = np.repeat(images[..., None], 3, axis=-1)
    if image_size and image_size != images.shape[1]:
        factor, rem = divmod(image_size, images.shape[1])
        if rem or factor < 1:
            raise ValueError(
                f"image_size {image_size} is not an integer multiple of the "
                f"native size {images.shape[1]}"
            )
        images = np.repeat(np.repeat(images, factor, axis=1), factor, axis=2)
    return images


# ---------------------------------------------------------------------------
# Elastic re-carve primitives (r12)
#
# The classic multi-host carve is ``windows[rank::nprocs]`` — a WORLD-SIZE-
# DEPENDENT stride: change nprocs and every rank's stream silently shifts,
# duplicating some windows and dropping others. Elastic gangs need the
# opposite invariant: one CANONICAL, world-size-independent global order G
# over all windows, plus a pure function from (consumed offset, rank, world
# size) to the windows a rank owns. Then a resize is just "survivors resume
# carving G from the global consumed offset with the new world size" — and
# the union of all rank streams across any shrink→grow→shrink sequence is
# exactly G[0:T], no token duplicated or dropped (tests/test_data_recarve.py
# pins this).
#
# Offset accounting is POSITION-based, not step-based: the global offset C
# counts how many positions of G the gang has consumed in total. During an
# epoch with world size n starting at offset C0, rank r owns positions
# C0+r, C0+r+n, C0+r+2n, ... — one position per rank per "deal row", so a
# gang that completes k rows advances C by k*n atomically.
# ---------------------------------------------------------------------------


def elastic_global_order(n_windows: int, seed: int = 0,
                         shuffle: bool = True) -> np.ndarray:
    """The canonical global window order G: a deterministic permutation of
    ``arange(n_windows)`` seeded by ``seed`` alone — independent of world
    size, rank, and epoch, so every member of every incarnation of an
    elastic gang derives the identical sequence."""
    order = np.arange(int(n_windows))
    if shuffle:
        np.random.default_rng(int(seed)).shuffle(order)
    return order


def elastic_rank_positions(start: int, end: int, rank: int,
                           world_size: int) -> range:
    """Positions of G that ``rank`` (of ``world_size``) owns within the
    half-open offset interval [start, end) — the ``rank::n`` stride
    re-anchored at the global consumed offset. The union over ranks is
    exactly range(start, end); disjointness and coverage are structural."""
    if world_size < 1:
        raise ValueError(f"world_size must be >= 1, got {world_size}")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} outside world of {world_size}")
    return range(int(start) + int(rank), int(end), int(world_size))


def elastic_coverage(segments) -> list:
    """Flatten a resize history into every (position, rank) assignment.

    ``segments``: iterable of ``(start, end, world_size)`` — one entry per
    resize epoch, offsets half-open and contiguous. Returns the list of
    (position, rank) pairs in position order; the positions are
    range(first start, last end) each exactly once, whatever the world
    sizes were. The verification half of the re-carve contract (used by
    the elastic soak checker and the recarve tests)."""
    out = []
    for start, end, n in segments:
        for r in range(int(n)):
            for p in elastic_rank_positions(start, end, r, n):
                out.append((p, r))
    out.sort(key=lambda pr: pr[0])
    return out


def write_token_corpus(path: str, tokens: np.ndarray, dtype=np.uint16) -> None:
    """Persist a 1-D token stream as a raw little-endian memmap file plus a
    sidecar ``path + '.meta'`` (dtype + count) so readers need no guessing."""
    arr = np.ascontiguousarray(tokens, dtype=dtype)
    arr.tofile(path)
    with open(path + ".meta", "w") as f:
        f.write(f"{np.dtype(dtype).name} {arr.size}\n")


class TokenMemmapDataset:
    """Tokenized-corpus reader: a flat memmapped token stream cut into
    non-overlapping [seq_len] windows, batched — the standard pretraining
    layout (tokenize once offline, train from the memmap; the file never
    loads into RAM). Yields {"tokens": [batch, seq_len] int32} forever,
    reshuffling window order per epoch.

    ``process_shard``: each process reads a disjoint stride of windows
    (rank::nprocs) for multi-host training.

    ``holdout``/``split`` (r5, VERDICT r4 #4): ``holdout=N`` reserves the
    LAST N windows of the corpus as a held-out split carved out BEFORE
    process-sharding, so it is disjoint from every trainer rank's stride
    by construction. split="train" (default) reads everything before the
    reservation; split="holdout" reads exactly the reserved windows — the
    evaluator's view. Trainer and evaluator agree on the boundary by
    sharing the same ``holdout_windows`` workload key."""

    def __init__(self, path: str, batch_size: int, seq_len: int, *,
                 dtype=None, shuffle: bool = True, seed: int = 0,
                 process_shard: bool = True, holdout: int = 0,
                 split: str = "train") -> None:
        import os

        if dtype is None:
            meta = path + ".meta"
            if os.path.exists(meta):
                with open(meta) as f:
                    dtype = np.dtype(f.read().split()[0])
            else:
                dtype = np.uint16
        self._mm = np.memmap(path, dtype=dtype, mode="r")
        n_windows = self._mm.size // seq_len
        if n_windows < 1:
            raise ValueError(
                f"{path}: {self._mm.size} tokens < one window of {seq_len}"
            )
        if split not in ("train", "holdout"):
            raise ValueError(f'unknown split {split!r}; use "train"|"holdout"')
        if split == "holdout" and not holdout:
            raise ValueError('split="holdout" requires holdout > 0')
        if holdout and holdout >= n_windows:
            raise ValueError(
                f"holdout {holdout} >= {n_windows} corpus windows — nothing "
                "left to train on"
            )
        self._windows = np.arange(n_windows)
        if holdout:
            self._windows = (
                self._windows[-holdout:] if split == "holdout"
                else self._windows[:-holdout]
            )
        # Pre-shard (post-holdout) window set: the domain of the elastic
        # canonical order (elastic_batches) — must be identical on every
        # rank at every world size, so it is captured BEFORE the
        # world-size-dependent rank::n carve below.
        self._global_windows = self._windows
        if process_shard:
            import jax

            rank, n = jax.process_index(), jax.process_count()
            if n > 1:
                self._windows = self._windows[rank::n]
        if batch_size > self._windows.size:
            raise ValueError(
                f"batch_size {batch_size} > {self._windows.size} local windows"
            )
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.shuffle = shuffle
        self.seed = seed

    def __len__(self) -> int:
        return self._windows.size // self.batch_size

    def epoch(self, epoch: int = 0) -> Iterator[Any]:
        order = self._windows.copy()
        if self.shuffle:
            np.random.default_rng(self.seed + epoch).shuffle(order)
        for i in range(len(self)):
            idx = order[i * self.batch_size : (i + 1) * self.batch_size]
            batch = np.stack(
                [self._mm[w * self.seq_len : (w + 1) * self.seq_len] for w in idx]
            )
            yield {"tokens": batch.astype(np.int32)}

    def __iter__(self) -> Iterator[Any]:
        epoch = 0
        while True:
            yield from self.epoch(epoch)
            epoch += 1

    # -- elastic re-carve (r12) -------------------------------------------

    def elastic_windows(self, start: int, end: int, rank: int,
                        world_size: int) -> np.ndarray:
        """This rank's window ids for offset interval [start, end) of the
        canonical global order — the re-carve seam: after a resize the
        caller re-invokes this with the new (rank, world_size) anchored at
        the global consumed offset, and token accounting stays exact
        (union over ranks and segments == the uninterrupted stream)."""
        order = elastic_global_order(
            self._global_windows.size, seed=self.seed, shuffle=self.shuffle
        )
        positions = np.fromiter(
            elastic_rank_positions(start, end, rank, world_size), dtype=np.int64
        )
        return self._global_windows[order[positions]] if positions.size else positions

    def elastic_batches(self, start: int, end: int, rank: int,
                        world_size: int) -> Iterator[Any]:
        """Batched view of :meth:`elastic_windows` (drops the ragged tail
        like :meth:`epoch` — callers that need exact accounting consume
        window-granular via elastic_windows)."""
        wins = self.elastic_windows(start, end, rank, world_size)
        for i in range(wins.size // self.batch_size):
            idx = wins[i * self.batch_size : (i + 1) * self.batch_size]
            batch = np.stack(
                [self._mm[w * self.seq_len : (w + 1) * self.seq_len] for w in idx]
            )
            yield {"tokens": batch.astype(np.int32)}


def local_loader(
    dataset_cls: Callable[..., "ArrayDataset"],
    global_batch: int,
    sharding: Any,
    *,
    min_examples: int = 32,
    prefetch: int = 2,
    skip: int = 0,
    **dataset_kw: Any,
) -> "DeviceLoader":
    """The multi-host stream contract in one place: split ``global_batch``
    across processes (must divide), seed the synthetic dataset by rank so
    shards carry distinct data, and wrap it in a prefetching DeviceLoader.
    ``skip`` fast-forwards past batches a previous incarnation already
    trained on (pass the resumed step count on restart-based recovery).
    Used by the lm/resnet workloads' ``data: "stream"`` paths."""
    import jax

    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(
            f"batch_size {global_batch} not divisible by {n_proc} processes"
        )
    local = global_batch // n_proc
    ds = dataset_cls(
        local,
        n=max(2 * local, min_examples),
        seed=jax.process_index(),
        **dataset_kw,
    )
    return DeviceLoader(ds, sharding, prefetch=prefetch, skip=skip)


class DeviceLoader:
    """Wraps a host batch iterable; yields device-resident sharded batches.

    A daemon thread pulls host batches, shards them onto the mesh, and
    keeps up to ``prefetch`` staged ahead of the consumer — transfer for
    step N+1 overlaps compute for step N. ``sharding`` is typically
    ``trainer.batch_sharding``; a pytree batch may also map to a pytree
    of shardings (dict batches get the one sharding on every leaf).

    Iteration ends when the source iterator does (pass a bounded iterable
    for epochs; ArrayDataset repeats forever). ``close()`` (or `with`)
    stops the stager; the thread also exits if the consumer drops the
    loader. Errors in the source re-raise at the consumer's next pull."""

    _END = object()

    def __init__(
        self,
        source: Iterable[Any],
        sharding: Any,
        *,
        prefetch: int = 2,
        skip: int = 0,
        put: Optional[Callable[[Any, Any], Any]] = None,
    ) -> None:
        if prefetch < 1:
            raise ValueError("prefetch must be >= 1")
        if skip < 0:
            raise ValueError("skip must be >= 0")
        self.sharding = sharding
        self._skip = skip
        self._put = put or self._default_put
        self._q: "queue.Queue" = queue.Queue(maxsize=prefetch)
        self._stop = threading.Event()
        self._err: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._stage, args=(iter(source),), name="device-loader", daemon=True
        )
        self._thread.start()

    def _default_put(self, batch: Any, sharding: Any) -> Any:
        import jax

        if isinstance(sharding, jax.sharding.Sharding):
            shardings = jax.tree_util.tree_map(lambda _: sharding, batch)
        else:  # a pytree of shardings matching the batch structure
            shardings = sharding
        if jax.process_count() > 1:
            # Each process holds its local slice of the global batch;
            # assemble the logically-global arrays from local data.
            return jax.tree_util.tree_map(
                lambda a, s: jax.make_array_from_process_local_data(s, a),
                batch,
                shardings,
            )
        return jax.device_put(batch, shardings)

    def _stage(self, it: Iterator[Any]) -> None:
        try:
            # Restart fast-forward: drop already-consumed batches on the
            # host (no staging cost) so a resumed job continues the stream
            # where the previous incarnation left off.
            try:
                for _ in range(self._skip):
                    next(it)
            except StopIteration:
                self._enqueue_end()
                return
            for batch in it:
                if self._stop.is_set():
                    return
                staged = self._put(batch, self.sharding)
                while not self._stop.is_set():
                    try:
                        self._q.put(staged, timeout=0.2)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
            self._enqueue_end()
        except BaseException as exc:  # surfaced to the consumer
            self._err = exc
            self._enqueue_end()

    def _enqueue_end(self) -> None:
        while not self._stop.is_set():
            try:
                self._q.put(self._END, timeout=0.2)
                return
            except queue.Full:
                continue

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        while True:
            try:
                item = self._q.get(timeout=0.2)
                break
            except queue.Empty:
                if not self._thread.is_alive() and self._q.empty():
                    item = self._END
                    break
        if item is self._END:
            self._stop.set()
            if self._err is not None:
                err, self._err = self._err, None
                raise err
            raise StopIteration
        return item

    def close(self) -> None:
        self._stop.set()
        # drain so a blocked stager can observe the stop flag
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)

    def __enter__(self) -> "DeviceLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
