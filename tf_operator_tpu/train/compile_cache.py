"""Persistent XLA compilation cache: submit→first-step latency control.

The north-star latency metric (BASELINE.json; SURVEY.md §7 hard part d) is
submit→first-step, and on TPU it is dominated by XLA compilation (~20-40 s
for the bench models) — a cost the reference never had to manage because it
ran TF's pre-compiled kernels. The TPU-native answer is JAX's persistent
compilation cache: executables are keyed by (HLO, compile options, backend)
and reloaded from disk, so

- a gang restart (the framework's recovery path — restart-based recovery,
  SURVEY.md §5) relaunches the training program at near-interactive speed,
- repeat submissions of the same workload skip straight to step 1.

r11 adds the fleet tier: when the controller stamps ``TPUJOB_COMPILE_CACHE``
(cachesvc/), the hardened get/put pair becomes read-through/write-back
against the shared service — a local miss fetches the sha256-verified
executable from the fleet before falling back to compilation, and every
local compile publishes asynchronously (off the step path). A dead or
unreachable service degrades to the PR 10 local-only path; the degradation
is recorded in ``stats()`` and surfaced as a span attribute by
``JobContext.mark_first_step``, never as a job failure.

``enable()`` is called by the rendezvous harness before user ``train_fn``
runs (every operator-launched process gets it), and by ``bench.py``. Safe
to call multiple times; honors an explicit ``JAX_COMPILATION_CACHE_DIR``.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from typing import Callable, Dict, Optional

log = logging.getLogger("tpujob.compile_cache")

DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "tf_operator_tpu", "xla"
)
ENV_DIR = "JAX_COMPILATION_CACHE_DIR"
ENV_DISABLE = "TPUJOB_NO_COMPILE_CACHE"
ENV_FORCE = "TPUJOB_FORCE_COMPILE_CACHE"
# Remote-tier wait budget for a key whose compile intent is live at the
# service (AOT-at-admission in flight): how long a worker polls before
# giving up and compiling locally.
ENV_REMOTE_WAIT = "TPUJOB_COMPILE_CACHE_WAIT_S"

_DIGEST_SUFFIX = "-sha256"
_LOCK_STALE_S = 60.0
_hardened = False

# Remote tier (cachesvc/): configured by enable() from the controller-
# stamped TPUJOB_COMPILE_CACHE env, or explicitly via configure_remote().
_remote = None
_remote_lock = threading.Lock()
_stats = {
    "local_hits": 0, "remote_hits": 0, "misses": 0,
    "local_puts": 0, "remote_puts": 0,
}


def _digest_path(cache_path):
    return cache_path.with_name(cache_path.name + _DIGEST_SUFFIX)


def publish_pair(dir_path, key: str, val: bytes) -> bool:
    """Atomically publish the ``{key}-cache`` payload and its sha256
    sidecar as a UNIT under ``dir_path``.

    The r10 version wrote the sidecar with a bare ``write_bytes()`` at
    its final name BEFORE the payload landed — two processes racing the
    same key could interleave (A's sidecar overwritten by B's, then A's
    payload published: a mismatched pair every get() purges), and a
    reader could even observe a partially-written sidecar. Now both
    files are written to writer-unique temp names and published with
    ``os.replace`` — sidecar strictly first, so no instant ever shows a
    payload ahead of its matching digest — and the publish sequence is
    serialized by an O_EXCL lock file, so concurrent writers cannot
    interleave their replaces: the winner publishes a matched pair, the
    losers skip (the entry exists). A stale lock (holder died mid-
    publish) is broken after ``_LOCK_STALE_S``; the half-published state
    it can leave (sidecar without payload, or a mismatched pair) is
    exactly what get()'s verify-and-purge already self-heals.

    Returns True when this writer published (or the entry already
    existed); False when the publish was skipped (lock contention) or
    failed — callers treat False as "not cached", never as an error."""
    import pathlib

    dir_path = pathlib.Path(dir_path)
    cache_path = dir_path / f"{key}-cache"
    if cache_path.exists():
        return True
    lock = dir_path / f"{key}-cache.lock"
    try:
        fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        # Another writer is publishing this key right now — unless it
        # died and left the lock behind: break stale locks once.
        try:
            import time as _time

            if _time.time() - lock.stat().st_mtime <= _LOCK_STALE_S:
                return False
            lock.unlink()
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except (OSError, FileExistsError):
            return False
    except OSError:
        return False
    try:
        if cache_path.exists():
            return True  # the previous lock holder finished first
        suffix = f".tmp{os.getpid()}-{threading.get_ident()}"
        digest_tmp = dir_path / f"{key}-cache{_DIGEST_SUFFIX}{suffix}"
        payload_tmp = dir_path / f"{key}-cache{suffix}"
        digest_tmp.write_bytes(hashlib.sha256(val).hexdigest().encode())
        payload_tmp.write_bytes(val)
        os.replace(digest_tmp, _digest_path(cache_path))  # digest first...
        os.replace(payload_tmp, cache_path)  # ...payload never ahead of it
        import time as _time

        (dir_path / f"{key}-atime").write_bytes(
            _time.time_ns().to_bytes(8, "little")
        )
        return True
    except OSError:
        return False
    finally:
        os.close(fd)
        try:
            lock.unlink()
        except OSError:
            pass


def configure_remote(url: Optional[str]) -> None:
    """Point the remote tier at a cachesvc URL (None disconnects it).
    ``enable()`` calls this from the controller-stamped env; tests and
    the AOT compiler call it directly."""
    global _remote
    from tf_operator_tpu.cachesvc.client import CacheClient

    with _remote_lock:
        _remote = CacheClient(url) if url else None


def remote_client():
    return _remote


def stats() -> Dict[str, object]:
    """Cache-tier counters for this process, plus the remote endpoint and
    whether it was ever observed dead — the payload of the workload's
    ``compile-cache`` span (JobContext.mark_first_step)."""
    out: Dict[str, object] = dict(_stats)
    client = _remote
    out["remote_url"] = client.url if client else ""
    out["remote_dead"] = bool(client.dead) if client else False
    return out


def _remote_jax_tier_active() -> bool:
    """The shared tier for JAX-PRODUCED executables. cpu-pinned processes
    are excluded UNCONDITIONALLY (not even ENV_FORCE overrides): jaxlib
    CPU executables embed process-local state, so publishing one to the
    fleet weaponizes the r10 crash across hosts. force only re-enables
    the LOCAL cache for machinery tests."""
    return _remote is not None and not _cpu_only_platform()


def _remote_wait_s() -> float:
    try:
        return float(os.environ.get(ENV_REMOTE_WAIT, "") or 10.0)
    except ValueError:
        return 10.0


def _publish_async(key: str, val: bytes) -> None:
    """Write-back to the fleet tier off the step path: the put that
    follows a compile must not serialize a network round-trip into the
    step loop."""
    client = _remote
    if client is None:
        return

    def _push():
        if client.publish(key, val):
            _stats["remote_puts"] += 1

    threading.Thread(target=_push, daemon=True, name=f"cc-publish-{key[:12]}").start()


def _harden_cache_io() -> None:
    """Crash-safe + fleet-tiered jax file cache (r10 hardening, r11
    remote tier): jax's ``LRUCache.put`` writes entries with a bare
    ``write_bytes()`` and never overwrites an existing key. A process
    killed mid-write — the operator's preempt path SIGKILLs workers, so
    this is a *routine* event, not a freak one — leaves a truncated blob
    under the final name; every warm-restarted incarnation that hits that
    key then deserializes garbage inside XLA and dies with
    SIGSEGV/SIGABRT, which the restart taxonomy rightly calls permanent.

    The wraps:

    - ``put``: atomic sidecar+payload pair publish (``publish_pair``) —
      a kill at any instant leaves either no entry or a complete one,
      and concurrent writers can no longer interleave a mismatched
      pair — then an async write-back to the fleet tier.
    - ``get``: verify the sidecar before handing bytes to XLA; a
      mismatching or missing sidecar deletes the entry and reports a
      miss (recompile), so pre-existing poison self-heals instead of
      aborting the process. A verified local miss read-throughs the
      fleet tier (sha256-checked again in transfer) and lands the entry
      locally before returning it.

    Private-API patch, same caveat and best-effort guard as the
    ``reset_cache()`` call in ``enable()`` below."""
    global _hardened
    if _hardened:
        return
    try:
        from jax._src.lru_cache import LRUCache
    except ImportError:
        return

    orig_put, orig_get = LRUCache.put, LRUCache.get

    def safe_put(self, key: str, val: bytes) -> None:
        try:
            published = publish_pair(self.path, key, val)
            if published:
                _stats["local_puts"] += 1
                if _remote_jax_tier_active():
                    _publish_async(key, val)
            # The original put sees the entry already present and returns
            # without rewriting the payload; calling it keeps the
            # eviction-lock bookkeeping of eviction-enabled caches intact.
        except OSError:
            pass
        orig_put(self, key, val)

    def _remote_fill(self, key: str):
        """Local miss: read-through the fleet tier. The fetched bytes are
        landed locally via the same atomic pair publish, so the next
        process on this host hits disk, not the network."""
        if not _remote_jax_tier_active():
            _stats["misses"] += 1
            return None
        val = _remote.fetch(key, wait_s=_remote_wait_s())
        if val is None:
            _stats["misses"] += 1
            return None
        try:
            publish_pair(self.path, key, val)
        except OSError:
            pass
        _stats["remote_hits"] += 1
        log.info("compilation cache remote hit for %s (%d bytes)", key, len(val))
        return val

    def safe_get(self, key: str):
        val = orig_get(self, key)
        if val is None:
            return _remote_fill(self, key)
        cache_path = self.path / f"{key}-cache"
        dpath = _digest_path(cache_path)
        try:
            want = dpath.read_bytes().decode()
        except OSError:
            want = ""
        if want == hashlib.sha256(val).hexdigest():
            _stats["local_hits"] += 1
            return val
        # Unverifiable (legacy or torn write): purge and recompile.
        log.warning(
            "compilation cache entry %s failed integrity check; "
            "dropping it (will recompile)", key,
        )
        for p in (cache_path, dpath, self.path / f"{key}-atime"):
            try:
                p.unlink()
            except OSError:
                pass
        _stats["misses"] += 1
        return None

    LRUCache.put, LRUCache.get = safe_put, safe_get
    _hardened = True


def _cpu_only_platform() -> bool:
    """True when JAX is pinned to the CPU backend (JAX_PLATFORMS=cpu).
    Env-only check on purpose: enable() runs BEFORE
    jax.distributed.initialize in the harness, and asking jax for its
    backend would initialize it too early."""
    plats = (os.environ.get("JAX_PLATFORMS") or "").replace(" ", "").lower()
    return plats.strip(",") == "cpu"


def cached_compile(
    key_material: str,
    compile_fn: Callable[[], bytes],
    cache_dir: Optional[str] = None,
    wait_s: Optional[float] = None,
) -> tuple:
    """Generic read-through/write-back compile against both cache tiers,
    for artifacts the jax LRUCache never sees (AOT-serialized executables
    published at admission time, the bench's modeled compiles).

    Key = sha256 of ``key_material`` (the caller's full config string —
    the analogue of jax's (HLO, compile options, backend) triple).
    Lookup order: local dir (sha-verified pair) → fleet tier (honoring a
    live compile intent with a bounded wait) → ``compile_fn()``, whose
    result is landed locally and published to the fleet asynchronously.

    Returns ``(data, source)`` with source in {"local", "remote",
    "compiled"}. Unlike the jax-executable tier this is platform-
    agnostic: payloads are caller-defined artifacts, not process-local
    jaxlib executables, so the cpu-pinned exclusion does not apply."""
    import pathlib

    key = hashlib.sha256(key_material.encode()).hexdigest()
    root = pathlib.Path(
        cache_dir or os.environ.get(ENV_DIR) or DEFAULT_CACHE_DIR
    )
    try:
        root.mkdir(parents=True, exist_ok=True)
    except OSError:
        root = None
    if root is not None:
        cache_path = root / f"{key}-cache"
        try:
            val = cache_path.read_bytes()
            want = _digest_path(cache_path).read_bytes().decode()
            if want == hashlib.sha256(val).hexdigest():
                _stats["local_hits"] += 1
                return val, "local"
        except OSError:
            pass
    from tf_operator_tpu.rendezvous.env import ENV_COMPILE_CACHE

    client = _remote
    if client is None and os.environ.get(ENV_COMPILE_CACHE):
        # Workloads that call cached_compile() directly (without the
        # enable() path initialize_distributed() runs) still get the
        # fleet tier the controller stamped into their env.
        configure_remote(os.environ[ENV_COMPILE_CACHE])
        client = _remote
    if client is not None:
        val = client.fetch(
            key, wait_s=_remote_wait_s() if wait_s is None else wait_s
        )
        if val is not None:
            _stats["remote_hits"] += 1
            if root is not None:
                publish_pair(root, key, val)
            return val, "remote"
    _stats["misses"] += 1
    val = compile_fn()
    if root is not None:
        try:
            publish_pair(root, key, val)
            _stats["local_puts"] += 1
        except OSError:
            pass
    _publish_async(key, val)
    return val, "compiled"


def enable(cache_dir: str | None = None, force: bool = False) -> str | None:
    """Turn on the persistent compilation cache; returns the directory in
    use, or None when disabled via TPUJOB_NO_COMPILE_CACHE=1 or because
    the process is pinned to the CPU backend.

    When the controller stamped a compile-cache service URL
    (TPUJOB_COMPILE_CACHE, cli/operator.py), the hardened cache I/O also
    becomes read-through/write-back against that fleet tier — except on
    cpu-pinned processes, where even force leaves the remote tier off
    (see below).

    CPU is excluded (r10, root-caused by the serve preemption probe):
    jaxlib 0.4.x serializes CPU executables with process-local state
    (custom-call pointers), so an entry deserialized by a DIFFERENT
    process than the one that compiled it can execute as heap
    corruption — observed as warm-restarted trainers dying with
    SIGSEGV/SIGABRT ("corrupted double-linked list") or, worse,
    silently computing garbage that trips the non-finite-loss
    checkpoint gate. Bit-identical entries reproduce it: the writing
    process runs fine, a second identical process reading the entry
    crashes. The cache is a TPU submit-latency lever; on CPU (tests,
    local benches) compiles are cheap and correctness wins.
    ``force=True`` / TPUJOB_FORCE_COMPILE_CACHE=1 override for cache
    machinery tests — the override re-enables only the LOCAL tier;
    process-local executables must never enter the shared one."""
    if os.environ.get(ENV_DISABLE, "") == "1":
        return None
    from tf_operator_tpu.rendezvous.env import ENV_COMPILE_CACHE

    if _remote is None and os.environ.get(ENV_COMPILE_CACHE, ""):
        configure_remote(os.environ[ENV_COMPILE_CACHE])
    if not force and os.environ.get(ENV_FORCE, "") != "1" and _cpu_only_platform():
        log.debug("persistent compilation cache disabled on cpu-only backend")
        return None
    path = cache_dir or os.environ.get(ENV_DIR) or DEFAULT_CACHE_DIR
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as exc:
        log.warning("compilation cache dir %s unusable: %s", path, exc)
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    # Cache even small/fast-compiling programs: the latency metric counts
    # every compile on the submit path.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # jax initializes its cache object lazily at the first compile and
    # then never re-reads the config dir — if ANYTHING compiled before
    # enable() (an orbax restore, a warmup jit), the cache would stay
    # pinned to that moment's (usually disabled) state and this call
    # would silently do nothing (r6: observed as checkpoint-restore →
    # compile-cache test-order pollution, present since the seed).
    try:
        from jax._src import compilation_cache as _jcc

        _jcc.reset_cache()
    except (ImportError, AttributeError):  # private API; best-effort
        pass
    _harden_cache_io()
    return path
