"""Persistent XLA compilation cache: submit→first-step latency control.

The north-star latency metric (BASELINE.json; SURVEY.md §7 hard part d) is
submit→first-step, and on TPU it is dominated by XLA compilation (~20-40 s
for the bench models) — a cost the reference never had to manage because it
ran TF's pre-compiled kernels. The TPU-native answer is JAX's persistent
compilation cache: executables are keyed by (HLO, compile options, backend)
and reloaded from disk, so

- a gang restart (the framework's recovery path — restart-based recovery,
  SURVEY.md §5) relaunches the training program at near-interactive speed,
- repeat submissions of the same workload skip straight to step 1.

``enable()`` is called by the rendezvous harness before user ``train_fn``
runs (every operator-launched process gets it), and by ``bench.py``. Safe
to call multiple times; honors an explicit ``JAX_COMPILATION_CACHE_DIR``.
"""

from __future__ import annotations

import hashlib
import logging
import os

log = logging.getLogger("tpujob.compile_cache")

DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "tf_operator_tpu", "xla"
)
ENV_DIR = "JAX_COMPILATION_CACHE_DIR"
ENV_DISABLE = "TPUJOB_NO_COMPILE_CACHE"
ENV_FORCE = "TPUJOB_FORCE_COMPILE_CACHE"

_DIGEST_SUFFIX = "-sha256"
_hardened = False


def _digest_path(cache_path):
    return cache_path.with_name(cache_path.name + _DIGEST_SUFFIX)


def _harden_cache_io() -> None:
    """Crash-safe the jax file cache (r10, found by the serve preemption
    probe): jax's ``LRUCache.put`` writes entries with a bare
    ``write_bytes()`` and never overwrites an existing key. A process
    killed mid-write — the operator's preempt path SIGKILLs workers, so
    this is a *routine* event, not a freak one — leaves a truncated blob
    under the final name; every warm-restarted incarnation that hits that
    key then deserializes garbage inside XLA and dies with
    SIGSEGV/SIGABRT, which the restart taxonomy rightly calls permanent.
    Net effect: one unlucky preemption poisons the cache key and turns
    every later warm restart of that program into a crash loop.

    Two wraps fix it for good:

    - ``put``: write a sha256 sidecar, then the payload via temp file +
      atomic ``os.replace`` — a kill at any instant leaves either no
      entry or a complete one.
    - ``get``: verify the sidecar before handing bytes to XLA; a
      mismatching or missing sidecar deletes the entry and reports a
      miss (recompile), so pre-existing poison self-heals instead of
      aborting the process.

    Private-API patch, same caveat and best-effort guard as the
    ``reset_cache()`` call in ``enable()`` below."""
    global _hardened
    if _hardened:
        return
    try:
        from jax._src.lru_cache import LRUCache
    except ImportError:
        return

    orig_put, orig_get = LRUCache.put, LRUCache.get

    def safe_put(self, key: str, val: bytes) -> None:
        cache_path = self.path / f"{key}-cache"
        try:
            if cache_path.exists():
                return
            _digest_path(cache_path).write_bytes(
                hashlib.sha256(val).hexdigest().encode()
            )
            tmp = cache_path.with_name(cache_path.name + f".tmp{os.getpid()}")
            tmp.write_bytes(val)
            os.replace(tmp, cache_path)
            import time as _time

            (self.path / f"{key}-atime").write_bytes(
                _time.time_ns().to_bytes(8, "little")
            )
            # The original put sees the entry already present and returns
            # without rewriting the payload; calling it keeps the
            # eviction-lock bookkeeping of eviction-enabled caches intact.
        except OSError:
            pass
        orig_put(self, key, val)

    def safe_get(self, key: str):
        val = orig_get(self, key)
        if val is None:
            return None
        cache_path = self.path / f"{key}-cache"
        dpath = _digest_path(cache_path)
        try:
            want = dpath.read_bytes().decode()
        except OSError:
            want = ""
        if want == hashlib.sha256(val).hexdigest():
            return val
        # Unverifiable (legacy or torn write): purge and recompile.
        log.warning(
            "compilation cache entry %s failed integrity check; "
            "dropping it (will recompile)", key,
        )
        for p in (cache_path, dpath, self.path / f"{key}-atime"):
            try:
                p.unlink()
            except OSError:
                pass
        return None

    LRUCache.put, LRUCache.get = safe_put, safe_get
    _hardened = True


def _cpu_only_platform() -> bool:
    """True when JAX is pinned to the CPU backend (JAX_PLATFORMS=cpu).
    Env-only check on purpose: enable() runs BEFORE
    jax.distributed.initialize in the harness, and asking jax for its
    backend would initialize it too early."""
    plats = (os.environ.get("JAX_PLATFORMS") or "").replace(" ", "").lower()
    return plats.strip(",") == "cpu"


def enable(cache_dir: str | None = None, force: bool = False) -> str | None:
    """Turn on the persistent compilation cache; returns the directory in
    use, or None when disabled via TPUJOB_NO_COMPILE_CACHE=1 or because
    the process is pinned to the CPU backend.

    CPU is excluded (r10, root-caused by the serve preemption probe):
    jaxlib 0.4.x serializes CPU executables with process-local state
    (custom-call pointers), so an entry deserialized by a DIFFERENT
    process than the one that compiled it can execute as heap
    corruption — observed as warm-restarted trainers dying with
    SIGSEGV/SIGABRT ("corrupted double-linked list") or, worse,
    silently computing garbage that trips the non-finite-loss
    checkpoint gate. Bit-identical entries reproduce it: the writing
    process runs fine, a second identical process reading the entry
    crashes. The cache is a TPU submit-latency lever; on CPU (tests,
    local benches) compiles are cheap and correctness wins.
    ``force=True`` / TPUJOB_FORCE_COMPILE_CACHE=1 override for cache
    machinery tests."""
    if os.environ.get(ENV_DISABLE, "") == "1":
        return None
    if not force and os.environ.get(ENV_FORCE, "") != "1" and _cpu_only_platform():
        log.debug("persistent compilation cache disabled on cpu-only backend")
        return None
    path = cache_dir or os.environ.get(ENV_DIR) or DEFAULT_CACHE_DIR
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as exc:
        log.warning("compilation cache dir %s unusable: %s", path, exc)
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    # Cache even small/fast-compiling programs: the latency metric counts
    # every compile on the submit path.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # jax initializes its cache object lazily at the first compile and
    # then never re-reads the config dir — if ANYTHING compiled before
    # enable() (an orbax restore, a warmup jit), the cache would stay
    # pinned to that moment's (usually disabled) state and this call
    # would silently do nothing (r6: observed as checkpoint-restore →
    # compile-cache test-order pollution, present since the seed).
    try:
        from jax._src import compilation_cache as _jcc

        _jcc.reset_cache()
    except (ImportError, AttributeError):  # private API; best-effort
        pass
    _harden_cache_io()
    return path
