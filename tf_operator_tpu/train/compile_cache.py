"""Persistent XLA compilation cache: submit→first-step latency control.

The north-star latency metric (BASELINE.json; SURVEY.md §7 hard part d) is
submit→first-step, and on TPU it is dominated by XLA compilation (~20-40 s
for the bench models) — a cost the reference never had to manage because it
ran TF's pre-compiled kernels. The TPU-native answer is JAX's persistent
compilation cache: executables are keyed by (HLO, compile options, backend)
and reloaded from disk, so

- a gang restart (the framework's recovery path — restart-based recovery,
  SURVEY.md §5) relaunches the training program at near-interactive speed,
- repeat submissions of the same workload skip straight to step 1.

``enable()`` is called by the rendezvous harness before user ``train_fn``
runs (every operator-launched process gets it), and by ``bench.py``. Safe
to call multiple times; honors an explicit ``JAX_COMPILATION_CACHE_DIR``.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("tpujob.compile_cache")

DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "tf_operator_tpu", "xla"
)
ENV_DIR = "JAX_COMPILATION_CACHE_DIR"
ENV_DISABLE = "TPUJOB_NO_COMPILE_CACHE"


def enable(cache_dir: str | None = None) -> str | None:
    """Turn on the persistent compilation cache; returns the directory in
    use, or None when disabled via TPUJOB_NO_COMPILE_CACHE=1."""
    if os.environ.get(ENV_DISABLE, "") == "1":
        return None
    path = cache_dir or os.environ.get(ENV_DIR) or DEFAULT_CACHE_DIR
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as exc:
        log.warning("compilation cache dir %s unusable: %s", path, exc)
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    # Cache even small/fast-compiling programs: the latency metric counts
    # every compile on the submit path.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # jax initializes its cache object lazily at the first compile and
    # then never re-reads the config dir — if ANYTHING compiled before
    # enable() (an orbax restore, a warmup jit), the cache would stay
    # pinned to that moment's (usually disabled) state and this call
    # would silently do nothing (r6: observed as checkpoint-restore →
    # compile-cache test-order pollution, present since the seed).
    try:
        from jax._src import compilation_cache as _jcc

        _jcc.reset_cache()
    except (ImportError, AttributeError):  # private API; best-effort
        pass
    return path
