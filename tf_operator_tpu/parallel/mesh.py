"""Mesh construction: logical parallelism axes over physical devices.

The canonical axis vocabulary (the scaling-book recipe — pick a mesh,
annotate shardings, let XLA insert collectives):

- ``dp``    — data parallelism (batch split, gradient all-reduce)
- ``fsdp``  — fully-sharded data parallelism (batch split + param shards,
              all-gather params / reduce-scatter grads)
- ``tp``    — tensor/model parallelism (matmul shards, activation
              all-gather/reduce along features)
- ``cp``    — context/sequence parallelism (ring attention over sequence)
- ``pp``    — pipeline parallelism (layer stages, ppermute activations)
- ``ep``    — expert parallelism (MoE experts, all-to-all dispatch)

Axis ORDER matters on TPU: the innermost (last) axes land on adjacent
devices, so put the most communication-hungry axis (tp) last so its
collectives ride the shortest ICI paths; dp/pp tolerate distance.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

AXIS_DATA = "dp"
AXIS_FSDP = "fsdp"
AXIS_TENSOR = "tp"
AXIS_CONTEXT = "cp"
AXIS_PIPELINE = "pp"
AXIS_EXPERT = "ep"

# Canonical order, outermost -> innermost (tp innermost: most traffic).
CANONICAL_ORDER = (AXIS_PIPELINE, AXIS_DATA, AXIS_FSDP, AXIS_EXPERT, AXIS_CONTEXT, AXIS_TENSOR)


@dataclass(frozen=True)
class MeshSpec:
    """A validated logical mesh layout.

    ``axes`` maps axis name -> size; unspecified axes are absent (size 1 is
    allowed and kept, so sharding rules can reference the axis uniformly).
    One axis may be -1: it absorbs the remaining devices (like a reshape).
    """

    axes: Dict[str, int] = field(default_factory=dict)

    def resolve(self, n_devices: int) -> "MeshSpec":
        axes = dict(self.axes) or {AXIS_DATA: n_devices}
        wild = [k for k, v in axes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {wild}")
        fixed = math.prod(v for v in axes.values() if v != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            axes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh axes {axes} multiply to {fixed} but there are {n_devices} devices"
            )
        return MeshSpec(axes)

    def ordered(self) -> Tuple[Tuple[str, int], ...]:
        """Axes in canonical TPU order; unknown axes keep insertion order,
        placed before the canonical ones (treated as outermost)."""
        known = [a for a in CANONICAL_ORDER if a in self.axes]
        unknown = [a for a in self.axes if a not in CANONICAL_ORDER]
        return tuple((a, self.axes[a]) for a in unknown + known)


def build_mesh(
    axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
):
    """Build a jax.sharding.Mesh from a logical axis spec.

    Device order: jax.devices() is already ICI-topology-ordered on TPU;
    reshaping into (ordered axis sizes) keeps the innermost logical axis on
    physically adjacent chips.
    """
    import jax
    from jax.sharding import Mesh

    devs = np.asarray(devices if devices is not None else jax.devices())
    spec = MeshSpec(dict(axes or {})).resolve(devs.size)
    ordered = spec.ordered()
    names = tuple(a for a, _ in ordered)
    sizes = tuple(s for _, s in ordered)
    return Mesh(devs.reshape(sizes), names)
