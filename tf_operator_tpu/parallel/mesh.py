"""Mesh construction: logical parallelism axes over physical devices.

The canonical axis vocabulary (the scaling-book recipe — pick a mesh,
annotate shardings, let XLA insert collectives):

- ``dp``    — data parallelism (batch split, gradient all-reduce)
- ``fsdp``  — fully-sharded data parallelism (batch split + param shards,
              all-gather params / reduce-scatter grads)
- ``tp``    — tensor/model parallelism (matmul shards, activation
              all-gather/reduce along features)
- ``cp``    — context/sequence parallelism (ring attention over sequence)
- ``pp``    — pipeline parallelism (layer stages, ppermute activations)
- ``ep``    — expert parallelism (MoE experts, all-to-all dispatch)

Axis ORDER matters on TPU: the innermost (last) axes land on adjacent
devices, so put the most communication-hungry axis (tp) last so its
collectives ride the shortest ICI paths; dp/pp tolerate distance.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

AXIS_DATA = "dp"
AXIS_FSDP = "fsdp"
AXIS_TENSOR = "tp"
AXIS_CONTEXT = "cp"
AXIS_PIPELINE = "pp"
AXIS_EXPERT = "ep"

# Canonical order, outermost -> innermost (tp innermost: most traffic).
CANONICAL_ORDER = (AXIS_PIPELINE, AXIS_DATA, AXIS_FSDP, AXIS_EXPERT, AXIS_CONTEXT, AXIS_TENSOR)


@dataclass(frozen=True)
class MeshSpec:
    """A validated logical mesh layout.

    ``axes`` maps axis name -> size; unspecified axes are absent (size 1 is
    allowed and kept, so sharding rules can reference the axis uniformly).
    One axis may be -1: it absorbs the remaining devices (like a reshape).
    """

    axes: Dict[str, int] = field(default_factory=dict)

    def resolve(self, n_devices: int) -> "MeshSpec":
        axes = dict(self.axes) or {AXIS_DATA: n_devices}
        wild = [k for k, v in axes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {wild}")
        fixed = math.prod(v for v in axes.values() if v != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            axes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh axes {axes} multiply to {fixed} but there are {n_devices} devices"
            )
        return MeshSpec(axes)

    def ordered(self) -> Tuple[Tuple[str, int], ...]:
        """Axes in canonical TPU order; unknown axes keep insertion order,
        placed before the canonical ones (treated as outermost)."""
        known = [a for a in CANONICAL_ORDER if a in self.axes]
        unknown = [a for a in self.axes if a not in CANONICAL_ORDER]
        return tuple((a, self.axes[a]) for a in unknown + known)


def build_mesh(
    axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
):
    """Build a jax.sharding.Mesh from a logical axis spec.

    Device order: jax.devices() is already ICI-topology-ordered on TPU;
    reshaping into (ordered axis sizes) keeps the innermost logical axis on
    physically adjacent chips.
    """
    import jax
    from jax.sharding import Mesh

    devs = np.asarray(devices if devices is not None else jax.devices())
    spec = MeshSpec(dict(axes or {})).resolve(devs.size)
    ordered = spec.ordered()
    names = tuple(a for a, _ in ordered)
    sizes = tuple(s for _, s in ordered)
    return Mesh(devs.reshape(sizes), names)


def build_hybrid_mesh(
    ici_axes: Dict[str, int],
    dcn_axes: Dict[str, int],
    devices: Optional[Sequence] = None,
):
    """Multi-slice mesh: ``dcn_axes`` span slices (data-center network),
    ``ici_axes`` stay within a slice (the fast fabric).

    Each logical axis's total size is ``ici * dcn`` for that name (either
    side defaulting to 1), and the DCN factor is the OUTER (slower-moving)
    block of the axis — so e.g. ``ici_axes={"dp": 4, "tp": 4},
    dcn_axes={"dp": 2}`` on 2 slices of 16 chips gives dp=8 where only the
    outermost dp hop crosses DCN and all tp collectives ride ICI. This is
    the SURVEY §5 cross-slice contract: intra-slice needs zero config;
    cross-slice rides DCN and must carry only gradient/AllReduce-class
    traffic (put dcn factors on dp/pp, never tp/cp).

    On TPU, devices carry ``slice_index`` and placement delegates to
    jax.experimental.mesh_utils.create_hybrid_device_mesh; elsewhere (the
    CPU test mesh) contiguous equal blocks of the device list stand in for
    slices.
    """
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else list(jax.devices())
    union = dict(dcn_axes)
    union.update({a: s for a, s in ici_axes.items()})
    if not union:
        raise ValueError("hybrid mesh needs at least one axis")
    names = tuple(a for a, _ in MeshSpec({a: 1 for a in union}).ordered())
    ici_shape = [int(ici_axes.get(a, 1)) for a in names]
    dcn_shape = [int(dcn_axes.get(a, 1)) for a in names]
    per_slice = math.prod(ici_shape)
    n_slices = math.prod(dcn_shape)
    if per_slice * n_slices != len(devs):
        raise ValueError(
            f"hybrid mesh ici{dict(zip(names, ici_shape))} x "
            f"dcn{dict(zip(names, dcn_shape))} needs {per_slice * n_slices} "
            f"devices, have {len(devs)}"
        )

    slice_ids = {getattr(d, "slice_index", None) for d in devs}
    # Slice topology is only meaningful on TPU: the CPU backend stamps
    # every device slice_index=0 across all processes, which would reject
    # any multi-process dcn mesh. On CPU the contiguous-block fallback
    # applies, and the global device list orders by process — so process
    # boundaries become the DCN stand-in (the gang e2e contract).
    has_slice_info = (
        None not in slice_ids and getattr(devs[0], "platform", "") == "tpu"
    )
    if has_slice_info and (len(slice_ids) > 1 or n_slices > 1):
        if len(slice_ids) != n_slices:
            # Never fall back silently: a contiguous-block layout here
            # would put ICI axes across physical slices (tp/cp over DCN).
            raise ValueError(
                f"devices span {len(slice_ids)} slices but dcn axes "
                f"{dict(zip(names, dcn_shape))} declare {n_slices}"
            )
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(ici_shape, dcn_shape, devs)
    else:
        # No slice topology info: contiguous blocks as slices. Shape
        # [d0..dk, i0..ik] -> interleave to [(d0,i0), (d1,i1), ...] so the
        # dcn factor is the outer block of each logical axis.
        k = len(names)
        a = np.asarray(devs).reshape(tuple(dcn_shape) + tuple(ici_shape))
        perm = [j for i in range(k) for j in (i, i + k)]
        arr = a.transpose(perm).reshape(
            [dcn_shape[i] * ici_shape[i] for i in range(k)]
        )
    return Mesh(arr, names)
