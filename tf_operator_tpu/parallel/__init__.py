"""Parallelism library: meshes, shardings, collectives, and parallel
attention/pipeline/MoE building blocks.

This layer is NEW surface relative to the reference: kubeflow/tf-operator
implements exactly one parallelism pattern (PS data parallelism as topology,
SURVEY.md §2.3) and delegates everything else to user code. On TPU the
framework owns it: a job declares mesh axes (api.types.TopologySpec), the
rendezvous layer builds the Mesh, and this package supplies the sharding
rules and parallel primitives — DP/FSDP/TP via pjit sharding annotations,
sequence/context parallelism via ring attention over ppermute, pipeline
parallelism via shard_map microbatch schedules, expert parallelism via
all-to-all — all compiled to XLA collectives that ride ICI.
"""

from tf_operator_tpu.parallel.mesh import (  # noqa: F401
    AXIS_CONTEXT,
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_PIPELINE,
    AXIS_TENSOR,
    MeshSpec,
    build_hybrid_mesh,
    build_mesh,
)
from tf_operator_tpu.parallel.sharding import (  # noqa: F401
    ShardingRules,
    batch_sharding,
    logical_to_sharding,
    replicated,
)
